"""Stateless schedule exploration: DFS over controller choice points.

Each run re-executes the scenario from scratch (fresh objects, frozen
clock re-seeded) following a *forced prefix* of task choices, then the
deterministic default continuation (stay on the current task while
enabled — minimizes preemptions).  After a run, every step at or past
the prefix length becomes a backtrack point: each enabled-but-not-
chosen task yields a new prefix to explore.  Schedules are uniquely
determined by their choice sequence, so the DFS enumerates each
maximal schedule at most once.

Modes:

- ``full``  — every alternative at every step.  Ground truth; the
  budget ceiling for the @slow suite.
- ``dpor``  — conflict-directed pruning (dynamic partial-order
  reduction, conservative approximation): an alternative task is
  explored at step i only if its pending operation CONFLICTS with
  some operation another task executes at step >= i in the observed
  run.  Independent (never-conflicting) ops commute — running the
  alternative earlier reaches a state the observed run also reaches,
  so the alternative schedule is redundant.  Conflict = same resource
  (lock / event / condition), or anything against a clock tick
  (sched.Op.conflicts).  tests/test_gubercheck.py cross-validates
  dpor against full on the mutation scenarios.

Preemption bound (CHESS): a *preemption* is choosing away from a task
that is still enabled.  ``preemption_bound=N`` skips alternatives
whose prefix would exceed N preemptions — the polynomial smoke budget
for ci_fast; most shipped concurrency bugs reproduce within 2
(Musuvathi & Qadeer, PLDI'07).
"""

from __future__ import annotations

import time as _walltime
from dataclasses import dataclass, field
from typing import Callable, List, Optional

from tools.gubercheck.properties import PropertyViolation
from tools.gubercheck.sched import (
    DeadlockError,
    DivergenceError,
    StepRecord,
)


@dataclass
class Violation:
    """One finding: which property (or structural failure), on which
    schedule."""

    kind: str  # "property" | "deadlock" | "task-exception" | "divergence"
    prop: Optional[str]
    detail: str
    schedule: List[str]
    step: int


@dataclass
class RunResult:
    steps: List[StepRecord]
    violation: Optional[Violation]


@dataclass
class ExplorationResult:
    scenario: str
    mode: str
    runs: int = 0
    max_steps_seen: int = 0
    violations: List[Violation] = field(default_factory=list)
    complete: bool = False  # every reachable schedule (mode-reduced) visited
    truncated_by: Optional[str] = None  # "max_runs" | "wall_budget"
    elapsed_s: float = 0.0

    @property
    def ok(self) -> bool:
        return not self.violations


class _Prefix:
    __slots__ = ("schedule", "preemptions")

    def __init__(self, schedule: List[str], preemptions: int):
        self.schedule = schedule
        self.preemptions = preemptions


def run_once(
    scenario_factory: Callable[[], "object"],
    forced: List[str],
    max_steps: int = 2000,
) -> RunResult:
    """Execute one schedule.  The factory builds a fresh scenario; the
    scenario object drives setup/tasks/check/finish (see scenarios.py
    Scenario protocol)."""
    scn = scenario_factory()
    try:
        steps = scn.run(forced, max_steps=max_steps)
    except PropertyViolation as e:
        return RunResult(scn.trace(), Violation(
            "property", e.prop, e.detail,
            [s.chosen for s in scn.trace()], len(scn.trace()),
        ))
    except DeadlockError as e:
        return RunResult(scn.trace(), Violation(
            "deadlock", None, str(e),
            [s.chosen for s in scn.trace()], len(scn.trace()),
        ))
    except DivergenceError:
        raise  # scenario not schedule-deterministic: a checker bug
    task_exc = scn.task_exception()
    if task_exc is not None:
        name, exc = task_exc
        return RunResult(steps, Violation(
            "task-exception", None, f"task {name!r}: {exc!r}",
            [s.chosen for s in steps], len(steps),
        ))
    return RunResult(steps, None)


def _count_preemptions(steps: List[StepRecord], upto: int) -> int:
    return sum(1 for s in steps[:upto] if s.preempting)


def _op_conflicts(a, b) -> bool:
    ak, ar = a
    bk, br = b
    if ak in ("start", "join") or bk in ("start", "join"):
        return False  # pure control flow commutes with everything
    return ar == "clock" or br == "clock" or ar == br


def _conflicts_later(steps: List[StepRecord], i: int, alt: str) -> bool:
    """DPOR race check: is scheduling ``alt`` at step i (instead of
    the observed choice) potentially observable?  True iff the op
    EXECUTED at step i conflicts with anything ``alt`` is observed to
    do from step i onward — its pending op, or any op it executes
    later in this run.  (The pending op alone is not enough: a task
    that has not started yet pends on ``start``, which commutes with
    everything, yet its post-start ops may race with the op executed
    here.  Races seeded by later steps are covered when the backtrack
    loop reaches those i values.)"""
    executed = steps[i].op
    fut = steps[i].pending.get(alt)
    if fut is None:
        return True  # defensive: unknown pending — do not prune
    if _op_conflicts(executed, fut):
        return True
    for s in steps[i + 1:]:
        if s.chosen == alt and _op_conflicts(executed, s.op):
            return True
    return False


def explore(
    scenario_factory: Callable[[], "object"],
    *,
    mode: str = "dpor",
    preemption_bound: Optional[int] = None,
    max_runs: int = 20000,
    max_steps: int = 2000,
    wall_budget_s: Optional[float] = None,
    stop_on_violation: bool = True,
    scenario_name: str = "?",
) -> ExplorationResult:
    """Enumerate schedules of one scenario.  Returns the aggregate;
    ``complete`` is True only when the DFS drained with no budget
    truncation."""
    if mode not in ("full", "dpor"):
        raise ValueError(f"unknown mode {mode!r}")
    res = ExplorationResult(scenario=scenario_name, mode=mode)
    t0 = _walltime.monotonic()
    stack: List[_Prefix] = [_Prefix([], 0)]
    while stack:
        if res.runs >= max_runs:
            res.truncated_by = "max_runs"
            break
        if (
            wall_budget_s is not None
            and _walltime.monotonic() - t0 > wall_budget_s
        ):
            res.truncated_by = "wall_budget"
            break
        prefix = stack.pop()
        rr = run_once(scenario_factory, prefix.schedule, max_steps)
        res.runs += 1
        res.max_steps_seen = max(res.max_steps_seen, len(rr.steps))
        if rr.violation is not None:
            res.violations.append(rr.violation)
            if stop_on_violation:
                res.elapsed_s = _walltime.monotonic() - t0
                return res
        steps = rr.steps
        # Backtrack points: alternatives at/after the prefix boundary.
        # Reversed push order keeps the DFS depth-first left-to-right.
        new_prefixes: List[_Prefix] = []
        acc = _count_preemptions(steps, len(prefix.schedule))
        for i in range(len(prefix.schedule), len(steps)):
            s = steps[i]
            prev = steps[i - 1].chosen if i > 0 else None
            for alt in s.enabled:
                if alt == s.chosen:
                    continue
                alt_preempts = acc + (
                    1 if (prev is not None and prev != alt
                          and prev in s.enabled) else 0
                )
                if (
                    preemption_bound is not None
                    and alt_preempts > preemption_bound
                ):
                    continue
                if mode == "dpor" and not _conflicts_later(steps, i, alt):
                    continue
                new_prefixes.append(_Prefix(
                    [st.chosen for st in steps[:i]] + [alt], alt_preempts,
                ))
            acc += 1 if s.preempting else 0
        stack.extend(reversed(new_prefixes))
    else:
        res.complete = True
    res.elapsed_s = _walltime.monotonic() - t0
    return res

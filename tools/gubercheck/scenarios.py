"""The scenario catalog: small fixed workloads over the REAL protocol
modules, explored by explore.py.

Each scenario is a deterministic world: a frozen Clock, a handful of
named tasks (at most 4 — the state-space budget ISSUE 18 commits to),
and the repo's actual protocol objects constructed under the
instrumented ``threading`` patch so every lock acquire is a schedule
choice point.  ``check()`` runs at every quiescent controller step
(no managed task holds an instrumented lock), ``finish()`` runs after
all tasks complete — both raise
``properties.PropertyViolation`` on an invariant break.

What is real and what is stubbed:

- REAL: ``core/ledger.py`` (plan/learn/settle/revoke — the full
  serve partition), ``cluster/health.py`` PeerHealth,
  ``cluster/membership.py`` apply_view/transition/commit (including
  its real per-epoch transition threads), ``cluster/replication.py``
  receive/install/try_answer/expire, ``cluster/multiregion.py``
  _push_region/_requeue_region (the requeue-and-converge core).
- STUBBED: the decision ENGINE is ``SpecEngine`` — the sequential
  scalar spec (models/spec.py) applied row-by-row under one lock.
  This keeps jax off the hot path (a gubercheck run re-executes the
  scenario thousands of times) and makes the device tier itself an
  oracle: the ledger's cached answers are checked against exactly the
  state a spec-conformant device holds.  Transports (peer RPC, the
  native C plane, the interval batcher) are in-memory fakes with the
  same contracts the protocol code drives.

Scenario determinism contract: given the same forced schedule prefix,
a scenario must make identical choices (explore.py raises
DivergenceError otherwise).  No wall clock, no randomness that feeds
a branch, dict iteration in insertion order only.
"""

from __future__ import annotations

import dataclasses
import json
from collections import OrderedDict
from types import SimpleNamespace
from typing import Dict, List, Optional, Tuple

import numpy as np

from gubernator_tpu.clock import Clock
from gubernator_tpu.hashing import fnv1a_64
from gubernator_tpu.models.spec import SpecInput, apply_spec
from gubernator_tpu.types import Algorithm, PeerInfo, Status

from tools.gubercheck import properties as props
from tools.gubercheck.properties import PropertyViolation
from tools.gubercheck.sched import Scheduler, instrumented, virtual_time

# A fixed virtual epoch: every run of every scenario starts at the
# same instant, so TTL/expiry arithmetic is identical run to run.
EPOCH_NS = 1_700_000_000_000_000_000

_TOKEN = int(Algorithm.TOKEN_BUCKET)
_UNDER = int(Status.UNDER_LIMIT)
_OVER = int(Status.OVER_LIMIT)

# Ledger entry kinds (core/ledger.py) — read-only mirror for the
# invariant extractors.
_K_COUNTER, _K_OVER, _K_LEASE, _K_NATIVE = 0, 1, 2, 3
_KIND_NAME = {0: "counter", 1: "over", 2: "lease", 3: "native"}


# ---------------------------------------------------------------------
# The spec-backed engine stub.


class _Packed:
    """Duck-typed PackedKeys (avoids importing core.engine → jax)."""

    __slots__ = ("key_buf", "key_offsets", "n")

    def __init__(self, key_buf, key_offsets, n):
        self.key_buf = key_buf
        self.key_offsets = key_offsets
        self.n = n


class SpecEngine:
    """Sequential-spec device tier with the engine's columnar calling
    convention.  One lock around the whole apply: the real engine's
    batch apply is atomic w.r.t. other batches, and modeling it as
    one critical section keeps the schedule space honest."""

    def __init__(self, clock):
        self.clock = clock
        self.states: Dict[bytes, object] = {}
        self._lock = None  # created in bind() under instrumentation

    def bind_lock(self, lock) -> None:
        self._lock = lock

    def _keys(self, keys) -> List[bytes]:
        if hasattr(keys, "key_buf"):
            buf = bytes(bytearray(np.asarray(keys.key_buf, dtype=np.uint8)))
            off = [int(o) for o in keys.key_offsets]
            return [buf[off[i]:off[i + 1]] for i in range(int(keys.n))]
        return [bytes(k) for k in keys]

    def apply_columnar(
        self, keys, algo, behavior, hits, limit, duration, burst,
        now_ms=None, count_decisions=True,
    ):
        kl = self._keys(keys)
        now = int(now_ms) if now_ms is not None else self.clock.now_ms()
        st_o: List[int] = []
        lim_o: List[int] = []
        rem_o: List[int] = []
        rst_o: List[int] = []
        with self._lock:
            for i, k in enumerate(kl):
                inp = SpecInput(
                    hits=int(hits[i]), limit=int(limit[i]),
                    duration=int(duration[i]), burst=int(burst[i]),
                    algorithm=int(algo[i]), behavior=int(behavior[i]),
                )
                new_state, resp = apply_spec(self.states.get(k), inp, now)
                if new_state is None:
                    self.states.pop(k, None)
                else:
                    self.states[k] = new_state
                st_o.append(int(resp.status))
                lim_o.append(int(resp.limit))
                rem_o.append(int(resp.remaining))
                rst_o.append(int(resp.reset_time))
        return (
            np.asarray(st_o, np.int32), np.asarray(lim_o, np.int64),
            np.asarray(rem_o, np.int64), np.asarray(rst_o, np.int64),
        )

    def spec_probe(self, key: bytes, limit: int, duration: int,
                   burst: int, now: int) -> Tuple[int, int]:
        """(status, remaining) a hits=0 query would answer right now —
        computed on a COPY of the state, no mutation."""
        state = self.states.get(key)
        if state is not None:
            state = dataclasses.replace(state)
        inp = SpecInput(
            hits=0, limit=limit, duration=duration, burst=burst,
            algorithm=_TOKEN, behavior=0,
        )
        _, resp = apply_spec(state, inp, now)
        return int(resp.status), int(resp.remaining)


def _make_dec(rows):
    """rows: (key, algo, behavior, hits, limit, duration, burst) —
    the DecodedBatch shape ledger.plan consumes."""
    d = SimpleNamespace()
    keys = [r[0] for r in rows]
    d.n = len(rows)
    d.key_buf = np.frombuffer(b"".join(keys), dtype=np.uint8)
    off = np.zeros(len(rows) + 1, dtype=np.int64)
    np.cumsum([len(k) for k in keys], out=off[1:])
    d.key_offsets = off
    d.algo = np.asarray([r[1] for r in rows], np.int32)
    d.behavior = np.asarray([r[2] for r in rows], np.int32)
    d.hits = np.asarray([r[3] for r in rows], np.int64)
    d.limit = np.asarray([r[4] for r in rows], np.int64)
    d.duration = np.asarray([r[5] for r in rows], np.int64)
    d.burst = np.asarray([r[6] for r in rows], np.int64)
    d.fnv1a = np.asarray([fnv1a_64(k) for k in keys], np.uint64)
    return d


# ---------------------------------------------------------------------
# Scenario protocol.


class Scenario:
    """Base: one fresh world per run (see explore.run_once)."""

    name = "?"
    summary = ""
    #: property names this scenario checks (must all be registered).
    properties: Tuple[str, ...] = ()
    #: module paths whose ``time`` attribute reads the frozen Clock.
    time_modules: Tuple[str, ...] = ()
    #: ci_fast smoke budget (CHESS-bounded).
    smoke = dict(mode="dpor", preemption_bound=2, max_runs=2000,
                 max_steps=400)
    #: committed full-exploration budget (@slow + tests assert
    #: ``complete`` under it).
    full = dict(mode="dpor", max_runs=60000, max_steps=400)

    def __init__(self):
        self.clock = Clock().freeze_at(EPOCH_NS)
        self.sched: Optional[Scheduler] = None

    # -- hooks ---------------------------------------------------------

    def build(self, sched: Scheduler) -> None:
        raise NotImplementedError

    def check(self) -> None:  # quiescent-point invariants
        pass

    def finish(self) -> None:  # terminal probes
        pass

    # -- explore.run_once protocol -------------------------------------

    def _time_mods(self):
        import importlib

        return [importlib.import_module(m) for m in self.time_modules]

    def run(self, forced: List[str], max_steps: int = 2000):
        sched = Scheduler(self.clock, max_steps=max_steps)
        self.sched = sched
        mods = self._time_mods()
        with virtual_time(self.clock, mods), instrumented(sched):
            self.build(sched)
            sched.run(forced, check=self.check)
            self.finish()
        return sched.steps

    def trace(self):
        return self.sched.steps if self.sched is not None else []

    def task_exception(self):
        if self.sched is None:
            return None
        for t in self.sched.tasks:
            if t.exc is not None:
                return (t.name, t.exc)
        return None


# ---------------------------------------------------------------------
# Ledger scenarios.


class _LedgerScenario(Scenario):
    """Shared ledger/SpecEngine plumbing.  ``ledger_mod`` defaults to
    the real module; mutations.py points it at a mutated twin (same
    source, one guard disabled) to prove the checker has teeth."""

    ledger_mod = None

    def _ledger_module(self):
        if self.ledger_mod is None:
            from gubernator_tpu.core import ledger as ledger_mod

            self.ledger_mod = ledger_mod
        return self.ledger_mod

    def _time_mods(self):
        return [self._ledger_module()]

    def _mk_ledger(self, **kw):
        self.ledger_mod = self._ledger_module()
        self.engine = SpecEngine(self.clock)
        # The engine lock is created HERE, under instrumentation, from
        # this module (not a passthrough) — one schedule point per
        # device batch apply.
        import threading

        self.engine.bind_lock(threading.RLock())
        kw.setdefault("settle_interval", 0)  # no background flusher
        self.ledger = self.ledger_mod.DecisionLedger(self.engine, **kw)
        return self.ledger

    def serve(self, rows):
        """The exact serve partition the fronts use (tests/test_ledger
        Harness.serve)."""
        dec = _make_dec(rows)
        now = self.clock.now_ms()
        plan = self.ledger.plan(dec, now)
        if plan.full:
            return plan.dense_cols()
        lane = plan.build_engine_lane()
        st, lim, rem, rst = self.engine.apply_columnar(
            _Packed(lane.key_buf, lane.key_offsets, lane.n),
            lane.algo, lane.behavior, lane.hits, lane.limit,
            lane.duration, lane.burst, now_ms=now,
        )
        plan.learn(st, lim, rem, rst)
        return plan.merge_outputs(st, rem, rst)

    # -- invariant extractors ------------------------------------------

    def _spec_live(self, state, now: int) -> bool:
        if state is None:
            return False
        if state.expire_at < now:
            return False
        if state.invalid_at != 0 and state.invalid_at < now:
            return False
        return True

    def check_sticky_over_exact(self) -> None:
        now = self.clock.now_ms()
        entries = []
        for e in self.ledger._items.values():
            if e.kind != _K_OVER or now > e.reset:
                continue
            st = self.engine.states.get(e.key)
            entries.append((
                e.key,
                int(st.remaining) if st is not None else 0,
                self._spec_live(st, now),
            ))
        props.check_sticky_over_exact(entries)

    def check_probe_conformance(self, key, limit, duration, burst):
        now = self.clock.now_ms()
        spec_ans = self.engine.spec_probe(key, limit, duration, burst, now)
        st, _lim, rem, _rst = self.serve(
            [(key, _TOKEN, 0, 0, limit, duration, burst)]
        )
        props.check_probe_conformance(
            key, (int(st[0]), int(rem[0])), spec_ans
        )


class LedgerLeaseChurn(_LedgerScenario):
    """PR 13's bug class: a small hot bucket whose lease is revoked
    (over-ask) while other serves race the in-flight credit return.
    A sticky-OVER insert that captures the PRE-return device snapshot
    strands the returned credit until the window resets."""

    name = "ledger-lease-churn"
    summary = ("lease revoke vs racing serves on a small hot bucket; "
               "the in-flight-return window must not mint sticky OVER")
    properties = ("sticky-over-exact", "hot-key-no-starvation",
                  "over-admission-bound")
    K = b"churn-hot"
    LIMIT, DUR = 4, 60_000

    def build(self, sched: Scheduler) -> None:
        led = self._mk_ledger(
            lease_size=8, lease_ttl=0.2, hot_threshold=2, hot_window=10.0,
        )
        self.admitted: Dict[str, int] = {}
        row1 = (self.K, _TOKEN, 0, 1, self.LIMIT, self.DUR, self.LIMIT)
        # Warmup (unmanaged, atomic): make the key hot and grant the
        # lease — 2 hits + 1 lease debit leave the device at rem=1.
        self.serve([row1])
        self.serve([row1])

        def hit(task: str, hits: int):
            def body():
                row = (self.K, _TOKEN, 0, hits, self.LIMIT, self.DUR,
                       self.LIMIT)
                st, _lim, _rem, _rst = self.serve([row])
                if int(st[0]) == _UNDER and hits > 0:
                    self.admitted[task] = self.admitted.get(task, 0) + hits
            return body

        sched.spawn("revoker", hit("revoker", 2))   # over-ask → revoke
        sched.spawn("prober-a", hit("prober-a", 1))
        sched.spawn("prober-b", hit("prober-b", 1))

    def check(self) -> None:
        self.check_sticky_over_exact()

    def finish(self) -> None:
        # Drain: lapse the lease TTL and settle, then the terminal
        # probe must answer exactly what the spec answers (returned
        # credit is servable — the PR 13 starvation signature).
        self.clock.advance(ms=300)
        self.ledger.flush_settles()
        self.check_sticky_over_exact()
        # Warmup admitted 2 before the tasks ran.
        total = 2 + sum(self.admitted.values())
        props.check_over_admission(self.K, total, self.LIMIT)
        self.check_probe_conformance(self.K, self.LIMIT, self.DUR,
                                     self.LIMIT)


class LedgerRenewal(_LedgerScenario):
    """PR 4's bug class: a duration change renews the spec bucket
    (remaining snaps back to limit) while the response snapshot is the
    pre-renewal OVER — inserting sticky OVER from that snapshot caches
    a rejection for a bucket that is actually full of credit."""

    name = "ledger-renewal"
    summary = ("duration-change renewal racing a sticky-OVER window "
               "and the reset boundary tick")
    properties = ("sticky-over-exact",)
    K = b"renew"
    LIMIT, D1, D2 = 3, 500, 300

    def build(self, sched: Scheduler) -> None:
        self._mk_ledger(hot_threshold=99)  # no leasing here
        row = lambda h, d: (self.K, _TOKEN, 0, h, self.LIMIT, d,
                            self.LIMIT)  # noqa: E731
        # Setup: exhaust, flip sticky-OVER (legit: device rem=0),
        # then move near the reset boundary.
        self.serve([row(self.LIMIT, self.D1)])
        self.serve([row(1, self.D1)])
        self.clock.advance(ms=400)

        sched.spawn("changer", lambda: self.serve([row(1, self.D2)]))
        sched.spawn("prober", lambda: self.serve([row(1, self.D1)]))
        sched.spawn("ticker", lambda: self.sched.tick(200))

    def check(self) -> None:
        self.check_sticky_over_exact()

    def finish(self) -> None:
        self.check_sticky_over_exact()


class FakeNativePlane:
    """In-memory native decision plane with the bridge contract the
    ledger drives (core/native's table): install/pull/peek/clear.
    kind 2 = lease, 1 = over — the wire-level kinds the ledger tests
    (``res[0] == 2``)."""

    def __init__(self):
        self.table: Dict[bytes, list] = {}
        self.offset = 0

    def set_clock_offset(self, now_ms: int) -> None:
        self.offset = now_ms

    def install_lease(self, key, limit, duration, reset, rem, credit,
                      consumed, expiry) -> bool:
        self.table[key] = [2, int(consumed), int(credit)]
        return True

    def install_over(self, key, limit, duration, reset) -> None:
        self.table[key] = [1, 0, 0]

    def pull(self, key):
        row = self.table.pop(key, None)
        return None if row is None else (row[0], row[1], row[2])

    def peek(self, key):
        row = self.table.get(key)
        return None if row is None else (row[0], row[1], row[2])

    def holds_lease(self, key) -> bool:
        row = self.table.get(key)
        return row is not None and row[0] == 2

    def clear(self) -> None:
        self.table.clear()

    def stats(self) -> dict:
        return {"native_answered": 0}


class LedgerNativeDelegation(_LedgerScenario):
    """Two-tier custody: a delegated lease lives in the C plane until
    a Python touch pulls it back.  Credit must be drainable in exactly
    one tier at every quiescent point."""

    name = "ledger-native-delegation"
    summary = ("python touch vs drain vs TTL flush on a delegated "
               "lease; credit lives in exactly one tier")
    properties = ("lease-single-tier", "sticky-over-exact")
    K = b"native-hot"
    LIMIT, DUR = 100, 60_000

    def build(self, sched: Scheduler) -> None:
        led = self._mk_ledger(
            lease_size=8, lease_ttl=0.2, hot_threshold=2, hot_window=10.0,
        )
        self.plane = FakeNativePlane()
        led.attach_native(self.plane)
        row = lambda h: (self.K, _TOKEN, 0, h, self.LIMIT, self.DUR,
                         self.LIMIT)  # noqa: E731
        self.serve([row(1)])
        self.serve([row(1)])  # hot → lease granted → delegated

        sched.spawn("toucher", lambda: self.serve([row(0)]))
        sched.spawn("driver", lambda: self.serve([row(2)]))

        def ticker():
            self.sched.tick(250)  # past the 200ms lease TTL
            self.ledger.flush_settles()

        sched.spawn("ticker", ticker)

    def check(self) -> None:
        entries = []
        for e in self.ledger._items.values():
            if e.kind in (_K_LEASE, _K_NATIVE):
                entries.append((
                    e.key, _KIND_NAME[e.kind],
                    self.plane.holds_lease(e.key),
                ))
        props.check_lease_single_tier(entries)
        self.check_sticky_over_exact()

    def finish(self) -> None:
        self.check()


# ---------------------------------------------------------------------
# Circuit-breaker scenario.


class CircuitBreaker(Scenario):
    """Concurrent failure/success/probe reports against one real
    PeerHealth: every observed transition must be an edge of the
    documented table (RESILIENCE.md §1)."""

    name = "circuit-breaker"
    summary = ("racing failure/success/half-open-probe reports; "
               "transitions stay inside the legal table")

    properties = ("circuit-legal-transitions",)
    time_modules = ("gubernator_tpu.cluster.health",)

    def build(self, sched: Scheduler) -> None:
        from gubernator_tpu.cluster.health import PeerHealth

        clock = self.clock

        class TracedPeerHealth(PeerHealth):
            __slots__ = ("edges",)

            def __init__(self, *a, **kw):
                self.edges: List[Tuple[str, str]] = []
                super().__init__(*a, **kw)

            def _to(self, state):
                prev = getattr(self, "_state", None)
                if prev is not None and state != prev:
                    self.edges.append((prev, state))
                super()._to(state)

        self.health = TracedPeerHealth(
            "peer:81", failure_threshold=2, backoff=0.1,
            now=lambda: clock.now_ms() / 1000.0,
        )
        h = self.health

        def failer_a():
            h.record_failure()
            h.record_failure()

        def failer_b():
            h.record_failure()
            h.record_success()

        def prober():
            self.sched.tick(400)  # past any doubled open period
            if h.allow():
                h.record_failure()

        sched.spawn("failer-a", failer_a)
        sched.spawn("failer-b", failer_b)
        sched.spawn("prober", prober)

    def check(self) -> None:
        props.check_circuit_transitions(self.health.edges)

    def finish(self) -> None:
        self.check()


# ---------------------------------------------------------------------
# Membership epoch scenario.


class MembershipEpoch(Scenario):
    """Two racing view changes drive REAL apply_view → per-epoch
    transition threads → commit.  Commits must be strictly epoch-
    monotonic (a superseded transition never commits after its
    successor) and dual-window routing never leaves the old/new owner
    pair."""

    name = "membership-epoch"
    summary = ("concurrent apply_view transitions; epoch-monotonic "
               "commit + dual-window routing")
    properties = ("epoch-monotonic-commit", "dual-window-no-third-owner")
    time_modules = ("gubernator_tpu.cluster.membership",)
    SAMPLE_KEYS = ("alpha", "beta", "gamma", "delta")

    def build(self, sched: Scheduler) -> None:
        from gubernator_tpu.cluster.membership import MembershipManager

        me = PeerInfo(grpc_address="a:81", http_address="a:80",
                      datacenter="dc1", is_owner=True)
        pb = PeerInfo(grpc_address="b:81", http_address="b:80",
                      datacenter="dc1")
        pc = PeerInfo(grpc_address="c:81", http_address="c:80",
                      datacenter="dc1")
        daemon = SimpleNamespace(
            conf=SimpleNamespace(
                data_center="dc1", hash_algorithm="fnv1a",
                peer_picker="replicated-hash", picker_replicas=64,
                behaviors=None,
            ),
            instance=None,  # no engine: transition = join prev + commit
            peer_info=lambda: me,
        )
        self.mm = MembershipManager(daemon)
        self.mm.apply_view([me])  # first view: ring only, no transition
        self.committed: List[int] = []
        mm, committed = self.mm, self.committed
        real_set = mm._settled.set

        def traced_set():
            # Called only from _commit's effective path, under _lock:
            # _active_transition IS the committing epoch.
            committed.append(mm._active_transition)
            real_set()

        mm._settled.set = traced_set
        sched.spawn("viewer-a", lambda: mm.apply_view([me, pb]))
        sched.spawn("viewer-b", lambda: mm.apply_view([me, pb, pc]))

    def check(self) -> None:
        props.check_epoch_monotonic(self.committed)
        w = self.mm._dual_window
        if w is not None:
            props.check_dual_window_routing([
                (k.encode(), w.owner(k), w.owners(k))
                for k in self.SAMPLE_KEYS
            ])

    def finish(self) -> None:
        props.check_epoch_monotonic(self.committed)
        if not self.committed:
            raise PropertyViolation(
                "epoch-monotonic-commit",
                "no transition ever committed (lost epoch)",
            )
        if self.mm.phase() != "stable":
            raise PropertyViolation(
                "epoch-monotonic-commit",
                f"terminal phase {self.mm.phase()!r} != stable",
            )


# ---------------------------------------------------------------------
# Multi-region requeue scenario.


class _FakeRegionPeer:
    """send_peer_hits with a bounded failure budget; deliveries are
    tallied per (region, key) for the double-send check."""

    def __init__(self, scenario, dc: str, fail_times: int = 0):
        self.scenario = scenario
        self.dc = dc
        self.fail_times = fail_times

    def send_peer_hits(self, reqs, timeout=None):
        from gubernator_tpu.cluster.peer_client import PeerError

        if self.fail_times > 0:
            self.fail_times -= 1
            raise PeerError("region unreachable", not_ready=True)
        delivered = self.scenario.delivered
        for r in reqs:
            rk = (self.dc, r.key)
            delivered[rk] = delivered.get(rk, 0) + int(r.hits)


class _FakeBatcher:
    """IntervalBatcher stand-in: records requeues, signals the retry
    task (the real batcher defers by ``delay`` on its flush thread)."""

    def __init__(self, event):
        self.requeued: List[tuple] = []
        self.event = event

    def requeue_many(self, pairs, oldest_ts=0.0, delay=0.0):
        self.requeued.extend(pairs)
        self.event.set()
        return len(pairs)


class MultiregionRequeue(Scenario):
    """REAL _push_region/_requeue_region under a partial region
    failure: the delivered prefix must never be re-queued (no double
    send), and the retry must converge — every offered hit delivered
    exactly once."""

    name = "multiregion-requeue"
    summary = ("partial region push failure + retry; delivered "
               "hits never exceed offered (requeue-and-converge)")
    properties = ("region-no-double-send",)
    time_modules = ("gubernator_tpu.cluster.multiregion",)
    DC = "eu"

    def build(self, sched: Scheduler) -> None:
        import threading

        from gubernator_tpu.cluster.multiregion import MultiRegionManager
        from gubernator_tpu.utils.metrics import DurationStat

        self.offered: Dict[Tuple[str, str], int] = {}
        self.delivered: Dict[Tuple[str, str], int] = {}

        # The real protocol methods on a hand-built instance: the
        # __init__ scaffolding (RPC pool, interval batcher threads) is
        # transport, not protocol — stubbed per the module docstring.
        mrm = MultiRegionManager.__new__(MultiRegionManager)
        mrm.conf = SimpleNamespace(
            multi_region_timeout=1.0, multi_region_backoff=0.05,
            multi_region_backoff_cap=0.5, multi_region_requeue_age=30.0,
            multi_region_batch_limit=64,
        )
        mrm.instance = None
        mrm._counter_lock = threading.Lock()
        mrm._requeue_lock = threading.Lock()
        mrm._region_attempts = {}
        mrm._requeue_first = {}
        mrm.windows = 0
        mrm.region_sends = 0
        mrm.region_sends_by = {}
        mrm.hits_requeued = 0
        mrm.hits_dropped = 0
        mrm.region_rpc = DurationStat()
        self.retry_ready = threading.Event()
        mrm._hits = _FakeBatcher(self.retry_ready)
        self.mrm = mrm

        ok_peer = _FakeRegionPeer(self, self.DC)
        flaky = _FakeRegionPeer(self, self.DC, fail_times=1)
        self.flaky = flaky

        def req(key, hits):
            r = SimpleNamespace(key=key, hits=hits)
            self.offered[(self.DC, key)] = hits
            return r

        pairs_a1 = [("mr-a", req("mr-a", 1))]
        pairs_a2 = [("mr-b", req("mr-b", 2)), ("mr-c", req("mr-c", 1))]
        pairs_b = [("mr-d", req("mr-d", 1))]

        def pusher_a():
            self.mrm._push_region(self.DC, {
                "ok:81": (ok_peer, pairs_a1),
                "flaky:81": (flaky, pairs_a2),
            })

        def pusher_b():
            self.mrm._push_region(self.DC, {"ok:81": (ok_peer, pairs_b)})

        def retrier():
            if not self.retry_ready.wait(timeout=5.0):
                return
            items = list(self.mrm._hits.requeued)
            del self.mrm._hits.requeued[:]
            if not items:
                return
            retry_pairs = [(kk[1], r) for kk, r in items]
            self.mrm._push_region(self.DC, {"flaky:81": (flaky, retry_pairs)})

        sched.spawn("pusher-a", pusher_a)
        sched.spawn("pusher-b", pusher_b)
        sched.spawn("retrier", retrier)

    def check(self) -> None:
        props.check_region_no_double_send(self.offered, self.delivered)

    def finish(self) -> None:
        self.check()
        # Convergence: nothing pending, nothing dropped → delivered
        # must equal offered exactly once each.
        if not self.mrm._hits.requeued and self.mrm.hits_dropped == 0:
            for rk, want in self.offered.items():
                got = self.delivered.get(rk, 0)
                if got != want:
                    raise PropertyViolation(
                        "region-no-double-send",
                        f"region/key {rk} failed to converge: delivered "
                        f"{got} of {want} offered",
                    )


# ---------------------------------------------------------------------
# Replication grant scenario.


class ReplicationGrant(Scenario):
    """REAL replica-side lease table: an epoch-racing re-grant
    supersedes a draining lease while the TTL expirer runs.  Credit
    conservation: drained hits never exceed granted credit, and every
    live lease's consumed stays inside its slice."""

    name = "replication-grant"
    summary = ("re-grant vs drain vs expiry on the replica lease "
               "table; consumed never exceeds granted credit")
    properties = ("over-admission-bound",)
    time_modules = ("gubernator_tpu.cluster.replication",)
    K = b"repl-hot"
    LIMIT, DUR = 10, 1_000

    def _grant_doc(self, seq, epoch, rem, credit, expiry_ms):
        now = self.clock.now_ms()
        return json.dumps({
            "op": "grant", "src": "owner:81", "boot": "boot-1",
            "seq": seq, "epoch": epoch,
            "grants": [[
                self.K.decode(), self.LIMIT, self.DUR, now + self.DUR,
                rem, credit, now + expiry_ms,
            ]],
        }).encode()

    def build(self, sched: Scheduler) -> None:
        from gubernator_tpu.cluster.replication import ReplicationManager

        daemon = SimpleNamespace(
            membership=None,
            instance=SimpleNamespace(
                engine=SimpleNamespace(clock=self.clock),
                ledger=None, hotkeys=None,
                get_peer=lambda k: None,
            ),
            peer_info=lambda: PeerInfo(grpc_address="replica:81"),
        )
        self.rm = ReplicationManager(daemon)  # no start(): no loop
        self.granted = 0
        self.admitted = 0
        rm = self

        def grant(seq, epoch, rem, credit, expiry_ms):
            resp = json.loads(self.rm.receive(
                self._grant_doc(seq, epoch, rem, credit, expiry_ms)
            ))
            if not resp.get("stale") and not resp.get("disabled"):
                rm.granted += credit

        # Seed lease installed during (unmanaged) setup: the raced
        # part is the re-grant / duplicate / drain / expiry episode on
        # an EXISTING lease — installing the seed under the scheduler
        # would triple the schedule space without new orderings.
        grant(1, 1, rem=8, credit=4, expiry_ms=500)

        def regrant():
            grant(2, 2, rem=6, credit=3, expiry_ms=500)

        def stale_then_expire():
            # Duplicate delivery of the seed grant doc: the seq guard
            # must refuse it (accepting would resurrect the seed's
            # credit slice AFTER drains consumed from it).  Then drive
            # TTL expiry past the 500ms grant expiry.
            grant(1, 1, rem=8, credit=4, expiry_ms=500)
            self.sched.tick(600)
            self.rm._expire_replica_leases(self.clock.now_ms() / 1000.0)

        def drainer():
            # try_answer's lock acquire is the yield point; an extra
            # checkpoint here would double the schedule space for no
            # new orderings.
            for _ in range(2):
                out = self.rm.try_answer(
                    self.K, _TOKEN, 0, 1, self.LIMIT, self.DUR,
                    self.clock.now_ms(),
                )
                if out is not None:
                    rm.admitted += 1

        sched.spawn("regrant", regrant)
        sched.spawn("stale-expirer", stale_then_expire)
        sched.spawn("drainer", drainer)

    def check(self) -> None:
        for lease in self.rm._leases.values():
            if lease.consumed > lease.credit:
                raise PropertyViolation(
                    "over-admission-bound",
                    f"{lease.key!r}: replica slice drained "
                    f"{lease.consumed} > granted {lease.credit}",
                )

    def finish(self) -> None:
        self.check()
        if self.admitted > self.granted:
            raise PropertyViolation(
                "over-admission-bound",
                f"{self.K!r}: replica admitted {self.admitted} hits "
                f"from only {self.granted} granted credit",
            )


# ---------------------------------------------------------------------
# Registry.

SCENARIOS = OrderedDict(
    (cls.name, cls)
    for cls in (
        LedgerLeaseChurn, LedgerRenewal, LedgerNativeDelegation,
        CircuitBreaker, MembershipEpoch, MultiregionRequeue,
        ReplicationGrant,
    )
)


def scenario_names() -> List[str]:
    return list(SCENARIOS)


def get_scenario(name: str):
    try:
        return SCENARIOS[name]
    except KeyError:
        raise KeyError(
            f"unknown scenario {name!r}; have {', '.join(SCENARIOS)}"
        ) from None

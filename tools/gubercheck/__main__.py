"""CLI for the gubercheck model checker.

Usage:

    python -m tools.gubercheck --list
    python -m tools.gubercheck --scenario ledger-renewal [--mode full]
    python -m tools.gubercheck --mutation pr4-duration-renewal-guard
    python -m tools.gubercheck --smoke [--budget 30]
    python -m tools.gubercheck --all            # full @slow budgets

Exit codes: 0 = all explorations behaved as expected (clean scenarios
clean, mutations caught); 1 = a violation on pristine code OR a
mutation that exploration failed to catch; 2 = usage error.

``--smoke`` is the ci_fast stage: every scenario under its committed
smoke budget (DPOR + preemption bound 2) plus both mutation fixtures,
all inside one enforced wall budget.
"""

from __future__ import annotations

import argparse
import logging
import sys
import time as _walltime

# Scenario runs intentionally exercise failure paths thousands of
# times; the protocol modules' warnings are noise here.
logging.getLogger("gubernator_tpu").setLevel(logging.CRITICAL)


def _explore_scenario(name, *, mode, preemption_bound, max_runs,
                      max_steps, wall_budget_s, expect_violation=False,
                      factory=None):
    from tools.gubercheck import scenarios as scn_mod
    from tools.gubercheck.explore import explore

    cls = scn_mod.get_scenario(name)
    res = explore(
        factory or cls,
        mode=mode,
        preemption_bound=preemption_bound,
        max_runs=max_runs,
        max_steps=max_steps,
        wall_budget_s=wall_budget_s,
        stop_on_violation=True,
        scenario_name=name,
    )
    return res


def _report(res, *, expect_violation, label=None):
    tag = label or res.scenario
    if res.complete:
        state = "complete"
    elif res.truncated_by:
        state = f"truncated:{res.truncated_by}"
    else:
        state = "stopped"  # stop_on_violation exit
    if expect_violation:
        ok = bool(res.violations)
        verdict = "CAUGHT" if ok else "MISSED"
    else:
        ok = res.ok
        verdict = "clean" if ok else "VIOLATION"
    print(
        f"[gubercheck] {tag:38s} {verdict:9s} runs={res.runs:<6d} "
        f"max_steps={res.max_steps_seen:<4d} {state} "
        f"({res.elapsed_s:.2f}s)"
    )
    for v in res.violations:
        print(f"    {v.kind} {v.prop or ''}: {v.detail}")
        print(f"    schedule[{v.step}]: {' '.join(v.schedule)}")
    return ok


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tools.gubercheck")
    ap.add_argument("--list", action="store_true",
                    help="list scenarios, properties, mutations")
    ap.add_argument("--scenario", help="explore one scenario")
    ap.add_argument("--mutation", help="explore one mutation fixture "
                    "(exit 0 iff the bug is caught)")
    ap.add_argument("--smoke", action="store_true",
                    help="ci_fast stage: smoke budgets + mutations")
    ap.add_argument("--all", action="store_true",
                    help="full budgets for every scenario + mutations")
    ap.add_argument("--mode", choices=("dpor", "full"), default=None)
    ap.add_argument("--preemption-bound", type=int, default=None)
    ap.add_argument("--max-runs", type=int, default=None)
    ap.add_argument("--max-steps", type=int, default=None)
    ap.add_argument("--budget", type=float, default=None,
                    help="overall wall budget in seconds")
    args = ap.parse_args(argv)

    if args.list:
        from tools.gubercheck import mutations as mut_mod
        from tools.gubercheck import properties as prop_mod
        from tools.gubercheck import scenarios as scn_mod

        print("scenarios:")
        for name in scn_mod.scenario_names():
            cls = scn_mod.get_scenario(name)
            print(f"  {name:26s} {cls.summary}")
            print(f"  {'':26s}   properties: "
                  f"{', '.join(cls.properties)}")
        print("properties:")
        for pname in prop_mod.names():
            p = prop_mod.get(pname)
            print(f"  {pname:26s} [{p.doc}] {p.summary}")
        print("mutations:")
        for mname, m in mut_mod.MUTATIONS.items():
            print(f"  {mname:34s} scenario={m.scenario} "
                  f"expects={','.join(m.properties)}")
        return 0

    if args.scenario:
        from tools.gubercheck import scenarios as scn_mod

        cls = scn_mod.get_scenario(args.scenario)
        budget = dict(cls.full)
        if args.mode:
            budget["mode"] = args.mode
        if args.preemption_bound is not None:
            budget["preemption_bound"] = args.preemption_bound
        if args.max_runs is not None:
            budget["max_runs"] = args.max_runs
        if args.max_steps is not None:
            budget["max_steps"] = args.max_steps
        res = _explore_scenario(
            args.scenario,
            mode=budget.get("mode", "dpor"),
            preemption_bound=budget.get("preemption_bound"),
            max_runs=budget.get("max_runs", 20000),
            max_steps=budget.get("max_steps", 2000),
            wall_budget_s=args.budget,
        )
        return 0 if _report(res, expect_violation=False) else 1

    if args.mutation:
        from tools.gubercheck import mutations as mut_mod
        from tools.gubercheck import scenarios as scn_mod

        mut = mut_mod.MUTATIONS[args.mutation]
        cls = scn_mod.get_scenario(mut.scenario)
        budget = dict(cls.full)
        if args.mode:
            budget["mode"] = args.mode
        res = _explore_scenario(
            mut.scenario,
            mode=budget.get("mode", "dpor"),
            preemption_bound=args.preemption_bound
            if args.preemption_bound is not None
            else budget.get("preemption_bound"),
            max_runs=args.max_runs or budget.get("max_runs", 20000),
            max_steps=args.max_steps or budget.get("max_steps", 2000),
            wall_budget_s=args.budget,
            factory=mut_mod.mutated_scenario_factory(args.mutation),
        )
        ok = _report(res, expect_violation=True,
                     label=f"{mut.scenario}[{args.mutation}]")
        return 0 if ok else 1

    if args.smoke or args.all:
        from tools.gubercheck import mutations as mut_mod
        from tools.gubercheck import scenarios as scn_mod

        overall = args.budget if args.budget is not None else (
            30.0 if args.smoke else None
        )
        t0 = _walltime.monotonic()

        def left():
            if overall is None:
                return None
            return max(0.5, overall - (_walltime.monotonic() - t0))

        all_ok = True
        for name in scn_mod.scenario_names():
            cls = scn_mod.get_scenario(name)
            budget = dict(cls.smoke if args.smoke else cls.full)
            res = _explore_scenario(
                name,
                mode=budget.get("mode", "dpor"),
                preemption_bound=budget.get("preemption_bound"),
                max_runs=budget.get("max_runs", 20000),
                max_steps=budget.get("max_steps", 2000),
                wall_budget_s=left(),
            )
            all_ok = _report(res, expect_violation=False) and all_ok
        for mname, mut in mut_mod.MUTATIONS.items():
            cls = scn_mod.get_scenario(mut.scenario)
            budget = dict(cls.smoke if args.smoke else cls.full)
            res = _explore_scenario(
                mut.scenario,
                mode=budget.get("mode", "dpor"),
                preemption_bound=budget.get("preemption_bound"),
                max_runs=budget.get("max_runs", 20000),
                max_steps=budget.get("max_steps", 2000),
                wall_budget_s=left(),
                factory=mut_mod.mutated_scenario_factory(mname),
            )
            all_ok = _report(
                res, expect_violation=True,
                label=f"{mut.scenario}[{mname}]",
            ) and all_ok
        elapsed = _walltime.monotonic() - t0
        print(f"[gubercheck] total {elapsed:.1f}s"
              + (f" (budget {overall:.0f}s)" if overall else ""))
        if overall is not None and elapsed > overall:
            print("[gubercheck] WALL BUDGET EXCEEDED", file=sys.stderr)
            all_ok = False
        return 0 if all_ok else 1

    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())

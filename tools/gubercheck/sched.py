"""Cooperative scheduler: run real threaded code one step at a time.

The model-checking substrate.  Scenario code (the REAL ledger /
health / membership / replication modules) runs on real OS threads,
but every ``threading.Lock/RLock/Condition/Event/Thread`` the scenario
constructs is replaced by an instrumented twin that parks the thread
at a *yield point* before each synchronization operation.  Exactly one
task runs between yield points, chosen by the controller from the set
of *enabled* tasks — so a run is fully determined by its schedule (the
sequence of chosen task names), and the explorer (explore.py) can
enumerate schedules.

Model (CHESS-style):

- Yield points sit BEFORE each sync op (lock acquire, cond/event
  wait, thread join, explicit ``checkpoint``/``tick``).  Code between
  two yield points executes atomically.  This is sound for
  data-race-free code — every shared mutation in the scenario modules
  happens under a lock (guberlint's lock pass enforces the
  guarded-by annotations).
- An op is *enabled* when it can complete without blocking (the lock
  is free, the join target is done, the event is set …).  The
  controller only schedules enabled tasks, so instrumented ops never
  actually block at the OS level.
- Timeouts are virtual: a timed wait fires only when NO task is
  enabled — the controller advances the repo's frozen ``Clock`` to
  the earliest deadline.  No runnable task + no deadline = deadlock,
  reported as a finding.
- Wall time is excised: the scenario freezes ``Clock`` at a fixed
  epoch and ``virtual_time`` rebinds a module's ``time`` attribute to
  the clock, so ``time.monotonic()`` inside the module under test is
  schedule-deterministic.

Noise filter: locks created by modules on ``_PASSTHROUGH_MODULES``
(metrics counters, the Clock's own guard, logging) stay REAL locks —
they guard leaf counters whose interleavings cannot affect protocol
invariants, and instrumenting them would blow up the schedule space
with irrelevant choice points.  STATIC_ANALYSIS.md documents this
boundary.
"""

from __future__ import annotations

import sys
import threading
from typing import Callable, Dict, List, Optional, Tuple

# Real primitives, captured at import time.  NOTE: stdlib Semaphore /
# Thread construct Condition/Event through the *threading module
# globals*, which the patch replaces — so the scheduler's own
# machinery must never instantiate stdlib sync helpers while the
# patch is active.  _RealSem below is self-contained, and real thread
# creation is wrapped in _with_real (the factories check the guard).
_RealThread = threading.Thread
_RealLock = threading.Lock
_RealRLock = threading.RLock
_RealCondition = threading.Condition
_RealEvent = threading.Event

# Modules whose locks stay real (leaf counters / clock guard / stdlib
# logging): no protocol state, no scheduling value.
_PASSTHROUGH_MODULES = (
    "logging",
    "gubernator_tpu.clock",
    "gubernator_tpu.utils.",
)

_UNMANAGED = "<unmanaged>"

# Thread-local guard: while set, the instrumented factories return
# REAL primitives (scheduler machinery constructing threads).
_machinery = threading.local()


def _with_real(fn):
    _machinery.on = True
    try:
        return fn()
    finally:
        _machinery.on = False


class _RealSem:
    """Counting semaphore built only from captured real primitives —
    safe to construct while the threading patch is active."""

    __slots__ = ("_cond", "_value")

    def __init__(self) -> None:
        self._cond = _RealCondition(_RealLock())
        self._value = 0

    def acquire(self) -> None:
        with self._cond:
            while self._value == 0:
                self._cond.wait()
            self._value -= 1

    def release(self) -> None:
        with self._cond:
            self._value += 1
            self._cond.notify()


class _Kill(BaseException):
    """Raised inside a task thread to unwind it when a run aborts."""


class DeadlockError(Exception):
    """No task enabled, no timeout pending: the schedule deadlocked."""


class DivergenceError(Exception):
    """A forced schedule step named a task that is not enabled — the
    scenario executed differently than when the prefix was recorded,
    i.e. it is not schedule-deterministic."""


class Op:
    """One pending synchronization operation."""

    __slots__ = ("kind", "resource", "deadline")

    def __init__(self, kind: str, resource: str, deadline: Optional[int] = None):
        self.kind = kind
        self.resource = resource
        self.deadline = deadline  # virtual-clock ms; None = untimed

    def conflicts(self, other: "Op") -> bool:
        """Conservative dependence: ops on the same resource, or any
        op against a clock tick (time feeds TTL/expiry branches
        everywhere, so reordering across a tick never commutes)."""
        if self.resource == "clock" or other.resource == "clock":
            return True
        return self.resource == other.resource

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"Op({self.kind},{self.resource})"


class Task:
    """One managed thread of the scenario."""

    NEW = "new"
    RUNNABLE = "runnable"
    DONE = "done"

    __slots__ = (
        "sched", "name", "fn", "index", "state", "pending", "sem",
        "exc", "thread", "timed_out",
    )

    def __init__(self, sched: "Scheduler", name: str, fn: Callable[[], None], index: int):
        self.sched = sched
        self.name = name
        self.fn = fn
        self.index = index
        self.state = Task.NEW
        self.pending: Optional[Op] = Op("start", f"task:{name}")
        self.sem = _RealSem()
        self.exc: Optional[BaseException] = None
        self.thread = None
        self.timed_out = False

    def start_thread(self) -> None:
        self.state = Task.RUNNABLE

        def make():
            th = _RealThread(
                target=self._body, name=f"gubercheck-{self.name}",
                daemon=True,
            )
            th.start()
            return th

        self.thread = _with_real(make)

    def _body(self) -> None:
        self.sched._tls.task = self
        self.sem.acquire()
        if self.sched.killed:
            self.state = Task.DONE
            self.sched._ctl.release()
            return
        try:
            self.fn()
        except _Kill:
            pass
        except BaseException as e:  # noqa: BLE001 - surfaced as a finding
            self.exc = e
        self.state = Task.DONE
        self.sched._ctl.release()


class StepRecord:
    """One controller decision: who was enabled, what each wanted to
    do, who ran.  The explorer derives backtrack points from these."""

    __slots__ = ("enabled", "pending", "chosen", "op", "preempting")

    def __init__(self, enabled, pending, chosen, op, preempting):
        self.enabled: List[str] = enabled
        self.pending: Dict[str, Tuple[str, str]] = pending  # name -> (kind, resource)
        self.chosen: str = chosen
        self.op: Tuple[str, str] = op
        self.preempting: bool = preempting


class Scheduler:
    """Controller + task registry for ONE run of one scenario."""

    def __init__(self, clock, max_steps: int = 2000):
        self.clock = clock  # repo Clock, frozen at a fixed epoch
        self.max_steps = max_steps
        self.tasks: List[Task] = []
        self._by_name: Dict[str, Task] = {}
        self._ctl = _RealSem()
        self._tls = threading.local()
        self.killed = False
        self.active = False
        self._locks: List["ILock"] = []
        self._conds: List["ICondition"] = []
        self._events: List["IEvent"] = []
        self._lock_by_rid: Dict[str, "ILock"] = {}
        self._next_rid = 0
        self.steps: List[StepRecord] = []
        self.check: Optional[Callable[[], None]] = None

    # -- registry ------------------------------------------------------

    def spawn(self, name: str, fn: Callable[[], None]) -> Task:
        if name in self._by_name:
            raise ValueError(f"duplicate task name {name!r}")
        t = Task(self, name, fn, len(self.tasks))
        self.tasks.append(t)
        self._by_name[name] = t
        t.start_thread()
        return t

    def _rid(self, kind: str) -> str:
        self._next_rid += 1
        return f"{kind}:{self._next_rid}"

    def current(self) -> Optional[Task]:
        return getattr(self._tls, "task", None)

    # -- task-side -----------------------------------------------------

    def yield_point(self, op: Op) -> None:
        """Park the calling task until the controller schedules it.
        No-op outside a managed task (setup / terminal phases)."""
        t = self.current()
        if t is None or not self.active:
            return
        if self.killed:
            raise _Kill()
        t.pending = op
        self._ctl.release()
        t.sem.acquire()
        if self.killed:
            raise _Kill()
        t.pending = None

    def checkpoint(self, resource: str = "checkpoint") -> None:
        """Explicit scheduling point for scenario task code."""
        self.yield_point(Op("checkpoint", resource))

    def tick(self, ms: int) -> None:
        """Advance the virtual clock from a task — a schedulable event
        so expiry/TTL boundaries interleave with protocol steps."""
        self.yield_point(Op("tick", "clock"))
        self.clock.advance(ms=ms)

    # -- controller ----------------------------------------------------

    def _enabled(self, t: Task) -> bool:
        if t.state != Task.RUNNABLE:
            return False
        op = t.pending
        if op is None:
            return False
        if t.timed_out:
            return True  # the controller fired this op's deadline
        if op.kind in ("start", "checkpoint", "tick", "tryacquire"):
            return True
        if op.kind == "acquire":
            lock = self._lock_by_rid.get(op.resource)
            return lock is None or lock._available_for(t)
        if op.kind == "join":
            target = self._by_name.get(op.resource.split(":", 1)[1])
            return target is None or target.state == Task.DONE
        if op.kind == "wait":  # condition: enabled once notified
            cond = next((c for c in self._conds if c.rid == op.resource), None)
            return cond is None or t in cond._notified
        if op.kind == "evwait":
            ev = next((e for e in self._events if e.rid == op.resource), None)
            return ev is None or ev._flag
        return True

    def run(self, forced: List[str], check: Optional[Callable[[], None]] = None):
        """Drive all spawned tasks to completion following ``forced``
        as a schedule prefix, default continuation after it.  Returns
        the step trace; raises DeadlockError / DivergenceError /
        PropertyViolation (from ``check``) on findings."""
        self.check = check
        self.active = True
        last: Optional[Task] = None
        try:
            while True:
                if len(self.steps) > self.max_steps:
                    raise DeadlockError(
                        f"step budget exceeded ({self.max_steps}): "
                        "livelock or runaway scenario"
                    )
                runnable = [t for t in self.tasks if self._enabled(t)]
                if not runnable:
                    if all(t.state == Task.DONE for t in self.tasks):
                        break
                    timed = [
                        t for t in self.tasks
                        if t.state == Task.RUNNABLE and t.pending is not None
                        and t.pending.deadline is not None and not t.timed_out
                    ]
                    if not timed:
                        blocked = [
                            f"{t.name}@{t.pending}" for t in self.tasks
                            if t.state != Task.DONE
                        ]
                        raise DeadlockError(
                            "deadlock: no enabled task, no pending timeout; "
                            f"blocked: {blocked}"
                        )
                    # Fire the earliest virtual deadline.  Deterministic:
                    # ties broken by task index.
                    timed.sort(key=lambda t: (t.pending.deadline, t.index))
                    first = timed[0]
                    now = self.clock.now_ms()
                    if first.pending.deadline > now:
                        self.clock.advance(ms=first.pending.deadline - now)
                    first.timed_out = True
                    continue
                step_i = len(self.steps)
                if step_i < len(forced):
                    want = forced[step_i]
                    chosen = self._by_name.get(want)
                    if chosen is None or chosen not in runnable:
                        raise DivergenceError(
                            f"step {step_i}: forced task {want!r} not enabled "
                            f"(enabled: {[t.name for t in runnable]})"
                        )
                else:
                    chosen = last if last in runnable else runnable[0]
                preempting = (
                    last is not None and last is not chosen and last in runnable
                )
                self.steps.append(StepRecord(
                    enabled=[t.name for t in runnable],
                    pending={
                        t.name: (t.pending.kind, t.pending.resource)
                        for t in runnable
                    },
                    chosen=chosen.name,
                    op=(chosen.pending.kind, chosen.pending.resource),
                    preempting=preempting,
                ))
                self._switch(chosen)
                last = chosen if chosen.state != Task.DONE else None
                if self.check is not None and not self._lock_held_by_task():
                    self.check()
            return self.steps
        finally:
            self.active = False
            self._reap()

    def _switch(self, t: Task) -> None:
        t.sem.release()
        self._ctl.acquire()

    def _lock_held_by_task(self) -> bool:
        return any(isinstance(l._owner, Task) for l in self._locks)

    def _reap(self) -> None:
        """Abort: unwind every still-parked task so threads exit."""
        if all(t.state == Task.DONE for t in self.tasks):
            return
        self.killed = True
        for t in self.tasks:
            if t.state != Task.DONE:
                t.sem.release()
        for t in self.tasks:
            if t.thread is not None:
                t.thread.join(timeout=5.0)


# ---------------------------------------------------------------------
# Instrumented primitives


class ILock:
    """Instrumented mutex.  Managed tasks yield before acquiring (the
    controller only schedules them when the lock is free); unmanaged
    contexts (setup/terminal, single-threaded by construction) take it
    directly and assert it was uncontended."""

    _reentrant = False

    def __init__(self, sched: Scheduler):
        self.sched = sched
        self.rid = sched._rid("lock")
        self._owner = None
        self._count = 0
        sched._locks.append(self)
        sched._lock_by_rid[self.rid] = self

    def _available_for(self, t: Task) -> bool:
        return self._owner is None or (self._reentrant and self._owner is t)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        t = self.sched.current()
        if t is None or not self.sched.active:
            if isinstance(self._owner, Task):
                raise RuntimeError(
                    f"unmanaged acquire of task-held lock {self.rid}"
                )
            self._owner = _UNMANAGED
            self._count += 1
            return True
        if not blocking:
            self.sched.yield_point(Op("tryacquire", self.rid))
            if not self._available_for(t):
                return False
            self._owner = t
            self._count += 1
            return True
        self.sched.yield_point(Op("acquire", self.rid))
        # Scheduled => enabled => free (or reentrant): nothing ran in
        # between, so this cannot block.
        assert self._available_for(t), "scheduler enabledness broken"
        self._owner = t
        self._count += 1
        return True

    def release(self) -> None:
        self._count -= 1
        if self._count <= 0:
            self._owner = None
            self._count = 0

    def locked(self) -> bool:
        return self._owner is not None

    def _at_fork_reinit(self) -> None:
        # Stdlib modules (concurrent.futures.thread) register this as
        # an os.register_at_fork hook; scenarios never fork.
        pass

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()


class IRLock(ILock):
    _reentrant = True


class ICondition:
    """Instrumented condition variable over an ILock."""

    def __init__(self, sched: Scheduler, lock=None):
        self.sched = sched
        self.rid = sched._rid("cond")
        self._lock = lock if lock is not None else IRLock(sched)
        self._waiters: List[Task] = []
        self._notified: set = set()
        sched._conds.append(self)

    def acquire(self, *a, **kw):
        return self._lock.acquire(*a, **kw)

    def release(self):
        return self._lock.release()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        t = self.sched.current()
        if t is None or not self.sched.active:
            raise RuntimeError("ICondition.wait outside a managed task")
        saved = self._lock._count
        self._lock._count = 0
        self._lock._owner = None
        self._waiters.append(t)
        deadline = None
        if timeout is not None:
            deadline = self.sched.clock.now_ms() + max(0, int(timeout * 1000))
        self.sched.yield_point(Op("wait", self.rid, deadline))
        fired = t.timed_out
        t.timed_out = False
        self._notified.discard(t)
        if t in self._waiters:
            self._waiters.remove(t)
        # Reacquire before returning (standard condition contract).
        self.sched.yield_point(Op("acquire", self._lock.rid))
        self._lock._owner = t
        self._lock._count = saved
        return not fired

    def notify(self, n: int = 1) -> None:
        for t in self._waiters[:n]:
            self._notified.add(t)

    def notify_all(self) -> None:
        self.notify(len(self._waiters))


class IEvent:
    """Instrumented event."""

    def __init__(self, sched: Scheduler):
        self.sched = sched
        self.rid = sched._rid("event")
        self._flag = False
        sched._events.append(self)

    def is_set(self) -> bool:
        return self._flag

    def set(self) -> None:
        t = self.sched.current()
        if t is not None and self.sched.active:
            self.sched.yield_point(Op("checkpoint", self.rid))
        self._flag = True

    def clear(self) -> None:
        self._flag = False

    def wait(self, timeout: Optional[float] = None) -> bool:
        t = self.sched.current()
        if t is None or not self.sched.active:
            return self._flag
        deadline = None
        if timeout is not None:
            deadline = self.sched.clock.now_ms() + max(0, int(timeout * 1000))
        self.sched.yield_point(Op("evwait", self.rid, deadline))
        t.timed_out = False
        return self._flag


class IThread:
    """Instrumented thread: code under test that spawns helpers (the
    membership manager's per-epoch transition threads) gets a managed
    task instead, so the helper's steps are explored too."""

    _seq = 0

    def __init__(self, sched: Scheduler, target=None, args=(), kwargs=None,
                 name=None, daemon=None):
        self.sched = sched
        self._target = target
        self._args = args
        self._kwargs = kwargs or {}
        IThread._seq += 1
        self.name = name or f"ithread-{IThread._seq}"
        self.daemon = bool(daemon)
        self._task: Optional[Task] = None
        self._started = False

    def start(self) -> None:
        if self._started:
            raise RuntimeError("threads can only be started once")
        self._started = True
        if self.sched.active or not self.sched.steps:
            # Pre-run or mid-run: becomes a schedulable task.
            name = self.name
            if name in self.sched._by_name:
                name = f"{name}#{len(self.sched.tasks)}"
                self.name = name
            self._task = self.sched.spawn(name, self._run_target)
        else:
            # Post-run (terminal phase): run inline, synchronously.
            self._run_target()

    def _run_target(self) -> None:
        if self._target is not None:
            self._target(*self._args, **self._kwargs)

    def is_alive(self) -> bool:
        return self._task is not None and self._task.state != Task.DONE

    def join(self, timeout: Optional[float] = None) -> None:
        if self._task is None:
            return
        t = self.sched.current()
        if t is None or not self.sched.active:
            return  # inline/terminal: target already ran or will not
        deadline = None
        if timeout is not None:
            deadline = self.sched.clock.now_ms() + max(0, int(timeout * 1000))
        self.sched.yield_point(Op("join", f"task:{self._task.name}", deadline))
        t.timed_out = False


# ---------------------------------------------------------------------
# Patching


def _caller_module(depth: int = 2) -> str:
    try:
        frame = sys._getframe(depth)
        return frame.f_globals.get("__name__", "") or ""
    except ValueError:  # pragma: no cover - shallow stack
        return ""


def _passthrough(mod: str) -> bool:
    return any(
        mod == p or mod.startswith(p) for p in _PASSTHROUGH_MODULES
    )


def _real_wanted() -> bool:
    return bool(getattr(_machinery, "on", False)) or _passthrough(
        _caller_module(3)
    )


class instrumented:
    """Context manager: while active, ``threading.Lock()`` etc. return
    instrumented twins bound to ``sched`` — EXCEPT when constructed by
    scheduler machinery or a passthrough module (noise filter, see
    module docstring)."""

    def __init__(self, sched: Scheduler):
        self.sched = sched
        self._saved = {}

    def __enter__(self):
        sched = self.sched

        # Stdlib modules that lazily create module-level primitives
        # must import BEFORE the patch: an instrumented lock cached in
        # sys.modules would outlive the scheduler that owns it.
        import concurrent.futures.thread  # noqa: F401

        def lock_factory():
            if _real_wanted():
                return _RealLock()
            return ILock(sched)

        def rlock_factory():
            if _real_wanted():
                return _RealRLock()
            return IRLock(sched)

        def cond_factory(lock=None):
            if _real_wanted():
                return _RealCondition(lock)
            return ICondition(sched, lock)

        def event_factory():
            if _real_wanted():
                return _RealEvent()
            return IEvent(sched)

        class thread_factory:
            def __new__(cls, group=None, target=None, name=None,
                        args=(), kwargs=None, *, daemon=None):
                if _real_wanted():
                    return _RealThread(
                        group=group, target=target, name=name, args=args,
                        kwargs=kwargs, daemon=daemon,
                    )
                return IThread(sched, target=target, args=args,
                               kwargs=kwargs, name=name, daemon=daemon)

        self._saved = {
            "Lock": threading.Lock,
            "RLock": threading.RLock,
            "Condition": threading.Condition,
            "Event": threading.Event,
            "Thread": threading.Thread,
        }
        threading.Lock = lock_factory
        threading.RLock = rlock_factory
        threading.Condition = cond_factory
        threading.Event = event_factory
        threading.Thread = thread_factory
        return self

    def __exit__(self, *exc) -> None:
        for name, val in self._saved.items():
            setattr(threading, name, val)


class VirtualTime:
    """Stand-in for a module's ``time`` attribute: monotonic/time read
    the frozen Clock, so TTL comparisons are schedule-deterministic."""

    def __init__(self, clock):
        self._clock = clock

    def monotonic(self) -> float:
        return self._clock.now_ms() / 1000.0

    def time(self) -> float:
        return self._clock.now_ms() / 1000.0

    def monotonic_ns(self) -> int:
        return self._clock.now_ms() * 1_000_000

    def time_ns(self) -> int:
        return self._clock.now_ms() * 1_000_000

    def sleep(self, seconds: float) -> None:
        # Sleeping in a scenario is a modeling error: time only moves
        # via Scheduler.tick.  Make it loud.
        raise RuntimeError("time.sleep() under gubercheck — use tick()")


class virtual_time:
    """Context manager: rebind ``module.time`` to a VirtualTime."""

    def __init__(self, clock, modules):
        self.vt = VirtualTime(clock)
        self.modules = modules
        self._saved: List[Tuple[object, object]] = []

    def __enter__(self):
        for mod in self.modules:
            self._saved.append((mod, mod.time))
            mod.time = self.vt
        return self

    def __exit__(self, *exc) -> None:
        for mod, t in self._saved:
            mod.time = t

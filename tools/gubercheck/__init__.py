"""gubercheck: deterministic-schedule model checking of the repo's
concurrency protocols.

The package splits along an import-weight boundary:

- ``properties``  — the invariant registry + pure predicates.  Stdlib
  only: guberlint's ``proto`` pass imports it to cross-check doc
  claims and source annotations without dragging numpy/jax into the
  linter.
- ``sched``       — the cooperative scheduler (instrumented
  ``threading`` primitives + the repo's frozen ``Clock``).
- ``explore``     — stateless DFS over schedules with conflict-
  directed pruning and a CHESS-style preemption bound.
- ``scenarios``   — the scenario catalog: small fixed workloads over
  the REAL protocol modules (ledger, health, membership, replication,
  multiregion).
- ``mutations``   — mechanical re-introduction of shipped-then-fixed
  bugs, used to prove the checker has teeth.

Keep this module empty of heavy imports: ``import tools.gubercheck``
must stay cheap (the linter does it on every run).
"""

"""Resurrected historical bugs: mutation fixtures that prove teeth.

A model checker that never fails is indistinguishable from one that
checks nothing.  Each mutation here textually disables ONE guard in a
twin copy of ``gubernator_tpu/core/ledger.py`` — re-introducing a bug
this repo actually shipped and later fixed — and names the scenario
whose exploration must find a schedule that violates a registered
property.  tests/test_gubercheck.py asserts both directions: the
mutated module is caught, the pristine module explores clean.

The mutation is applied to SOURCE TEXT and executed into a fresh
module object (never installed in ``sys.modules``), so the real ledger
in the running process is untouched.  Each needle is asserted to occur
exactly once — if a refactor moves or rewords the guard, the mutation
fails loudly instead of silently testing nothing.
"""

from __future__ import annotations

import types
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Dict

from gubernator_tpu.core import ledger as _real_ledger


@dataclass(frozen=True)
class Mutation:
    """One resurrected bug."""

    name: str
    summary: str
    needle: str  # exact guard text in ledger.py (must occur once)
    replacement: str
    scenario: str  # scenario whose exploration must catch it
    properties: tuple  # property names expected to fire


MUTATIONS: "OrderedDict[str, Mutation]" = OrderedDict()


def _register(m: Mutation) -> None:
    MUTATIONS[m.name] = m


_register(Mutation(
    name="pr13-lease-churn-return-guard",
    summary=(
        "Drop _learn's pending/returning guard (the PR 13 fix): a "
        "concurrent learn may insert a pre-return (OVER, 0) snapshot "
        "while a revoked lease's credit is queued or mid-apply, "
        "starving the bucket behind a false sticky-OVER entry."
    ),
    needle="if h in self._pending or h in self._returning:",
    replacement=(
        "if False and (h in self._pending or h in self._returning):"
    ),
    scenario="ledger-lease-churn",
    properties=("sticky-over-exact", "hot-key-no-starvation"),
))

_register(Mutation(
    name="pr4-duration-renewal-guard",
    summary=(
        "Drop _learn's fall_dur_ok guard (the PR 4 fix): a duration "
        "change renews the device bucket, so an (OVER, 0) response "
        "observed across the renewal describes the PRE-renewal bucket "
        "— inserting it pins OVER over a bucket whose stored "
        "remaining just became `limit`."
    ),
    needle="if not plan.fall_dur_ok[j]:",
    replacement="if False and not plan.fall_dur_ok[j]:",
    scenario="ledger-renewal",
    properties=("sticky-over-exact",),
))


def mutation_names():
    return list(MUTATIONS)


def build_mutated_ledger(name: str) -> types.ModuleType:
    """Compile a twin ledger module with one guard disabled."""
    mut = MUTATIONS[name]
    path = _real_ledger.__file__
    with open(path, "r") as fh:
        src = fh.read()
    n = src.count(mut.needle)
    if n != 1:
        raise RuntimeError(
            f"mutation {name!r}: needle occurs {n} times in {path} "
            "(expected exactly 1) — the guard moved; update the fixture"
        )
    src = src.replace(mut.needle, mut.replacement)
    mod = types.ModuleType("gubernator_tpu.core.ledger")
    mod.__file__ = path + f"  [mutated:{name}]"
    code = compile(src, mod.__file__, "exec")
    exec(code, mod.__dict__)
    return mod


def mutated_scenario_factory(name: str) -> Callable[[], object]:
    """A scenario factory wired to the mutated ledger twin.  The twin
    module is compiled once and shared across re-executions — module
    code is immutable; all mutable state lives in per-run objects."""
    from tools.gubercheck import scenarios as _scn

    mut = MUTATIONS[name]
    cls = _scn.get_scenario(mut.scenario)
    mod = build_mutated_ledger(name)

    def factory():
        scn = cls()
        scn.ledger_mod = mod
        return scn

    factory.__name__ = f"mutated_{name.replace('-', '_')}"
    return factory

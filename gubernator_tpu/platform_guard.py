"""Force the CPU platform in environments that pre-register a TPU backend.

The build/CI environment force-selects a TPU PJRT plugin via
`sitecustomize` (`JAX_PLATFORMS=axon`) that can be wedged: round 1's
driver artifacts recorded both an init error and an init hang from it.
Merely setting the `JAX_PLATFORMS` env var does NOT override the
registration — `jax.config.update("jax_platforms", "cpu")` after import
does.  This helper is the single shared defense used by
`tests/conftest.py`, `__graft_entry__.dryrun_multichip`, and
`bench.py`'s CPU fallback; keep the logic here so it cannot drift.

Must be called before any jax backend initializes (first array op /
`jax.devices()`): `XLA_FLAGS` is read at backend-init time, and the
platform switch cannot evict an already-initialized backend.
"""

from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def force_cpu_platform(n_devices: int | None = None) -> None:
    """Pin jax to the CPU platform, with ≥ `n_devices` virtual devices.

    Safe to call repeatedly; raises the virtual device count to the max
    ever requested (a pre-existing smaller count in `XLA_FLAGS` is
    rewritten, not trusted)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        m = re.search(rf"{_COUNT_FLAG}=(\d+)", flags)
        if m is None:
            flags = (flags + f" {_COUNT_FLAG}={n_devices}").strip()
        elif int(m.group(1)) < n_devices:
            flags = flags.replace(m.group(0), f"{_COUNT_FLAG}={n_devices}")
        os.environ["XLA_FLAGS"] = flags

    import jax

    jax.config.update("jax_platforms", "cpu")

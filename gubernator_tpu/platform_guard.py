"""Force the CPU platform in environments that pre-register a TPU backend.

The build/CI environment force-selects a TPU PJRT plugin via
`sitecustomize` (`JAX_PLATFORMS=axon`) that can be wedged: round 1's
driver artifacts recorded both an init error and an init hang from it.
Merely setting the `JAX_PLATFORMS` env var does NOT override the
registration — `jax.config.update("jax_platforms", "cpu")` after import
does.  This helper is the single shared defense used by
`tests/conftest.py`, `__graft_entry__.dryrun_multichip`, and
`bench.py`'s CPU fallback; keep the logic here so it cannot drift.

Must be called before any jax backend initializes (first array op /
`jax.devices()`): `XLA_FLAGS` is read at backend-init time, and the
platform switch cannot evict an already-initialized backend.
"""

from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def force_cpu_platform(n_devices: int | None = None) -> None:
    """Pin jax to the CPU platform, with ≥ `n_devices` virtual devices.

    Safe to call repeatedly; raises the virtual device count to the max
    ever requested (a pre-existing smaller count in `XLA_FLAGS` is
    rewritten, not trusted)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        m = re.search(rf"{_COUNT_FLAG}=(\d+)", flags)
        if m is None:
            flags = (flags + f" {_COUNT_FLAG}={n_devices}").strip()
        elif int(m.group(1)) < n_devices:
            flags = flags.replace(m.group(0), f"{_COUNT_FLAG}={n_devices}")
        os.environ["XLA_FLAGS"] = flags

    import jax

    jax.config.update("jax_platforms", "cpu")
    disable_cpu_persistent_cache()


def disable_cpu_persistent_cache() -> None:
    """Turn the persistent compile cache OFF when the effective
    backend is CPU.

    Serializing certain XLA:CPU executables (the pump's donated
    lax.scan programs) SEGFAULTS in jaxlib's AOT export, and loading
    entries written by a different CPU model is a fatal abort — both
    hit this build mid-suite.  The cache exists for the multi-second
    TPU compiles; CPU compiles are cheap, so the safe configuration is
    cache-off whenever the effective backend is CPU.  Called by
    force_cpu_platform and by engine construction (which also covers
    the in-process wedged-TPU fallback path).

    Updating the config alone is NOT enough once anything compiled:
    jax memoizes the cache-enabled decision — reset it too."""
    import jax

    if jax.default_backend() != "cpu":
        return
    try:
        jax.config.update("jax_compilation_cache_dir", None)
    except Exception:  # noqa: BLE001 — older jax without the knob
        pass
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:  # noqa: BLE001 — private API; best effort
        pass


def probe_backend_subprocess(timeout: float) -> "tuple[bool, str]":
    """Initialize the configured jax backend in a THROWAWAY subprocess
    with a real timeout.  Shared by bench.py and Daemon.start — the
    subtle part is identical in both: subprocess.run's timeout path
    re-waits on the pipes with NO timeout, so a plugin relay grandchild
    holding them open would wedge the caller forever; the probe runs in
    its own process group, group-SIGKILLs on timeout, and abandons
    unreapable pipes.  Returns (ok, detail): detail is the platform
    name on success, the failure reason otherwise."""
    import signal
    import subprocess
    import sys

    proc = subprocess.Popen(
        [sys.executable, "-c",
         "import jax; d = jax.devices(); print(d[0].platform)"],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        stdin=subprocess.DEVNULL,
        text=True,
        start_new_session=True,
    )
    try:
        out_s, err_s = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        try:
            proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            if proc.stdout:
                proc.stdout.close()
            if proc.stderr:
                proc.stderr.close()
        return False, f"backend init timed out after {timeout:.0f}s"
    if proc.returncode != 0:
        tail = (err_s or out_s or "").strip().splitlines()
        return False, (tail[-1][:300] if tail else f"rc={proc.returncode}")
    lines = (out_s or "").strip().splitlines()
    return True, (lines[-1].strip() if lines else "unknown")

"""Client library for gubernator_tpu (and reference) daemons.

reference: client.go — DialV1Server (:42-64), HashKey (:37-39, lives on
RateLimitReq.hash_key here), millisecond timestamp helpers (:69-85),
RandomPeer/RandomString (:88-104).
"""

from __future__ import annotations

import random
import string
import time
from typing import List, Optional, Sequence

import grpc

from gubernator_tpu.net import serde
from gubernator_tpu.net.grpc_service import V1Stub, dial
from gubernator_tpu.net.pb import gubernator_pb2 as pb
from gubernator_tpu.types import (
    HealthCheckResp,
    PeerInfo,
    RateLimitReq,
    RateLimitResp,
)


class V1Client:
    """Typed client over the V1 gRPC service."""

    def __init__(
        self,
        address: str,
        *,
        credentials: Optional[grpc.ChannelCredentials] = None,
    ):
        self.address = address
        self._channel = dial(address, credentials=credentials)
        self._stub = V1Stub(self._channel)

    def get_rate_limits(
        self, requests: Sequence[RateLimitReq], timeout: Optional[float] = None
    ) -> List[RateLimitResp]:
        resp = self._stub.GetRateLimits(
            serde.get_rate_limits_req_to_pb(requests), timeout=timeout
        )
        return [serde.rate_limit_resp_from_pb(m) for m in resp.responses]

    def health_check(self, timeout: Optional[float] = None) -> HealthCheckResp:
        return serde.health_check_resp_from_pb(
            self._stub.HealthCheck(pb.HealthCheckReq(), timeout=timeout)
        )

    def close(self) -> None:
        self._channel.close()

    def __enter__(self) -> "V1Client":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def to_timestamp(t: float) -> int:
    """Seconds → unix-epoch ms. reference: client.go:69-77."""
    return int(t * 1000)


def from_timestamp(ms: int) -> float:
    """Unix-epoch ms → seconds. reference: client.go:80-85."""
    return ms / 1000.0


def now_ms() -> int:
    return to_timestamp(time.time())


def random_peer(peers: List[PeerInfo]) -> PeerInfo:
    """reference: client.go:88-91."""
    return random.choice(peers)


def random_string(n: int = 10, prefix: str = "") -> str:
    """reference: client.go:94-104."""
    return prefix + "".join(
        random.choices(string.ascii_lowercase + string.digits, k=n)
    )

"""Platform-aware f64 primitives for the TPU hot path.

TPU f64 emulation has fast multiply/add/reciprocal but a catastrophically
slow general division (~1µs/element measured on v5e — it dominates the
whole kernel).  `f64_div` keeps exact IEEE division on CPU (where the
conformance suite runs, bit-equal to the Go reference's float64) and
uses reciprocal + two Newton corrections on accelerators (≤1 ulp error;
the truncated-to-int64 results the API exposes are unaffected for the
magnitudes rate limiting produces).

Callers must keep divisors positive and finite — guard with jnp.where
*before* calling (a 0 or inf divisor yields NaN through the Newton path).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _newton_div(a: jax.Array, b: jax.Array) -> jax.Array:
    r = 1.0 / b
    q = a * r
    q = q + (a - q * b) * r
    q = q + (a - q * b) * r
    return q


def _true_div(a: jax.Array, b: jax.Array) -> jax.Array:
    return a / b


def f64_div(a: jax.Array, b: jax.Array) -> jax.Array:
    """a / b in float64; exact on CPU, Newton-refined on accelerators."""
    return jax.lax.platform_dependent(a, b, cpu=_true_div, default=_newton_div)

"""The vectorized bucket-update kernel: one XLA call per request batch.

This is the TPU-native replacement for the reference's entire local
execution engine — the worker-pool channel hop plus the per-key
`tokenBucket`/`leakyBucket` call (reference: gubernator_pool.go:250-336,
algorithms.go:31-516).  Bucket state is a struct-of-arrays in device
memory; a batch of requests is applied as gather → branch-free update
(`jnp.where` chains over the algorithm/behavior flags) → scatter.

Semantics are defined by the scalar spec in
`gubernator_tpu.models.spec` (bit-equivalence is fuzz-tested); see that
module's docstring for the preserved reference quirks.

Duplicate slots within one call are NOT allowed (scatter order would be
unspecified); the engine splits a batch into rounds so each slot appears
at most once per call, which reproduces the reference's per-key
serialization (reference: gubernator_pool.go:19-37) while keeping every
round a single vectorized device step.

`now_ms` is an explicit input — the device never reads time — so frozen
clock conformance tests drive the kernel directly (SURVEY.md §4.5).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from gubernator_tpu.ops.fastmath import f64_div
from gubernator_tpu.types import Algorithm, Behavior, Status

_I64 = jnp.int64
_I32 = jnp.int32
_F64 = jnp.float64

# numpy scalars (not jnp): they inline as jaxpr literals, which keeps
# the shared lane math embeddable in a Pallas kernel body — a kernel
# may not close over materialized device constants (ops/pallas_step.py).
_OVER = np.int32(int(Status.OVER_LIMIT))
_UNDER = np.int32(int(Status.UNDER_LIMIT))


class BucketState(NamedTuple):
    """Struct-of-arrays bucket state, 48 bytes/slot (VERDICT r4 #6;
    the round-4 layout was 19 plain arrays at 73 B/slot — 7.6 GB at
    100 M keys).

    The fields of TokenBucketItem/LeakyBucketItem (reference:
    store.go:29-43) plus cache-item metadata (reference: cache.go:30-42):
    `t0` = CreatedAt (token) / UpdatedAt (leaky); expire/invalid mirror
    CacheItem.ExpireAt / InvalidAt.

    64-bit logical fields travel as (hi: int32, lo: uint32) word pairs
    because the TPU runtime has no native 64-bit arrays (JAX's x64 shim
    would otherwise split/recombine every capacity-sized array at the
    jit boundary — O(state) per step).  Three packings shrink the slot:

    - `meta` folds occupied (bit 0), the algorithm (bit 1, normalized
      to {0,1} — every non-zero wire value means LEAKY_BUCKET, the
      documented divergence for out-of-enum algorithm ints), the
      sticky token status (bits 2-3), and the HI WORDS of t0 and
      invalid_at (11 bits each at bits 4-14 / 15-25): millisecond
      timestamps fit 43 bits until the year 2248, so their hi words
      fit 11.  Values clamp to [0, 2^43) at encode.
    - `hi2` likewise folds the expire and duration hi words (duration
      clamps at 2^43 ms ≈ 278 years; negative durations clamp to 0 —
      both documented divergences at absurd inputs only).
    - `rem` merges the token remaining (int64 words) and the leaky
      32.32 fixed-point remaining (whole:int32, frac:uint32): a slot
      runs one algorithm at a time, so the pair is interpreted through
      the meta algo bit (`models/spec.py quantize_remf` defines the
      leaky quantization; bit-equality stays fuzz-pinned either way).
    """

    meta: jax.Array  # int32 — see docstring bit layout
    hi2: jax.Array  # int32 — expire hi (bits 0-10) | duration hi (11-21)
    t0_lo: jax.Array  # uint32
    expire_lo: jax.Array  # uint32
    invalid_lo: jax.Array  # uint32
    duration_lo: jax.Array  # uint32
    limit_hi: jax.Array  # int32
    limit_lo: jax.Array  # uint32
    rem_hi: jax.Array  # int32   (token int64 hi / leaky whole)
    rem_lo: jax.Array  # uint32  (token int64 lo / leaky fraction)
    burst_hi: jax.Array  # int32
    burst_lo: jax.Array  # uint32


# Millisecond-timestamp clamp bound for the packed 11-bit hi words.
TS_CLAMP_MAX = (1 << 43) - 1
_HI11 = 0x7FF


class BatchInput(NamedTuple):
    """One request batch, shape [B] per field.

    Padding lanes MUST use distinct, ascending, out-of-range slots
    (capacity + lane) — the kernel declares its gather/scatter indices
    sorted and unique, and -1 padding would both defeat the
    `slot < capacity` mask and violate the uniqueness contract.

    `greg_duration`/`greg_expire` are host-precomputed per request when
    DURATION_IS_GREGORIAN is set (reference: interval.go:84-148 — the
    calendar math never runs on device)."""

    slot: jax.Array  # int32; padding = capacity + lane (see above)
    algo: jax.Array  # int32
    behavior: jax.Array  # int32
    hits: jax.Array  # int64
    limit: jax.Array  # int64
    duration: jax.Array  # int64
    burst: jax.Array  # int64
    greg_duration: jax.Array  # int64
    greg_expire: jax.Array  # int64


class BatchOutput(NamedTuple):
    """Per-request responses (reference: proto/gubernator.proto:169-182)."""

    status: jax.Array  # int32
    limit: jax.Array  # int64
    remaining: jax.Array  # int64
    reset_time: jax.Array  # int64


_U32 = jnp.uint32


def make_state(capacity: int) -> BucketState:
    """Allocate an empty state of `capacity` slots.

    Every field gets its own buffer — `apply_batch` donates the whole
    state, and aliased buffers cannot be donated twice."""

    def z(dt):
        return jnp.zeros((capacity,), dtype=dt)

    return BucketState(
        meta=z(_I32),
        hi2=z(_I32),
        t0_lo=z(_U32),
        expire_lo=z(_U32),
        invalid_lo=z(_U32),
        duration_lo=z(_U32),
        limit_hi=z(_I32),
        limit_lo=z(_U32),
        rem_hi=z(_I32),
        rem_lo=z(_U32),
        burst_hi=z(_I32),
        burst_lo=z(_U32),
    )


def clamp_ts(v):
    """Clamp a millisecond value into the packed-hi-word range (works
    on jnp and np arrays alike)."""
    return jnp.clip(v, 0, TS_CLAMP_MAX)


def pack_meta(occ, algo_norm, status, t0c, invc):
    """occupied/algo/status/t0/invalid → the meta word (values already
    normalized/clamped; t0c/invc int64 in [0, 2^43))."""
    return (
        occ.astype(_I32)
        | (algo_norm.astype(_I32) << 1)
        | ((status & 3).astype(_I32) << 2)
        | ((t0c >> 32).astype(_I32) << 4)
        | ((invc >> 32).astype(_I32) << 15)
    )


def meta_occupied(meta):
    return (meta & 1) != 0


def meta_algo(meta):
    return ((meta >> 1) & 1).astype(_I32)


def meta_status(meta):
    return ((meta >> 2) & 3).astype(_I32)


def meta_t0(meta, t0_lo):
    return (((meta >> 4) & _HI11).astype(_I64) << 32) | t0_lo.astype(_I64)


def meta_invalid(meta, inv_lo):
    return (((meta >> 15) & _HI11).astype(_I64) << 32) | inv_lo.astype(_I64)


def pack_hi2(expc, durc):
    """expire/duration (clamped int64) → the hi2 word."""
    return ((expc >> 32).astype(_I32)) | (((durc >> 32).astype(_I32)) << 11)


def hi2_expire(hi2, exp_lo):
    return ((hi2 & _HI11).astype(_I64) << 32) | exp_lo.astype(_I64)


def hi2_duration(hi2, dur_lo):
    return (((hi2 >> 11) & _HI11).astype(_I64) << 32) | dur_lo.astype(_I64)


def pack_state_host(logical: dict) -> dict:
    """Encode logical numpy columns (keys as in `unpack_state_host`,
    with the leaky remaining given as remf_hi/remf_lo words) into the
    packed BucketState field arrays — bulk load/restore paths only."""
    occ = np.asarray(logical["occupied"]).astype(bool)
    algo = (np.asarray(logical["algo"]) != 0).astype(np.int32)
    status = np.asarray(logical["status"]).astype(np.int64)
    t0c = np.clip(np.asarray(logical["t0"]), 0, TS_CLAMP_MAX)
    invc = np.clip(np.asarray(logical["invalid"]), 0, TS_CLAMP_MAX)
    expc = np.clip(np.asarray(logical["expire"]), 0, TS_CLAMP_MAX)
    durc = np.clip(np.asarray(logical["duration"]), 0, TS_CLAMP_MAX)
    meta = (
        occ.astype(np.int32)
        | (algo << 1)
        | ((status & 3).astype(np.int32) << 2)
        | ((t0c >> 32).astype(np.int32) << 4)
        | ((invc >> 32).astype(np.int32) << 15)
    )
    hi2 = ((expc >> 32).astype(np.int32)) | (
        (durc >> 32).astype(np.int32) << 11
    )
    rem64 = np.asarray(logical["remaining"]).astype(np.int64)
    leaky = algo == 1
    rem_hi = np.where(
        leaky, np.asarray(logical["remf_hi"]).astype(np.int32),
        (rem64 >> 32).astype(np.int32),
    )
    rem_lo = np.where(
        leaky, np.asarray(logical["remf_lo"]).astype(np.uint32),
        (rem64 & 0xFFFFFFFF).astype(np.uint32),
    )
    limit64 = np.asarray(logical["limit"]).astype(np.int64)
    burst64 = np.asarray(logical["burst"]).astype(np.int64)
    return {
        "meta": meta,
        "hi2": hi2,
        "t0_lo": (t0c & 0xFFFFFFFF).astype(np.uint32),
        "expire_lo": (expc & 0xFFFFFFFF).astype(np.uint32),
        "invalid_lo": (invc & 0xFFFFFFFF).astype(np.uint32),
        "duration_lo": (durc & 0xFFFFFFFF).astype(np.uint32),
        "limit_hi": (limit64 >> 32).astype(np.int32),
        "limit_lo": (limit64 & 0xFFFFFFFF).astype(np.uint32),
        "rem_hi": rem_hi,
        "rem_lo": rem_lo,
        "burst_hi": (burst64 >> 32).astype(np.int32),
        "burst_lo": (burst64 & 0xFFFFFFFF).astype(np.uint32),
    }


def unpack_state_host(state) -> dict:
    """Decode a full state into logical numpy columns (export /
    checkpoint / inspection — full-state host ops, never the hot
    path).  Keys: occupied, algo, status, t0, invalid, expire,
    duration, limit, remaining (token view), remf_hi/remf_lo (leaky
    words), burst."""
    meta = np.asarray(state.meta)
    hi2 = np.asarray(state.hi2)
    t0_lo = np.asarray(state.t0_lo)
    inv_lo = np.asarray(state.invalid_lo)
    exp_lo = np.asarray(state.expire_lo)
    dur_lo = np.asarray(state.duration_lo)

    def c64(hi, lo):
        return (np.asarray(hi).astype(np.int64) << 32) | np.asarray(
            lo
        ).astype(np.int64)

    rem_hi = np.asarray(state.rem_hi)
    rem_lo = np.asarray(state.rem_lo)
    return {
        "occupied": (meta & 1) != 0,
        "algo": (meta >> 1) & 1,
        "status": (meta >> 2) & 3,
        "t0": (((meta >> 4) & _HI11).astype(np.int64) << 32)
        | t0_lo.astype(np.int64),
        "invalid": (((meta >> 15) & _HI11).astype(np.int64) << 32)
        | inv_lo.astype(np.int64),
        "expire": ((hi2 & _HI11).astype(np.int64) << 32)
        | exp_lo.astype(np.int64),
        "duration": (((hi2 >> 11) & _HI11).astype(np.int64) << 32)
        | dur_lo.astype(np.int64),
        "limit": c64(state.limit_hi, state.limit_lo),
        "remaining": c64(rem_hi, rem_lo),
        "remf_hi": rem_hi,
        "remf_lo": rem_lo,
        "burst": c64(state.burst_hi, state.burst_lo),
    }


def combine_i64(hi: jax.Array, lo: jax.Array) -> jax.Array:
    """(hi:int32, lo:uint32) → int64 (two's complement)."""
    return (hi.astype(_I64) << 32) | lo.astype(_I64)


def split_i64(v: jax.Array) -> tuple[jax.Array, jax.Array]:
    """int64 → (hi:int32, lo:uint32)."""
    return (v >> 32).astype(_I32), (v & 0xFFFFFFFF).astype(_U32)


def combine_remf(hi: jax.Array, lo: jax.Array) -> jax.Array:
    """(whole:int32, frac:uint32) fixed-point → float64.

    The leaky remaining (float64 in the reference, store.go:36) is
    persisted as 32.32 fixed point: the backend's X64 rewriter cannot
    bitcast f64 words, so the value is quantized to 2^-32 on store.
    The scalar spec applies the identical quantization
    (models/spec.py `quantize_remf`), keeping spec↔kernel bit-equality.
    Whole parts saturate at ±2^31 (far beyond any observable behavior
    in the reference test suite)."""
    return hi.astype(_F64) + lo.astype(_F64) * (2.0**-32)


def split_remf(v: jax.Array) -> tuple[jax.Array, jax.Array]:
    """float64 → (whole:int32, frac:uint32) with floor quantization."""
    w = jnp.floor(v)
    wc = jnp.clip(w, -(2.0**31), 2.0**31 - 1)
    return wc.astype(_I32), ((v - w) * (2.0**32)).astype(_U32)


# guberlint: shapes meta [capacity] fixed at engine build; slots [C], C in the pow2 clear ladder (warmup)
def _clear_occupied_impl(meta: jax.Array, slots: jax.Array) -> jax.Array:
    """Mark evicted slots unoccupied (host eviction executed on device).

    Split out of the apply kernel so the compile cache is one shape per
    clear width instead of a (batch width × clear width) matrix —
    eviction bursts then never trigger apply-kernel recompiles.
    Padding lanes use distinct ascending out-of-range slots.  With the
    packed layout this is a sparse read-modify-write of the meta word
    (clear bit 0); the gather+scatter touch O(clears) cells only."""
    s = jnp.sort(slots)
    cur = meta.at[s].get(
        mode="fill", fill_value=0, indices_are_sorted=True,
        unique_indices=True,
    )
    return meta.at[s].set(
        cur & ~1, mode="drop", indices_are_sorted=True, unique_indices=True
    )


# Donated: write-only scatter, compiles in place (no occupancy-array
# copy).  Callers must treat the input buffer as consumed.  Inside
# shard_map/jit tracing use `_clear_occupied_impl` (inner donation has
# no effect there).
clear_occupied = jax.jit(_clear_occupied_impl, donate_argnums=(0,))


# guberlint: shapes state fixed at capacity; batch lanes padded to the pow2 width ladder (warmup 64..1024)
def _apply_batch_impl(
    state: BucketState,
    batch: BatchInput,
    clear_slots: jax.Array,  # int32 [C]; padding = out-of-range ascending
    now_ms: jax.Array,  # int64 scalar
) -> tuple[BucketState, BatchOutput]:
    cap = state.meta.shape[0]
    now = now_ms.astype(_I64)

    # TPU gather/scatter with arbitrary indices lowers to a serial
    # per-element loop (~1µs each — measured 8ms for an 8k batch).  With
    # `indices_are_sorted` + `unique_indices` the same ops are ~200x
    # faster.  Rounds guarantee uniqueness (engine invariant); sortedness
    # comes from co-sorting the whole batch by slot with one multi-
    # operand lax.sort (a sorting network — no random access), and
    # responses are restored to request order by a second sort keyed on
    # the lane index.  Padding uses distinct ascending out-of-range
    # slots (cap + lane) so both flags stay truthful.
    lane = jnp.arange(batch.slot.shape[0], dtype=_I32)
    (
        slot,
        lane_s,
        r_algo,
        r_beh,
        r_hits,
        r_limit,
        r_dur,
        r_burst,
        r_gdur,
        r_gexp,
    ) = jax.lax.sort(
        (
            batch.slot,
            lane,
            batch.algo,
            batch.behavior,
            batch.hits,
            batch.limit,
            batch.duration,
            batch.burst,
            batch.greg_duration,
            batch.greg_expire,
        ),
        num_keys=1,
    )
    # Host-side eviction: mark reclaimed slots unoccupied before applying
    # the batch (the reference evicts inline in the LRU; here eviction is
    # a host decision executed on device, SURVEY.md §7.3 item 6).
    occupied = _clear_occupied_impl(state.meta, clear_slots)

    new_state, resp_status, resp_rem, resp_reset = _apply_core(
        state, occupied, slot, r_algo, r_beh, r_hits, r_limit, r_dur,
        r_burst, r_gdur, r_gexp, now,
    )

    # Un-sort: restore responses to request order via a sort on lane idx.
    _, o_status, o_limit, o_rem, o_reset = jax.lax.sort(
        (lane_s, resp_status.astype(_I32), r_limit, resp_rem, resp_reset),
        num_keys=1,
    )
    out = BatchOutput(
        status=o_status,
        limit=o_limit,
        remaining=o_rem,
        reset_time=o_reset,
    )
    return new_state, out


def _apply_core(
    state: BucketState,
    occupied: jax.Array,
    slot: jax.Array,
    *args,
):
    """gather → update → scatter in ONE program (single-call variants).

    Hot paths use the split pair (`_compute_update` + `scatter_store`)
    instead — see `_scatter_values` for why."""
    vals, resp_status, resp_rem, resp_reset = _compute_update(
        state, occupied, slot, *args
    )
    new_state = _scatter_values(state._replace(meta=occupied), slot, vals)
    return new_state, resp_status, resp_rem, resp_reset


class GatheredSlots(NamedTuple):
    """Raw per-lane state words after the gather — the packed column
    values for each request lane's slot, still encoded (meta/hi2 bit
    packings, hi/lo word pairs).  Shape [B] per field.

    This is the seam between the two halves of the decision step: the
    XLA path produces it with `gather_slots` (one sorted/unique gather
    per column) and the Pallas kernel produces it with its in-kernel
    gather loop (ops/pallas_step.py) — both feed the SAME
    `update_lanes` math, so the two backends cannot drift."""

    meta: jax.Array  # int32 (possibly clear-updated meta array)
    hi2: jax.Array  # int32
    t0_lo: jax.Array  # uint32
    expire_lo: jax.Array  # uint32
    invalid_lo: jax.Array  # uint32
    duration_lo: jax.Array  # uint32
    limit_hi: jax.Array  # int32
    limit_lo: jax.Array  # uint32
    rem_hi: jax.Array  # int32
    rem_lo: jax.Array  # uint32
    burst_hi: jax.Array  # int32
    burst_lo: jax.Array  # uint32


def gather_slots(
    state: BucketState, occupied: jax.Array, slot: jax.Array
) -> GatheredSlots:
    """Gather the raw state words for slot-sorted lanes (fill 0 for
    out-of-range padding lanes).  `occupied` is the meta array to read
    occupancy from (it may carry this round's eviction clears).
    Field order tracks BucketState (the gather zips the two)."""

    def g(arr):
        return arr.at[slot].get(
            mode="fill",
            fill_value=0,
            indices_are_sorted=True,
            unique_indices=True,
        )

    return GatheredSlots(
        *(g(arr) for arr in state._replace(meta=occupied))
    )


def _compute_update(
    state: BucketState,
    occupied: jax.Array,
    slot: jax.Array,  # int32 [B] SORTED ascending, unique; padding = cap+i
    r_algo: jax.Array,
    r_beh: jax.Array,
    r_hits: jax.Array,
    r_limit: jax.Array,
    r_dur: jax.Array,
    r_burst: jax.Array,
    r_gdur: jax.Array,
    r_gexp: jax.Array,
    now: jax.Array,
):
    """The READ-ONLY half of the branch-free bucket update over
    slot-sorted lanes: gather → update.  Returns (SlotValues, status,
    remaining, reset_time) with everything in the SORTED lane order."""
    cap = state.meta.shape[0]
    mask = slot < cap
    g = gather_slots(state, occupied, slot)
    return update_lanes(
        g, mask, r_algo, r_beh, r_hits, r_limit, r_dur, r_burst,
        r_gdur, r_gexp, now,
    )


def update_lanes(
    g: GatheredSlots,
    mask: jax.Array,  # bool [B]: lane in range (padding lanes False)
    r_algo: jax.Array,
    r_beh: jax.Array,
    r_hits: jax.Array,
    r_limit: jax.Array,
    r_dur: jax.Array,
    r_burst: jax.Array,
    r_gdur: jax.Array,
    r_gexp: jax.Array,
    now: jax.Array,
):
    """The branch-free bucket update over already-gathered lanes: the
    pure vector math between gather and scatter, shared verbatim by the
    XLA programs and the Pallas kernel (see GatheredSlots)."""
    s_meta = g.meta
    s_occ = meta_occupied(s_meta) & mask
    s_algo = meta_algo(s_meta)
    s_status = meta_status(s_meta)
    s_t0 = meta_t0(s_meta, g.t0_lo)
    s_inv = meta_invalid(s_meta, g.invalid_lo)
    s_hi2 = g.hi2
    s_exp = hi2_expire(s_hi2, g.expire_lo)
    s_dur = hi2_duration(s_hi2, g.duration_lo)
    s_limit = combine_i64(g.limit_hi, g.limit_lo)
    # The merged remaining words: int64 for token slots, 32.32 fixed
    # point for leaky — both views computed, the algo paths pick.
    _rem_hi, _rem_lo = g.rem_hi, g.rem_lo
    s_rem = combine_i64(_rem_hi, _rem_lo)
    s_rem_f = combine_remf(_rem_hi, _rem_lo)
    s_burst = combine_i64(g.burst_hi, g.burst_lo)

    # Normalize the request algorithm to the stored 1-bit domain (see
    # BucketState docstring).
    r_algo = (r_algo != 0).astype(_I32)

    greg = (r_beh & int(Behavior.DURATION_IS_GREGORIAN)) != 0
    rst = (r_beh & int(Behavior.RESET_REMAINING)) != 0

    # Cache-hit check (reference: lrucache.go:112-138): strict
    # `expire_at < now` / non-zero `invalid_at < now` are misses.
    live = s_occ & ~((s_inv != 0) & (s_inv < now)) & (s_exp >= now)
    same = live & (s_algo == r_algo)
    is_tok = r_algo == int(Algorithm.TOKEN_BUCKET)

    p_tok_reset = same & is_tok & rst
    p_tok_ex = same & is_tok & ~rst
    p_leak_ex = same & ~is_tok
    p_tok_new = ~same & is_tok
    p_leak_new = ~same & ~is_tok

    zero64 = jnp.zeros_like(r_limit)

    # ---------------- token bucket, existing item (algorithms.go:79-208)
    limit_changed = s_limit != r_limit
    te_rem0 = jnp.where(
        limit_changed, jnp.maximum(s_rem + (r_limit - s_limit), 0), s_rem
    )
    dur_changed = s_dur != r_dur
    te_new_exp = jnp.where(greg, r_gexp, s_t0 + r_dur)
    te_renew = dur_changed & (te_new_exp <= now)
    te_exp = jnp.where(dur_changed, jnp.where(te_renew, now + r_dur, te_new_exp), s_exp)
    te_created = jnp.where(te_renew, now, s_t0)
    te_rem_store = jnp.where(te_renew, r_limit, te_rem0)

    # Branch chain — priority: query > empty > exact > over > consume
    # (sequential ifs at algorithms.go:173-207).  `te_rem0` is the
    # response snapshot, `te_rem_store` the stored value (they differ
    # only on renewal; see models/spec.py docstring).
    te_q = r_hits == 0
    te_e = (te_rem0 == 0) & (r_hits > 0)
    te_x = te_rem_store == r_hits
    te_o = r_hits > te_rem_store

    te_rem_out = te_rem_store - r_hits  # consume
    te_rem_out = jnp.where(te_o, te_rem_store, te_rem_out)
    te_rem_out = jnp.where(te_x, zero64, te_rem_out)
    te_rem_out = jnp.where(te_e, te_rem_store, te_rem_out)
    te_rem_out = jnp.where(te_q, te_rem_store, te_rem_out)

    te_resp_rem = te_rem_store - r_hits
    te_resp_rem = jnp.where(te_o, te_rem0, te_resp_rem)
    te_resp_rem = jnp.where(te_x, zero64, te_resp_rem)
    te_resp_rem = jnp.where(te_e, te_rem0, te_resp_rem)
    te_resp_rem = jnp.where(te_q, te_rem0, te_resp_rem)

    te_resp_status = jnp.where(
        te_q, s_status, jnp.where(te_e | (~te_x & te_o), _OVER, s_status)
    )
    te_status_store = jnp.where(te_e & ~te_q, _OVER, s_status)

    # ---------------- token bucket, new item (algorithms.go:215-272)
    tn_exp = jnp.where(greg, r_gexp, now + r_dur)
    tn_over = r_hits > r_limit
    tn_rem = jnp.where(tn_over, r_limit, r_limit - r_hits)
    tn_resp_status = jnp.where(tn_over, _OVER, _UNDER)

    # ---------------- leaky bucket shared
    # `rate` = D/L is conceptually +inf when limit<=0 and 0 when D==0
    # (Go divides by zero and carries ±inf); instead of materializing
    # infinities (isposinf/isfinite are ~1µs/elt on TPU) we track the
    # classification with integer masks and only divide safe operands
    # via the platform-aware f64_div (see ops/fastmath.py).
    burst_eff = jnp.where(r_burst == 0, r_limit, r_burst)
    limit_pos = r_limit > 0
    lk_D = jnp.where(greg, r_gdur, r_dur)  # rate numerator (ms)
    rate_finite = limit_pos  # else conceptual rate = +inf
    rate_zero = limit_pos & (lk_D == 0)
    lk_rate = f64_div(
        lk_D.astype(_F64),
        jnp.where(limit_pos, r_limit, 1).astype(_F64),
    )
    lk_rate = jnp.where(rate_finite, lk_rate, 0.0)
    # int64(rate); conceptual-inf rate truncates to 0 like the spec.
    lk_rate_i = lk_rate.astype(_I64)

    # ---------------- leaky bucket, existing item (algorithms.go:329-448)
    le_rem = jnp.where(rst, burst_eff.astype(_F64), s_rem_f)
    burst_changed = s_burst != burst_eff
    le_rem = jnp.where(
        burst_changed & (burst_eff > le_rem.astype(_I64)),
        burst_eff.astype(_F64),
        le_rem,
    )
    le_eff_dur = jnp.where(greg, r_gexp - now, r_dur)
    le_exp = jnp.where(r_hits != 0, now + le_eff_dur, s_exp)

    elapsed = (now - s_t0).astype(_F64)
    rate_pos = rate_finite & ~rate_zero
    le_leak = f64_div(elapsed, jnp.where(rate_pos, lk_rate, 1.0))
    le_leak = jnp.where(rate_pos, le_leak, 0.0)
    # Conceptual leak = +inf (rate==0, elapsed>0) refills to burst
    # (Go: elapsed/0.0 = +Inf; int64(+inf) is platform-defined, so
    # model "huge leak" explicitly instead of casting it).
    leak_inf = rate_zero & (elapsed > 0)
    leak_applies = (le_leak.astype(_I64) > 0) | leak_inf
    le_rem = jnp.where(leak_applies, le_rem + le_leak, le_rem)
    le_rem = jnp.where(leak_inf, burst_eff.astype(_F64), le_rem)
    le_t0 = jnp.where(leak_applies, now, s_t0)
    le_rem = jnp.where(le_rem.astype(_I64) > burst_eff, burst_eff.astype(_F64), le_rem)

    le_rem_i = le_rem.astype(_I64)
    le_rate_i = lk_rate_i
    le_reset0 = now + (r_limit - le_rem_i) * le_rate_i

    # Branch chain — priority: empty > exact > over > query > consume
    # (sequential ifs at algorithms.go:416-447; order differs from token).
    le_e = (le_rem_i == 0) & (r_hits > 0)
    le_x = le_rem_i == r_hits
    le_o = r_hits > le_rem_i
    le_q = r_hits == 0

    le_consume = le_rem - r_hits.astype(_F64)
    le_rem_out = le_consume
    le_rem_out = jnp.where(le_q, le_rem, le_rem_out)
    le_rem_out = jnp.where(le_o, le_rem, le_rem_out)
    le_rem_out = jnp.where(le_x, le_consume, le_rem_out)
    le_rem_out = jnp.where(le_e, le_rem, le_rem_out)

    le_consume_i = le_consume.astype(_I64)
    le_resp_rem = le_consume_i
    le_resp_rem = jnp.where(le_q, le_rem_i, le_resp_rem)
    le_resp_rem = jnp.where(le_o, le_rem_i, le_resp_rem)
    le_resp_rem = jnp.where(le_x, zero64, le_resp_rem)
    le_resp_rem = jnp.where(le_e, le_rem_i, le_resp_rem)

    le_resp_status = jnp.where(
        le_e | (~le_x & le_o), _OVER, _UNDER
    )
    le_reset = now + (r_limit - le_consume_i) * le_rate_i
    le_reset = jnp.where(le_q, le_reset0, le_reset)
    le_reset = jnp.where(le_o, le_reset0, le_reset)
    le_reset = jnp.where(le_x, now + r_limit * le_rate_i, le_reset)
    le_reset = jnp.where(le_e, le_reset0, le_reset)

    # ---------------- leaky bucket, new item (algorithms.go:454-516)
    # Shares lk_rate with the existing-item path (identical formula).
    ln_dur = jnp.where(greg, r_gexp - now, r_dur)
    ln_rate_i = lk_rate_i
    ln_over = r_hits > burst_eff
    ln_rem = burst_eff - r_hits
    ln_resp_rem = jnp.where(ln_over, zero64, ln_rem)
    ln_rem_f = jnp.where(ln_over, 0.0, ln_rem.astype(_F64))
    ln_resp_status = jnp.where(ln_over, _OVER, _UNDER)
    ln_reset = now + (r_limit - ln_resp_rem) * ln_rate_i

    # ---------------- combine paths → responses
    def pick(tok_reset, tok_ex, tok_new, leak_ex, leak_new):
        out = jnp.where(p_leak_new, leak_new, 0)
        out = jnp.where(p_leak_ex, leak_ex, out)
        out = jnp.where(p_tok_new, tok_new, out)
        out = jnp.where(p_tok_ex, tok_ex, out)
        out = jnp.where(p_tok_reset, tok_reset, out)
        return out

    resp_status = pick(_UNDER, te_resp_status, tn_resp_status, le_resp_status, ln_resp_status)
    resp_rem = pick(r_limit, te_resp_rem, tn_rem, le_resp_rem, ln_resp_rem)
    resp_reset = pick(zero64, te_exp, tn_exp, le_reset, ln_reset)

    # ---------------- combine paths → stored state, then scatter
    n_occ = ~p_tok_reset
    n_algo = r_algo
    n_limit = r_limit
    n_rem = pick(zero64, te_rem_out, tn_rem, zero64, zero64)
    n_rem_f = pick(jnp.zeros_like(le_rem), jnp.zeros_like(le_rem), jnp.zeros_like(le_rem), le_rem_out, ln_rem_f)
    # Stored duration: leaky-existing keeps the *raw* request duration
    # (algorithms.go:360) but leaky-new stores the Gregorian remainder
    # (algorithms.go:472,479); token paths store the request duration.
    n_dur = pick(r_dur, r_dur, r_dur, r_dur, ln_dur)
    n_t0 = pick(zero64, te_created, now, le_t0, now)
    n_exp = pick(zero64, te_exp, tn_exp, le_exp, now + ln_dur)
    n_burst = pick(zero64, zero64, zero64, burst_eff, burst_eff)
    n_status = pick(_UNDER, te_status_store, _UNDER, _UNDER, _UNDER)

    vals = SlotValues(
        occ=n_occ,
        algo=n_algo,
        status=n_status,
        limit=n_limit,
        remaining=n_rem,
        rem_f=n_rem_f,
        duration=n_dur,
        t0=n_t0,
        expire=n_exp,
        burst=n_burst,
    )
    return vals, resp_status, resp_rem, resp_reset


class SlotValues(NamedTuple):
    """Per-lane values to store after an update — the write half of the
    split kernel, shape [B] per field (combined int64; split into hi/lo
    words inside the scatter program)."""

    occ: jax.Array  # bool
    algo: jax.Array  # int32
    status: jax.Array  # int32
    limit: jax.Array  # int64
    remaining: jax.Array  # int64
    rem_f: jax.Array  # float64 (leaky 32.32 source)
    duration: jax.Array  # int64
    t0: jax.Array  # int64
    expire: jax.Array  # int64
    burst: jax.Array  # int64


class StoredWords(NamedTuple):
    """Per-lane encoded column words to store — field-for-field aligned
    with BucketState so a scatter (XLA) or an in-kernel store loop
    (Pallas) can zip the two.  Shape [B] per field; dtypes are the
    logical pre-cast ones (the store casts to each column's dtype)."""

    meta: jax.Array
    hi2: jax.Array
    t0_lo: jax.Array
    expire_lo: jax.Array
    invalid_lo: jax.Array
    duration_lo: jax.Array
    limit_hi: jax.Array
    limit_lo: jax.Array
    rem_hi: jax.Array
    rem_lo: jax.Array
    burst_hi: jax.Array
    burst_lo: jax.Array


def encode_slot_values(vals: SlotValues) -> StoredWords:
    """Encode computed slot values into the packed column words — the
    pure half of the write path, shared by `_scatter_values` and the
    Pallas kernel's store loop (update always clears invalid_at)."""
    algo_norm = (vals.algo != 0).astype(_I32)
    t0c = clamp_ts(vals.t0)
    invc = jnp.zeros_like(t0c)  # updates always clear invalid_at
    expc = clamp_ts(vals.expire)
    durc = clamp_ts(vals.duration)
    meta_v = pack_meta(vals.occ, algo_norm, vals.status, t0c, invc)
    hi2_v = pack_hi2(expc, durc)
    # Merged remaining: token int64 words vs leaky 32.32 words.
    tok_hi, tok_lo = split_i64(vals.remaining)
    remf_hi_v, remf_lo_v = split_remf(vals.rem_f)
    leaky = algo_norm == 1
    limit_hi, limit_lo = split_i64(vals.limit)
    burst_hi, burst_lo = split_i64(vals.burst)
    return StoredWords(
        meta=meta_v,
        hi2=hi2_v,
        t0_lo=t0c & 0xFFFFFFFF,
        expire_lo=expc & 0xFFFFFFFF,
        invalid_lo=jnp.zeros_like(meta_v),
        duration_lo=durc & 0xFFFFFFFF,
        limit_hi=limit_hi,
        limit_lo=limit_lo,
        rem_hi=jnp.where(leaky, remf_hi_v, tok_hi),
        rem_lo=jnp.where(leaky, remf_lo_v, tok_lo),
        burst_hi=burst_hi,
        burst_lo=burst_lo,
    )


# guberlint: shapes state fixed at capacity; slot/vals [W] on the same pow2 width ladder as the compute step
def _scatter_values(
    state: BucketState, slot: jax.Array, vals: SlotValues
) -> BucketState:
    """WRITE-ONLY scatter of computed slot values into the state.

    Kept free of any other read of the state arrays on purpose: when
    jitted with donated state this compiles to a true in-place update.
    A program that gathers from and scatters into the same donated
    buffer forces XLA's copy-insertion to clone every state array —
    measured 18 full-capacity copies (~41ms at 2M slots, O(capacity)
    per batch) before the kernel was split into compute + scatter.
    `slot` is sorted with distinct out-of-range padding → flags hold;
    out-of-range (padding) lanes are dropped.
    """

    def sc(arr, v):
        return arr.at[slot].set(
            v.astype(arr.dtype),
            mode="drop",
            indices_are_sorted=True,
            unique_indices=True,
        )

    words = encode_slot_values(vals)
    return BucketState(
        *(sc(arr, w) for arr, w in zip(state, words))
    )


# Donated write-only scatter: compiles to a true in-place update (no
# full-capacity copies) because the program never reads what it writes.
scatter_store = jax.jit(_scatter_values, donate_argnums=(0,))

apply_batch = jax.jit(_apply_batch_impl, donate_argnums=(0,))


# guberlint: shapes state fixed at capacity; batch lanes padded to the pow2 width ladder (warmup 64..1024)
def _apply_batch_sorted_impl(
    state: BucketState,
    batch: BatchInput,  # lanes PRE-SORTED by slot ascending (host sorts)
    now_ms: jax.Array,
):
    """Sort-free variant: the host (which assigned the slots) delivers
    lanes already slot-sorted, so the device runs only gather → update
    → scatter — no O(B log²B) sorting network to compile or execute.
    Outputs are packed into ONE flat int64 buffer
    [status… remaining… reset_time…] so the host pays a single
    device→host transfer per step.  Responses stay in the sorted lane
    order; the host unpermutes with the inverse of its own argsort.
    """
    new_state, resp_status, resp_rem, resp_reset = _apply_core(
        state,
        state.meta,
        batch.slot,
        batch.algo,
        batch.behavior,
        batch.hits,
        batch.limit,
        batch.duration,
        batch.burst,
        batch.greg_duration,
        batch.greg_expire,
        now_ms.astype(_I64),
    )
    packed = jnp.concatenate(
        [resp_status.astype(_I64), resp_rem, resp_reset]
    )
    return new_state, packed


apply_batch_sorted = jax.jit(_apply_batch_sorted_impl, donate_argnums=(0,))


# guberlint: shapes state fixed at capacity; batch lanes padded to the pow2 width ladder (warmup 64..1024)
def _compute_update_sorted_impl(
    state: BucketState,
    batch: BatchInput,  # lanes PRE-SORTED by slot ascending (host sorts)
    now_ms: jax.Array,
):
    """Compute half of the sorted columnar step: gathers + bucket math,
    NO state writes.  Pair with `scatter_store` (donated) — the split
    keeps the in-place scatter free of full-capacity copy-insertion
    (see `_scatter_values`)."""
    vals, resp_status, resp_rem, resp_reset = _compute_update(
        state,
        state.meta,
        batch.slot,
        batch.algo,
        batch.behavior,
        batch.hits,
        batch.limit,
        batch.duration,
        batch.burst,
        batch.greg_duration,
        batch.greg_expire,
        now_ms.astype(_I64),
    )
    packed = jnp.concatenate(
        [resp_status.astype(_I64), resp_rem, resp_reset]
    )
    return vals, packed


compute_update_sorted = jax.jit(_compute_update_sorted_impl)


# ---------------------------------------------------------------------------
# Packed single-transfer step — the serving fast path.
#
# Measured on the tunneled TPU backend (scripts/profile_dispatch.py,
# PERF.md): every device operation — transfer or kernel, any size —
# costs a near-constant dispatch overhead that dwarfs the actual
# HBM/compute time of an 8k-lane step.  The columnar path therefore
# packs the WHOLE request round into ONE int32 [PACKED_IN_ROWS, B]
# host buffer (one h2d op), runs ONE (or two, see below) kernels, and
# reads back ONE int32 [PACKED_OUT_ROWS, B] buffer.  Layout:
#
#   row 0      header: [now_hi, now_lo, 0, ...]   (now_ms int64 words)
#   row 1      slot    (int32; sorted ascending; padding = cap + lane)
#   row 2      algo    row 3   behavior
#   rows 4-5   hits    rows 6-7   limit     rows 8-9  duration
#   rows 10-11 burst   rows 12-13 greg_dur  rows 14-15 greg_exp
#   (64-bit fields as (hi, lo) int32 word rows)
#
# Output rows: 0 status, 1-2 remaining (hi, lo), 3-4 reset_time.
# The request `limit` is echoed host-side (the kernel's limit output
# is always the request limit), so it is not read back.

PACKED_IN_ROWS = 16
PACKED_OUT_ROWS = 5


def _row64(pin: jax.Array, hi_row: int, lo_row: int) -> jax.Array:
    """Recombine (hi, lo) int32 word rows into int64 (two's complement)."""
    return (pin[hi_row].astype(_I64) << 32) | (pin[lo_row].astype(_I64) & 0xFFFFFFFF)


def _unpack_in(pin: jax.Array) -> tuple[BatchInput, jax.Array]:
    batch = BatchInput(
        slot=pin[1],
        algo=pin[2],
        behavior=pin[3],
        hits=_row64(pin, 4, 5),
        limit=_row64(pin, 6, 7),
        duration=_row64(pin, 8, 9),
        burst=_row64(pin, 10, 11),
        greg_duration=_row64(pin, 12, 13),
        greg_expire=_row64(pin, 14, 15),
    )
    now = (pin[0, 0].astype(_I64) << 32) | (pin[0, 1].astype(_I64) & 0xFFFFFFFF)
    return batch, now


def _pack_out(status: jax.Array, rem: jax.Array, reset: jax.Array) -> jax.Array:
    # int64→int32 astype truncates to the low word (numpy/XLA C-cast
    # semantics) — exactly the bit split the host recombines.
    return jnp.stack(
        [
            status.astype(_I32),
            (rem >> 32).astype(_I32),
            rem.astype(_I32),
            (reset >> 32).astype(_I32),
            reset.astype(_I32),
        ]
    )


def pack_batch_host(
    size: int,
    now_ms: int,
    capacity: int,
    slot_sorted: np.ndarray,  # int32 [m] sorted ascending
    algo: np.ndarray,
    behavior: np.ndarray,
    hits: np.ndarray,
    limit: np.ndarray,
    duration: np.ndarray,
    burst: np.ndarray,
    greg_duration: np.ndarray,
    greg_expire: np.ndarray,
    out: np.ndarray | None = None,  # reusable [PACKED_IN_ROWS, size] int32
) -> np.ndarray:
    """Build the packed input buffer on the host (vectorized numpy).

    Lanes beyond `len(slot_sorted)` are padding: distinct ascending
    out-of-range slots, zero fields."""
    m = len(slot_sorted)
    if out is None:
        out = np.zeros((PACKED_IN_ROWS, size), dtype=np.int32)
    else:
        out[:, m:] = 0
    out[0, 0] = (np.int64(now_ms) >> 32).astype(np.int32)
    out[0, 1] = np.int64(now_ms).astype(np.int32)  # low-word bit pattern
    out[1, :m] = slot_sorted
    if size > m:
        out[1, m:] = (
            np.arange(capacity, capacity + (size - m), dtype=np.int64)
            .astype(np.int32)
        )
    out[2, :m] = algo
    out[3, :m] = behavior

    def w64(hi_row, lo_row, col):
        c = col.astype(np.int64, copy=False)
        out[hi_row, :m] = (c >> 32).astype(np.int32)
        out[lo_row, :m] = c.astype(np.int32)  # low-word bit pattern

    w64(4, 5, hits)
    w64(6, 7, limit)
    w64(8, 9, duration)
    w64(10, 11, burst)
    w64(12, 13, greg_duration)
    w64(14, 15, greg_expire)
    return out


def unpack_out_host(arr: np.ndarray, m: int) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Packed output rows → (status int32[m], remaining i64[m], reset i64[m])."""
    status = arr[0, :m]
    rem = (arr[1, :m].astype(np.int64) << 32) | (
        arr[2, :m].astype(np.int64) & 0xFFFFFFFF
    )
    reset = (arr[3, :m].astype(np.int64) << 32) | (
        arr[4, :m].astype(np.int64) & 0xFFFFFFFF
    )
    return status, rem, reset


# guberlint: shapes pin [PACKED_IN_ROWS, W] int32, W on the pow2 width ladder; state fixed at capacity
def _fused_step_core(state: BucketState, pin: jax.Array):
    batch, now = _unpack_in(pin)
    new_state, resp_status, resp_rem, resp_reset = _apply_core(
        state,
        state.meta,
        batch.slot,
        batch.algo,
        batch.behavior,
        batch.hits,
        batch.limit,
        batch.duration,
        batch.burst,
        batch.greg_duration,
        batch.greg_expire,
        now,
    )
    return new_state, _pack_out(resp_status, resp_rem, resp_reset)


# Fused gather→update→scatter with donated state: ONE device op per
# round.  Whether XLA compiles the in-place RMW without cloning the
# state is platform-dependent — callers MUST check `fused_step_ok()`
# (memory_analysis probe) and fall back to the split pair below.
fused_step = jax.jit(_fused_step_core, donate_argnums=(0,))


# guberlint: shapes pins [R, PACKED_IN_ROWS, W], R in {2,4,8,16} (pump rounds up), W on the width ladder
def _multi_fused_core(state: BucketState, pins: jax.Array):
    """R packed rounds applied SEQUENTIALLY in one device program.

    pins int32 [R, PACKED_IN_ROWS, W] → outputs [R, PACKED_OUT_ROWS, W].
    lax.scan preserves the per-slot sequential semantics the rounds
    scheme guarantees per step, while collapsing R execute RPCs + R
    readbacks into ONE of each — the tunneled backend charges ~10ms per
    execute and ~25-40ms per readback regardless of payload
    (scripts/probe_tunnel.py), so RPC count is the throughput ceiling,
    not FLOPs.  Padding rounds (all lanes out of range) are no-ops by
    the same mechanism as padding lanes."""

    def body(st, pin):
        return _fused_step_core(st, pin)

    state, pouts = jax.lax.scan(body, state, pins)
    return state, pouts


multi_fused_step = jax.jit(_multi_fused_core, donate_argnums=(0,))


# ---------------------------------------------------------------------------
# Uniform-batch narrow format.
#
# The tunneled backend moves ~75MB/s host→device and ~20MB/s device→
# host (scripts/probe_transfer_api.py), so the 16-row packed input
# (64B/decision) + 5-row output (20B/decision) cap serving at ~500k
# decisions/s REGARDLESS of compute.  Real traffic is overwhelmingly
# "one limit config, many keys" (the reference's request shape too:
# same name/limit/duration across a client's batch), and such batches
# need only THE SLOT per lane uphill and status+remaining+reset
# downhill:
#
#   pin  int32 [2, W]: row0 header
#        [now_hi, now_lo, algo, behavior, hits_hi, hits_lo,
#         limit, duration_lo, burst, duration_hi]  (scalars, W >= 64)
#        row1 slot (sorted; padding = cap + lane)
#   pout int32 [2, W]: row0 = (status << 31) | remaining
#        (remaining < 2^31 — guaranteed by the uniformity gate
#         limit, burst < 2^31), row1 = reset_time - now (< duration
#        < 2^31 by the gate).
#
# 4B up + 8B down per decision → ~2.2M dec/s transport ceiling.
# Host-side gating (engine._uniform_cols): no Gregorian, all config
# columns constant, limit/duration/burst < 2^31.

UNIFORM_IN_ROWS = 2
UNIFORM_OUT_ROWS = 2


def pack_uniform_host(
    size: int,
    now_ms: int,
    capacity: int,
    slot_sorted: np.ndarray,  # int32 [m] sorted ascending
    algo: int,
    behavior: int,
    hits: int,
    limit: int,
    duration: int,
    burst: int,
    out: np.ndarray | None = None,
) -> np.ndarray:
    m = len(slot_sorted)
    if out is None:
        out = np.zeros((UNIFORM_IN_ROWS, size), dtype=np.int32)
    else:
        out[:, m:] = 0
    hdr = out[0]
    hdr[0] = (np.int64(now_ms) >> 32).astype(np.int32)
    hdr[1] = np.int64(now_ms).astype(np.int32)
    hdr[2] = algo
    hdr[3] = behavior
    hdr[4] = (np.int64(hits) >> 32).astype(np.int32)
    hdr[5] = np.int64(hits).astype(np.int32)
    hdr[6] = limit
    hdr[7] = np.int64(duration).astype(np.int32)
    hdr[8] = burst
    hdr[9] = (np.int64(duration) >> 32).astype(np.int32)
    out[1, :m] = slot_sorted
    if size > m:
        out[1, m:] = (
            np.arange(capacity, capacity + (size - m), dtype=np.int64)
            .astype(np.int32)
        )
    return out


# guberlint: shapes pin [UNIFORM_IN_ROWS, W] int32, W on the pow2 width ladder; state fixed at capacity
def _uniform_step_core(state: BucketState, pin: jax.Array):
    hdr = pin[0]
    now = (hdr[0].astype(_I64) << 32) | (hdr[1].astype(_I64) & 0xFFFFFFFF)
    w = pin.shape[1]
    slot = pin[1]

    def bc(x):
        return jnp.full((w,), x)

    algo = bc(hdr[2])
    behavior = bc(hdr[3])
    hits = bc((hdr[4].astype(_I64) << 32) | (hdr[5].astype(_I64) & 0xFFFFFFFF))
    limit = bc(hdr[6].astype(_I64))
    duration = bc(
        (hdr[9].astype(_I64) << 32) | (hdr[7].astype(_I64) & 0xFFFFFFFF)
    )
    burst = bc(hdr[8].astype(_I64))
    zeros = jnp.zeros((w,), dtype=_I64)
    new_state, status, rem, reset = _apply_core(
        state, state.meta, slot, algo, behavior, hits, limit,
        duration, burst, zeros, zeros, now,
    )
    pout = jnp.stack(
        [
            (
                (status.astype(_I64) << 31) | (rem & 0x7FFFFFFF)
            ).astype(_I32),
            (reset - now).astype(_I32),
        ]
    )
    return new_state, pout


uniform_step = jax.jit(_uniform_step_core, donate_argnums=(0,))


# guberlint: shapes pins [R, UNIFORM_IN_ROWS, W], R in {2,4,8,16}; W on the width ladder
def _multi_uniform_core(state: BucketState, pins: jax.Array):
    def body(st, pin):
        return _uniform_step_core(st, pin)

    state, pouts = jax.lax.scan(body, state, pins)
    return state, pouts


multi_uniform_step = jax.jit(_multi_uniform_core, donate_argnums=(0,))


def unpack_uniform_out_host(
    arr: np.ndarray, m: int, now_ms: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Narrow output rows → (status, remaining, reset) like
    unpack_out_host (the status/remaining packing is sign-safe via a
    uint32 view)."""
    u = arr[0, :m].view(np.uint32)
    status = (u >> 31).astype(np.int32)
    rem = (u & 0x7FFFFFFF).astype(np.int64)
    reset = arr[1, :m].astype(np.int64) + now_ms
    return status, rem, reset


@functools.lru_cache(maxsize=None)
def multi_step_ok(capacity: int, rounds: int = 2, width: int = 64) -> bool:
    """Probe whether the scanned multi-round program keeps the donated
    state in place (see fused_step_ok — a scan that clones the state
    per iteration would be O(R·capacity) memory)."""
    try:
        state_sds = jax.eval_shape(lambda: make_state(capacity))
        pins_sds = jax.ShapeDtypeStruct(
            (rounds, PACKED_IN_ROWS, width), jnp.int32
        )
        compiled = multi_fused_step.lower(state_sds, pins_sds).compile()
        ma = compiled.memory_analysis()
        if ma is None:
            return False
        state_bytes = sum(
            int(np.prod(l.shape)) * l.dtype.itemsize
            for l in jax.tree.leaves(state_sds)
        )
        return int(ma.temp_size_in_bytes) < max(state_bytes // 4, 1 << 20)
    except Exception:
        return False


# guberlint: shapes pin [PACKED_IN_ROWS, W] int32, W on the pow2 width ladder; state fixed at capacity
def _packed_compute_core(state: BucketState, pin: jax.Array):
    batch, now = _unpack_in(pin)
    vals, resp_status, resp_rem, resp_reset = _compute_update(
        state,
        state.meta,
        batch.slot,
        batch.algo,
        batch.behavior,
        batch.hits,
        batch.limit,
        batch.duration,
        batch.burst,
        batch.greg_duration,
        batch.greg_expire,
        now,
    )
    # `slot` is returned as a device output so the follow-up
    # scatter_store needs no second host transfer.
    return batch.slot, vals, _pack_out(resp_status, resp_rem, resp_reset)


# Split pair: read-only compute (no donation) + donated write-only
# scatter_store — two device ops, guaranteed copy-free everywhere.
packed_compute = jax.jit(_packed_compute_core)


# ---------------------------------------------------------------------------
# Collapsed duplicate-segment step.
#
# A batch with a hot key repeated m times would cost m serialization
# rounds (m device dispatches) under the rounds scheme — Zipf traffic
# measured 12k dec/s on the zipf bench config because of exactly this.
# When every occurrence of a key in the batch carries IDENTICAL request
# fields (the overwhelmingly common case — same limit/duration/hits per
# client), the sequential semantics have a CLOSED FORM:
#
#   After the first application (handled by the full _compute_update),
#   the remaining m-1 occurrences see an existing item with unchanged
#   config and zero elapsed time (same `now`), so each either consumes
#   `h` or is rejected without consuming.  With R1 = remaining after
#   the first application, the number of accepted extras is
#   a2 = clip(R1 // h, 0, m-1) (all, for h <= 0), occurrence p
#   (0-based among extras) responds
#     accepted (p < a2):  remaining R1-(p+1)h, sticky/UNDER status
#     rejected:           remaining R1-a2·h, OVER
#   and the stored remaining is R1 - a2·h.  The token bucket's sticky
#   status flips to OVER iff some extra actually saw remaining==0
#   (h > 0, R1-a2·h == 0, a2 < m-1).  The leaky bucket is identical
#   over floor(rem_f) with reset_time = now + (limit - rem_resp)·rate.
#
# One dispatch therefore serves ALL duplicates exactly; the kernel
# fuzz (tests/test_collapse.py) pins equality with the sequential
# scalar spec.  Segments with RESET_REMAINING, mid-batch slot reuse
# (eviction rounds > 0), or non-uniform fields fall back to rounds.
#
# Packed layout (int32 [COLLAPSED_IN_ROWS, W]):
#   row 0   header [now_hi, now_lo]
#   rows 1-16   SEGMENT level (first S lanes real; padding = m 0 +
#               ascending out-of-range slots): slot, m, algo, behavior,
#               hits, limit, duration, burst, greg_dur, greg_exp
#               (64-bit as hi/lo pairs)
#   row 17  lane → segment index;  row 18  lane → position in segment
# Output rows are PACKED_OUT_ROWS, lane order.

COLLAPSED_IN_ROWS = 19


# guberlint: shapes pin [COLLAPSED_IN_ROWS, W] int32, W on the pow2 width ladder; state fixed at capacity
def _collapsed_values(state: BucketState, pin: jax.Array):
    now = (pin[0, 0].astype(_I64) << 32) | (pin[0, 1].astype(_I64) & 0xFFFFFFFF)
    slot = pin[1]
    m = pin[2].astype(_I64)
    s_algo = pin[3]
    s_beh = pin[4]

    def r64(hi, lo):
        return (pin[hi].astype(_I64) << 32) | (pin[lo].astype(_I64) & 0xFFFFFFFF)

    s_hits = r64(5, 6)
    s_limit = r64(7, 8)
    s_dur = r64(9, 10)
    s_burst = r64(11, 12)
    s_gdur = r64(13, 14)
    s_gexp = r64(15, 16)
    seg = pin[17]
    pos = pin[18].astype(_I64)

    # First application per segment: the full bucket update.
    vals, st1, rem1, rst1 = _compute_update(
        state, state.meta, slot, s_algo, s_beh, s_hits, s_limit,
        s_dur, s_burst, s_gdur, s_gexp, now,
    )

    extras = jnp.maximum(m - 1, 0)
    h = s_hits
    h_safe = jnp.maximum(h, 1)
    is_tok = s_algo == int(Algorithm.TOKEN_BUCKET)

    # Token extras.
    R1 = vals.remaining
    a2_tok = jnp.where(h > 0, jnp.clip(R1 // h_safe, 0, extras), extras)
    rem2_tok = R1 - a2_tok * h
    sticky_over = (h > 0) & (rem2_tok == 0) & (a2_tok < extras)
    status2 = jnp.where(sticky_over & is_tok, _OVER, vals.status).astype(_I32)

    # Leaky extras (over floor of the fixed-point remaining).
    W1f = vals.rem_f
    W1 = W1f.astype(_I64)
    a2_lk = jnp.where(h > 0, jnp.clip(W1 // h_safe, 0, extras), extras)
    rem2_lkf = W1f - (a2_lk * h).astype(_F64)

    vals2 = vals._replace(
        remaining=jnp.where(is_tok, rem2_tok, vals.remaining),
        status=status2,
        rem_f=jnp.where(is_tok, vals.rem_f, rem2_lkf),
    )

    # Leaky reset slope (same formula as _compute_update's lk_rate_i).
    lk_D = jnp.where((s_beh & int(Behavior.DURATION_IS_GREGORIAN)) != 0, s_gdur, s_dur)
    limit_pos = s_limit > 0
    lk_rate = f64_div(
        lk_D.astype(_F64), jnp.where(limit_pos, s_limit, 1).astype(_F64)
    )
    lk_rate_i = jnp.where(limit_pos, lk_rate, 0.0).astype(_I64)

    # Lane-level responses.
    def g(x):
        return x[seg]

    p = jnp.maximum(pos - 1, 0)
    first = pos == 0
    l_tok = g(is_tok)
    l_h = g(h)

    acc_tok = p < g(a2_tok)
    rem_tok = jnp.where(acc_tok, g(R1) - (p + 1) * l_h, g(rem2_tok))
    st_tok = jnp.where(acc_tok, g(vals.status), _OVER)
    rst_tok = g(vals.expire)

    acc_lk = p < g(a2_lk)
    rem_lk = jnp.where(acc_lk, g(W1) - (p + 1) * l_h, g(W1 - a2_lk * h))
    st_lk = jnp.where(acc_lk, _UNDER, _OVER)
    rst_lk = now + (g(s_limit) - rem_lk) * g(lk_rate_i)

    o_status = jnp.where(first, g(st1), jnp.where(l_tok, st_tok, st_lk))
    o_rem = jnp.where(first, g(rem1), jnp.where(l_tok, rem_tok, rem_lk))
    o_reset = jnp.where(first, g(rst1), jnp.where(l_tok, rst_tok, rst_lk))
    return slot, vals2, _pack_out(o_status.astype(_I32), o_rem, o_reset)


def token_extras_host(R1: int, h: int, extras: int) -> tuple[int, int, bool]:
    """Host-scalar twin of the token branch of `_collapsed_values`:
    given remaining R1 after the first application, `extras` further
    occurrences each consuming `h` admit
    a2 = clip(R1 // h, 0, extras) of them (all, for h <= 0), leaving
    rem2 = R1 - a2*h, with the sticky status flipping OVER iff some
    extra actually saw remaining==0.  Returns (a2, rem2, sticky_over).

    The decision ledger (core/ledger.py) drains its credit leases with
    this same algebra — one source of truth for the closed form the
    kernel fuzz pins (tests/test_collapse.py, tests/test_ledger.py)."""
    if h > 0:
        a2 = min(max(R1 // h, 0), extras)
    else:
        a2 = extras
    rem2 = R1 - a2 * h
    sticky = h > 0 and rem2 == 0 and a2 < extras
    return a2, rem2, sticky


# guberlint: shapes pin [COLLAPSED_IN_ROWS, W] int32, W on the pow2 width ladder; state fixed at capacity
def _collapsed_step_core(state: BucketState, pin: jax.Array):
    slot, vals2, packed = _collapsed_values(state, pin)
    return _scatter_values(state, slot, vals2), packed


# Fused (donated RMW) and split variants, mirroring fused_step /
# packed_compute — the engine picks by the same fused_step_ok probe.
collapsed_step = jax.jit(_collapsed_step_core, donate_argnums=(0,))
collapsed_compute = jax.jit(_collapsed_values)


def pack_collapsed_host(
    size: int,
    now_ms: int,
    capacity: int,
    uniq_slots: np.ndarray,  # int32 [S] sorted unique
    counts: np.ndarray,  # int64 [S]
    seg_fields: tuple,  # (algo, behavior, hits, limit, duration, burst,
    #                      greg_dur, greg_exp) per segment, [S]
    seg_idx: np.ndarray,  # int32 [m_lanes]
    pos: np.ndarray,  # int32 [m_lanes]
    out: np.ndarray | None = None,  # reusable [COLLAPSED_IN_ROWS, size]
) -> np.ndarray:
    """Host packer for the collapsed step (layout above)."""
    s_count = len(uniq_slots)
    n_lanes = len(seg_idx)
    if out is None:
        out = np.zeros((COLLAPSED_IN_ROWS, size), dtype=np.int32)
    else:
        out[:] = 0
    out[0, 0] = (np.int64(now_ms) >> 32).astype(np.int32)
    out[0, 1] = np.int64(now_ms).astype(np.int32)
    out[1, :s_count] = uniq_slots
    if size > s_count:
        out[1, s_count:] = np.arange(
            capacity, capacity + (size - s_count), dtype=np.int64
        ).astype(np.int32)
    out[2, :s_count] = counts.astype(np.int32)
    algo, behavior, hits, limit, duration, burst, gdur, gexp = seg_fields
    out[3, :s_count] = algo
    out[4, :s_count] = behavior

    def w64(hi_row, lo_row, col):
        c = col.astype(np.int64, copy=False)
        out[hi_row, :s_count] = (c >> 32).astype(np.int32)
        out[lo_row, :s_count] = c.astype(np.int32)

    w64(5, 6, hits)
    w64(7, 8, limit)
    w64(9, 10, duration)
    w64(11, 12, burst)
    w64(13, 14, gdur)
    w64(15, 16, gexp)
    out[17, :n_lanes] = seg_idx
    # Padding lanes point at the last padding segment (m=0, harmless).
    if size > n_lanes:
        out[17, n_lanes:] = size - 1
    out[18, :n_lanes] = pos
    return out


@functools.lru_cache(maxsize=None)
def fused_step_ok(capacity: int, width: int = 64) -> bool:
    """Probe whether `fused_step` compiles to a true in-place update.

    Compiles the fused program at this capacity (tiny width) and reads
    XLA's memory analysis: if temp allocations are a fraction of the
    state size, donation aliased the buffers and no O(capacity) copy
    was inserted.  On backends where copy-insertion clones the state
    (measured 18 full-capacity copies in round 1 of this build), temp
    ≈ state size and callers must use the split pair instead."""
    try:
        state_sds = jax.eval_shape(lambda: make_state(capacity))
        pin_sds = jax.ShapeDtypeStruct((PACKED_IN_ROWS, width), jnp.int32)
        compiled = fused_step.lower(state_sds, pin_sds).compile()
        ma = compiled.memory_analysis()
        if ma is None:
            return False
        state_bytes = sum(
            int(np.prod(l.shape)) * l.dtype.itemsize
            for l in jax.tree.leaves(state_sds)
        )
        return int(ma.temp_size_in_bytes) < max(state_bytes // 4, 1 << 20)
    except Exception:
        return False


class SlotRecord(NamedTuple):
    """Persisted bucket values for restoring slots (Store.get /
    Loader.load hydration), shape [C] per field.

    `remf` carries the leaky remaining as 32.32 fixed point words so a
    Loader snapshot round-trips bit-exactly."""

    slot: jax.Array  # int32; padding = out-of-range ascending
    algo: jax.Array  # int32
    status: jax.Array  # int32
    limit: jax.Array  # int64
    remaining: jax.Array  # int64   (token)
    remf_hi: jax.Array  # int32    (leaky whole)
    remf_lo: jax.Array  # uint32   (leaky fraction)
    duration: jax.Array  # int64
    t0: jax.Array  # int64
    expire_at: jax.Array  # int64
    burst: jax.Array  # int64
    invalid_at: jax.Array  # int64


# guberlint: shapes rec columns padded to pow2 (build_restore_record _pad_size); state fixed at capacity
def _load_slots_impl(state: BucketState, rec: SlotRecord) -> BucketState:
    """Hydrate persisted bucket values into their slots.

    The scatter contract matches the apply kernel: `rec.slot` sorted,
    unique, padding out-of-range (dropped)."""

    def put(arr, vals):
        return arr.at[rec.slot].set(
            vals, mode="drop", indices_are_sorted=True, unique_indices=True
        )

    def put64(hi, lo, v):
        vh, vl = split_i64(v)
        return put(hi, vh), put(lo, vl)

    cap = state.meta.shape[0]
    algo_norm = (rec.algo != 0).astype(_I32)
    t0c = clamp_ts(rec.t0)
    invc = clamp_ts(rec.invalid_at)
    expc = clamp_ts(rec.expire_at)
    durc = clamp_ts(rec.duration)
    meta_v = pack_meta(
        (rec.slot < cap), algo_norm, rec.status, t0c, invc
    )
    hi2_v = pack_hi2(expc, durc)
    tok_hi, tok_lo = split_i64(rec.remaining)
    leaky = algo_norm == 1
    rem_hi_v = jnp.where(leaky, rec.remf_hi, tok_hi)
    rem_lo_v = jnp.where(leaky, rec.remf_lo, tok_lo)
    limit_hi, limit_lo = put64(state.limit_hi, state.limit_lo, rec.limit)
    burst_hi, burst_lo = put64(state.burst_hi, state.burst_lo, rec.burst)
    return state._replace(
        meta=put(state.meta, meta_v),
        hi2=put(state.hi2, hi2_v),
        t0_lo=put(state.t0_lo, (t0c & 0xFFFFFFFF).astype(_U32)),
        expire_lo=put(state.expire_lo, (expc & 0xFFFFFFFF).astype(_U32)),
        invalid_lo=put(state.invalid_lo, (invc & 0xFFFFFFFF).astype(_U32)),
        duration_lo=put(
            state.duration_lo, (durc & 0xFFFFFFFF).astype(_U32)
        ),
        limit_hi=limit_hi,
        limit_lo=limit_lo,
        rem_hi=put(state.rem_hi, rem_hi_v),
        rem_lo=put(state.rem_lo, rem_lo_v.astype(_U32)),
        burst_hi=burst_hi,
        burst_lo=burst_lo,
    )


load_slots = jax.jit(_load_slots_impl, donate_argnums=(0,))


# ----------------------------------------------------------------------
# Paged-state page transfer helpers (core/paging.py; PERF.md §30).
#
# A page is `page_size` consecutive rows of every state column.  Spill
# and refill move the RAW packed words — the same 12 int32/uint32
# columns the kernels read — so an evict→spill→refill roundtrip is
# bit-exact by construction (including the leaky 32.32 remaining and
# the folded hi-word packings; no decode/re-encode on the path).  One
# [PAGE_WORD_ROWS, page_size] int32 block per page keeps it to ONE d2h
# (spill, via the readback combiner) or one h2d + one donated in-place
# update (refill).  `start` is a traced device-row scalar, so each
# page size compiles exactly one gather and one load program.

PAGE_WORD_ROWS = len(BucketState._fields)  # 12 — one row per column


# guberlint: shapes state fixed at device capacity; start scalar device row; page_size static — one program per page size
@functools.partial(jax.jit, static_argnums=(2,))
def gather_page_words(
    state: BucketState, start: jax.Array, page_size: int
) -> jax.Array:
    """One page's raw column words as [PAGE_WORD_ROWS, page_size]
    int32 (uint32 columns bitcast, not converted)."""
    rows = []
    for name in BucketState._fields:
        col = getattr(state, name)
        sl = jax.lax.dynamic_slice_in_dim(col, start, page_size)
        if sl.dtype != jnp.int32:
            sl = jax.lax.bitcast_convert_type(sl, jnp.int32)
        rows.append(sl)
    return jnp.stack(rows)


# guberlint: shapes words fixed [PAGE_WORD_ROWS, page_size] per plane; state fixed at device capacity
def _load_page_words_impl(
    state: BucketState, start: jax.Array, words: jax.Array
) -> BucketState:
    """Write a page's raw words back into the state columns at device
    row `start` — the refill half of the spill roundtrip."""
    new = {}
    for i, name in enumerate(BucketState._fields):
        col = getattr(state, name)
        row = words[i]
        if col.dtype != jnp.int32:
            row = jax.lax.bitcast_convert_type(row, col.dtype)
        new[name] = jax.lax.dynamic_update_slice_in_dim(
            col, row, start, axis=0
        )
    return BucketState(**new)


load_page_words = jax.jit(_load_page_words_impl, donate_argnums=(0,))


def batch_input_from_numpy(
    slot: np.ndarray,
    algo: np.ndarray,
    behavior: np.ndarray,
    hits: np.ndarray,
    limit: np.ndarray,
    duration: np.ndarray,
    burst: np.ndarray,
    greg_duration: np.ndarray,
    greg_expire: np.ndarray,
) -> BatchInput:
    return BatchInput(
        slot=jnp.asarray(slot, dtype=_I32),
        algo=jnp.asarray(algo, dtype=_I32),
        behavior=jnp.asarray(behavior, dtype=_I32),
        hits=jnp.asarray(hits, dtype=_I64),
        limit=jnp.asarray(limit, dtype=_I64),
        duration=jnp.asarray(duration, dtype=_I64),
        burst=jnp.asarray(burst, dtype=_I64),
        greg_duration=jnp.asarray(greg_duration, dtype=_I64),
        greg_expire=jnp.asarray(greg_expire, dtype=_I64),
    )

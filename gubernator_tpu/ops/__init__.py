"""Device-side ops: the vectorized bucket-update kernel and expiry sweep.

These replace the reference's per-request goroutine hot loop
(reference: gubernator_pool.go:193-247 + algorithms.go) with one XLA
computation over the whole batch (SURVEY.md §7.1).
"""

from gubernator_tpu.ops.bucket_kernel import (
    BucketState,
    BatchInput,
    BatchOutput,
    apply_batch,
    make_state,
)

__all__ = ["BucketState", "BatchInput", "BatchOutput", "apply_batch", "make_state"]

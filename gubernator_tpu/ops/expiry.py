"""Periodic device-side expiry sweep.

The reference's LRU expires items lazily on read and evicts on overflow
(reference: lrucache.go:112-159).  With device-resident state, lazy
expiry is already handled by the kernel's liveness check; this sweep
reclaims slots of expired buckets in bulk so the host intern table can
reuse them (SURVEY.md §7.3 item 6).

The 64-bit `expire_at < now` compare is done on the stored (hi, lo)
word pairs directly — combining to int64 would reintroduce the
O(capacity) x64 boundary shim the split layout exists to avoid
(see BucketState docstring).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.jit
def sweep_expired(
    occupied: jax.Array,
    expire_hi: jax.Array,  # int32
    expire_lo: jax.Array,  # uint32
    now_hi: jax.Array,  # int32 scalar
    now_lo: jax.Array,  # uint32 scalar
) -> tuple[jax.Array, jax.Array]:
    """Returns (new_occupied, freed_mask)."""
    lt = (expire_hi < now_hi) | ((expire_hi == now_hi) & (expire_lo < now_lo))
    freed = occupied & lt
    return occupied & ~freed, freed

"""Periodic device-side expiry sweep.

The reference's LRU expires items lazily on read and evicts on overflow
(reference: lrucache.go:112-159).  With device-resident state, lazy
expiry is already handled by the kernel's liveness check; this sweep
reclaims slots of expired buckets in bulk so the host intern table can
reuse them (SURVEY.md §7.3 item 6).

Scaling (VERDICT r1 item 4): the round-1 sweep returned the full freed
MASK, forcing an O(capacity) device→host transfer per sweep (~100MB at
100M slots).  `sweep_window_scan` instead processes a fixed-width
window and compacts freed indices ON DEVICE (stable argsort puts freed
lanes first), so the host pulls one count scalar per window and then
only `count` indices — transfer is O(freed), not O(capacity).  The
meta buffer is donated on commit, so the windowed update is in-place:
device work per call is O(window).

With the packed layout (BucketState docstring) occupancy is meta bit 0
and the expire hi word is hi2 bits 0-10; the 64-bit `expire_at < now`
compare runs on the (hi-word, lo-word) pair directly — combining to
int64 across the window would reintroduce the O(capacity) x64 boundary
shim the split layout exists to avoid.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

# Layout constants live in ONE place (bucket_kernel); masking with a
# local copy would silently free wrong slots if the packing ever moved.
from gubernator_tpu.ops.bucket_kernel import _HI11


# guberlint: shapes columns [..., capacity] fixed at engine build; window static (SWEEP_WINDOW)
@partial(jax.jit, static_argnames=("window",))
def sweep_window_scan(
    meta: jax.Array,  # int32 [..., capacity]
    hi2: jax.Array,  # int32 [..., capacity]
    expire_lo: jax.Array,  # uint32 [..., capacity]
    now_hi: jax.Array,  # int32 scalar (now_ms >> 32; fits 11 bits)
    now_lo: jax.Array,  # uint32 scalar
    start: jax.Array,  # int32 scalar, window start (pre-clamped by host)
    *,
    window: int,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """READ-ONLY scan of `[start, start+window)` along the capacity axis.

    Returns (meta_window_new, freed_order, count): `meta_window_new`
    is the window's meta words with freed slots' occupied bit cleared;
    `freed_order[..., :count]` are the window-local indices of freed
    slots in ascending order (stable argsort compaction); entries
    beyond `count` are arbitrary non-freed lanes and must be ignored.
    Pair with `sweep_window_commit` — the read/write split keeps the
    donated commit copy-free (the fused slice+update variant forced a
    full meta copy per window).
    """
    axis = meta.ndim - 1
    meta_w = lax.dynamic_slice_in_dim(meta, start, window, axis)
    hi2_w = lax.dynamic_slice_in_dim(hi2, start, window, axis)
    elo_w = lax.dynamic_slice_in_dim(expire_lo, start, window, axis)
    occ_w = (meta_w & 1) != 0
    ehi_w = hi2_w & _HI11
    lt = (ehi_w < now_hi) | ((ehi_w == now_hi) & (elo_w < now_lo))
    freed = occ_w & lt
    count = jnp.sum(freed, axis=axis, dtype=jnp.int32)
    # Compaction: freed lanes (True) sort before kept lanes, stable →
    # ascending window-local index order.
    order = jnp.argsort(~freed, axis=axis, stable=True).astype(jnp.int32)
    return jnp.where(freed, meta_w & ~1, meta_w), order, count


# guberlint: shapes meta [..., capacity] fixed; meta_window [..., SWEEP_WINDOW] fixed per capacity
@partial(jax.jit, donate_argnums=(0,))
def sweep_window_commit(
    meta: jax.Array,  # int32 [..., capacity] (donated)
    meta_window: jax.Array,  # int32 [..., window]
    start: jax.Array,  # int32 scalar
) -> jax.Array:
    """WRITE-ONLY in-place commit of a scanned window's meta words."""
    return lax.dynamic_update_slice_in_dim(
        meta, meta_window, start, meta.ndim - 1
    )


def windowed_sweep(engine, cap: int, now_ms: int, max_windows, release) -> int:
    """Drive scan/commit windows over an engine's state.

    Shared by DecisionEngine.sweep and ShardedDecisionEngine.sweep (the
    clamp/overlap/cursor-wrap logic is subtle enough to exist once).
    `engine` supplies `_state`, `_sweep_cursor`, `SWEEP_WINDOW`; the
    caller holds the engine lock.  `release(order, count, start) -> n`
    frees the compacted slots in the host table(s) and returns how many.
    """
    window = min(cap, engine.SWEEP_WINDOW)
    n_windows = (cap + window - 1) // window
    if max_windows is not None:
        n_windows = min(n_windows, max_windows)
    now_hi = jnp.asarray(now_ms >> 32, dtype=jnp.int32)
    now_lo = jnp.asarray(now_ms & 0xFFFFFFFF, dtype=jnp.uint32)
    freed_total = 0
    for _ in range(n_windows):
        # Clamp the tail window; overlap is idempotent (slots freed
        # earlier in this pass are no longer occupied).
        start = min(engine._sweep_cursor, cap - window)
        start_dev = jnp.asarray(start, dtype=jnp.int32)
        meta_w, order, count = sweep_window_scan(
            engine._state.meta,
            engine._state.hi2,
            engine._state.expire_lo,
            now_hi,
            now_lo,
            start_dev,
            window=window,
        )
        engine._state = engine._state._replace(
            meta=sweep_window_commit(engine._state.meta, meta_w, start_dev)
        )
        freed_total += release(order, count, start)
        engine._sweep_cursor += window
        if engine._sweep_cursor >= cap:
            engine._sweep_cursor = 0
    return freed_total


# guberlint: shapes full-capacity columns fixed at engine build (legacy one-shot sweep)
@jax.jit
def sweep_expired(
    meta: jax.Array,  # int32
    hi2: jax.Array,  # int32
    expire_lo: jax.Array,  # uint32
    now_hi: jax.Array,  # int32 scalar
    now_lo: jax.Array,  # uint32 scalar
) -> tuple[jax.Array, jax.Array]:
    """Full-capacity sweep returning (new_meta, freed_mask).

    Kept for small-capacity callers and tests; production engines use
    the windowed compaction above."""
    occ = (meta & 1) != 0
    ehi = hi2 & _HI11
    lt = (ehi < now_hi) | ((ehi == now_hi) & (expire_lo < now_lo))
    freed = occ & lt
    return jnp.where(freed, meta & ~1, meta), freed

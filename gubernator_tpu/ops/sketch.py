"""Count-min-sketch rate limiting: approximate decisions at unbounded
key cardinality (BASELINE config 5 stretch; no reference counterpart —
the reference caps state at its LRU size and evicts, store.go/lrucache
.go, while a sketch answers for EVERY key in O(1) memory with a
one-sided overcount error).

TPU-first design:

- Sketch state: int32 `[depth, width]` counters in HBM, one sketch per
  fixed window duration.  Sliding behavior comes from TWO alternating
  epochs (current + previous) with linear interpolation — the classic
  sliding-window approximation, all branch-free arithmetic.
- Hashing: the host computes one fnv1a-64 per key (it already has the
  bytes); the device derives the `depth` row indexes via
  Kirsch-Mitzenmacher double hashing (h1 + r·h2) mod width — no
  per-row string hashing anywhere.
- Duplicate handling: scatter-add with arbitrary duplicate indexes
  lowers to a serial per-element loop on TPU, so the HOST pre-combines
  each row's duplicates (sort + reduce) and the device runs only
  sorted-unique gathers/scatter-adds — the same fast-path contract as
  the bucket kernel (ops/bucket_kernel.py).
- One packed int32 input `[2 + 3*depth, B]` per step (header, hits
  row, then per-row sorted unique indexes / summed hits / gather
  positions), one packed int32 output `[1, B]` (the estimate), so the
  step costs 3 device ops like the exact engine (PERF.md §4).

Estimate semantics: `est = min_r sketch[r][idx_r]` AFTER adding this
batch's hits, interpolated across the two epochs; OVER_LIMIT when
`est > limit`.  Errors are one-sided (never under-counts), matching a
rate limiter's fail-closed preference.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_I32 = jnp.int32
_I64 = jnp.int64
_U64 = np.uint64


class SketchState(NamedTuple):
    """Two-epoch count-min sketch, shape [2, depth, width] int32."""

    counts: jax.Array  # int32 [2, depth, width]
    epoch: jax.Array  # int64 scalar — window index of counts[cur]
    cur: jax.Array  # int32 scalar — which plane is the current epoch


def make_sketch(depth: int = 4, width: int = 1 << 20) -> SketchState:
    return SketchState(
        counts=jnp.zeros((2, depth, width), dtype=_I32),
        epoch=jnp.asarray(0, dtype=_I64),
        cur=jnp.asarray(0, dtype=_I32),
    )


# guberlint: shapes state planes [depth, width] fixed at sketch build; epoch_now scalar
def _rotate(state: SketchState, epoch_now: jax.Array) -> SketchState:
    """Advance to `epoch_now`: one step rotates planes (previous ←
    current, current ← zeros); a gap ≥ 2 windows zeroes both.

    The rotation is gated behind lax.cond so the COMMON step (same
    window as the last one, delta == 0) never touches the full
    [2, depth, width] state: an unconditional where-chain here cost an
    O(state) rewrite per batch — 32MB at the default shape, ~85ms per
    step on the CPU backend and pure wasted HBM bandwidth on TPU."""
    delta = epoch_now - state.epoch
    cur = state.cur

    def unchanged(counts):
        return counts, cur

    def rotate(counts):
        def one(c):
            other = 1 - cur
            return c.at[other].set(0), other.astype(_I32)

        def gap(c):
            # Both planes stale: zero everything, keep the plane index.
            return jnp.zeros_like(c), cur

        return jax.lax.cond(delta == 1, one, gap, counts)

    counts, cur2 = jax.lax.cond(
        delta <= 0, unchanged, rotate, state.counts
    )
    return SketchState(
        counts=counts,
        epoch=jnp.maximum(state.epoch, epoch_now),
        cur=cur2,
    )


def _sketch_step_impl(
    state: SketchState,
    pin: jax.Array,  # int32 [2 + 3*depth, B] (see host packer)
    depth: int,
    cur: int,
):
    # Header row 0: [epoch_hi, epoch_lo, frac_q16, ...].  Rotation is
    # NOT part of this program: the host mirrors the window epoch and
    # runs the (rare) rotate program first (SketchLimiter.apply) — an
    # in-program rotation, even lax.cond-gated, made XLA:CPU
    # materialize O(state) copies on every step (measured 69ms/step at
    # the default 32MB shape).  `cur` is STATIC (host-mirrored, two
    # compiled variants) for the same reason: a traced plane index in
    # the scatters also defeated in-place donation and kept the step
    # O(state); with static plane/row starts the program is O(batch).
    frac_q16 = pin[0, 2].astype(_I64)  # elapsed fraction of window, Q16
    width = state.counts.shape[2]
    size = pin.shape[1]
    prev = 1 - cur

    # ONE flat gather + ONE flat scatter + ONE flat gather over
    # globalized indexes (plane*depth + row)*width + idx — per-row
    # chained scatters interleaved with prev-plane gathers defeated
    # XLA:CPU's in-place donation analysis and copied the whole state
    # per step (measured 63ms at the default 32MB shape; this form
    # runs at ~0.09ms, and on TPU it is also the minimal-pass layout).
    flat = state.counts.reshape(-1)
    total = 2 * depth * width
    lanes = jnp.arange(size, dtype=_I64)
    rows64 = jnp.arange(depth, dtype=_I64)[:, None]
    idx_rows = jnp.stack(
        [pin[2 + 3 * r] for r in range(depth)]
    ).astype(_I64)  # [depth, size]; padding lanes hold width + lane
    add_rows = jnp.stack(
        [pin[2 + 3 * r + 1] for r in range(depth)]
    ).astype(_I64)
    valid = idx_rows < width
    # Padding indexes must stay unique ACROSS rows after flattening
    # (per-row `width + lane` repeats row to row), so they relocate to
    # total + row*size + lane, past every real cell.
    pad = total + rows64 * size + lanes[None, :]
    g_cur_idx = jnp.where(
        valid, (cur * depth + rows64) * width + idx_rows, pad
    ).reshape(-1)
    g_prev_idx = jnp.where(
        valid, (prev * depth + rows64) * width + idx_rows, pad
    ).reshape(-1)

    # Saturating add: gather current counters, add in int64, clamp to
    # the int32 range, scatter-set.  A plain int32 scatter-add would
    # wrap a saturated counter negative and silently turn the one-sided
    # "never under-counts" guarantee into under-counting.
    g0 = flat.at[g_cur_idx].get(
        mode="fill", fill_value=0, unique_indices=True
    )
    new_vals = jnp.clip(
        g0.astype(_I64) + add_rows.reshape(-1),
        -(2**31), 2**31 - 1,
    ).astype(_I32)
    flat = flat.at[g_cur_idx].set(
        new_vals, mode="drop", unique_indices=True
    )
    g_prev = flat.at[g_prev_idx].get(
        mode="fill", fill_value=0, unique_indices=True
    )
    # Sliding-window interpolation: prev·(1−f) + cur, in Q16.
    row_est = (
        g_prev.astype(_I64) * (65536 - frac_q16) // 65536
        + new_vals.astype(_I64)
    ).reshape(depth, size)
    est = jnp.full(size, jnp.iinfo(jnp.int64).max, dtype=_I64)
    for r in range(depth):
        pos = pin[2 + 3 * r + 2]  # lane → position into this row
        est = jnp.minimum(est, row_est[r][pos])

    new_state = SketchState(
        counts=flat.reshape(2, depth, width),
        epoch=state.epoch,
        cur=jnp.asarray(cur, dtype=_I32),
    )
    out = jnp.stack(
        [(est >> 32).astype(_I32), est.astype(_I32)]
    )  # int64 estimate as hi/lo rows
    return new_state, out


class SketchLimiter:
    """Approximate per-key rate limiter over a count-min sketch.

    One limiter = one (window_ms, depth, width) sketch; keys are
    unbounded.  `apply(keys, hits, limit)` returns (over_limit bool
    array, estimate array).  Overcounting is possible (collisions) at
    a rate bounded by ~batch_hits/width per row; undercounting is not.
    """

    def __init__(
        self,
        window_ms: int = 1_000,
        depth: int = 4,
        width: int = 1 << 20,
        *,
        seed: int = 0x9E3779B97F4A7C15,
    ):
        if depth < 1 or width < 2:
            raise ValueError("depth >= 1 and width >= 2 required")
        self.window_ms = int(window_ms)
        self.depth = depth
        self.width = width
        self._seed = np.uint64(seed)
        self._state = make_sketch(depth, width)
        # Serializes concurrent apply() calls: the step DONATES the
        # state, so two racing callers would hand the same deleted
        # buffer to the device (and even without donation the
        # read-modify-write of self._state would drop updates,
        # breaking the never-under-count contract).
        import threading

        self._lock = threading.Lock()
        # guberlint: shapes pin [rows, W] with W on the sketch pad ladder; depth static
        self._step = jax.jit(
            lambda s, pin, cur: _sketch_step_impl(s, pin, depth, cur),
            donate_argnums=(0,),
            static_argnums=(2,),
        )
        # Host mirrors of the state's window epoch and current plane:
        # apply() triggers the rotation program only when the window
        # actually advances, and passes the plane statically (see
        # _sketch_step_impl).
        self._epoch_host = 0
        self._cur_host = 0
        self._rotate_jit = jax.jit(_rotate, donate_argnums=(0,))

    # -- host packing --------------------------------------------------

    def _indexes(self, keys) -> np.ndarray:
        """[depth, B] int64 row indexes via double hashing."""
        from gubernator_tpu.hashing import fnv1a_64_batch, pack_keys

        padded, lengths = pack_keys(keys)
        return self._indexes_hashed(fnv1a_64_batch(padded, lengths))

    def _indexes_hashed(self, h1: np.ndarray) -> np.ndarray:
        """Row indexes from precomputed fnv1a-64 key hashes (the wire
        codec already hashed every key — no re-hash, no key
        materialization on the served path)."""
        h1 = np.asarray(h1, dtype=np.uint64)
        # Second hash: one multiply-xor over h1 (splitmix-style).
        h2 = (h1 ^ (h1 >> np.uint64(33))) * self._seed
        rows = np.empty((self.depth, len(h1)), dtype=np.int64)
        for r in range(self.depth):
            rows[r] = (
                (h1 + np.uint64(r) * h2) % np.uint64(self.width)
            ).astype(np.int64)
        return rows

    def apply(
        self,
        keys,
        hits: np.ndarray,
        limit: np.ndarray,
        now_ms: int,
        *,
        key_hashes: Optional[np.ndarray] = None,  # fnv1a-64 per key
    ) -> Tuple[np.ndarray, np.ndarray]:
        n = len(key_hashes) if key_hashes is not None else len(keys)
        if n == 0:
            return np.zeros(0, dtype=bool), np.zeros(0, dtype=np.int64)
        rows = (
            self._indexes_hashed(key_hashes)
            if key_hashes is not None
            else self._indexes(keys)
        )
        hits64 = np.asarray(hits, dtype=np.int64)

        size = 64
        while size < n:
            size *= 2
        pin = np.zeros((2 + 3 * self.depth, size), dtype=np.int32)
        epoch = now_ms // self.window_ms
        frac = (now_ms % self.window_ms) * 65536 // self.window_ms
        pin[0, 0] = np.int32(epoch >> 32)
        pin[0, 1] = np.int64(epoch).astype(np.int32)
        pin[0, 2] = frac
        pin[1, :n] = np.clip(hits64, -(2**31), 2**31 - 1).astype(np.int32)
        for r in range(self.depth):
            idx = rows[r]
            # Host pre-combine: unique sorted indexes + summed hits,
            # plus each lane's position into the unique array.
            uniq, inv = np.unique(idx, return_inverse=True)
            m = len(uniq)
            # Exact int64 per-index sums, clamped to int32: a hot key's
            # combined hits must not wrap negative in the int32 lane
            # (that would decrement the counter — under-counting, which
            # the one-sided error contract forbids).
            sums = np.zeros(m, dtype=np.int64)
            np.add.at(sums, inv, hits64)
            sums = np.clip(sums, -(2**31), 2**31 - 1)
            pin[2 + 3 * r, :m] = uniq.astype(np.int32)
            if size > m:
                pin[2 + 3 * r, m:] = (
                    np.arange(self.width, self.width + (size - m), dtype=np.int64)
                    .astype(np.int32)
                )
            pin[2 + 3 * r + 1, :m] = sums.astype(np.int32)
            pin[2 + 3 * r + 2, :n] = inv.astype(np.int32)

        with self._lock:
            if epoch > self._epoch_host:
                # Window advanced: run the (rare) rotation program —
                # see _sketch_step_impl for why it is not in-step.
                if epoch - self._epoch_host == 1:
                    self._cur_host ^= 1  # mirror _rotate's plane flip
                self._state = self._rotate_jit(
                    self._state, jnp.asarray(epoch, dtype=jnp.int64)
                )
                self._epoch_host = epoch
            self._state, out = self._step(
                self._state, jnp.asarray(pin), self._cur_host
            )
            arr = np.asarray(out)
        est = (arr[0, :n].astype(np.int64) << 32) | (
            arr[1, :n].astype(np.int64) & 0xFFFFFFFF
        )
        over = est > np.asarray(limit, dtype=np.int64)
        return over, est

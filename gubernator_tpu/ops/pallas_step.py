"""Fused single-kernel decision step as a Pallas program.

One `pl.pallas_call` runs the ENTIRE bucket decision for a packed
round — in-kernel gather of the touched slots' column words, the
branch-free token/leaky update, the write-back of the new words, and
the verdict/remaining/reset pack — over state columns aliased in
place (`input_output_aliases`), so the steady-state step is ONE device
program with zero intermediate HBM round trips between its phases.

The kernel shares its math with the XLA programs, by construction:

  * the lane update is `bucket_kernel.update_lanes` — the exact
    function the fused/split XLA steps call after their gather;
  * the store encoding is `bucket_kernel.encode_slot_values` — the
    exact function `_scatter_values` scatters.

Only the irregular-access halves (gather loop in, store loop out)
are kernel-specific: per-lane dynamic reads of the 12 state columns
at the lane's slot, predicated per-lane writes back (`pl.when`), with
the same fill-0 / drop semantics as the XLA gather/scatter flags.
This is the "Ragged Paged Attention" shape (PAPERS.md): scalar-driven
irregular access feeding wide vector math.

Backend reality (PERF.md §24): the leaky-bucket math needs f64
(32.32 fixed-point reconstruction), which Pallas TPU does not lower
today, so on TPU hardware the compiled probe can fail and the engine
falls back to the fused XLA program — same single-dispatch shape,
same math.  In interpret mode (`interpret=True`) the kernel runs as
traced jax ops under jit on ANY backend, which is how CPU CI pins the
kernel bit-equal to `models/spec.py` (tests/test_fused_parity.py)
without TPU hardware.  `GUBER_FUSED` selects the mode (core/engine).

Paged state (GUBER_PAGED, core/paging.py) needs NO kernel changes:
the engine translates logical slots to device rows (frame<<shift|row)
on the host before packing, so the packed buffer this kernel gathers
through already indexes the resident frame array — XLA, interpret,
and Pallas tiers all lower through the page table's indirection by
construction, exactly the paged-KV discipline of the attention kernel
this program is shaped after.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from gubernator_tpu.ops.bucket_kernel import (
    PACKED_IN_ROWS,
    PACKED_OUT_ROWS,
    BucketState,
    GatheredSlots,
    _pack_out,
    _unpack_in,
    encode_slot_values,
    update_lanes,
)

_I32 = jnp.int32

N_COLS = len(BucketState._fields)


def _fused_kernel(cap: int, width: int, pin_ref, *refs):
    """Kernel body: refs = 12 state in-refs, pout ref, 12 state
    out-refs (out aliased onto in, column for column)."""
    in_cols = refs[:N_COLS]
    pout_ref = refs[N_COLS]
    out_cols = refs[N_COLS + 1 :]

    pin = pin_ref[...]
    batch, now = _unpack_in(pin)
    slot = batch.slot
    mask = slot < cap

    # ---- gather loop: per-lane dynamic reads of the column words.
    # Padding / out-of-range lanes read index 0 and mask to fill 0 —
    # identical to the XLA gather's mode="fill" contract.
    def gather_body(i, cols):
        s = slot[i]
        valid = s < cap
        idx = jnp.where(valid, s, 0)
        return tuple(
            acc.at[i].set(
                jnp.where(valid, ref[idx], jnp.zeros((), ref.dtype))
            )
            for acc, ref in zip(cols, in_cols)
        )

    init = tuple(
        jnp.zeros((width,), dtype=ref.dtype) for ref in in_cols
    )
    gathered = jax.lax.fori_loop(0, width, gather_body, init)

    # ---- the shared vector math (bit-equal to the XLA step).
    vals, resp_status, resp_rem, resp_reset = update_lanes(
        GatheredSlots(*gathered),
        mask,
        batch.algo,
        batch.behavior,
        batch.hits,
        batch.limit,
        batch.duration,
        batch.burst,
        batch.greg_duration,
        batch.greg_expire,
        now,
    )
    words = encode_slot_values(vals)

    # ---- store loop: predicated per-lane write-back (mode="drop").
    def store_body(i, _):
        s = slot[i]
        valid = s < cap
        idx = jnp.where(valid, s, 0)

        for ref, w in zip(out_cols, words):

            @pl.when(valid)
            def _(ref=ref, w=w, idx=idx, i=i):
                ref[idx] = w[i].astype(ref.dtype)

        return 0

    jax.lax.fori_loop(0, width, store_body, 0)
    pout_ref[...] = _pack_out(resp_status, resp_rem, resp_reset)


def _build_call(cap: int, width: int, dtypes, interpret: bool):
    out_shape = tuple(
        [jax.ShapeDtypeStruct((PACKED_OUT_ROWS, width), jnp.int32)]
        + [jax.ShapeDtypeStruct((cap,), dt) for dt in dtypes]
    )
    # guberlint: shapes pin [PACKED_IN_ROWS, W] int32, W on the pow2 width ladder; state columns fixed at capacity, aliased in place
    return pl.pallas_call(
        functools.partial(_fused_kernel, cap, width),
        out_shape=out_shape,
        input_output_aliases={i + 1: i + 1 for i in range(N_COLS)},
        interpret=interpret,
    )


@functools.lru_cache(maxsize=None)
def _jitted_step(cap: int, width: int, dtypes, interpret: bool):
    call = _build_call(cap, width, dtypes, interpret)

    # guberlint: shapes state fixed at capacity; pin [PACKED_IN_ROWS, W] on the pow2 width ladder (engine warmup)
    def step(state: BucketState, pin: jax.Array):
        outs = call(pin, *state)
        return BucketState(*outs[1:]), outs[0]

    return jax.jit(step, donate_argnums=(0,))


def pallas_fused_step(
    state: BucketState, pin: jax.Array, *, interpret: bool
):
    """Drop-in twin of `bucket_kernel.fused_step`: (state, pin) →
    (new_state, packed_out), state donated/aliased in place.  One
    compiled family per (capacity, width) — widths ride the same pow2
    pad ladder as every other step program."""
    cap = state.meta.shape[0]
    width = pin.shape[1]
    dtypes = tuple(np.dtype(leaf.dtype).name for leaf in state)
    return _jitted_step(cap, width, dtypes, interpret)(state, pin)


@functools.lru_cache(maxsize=None)
def pallas_step_ok(cap: int, width: int = 64) -> bool:
    """Probe whether the COMPILED kernel lowers on this backend (TPU
    today: no — f64 in the leaky math; the engine then serves the
    fused XLA program instead).  Interpret mode needs no probe."""
    try:
        from gubernator_tpu.ops.bucket_kernel import make_state

        state_sds = jax.eval_shape(lambda: make_state(cap))
        dtypes = tuple(np.dtype(l.dtype).name for l in state_sds)
        pin_sds = jax.ShapeDtypeStruct((PACKED_IN_ROWS, width), jnp.int32)
        _jitted_step(cap, width, dtypes, False).lower(
            state_sds, pin_sds
        ).compile()
        return True
    except Exception:  # noqa: BLE001 — any lowering failure = no
        return False

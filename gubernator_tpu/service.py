"""V1Instance — the request router over the TPU decision engine.

reference: gubernator.go:46-854.  The reference walks each request item
through a goroutine maze (per-item peer pick → worker channel hop →
per-key algorithm call).  Here the router is *batch-first*, matching
how the TPU engine wants its work:

  1. validate every item (error-in-response, never error-in-RPC);
  2. one vectorized owner lookup for the whole batch (hash ring);
  3. partition: LOCAL (we own) / GLOBAL non-owner / FORWARD per peer;
  4. LOCAL items go to the engine as ONE batch (one device step per
     duplicate-key round) — the reference's worker fan-out collapses
     into the vmapped kernel;
  5. GLOBAL non-owners answer from the host status cache (owner
     broadcasts land there) and queue async hits;
  6. FORWARD items ride the per-peer batching client with the
     reference's 5-retry ownership-migration loop.

Responses keep request order exactly (reference: gubernator.go:524-531).
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import replace
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from gubernator_tpu.cluster.global_manager import GlobalManager
from gubernator_tpu.cluster.hash_ring import (
    RegionPicker,
    ReplicatedConsistentHash,
)
from gubernator_tpu.cluster.health import backoff_delay
from gubernator_tpu.cluster.multiregion import MultiRegionManager
from gubernator_tpu.cluster.peer_client import PeerClient, PeerError
from gubernator_tpu.config import BehaviorConfig, Config
from gubernator_tpu.types import (
    MAX_BATCH_SIZE,
    Algorithm,
    Behavior,
    HealthCheckResp,
    PeerInfo,
    RateLimitReq,
    RateLimitResp,
    Status,
    UpdatePeerGlobal,
    has_behavior,
)

log = logging.getLogger("gubernator_tpu.service")

# Hot-loop int constants (IntFlag ops are ~1.5µs each in CPython; see
# core/engine.py note).
_GLOBAL_I = int(Behavior.GLOBAL)
_MULTI_REGION_I = int(Behavior.MULTI_REGION)
_SKETCH_I = int(Behavior.SKETCH)
_TOKEN_I = int(Algorithm.TOKEN_BUCKET)
# Rows carrying these can never be answered from replicated leased
# credit: the ledger's precondition breakers, plus MULTI_REGION (a
# replica answer would skip the owner's region-hit queueing) and
# SKETCH (node-local approximate limiter — ownership doesn't apply).
# cluster/replication.py pins the same set on its serve probes.
_LEASE_BREAKERS = (
    int(Behavior.DURATION_IS_GREGORIAN)
    | int(Behavior.RESET_REMAINING)
    | _MULTI_REGION_I
    | _SKETCH_I
)

# Behaviors that need the dataclass path: GLOBAL (status cache + async
# queues), MULTI_REGION (region queues), Gregorian durations (per-item
# civil-time validation with error-in-response), SKETCH (the
# approximate limiter, not the bucket engine).
COLUMNAR_DISQUALIFIERS = (
    _GLOBAL_I | _MULTI_REGION_I | int(Behavior.DURATION_IS_GREGORIAN)
    | _SKETCH_I
)

HEALTHY = "healthy"
UNHEALTHY = "unhealthy"


class ServiceError(RuntimeError):
    """RPC-level error (maps to a gRPC status at the transport edge).

    The only RPC-level failure the contract allows is an oversized
    batch (reference: gubernator.go:212-216, 501-505); per-item
    problems travel in RateLimitResp.error.
    """

    def __init__(self, message: str, code: str = "OUT_OF_RANGE"):
        super().__init__(message)
        self.code = code


class _SubBatch:
    """Column view of a GLOBAL serve's engine sub-batch, shaped like a
    DecodedBatch so the group-commit window can concatenate it with
    concurrent submissions (net/wire_window.WireWindow)."""

    __slots__ = (
        "n", "key_buf", "key_offsets", "algo", "behavior", "hits",
        "limit", "duration", "burst", "fnv1a",
    )


def _slice_key_columns(key_buf: np.ndarray, key_offsets: np.ndarray, idx):
    """Vectorized sub-selection of a concatenated key buffer: returns
    (sub_buf, sub_offsets) for the items in `idx` without per-item
    Python (the GLOBAL wire route partitions batches this way)."""
    from gubernator_tpu.net.wire_codec import gather_key_slices

    lens = key_offsets[1:] - key_offsets[:-1]
    return gather_key_slices(key_buf, key_offsets[:-1][idx], lens[idx])


class _GlobalEntry:
    """One cached owner-broadcast status.  __slots__ + a hand-rolled
    __init__: broadcast receive is the cluster tier's highest-rate
    per-item loop (put_columns profiled at ~26% of a core under
    GLOBAL overload), so entry construction stays minimal."""

    __slots__ = ("resp", "algorithm", "expire_at", "cols")

    def __init__(self, resp, algorithm, expire_at, cols=()):
        self.resp = resp
        self.algorithm = algorithm
        self.expire_at = expire_at
        # (status, limit, remaining, reset) ints, preassembled at put
        # time so the columnar read does no attribute/enum work per
        # item.
        self.cols = cols


class _GlobalStatusCache:
    """Host cache of owner-broadcast GLOBAL statuses on non-owners.

    The reference stores a RateLimitResp (not bucket state) in the same
    size-bounded LRU as buckets (gubernator.go:470-490, read
    gubernator.go:440-453).  Our bucket state lives on device, so the
    non-owner overwrite dance gets its own host-side LRU with the same
    ExpireAt=ResetTime rule and capacity bound.
    """

    def __init__(self, capacity: int = 50_000) -> None:
        from collections import OrderedDict

        self.capacity = capacity
        # Keyed by the hash key BYTES: the columnar wire path reads
        # keys straight out of the decoded key buffer without ever
        # materializing Python strings.
        self._items: "OrderedDict[bytes, _GlobalEntry]" = OrderedDict()
        self._lock = threading.Lock()

    @staticmethod
    def _k(key) -> bytes:
        return key.encode() if isinstance(key, str) else key

    def get(self, key, now_ms: int) -> Optional[RateLimitResp]:
        with self._lock:
            return self._get_locked(self._k(key), now_ms)

    def get_many(
        self, keys: Sequence, now_ms: int
    ) -> List[Optional[RateLimitResp]]:
        """Batch lookup under ONE lock acquisition (VERDICT r1 weak 8:
        a lock per item on the GLOBAL read path becomes a contention
        point at wire batch sizes)."""
        with self._lock:
            return [self._get_locked(self._k(k), now_ms) for k in keys]

    def get_columns(self, keys: List[bytes], now_ms: int):
        """Columnar lookup: (hit bool[n], status i32[n], limit i64[n],
        remaining i64[n], reset i64[n]) — the GLOBAL wire fast path's
        read (no response objects, one lock)."""
        import numpy as np

        n = len(keys)
        hit = np.zeros(n, dtype=bool)
        status = np.zeros(n, dtype=np.int32)
        limit = np.zeros(n, dtype=np.int64)
        remaining = np.zeros(n, dtype=np.int64)
        reset = np.zeros(n, dtype=np.int64)
        with self._lock:
            items = self._items
            get = items.get
            move = items.move_to_end
            for i, k in enumerate(keys):
                e = get(k)
                if e is None:
                    continue
                if e.expire_at and now_ms >= e.expire_at:
                    del items[k]
                    continue
                move(k)
                hit[i] = True
                status[i], limit[i], remaining[i], reset[i] = e.cols
        return hit, status, limit, remaining, reset

    def _get_locked(self, key: bytes, now_ms: int) -> Optional[RateLimitResp]:
        e = self._items.get(key)
        if e is None:
            return None
        if e.expire_at and now_ms >= e.expire_at:
            del self._items[key]
            return None
        self._items.move_to_end(key)
        if e.resp is None:
            # Columnar puts (the broadcast wire path) defer the
            # response object; only the pb read path pays for it.
            st, lim, rem, rst = e.cols
            e.resp = RateLimitResp(
                status=Status(st), limit=lim, remaining=rem,
                reset_time=rst,
            )
        return e.resp

    def put_columns(self, dec) -> None:
        """Columnar insert from a decoded UpdatePeerGlobalsReq
        (net/wire_codec.DecodedGlobals) — no response objects.  The
        numpy→int conversions happen ONCE per batch via tolist();
        the loop body is dict ops only."""
        raw = dec.key_buf.tobytes()
        off = dec.key_offsets.tolist()
        has = dec.has_status.tolist()
        algo = dec.algo.tolist()
        status = dec.status.tolist()
        limit = dec.limit.tolist()
        remaining = dec.remaining.tolist()
        reset = dec.reset_time.tolist()
        entry = _GlobalEntry
        items = self._items
        move = items.move_to_end
        with self._lock:
            for i in range(dec.n):
                if not has[i]:
                    continue
                key = raw[off[i]:off[i + 1]]
                rst = reset[i]
                items[key] = entry(
                    None, algo[i], rst,
                    (status[i], limit[i], remaining[i], rst),
                )
                move(key)
            while len(items) > self.capacity:
                items.popitem(last=False)

    def put(self, key, resp: RateLimitResp, algorithm: int) -> None:
        with self._lock:
            self._put_locked(self._k(key), resp, algorithm)

    def put_many(self, entries) -> None:
        """Batch insert under ONE lock acquisition — UpdatePeerGlobals
        delivers up to MAX_BATCH_SIZE statuses per RPC and a lock per
        item contends with the serving path's get_many."""
        with self._lock:
            for key, resp, algorithm in entries:
                self._put_locked(self._k(key), resp, algorithm)

    def _put_locked(self, key: bytes, resp: RateLimitResp, algorithm: int) -> None:
        self._items[key] = _GlobalEntry(
            resp=resp,
            algorithm=algorithm,
            expire_at=resp.reset_time,
            cols=(
                int(resp.status), resp.limit, resp.remaining,
                resp.reset_time,
            ),
        )
        self._items.move_to_end(key)
        while len(self._items) > self.capacity:
            self._items.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)


class V1Instance:
    """The service core: routing + local engine + cluster managers."""

    def __init__(self, conf: Config, engine):
        """`engine` is a DecisionEngine or ShardedDecisionEngine (both
        expose get_rate_limits/sweep/cache_size/close)."""
        self.conf = conf
        self.engine = engine
        self.global_cache = _GlobalStatusCache(capacity=conf.cache_size)
        # Host-tier decision ledger (core/ledger.py): sticky over-limit
        # answers + bounded credit leases serve hot-key decisions with
        # zero device work.  The owner-broadcast status cache above is
        # its read-only tier (non-owner GLOBAL entries).
        self.ledger = None
        if getattr(conf, "ledger", True) and getattr(
            engine, "apply_columnar", None
        ) is not None and getattr(engine, "store", None) is None:
            from gubernator_tpu.core.ledger import DecisionLedger

            self.ledger = DecisionLedger(
                engine,
                lease_size=getattr(conf, "ledger_lease", 512),
                lease_ttl=getattr(conf, "ledger_lease_ttl", 0.2),
                hot_threshold=getattr(conf, "ledger_hot_threshold", 8),
                max_keys=getattr(conf, "ledger_keys", 65536),
                settle_interval=getattr(
                    conf, "ledger_settle_interval", 0.05
                ),
            )
            self.ledger.attach_readonly(self.global_cache)
        self.global_mgr = GlobalManager(conf.behaviors, self)
        self.multi_region_mgr = MultiRegionManager(conf.behaviors, self)
        from gubernator_tpu.cluster.hash_ring import make_picker

        # guberlint: guard local_picker, region_picker by _peer_lock
        self.local_picker: ReplicatedConsistentHash[PeerClient] = make_picker(
            getattr(conf, "peer_picker", "replicated-hash"),
            conf.hash_algorithm,
            getattr(conf, "picker_replicas", 512),
        )
        self.region_picker: RegionPicker[PeerClient] = RegionPicker(
            conf.hash_algorithm, getattr(conf, "picker_replicas", 512)
        )
        self._peer_lock = threading.RLock()
        self._forward_pool = ThreadPoolExecutor(
            max_workers=32, thread_name_prefix="guber-forward"
        )
        self._closed = False
        # Metric counters (reference: gubernator.go:59-113), scraped by
        # utils.metrics into the /metrics endpoint.
        self.counters = {
            "local": 0,
            "columnar": 0,  # items served via the columnar wire fast path
            "forward": 0,
            "global": 0,
            "sketch": 0,  # items decided by the approximate limiter
            # GLOBAL items served by a LOCAL eventually-consistent copy
            # (status-cache miss on a non-owner).  This is the source
            # of GLOBAL's bounded over-admission: worst case each
            # node's local copy admits up to `limit` before the first
            # broadcast converges the cache (see README, reference:
            # architecture.md:46-74).
            "global_miss_local": 0,
            "check_errors": 0,
            "async_retries": 0,
            # Forward retries that waited out a backoff window first
            # (the reference's loop re-picked with zero delay).
            "backoff_retries": 0,
            # Requests answered by OUR engine because every owner
            # candidate was circuit-open/unreachable (degraded mode,
            # GUBER_DEGRADED_LOCAL).  Each one is availability bought
            # with bounded over-admission — RESILIENCE.md.
            "degraded_answers": 0,
            # Peer-owned items answered LOCALLY from a replica credit
            # lease (cluster/replication.py) — the forward hops the
            # hot-key replication plane removed.
            "replicated_local": 0,
            # MULTI_REGION answers served while at least one remote
            # region's aggregate circuit was OPEN (the answer is
            # region-local as always, but cross-region convergence is
            # deferred): flagged metadata.degraded_region=true, drift
            # bounded at N_regions × limit (RESILIENCE.md §12).
            "degraded_region_answers": 0,
        }
        # Ownership-handoff traffic (cluster/handoff.py), exported as
        # gubernator_handoff_keys{event}: rows shipped to new owners,
        # rows forfeited at the epoch deadline, rows received and
        # restored here.  The membership manager (attached by the
        # daemon as `self.membership`) shares this dict.
        self.handoff_counters = {"shipped": 0, "forfeited": 0, "received": 0}
        # Highest handoff (boot, epoch) seen per source address — the
        # receiver's stale-window guard (cluster/handoff.py).
        self.handoff_epoch_seen: Dict[str, Tuple[str, int]] = {}
        # MembershipManager (cluster/membership.py), set by the daemon
        # after construction; None for bare library instances.
        self.membership = None
        from gubernator_tpu.utils.metrics import DurationStat

        # Peer-flush duration summary, shared by every PeerClient this
        # instance creates (reference: guber_batch_send_duration).
        self.flush_duration = DurationStat()
        # Stage timers: the cluster-tier p50 budget, end to end
        # (VERDICT r5 next-round #3).  Every serial stage a GLOBAL
        # decision can wait on is measured where it happens — the
        # client group-commit window, the engine dispatch, the hit
        # window, the owner RPC, and the broadcast's enqueue→delivered
        # age — and exported as gubernator_stage_duration{stage=...}.
        self.stage_timers = {
            "wire_window_wait": DurationStat(),
            "engine_serve": DurationStat(),
            "hits_window_wait": self.global_mgr.hits_window_wait,
            "owner_rpc": self.global_mgr.owner_rpc_duration,
            "broadcast_age": self.global_mgr.broadcast_age,
            # Cross-region hop budget (RESILIENCE.md §12 / PERF.md
            # §28): how long queued region deltas wait for their
            # window, and the per-region push RPC itself.
            "multiregion.window_wait": self.multi_region_mgr.window_wait,
            "multiregion.region_rpc": self.multi_region_mgr.region_rpc,
        }
        # Device-plane budget (PERF.md §24, mirroring the §10b host
        # stages): device.step is the per-dispatch wall time of the
        # fused decision kernel, device.readback the blocking d2h
        # materialization, device.window_wait the pump-queue wait of a
        # packed round before its fused dispatch.  All three ride
        # gubernator_stage_duration / gubernator_stage_quantile_seconds
        # and Daemon.stage_budget() → /debug/vars, so "where do device
        # milliseconds go" is answerable from a scrape.
        # getattr-guarded: jax-free smoke/test stubs stand in for the
        # engine without the device plane.
        round_dur = getattr(engine, "round_duration", None)
        if round_dur is not None:
            self.stage_timers["device.step"] = round_dur
        transfer = getattr(
            getattr(engine, "readback", None), "transfer_duration", None
        )
        if transfer is not None:
            self.stage_timers["device.readback"] = transfer
        pump = getattr(engine, "_pump", None)
        if pump is not None:
            self.stage_timers["device.window_wait"] = pump.window_wait
        # Paged plane (GUBER_PAGED; PERF.md §30): device.page_fault is
        # the per-fault spill+refill wall time a non-resident key pays
        # before its round can dispatch.
        paging = getattr(engine, "paging", None)
        if paging is not None:
            self.stage_timers["device.page_fault"] = paging.fault_duration
        # Optional group-commit window for client wire batches
        # (net/wire_window.py; conf.local_batch_wait > 0 enables).
        self._wire_window = None
        if conf.local_batch_wait > 0:
            from gubernator_tpu.net.wire_window import WireWindow

            self._wire_window = WireWindow(
                engine,
                conf.local_batch_wait,
                adaptive=getattr(conf.behaviors, "adaptive_windows", True),
                wait_stat=self.stage_timers["wire_window_wait"],
                apply_stat=self.stage_timers["engine_serve"],
            )
        # GLOBAL serve-route group commit: concurrent engine
        # sub-batches (client serves + peer hit pushes + miss copies)
        # share one dispatch.  Load-adaptive — an isolated apply pays
        # no window (conf.global_serve_window caps the wait).
        self._global_window = None
        if getattr(conf, "global_serve_window", 0.0) > 0:
            from gubernator_tpu.net.wire_window import WireWindow

            self._global_window = WireWindow(
                engine,
                conf.global_serve_window,
                adaptive=getattr(conf.behaviors, "adaptive_windows", True),
                # Both group-commit windows report into the same two
                # stages: wire_window_wait is "time spent waiting for a
                # shared window" and engine_serve is "one observation
                # per device dispatch" wherever the dispatch happens.
                wait_stat=self.stage_timers["wire_window_wait"],
                apply_stat=self.stage_timers["engine_serve"],
            )
        # Count-min-sketch approximate limiter (Behavior.SKETCH),
        # created lazily on first flagged request (GUBER_SKETCH_*).
        self._sketch = None
        self._sketch_lock = threading.Lock()
        # Hot-key attribution: space-saving top-K over decision keys
        # (utils/hotkeys.py; GUBER_HOTKEYS / GUBER_HOTKEYS_K — None
        # when disabled, costing one attribute check per batch).
        # Served by /debug/hotkeys and gubernator_hotkeys.
        from gubernator_tpu.utils import hotkeys as _hotkeys

        self.hotkeys = _hotkeys.from_env()
        # Feed the paged plane's clock-hand heat ranking from the same
        # sketch (core/paging._maybe_refresh_hot): pages holding top-K
        # keys get one eviction grace pass.  The provider runs under
        # the engine lock, so the contains→intern pair is atomic (the
        # native table has no read-only key→slot lookup; intern on a
        # present key is a pure lookup).
        if paging is not None and self.hotkeys is not None:
            _sketch = self.hotkeys
            _table = engine.table
            _clock = engine.clock

            def _hot_slots() -> List[int]:
                out: List[int] = []
                now = _clock.now_ms()
                for key, rate, _lim, _dur in _sketch.top_rates(32):
                    if rate <= 0:
                        break
                    try:
                        ks = key.decode()
                    except UnicodeDecodeError:
                        continue
                    if _table.contains(ks):
                        out.append(_table.intern(ks, now, []))
                return out

            paging.hot_slots_provider = _hot_slots
        # Hot-key replication plane (cluster/replication.py), attached
        # by the daemon: peer-owned keys with a live replica lease
        # answer locally from pre-debited credit — zero forward hops.
        # None for bare library instances (one attribute check per
        # batch when absent).
        self.replication = None
        if self.ledger is not None and self.hotkeys is not None:
            # Native-plane drains surface per-key counts only at pull
            # time (core/ledger._undelegate_locked) — credit them so
            # natively-answered keys appear in /debug/hotkeys too.
            self.ledger.hotkeys = self.hotkeys
        # Tail flight recorder (utils/flight_recorder.py), attached by
        # the daemon when in-memory tracing is active; /debug/trace
        # serves its dump.
        self.flight_recorder = None
        # Native event collector (utils/native_events.py), attached by
        # the daemon when the h2 fast front runs with its event ring.
        self.native_events = None
        # Fleet observability plane (obs/): the rollup collector and
        # SLO watchdog, attached by the daemon (GUBER_OBS); None for
        # bare library instances.  The admission watch is always
        # present — it costs one attribute peek while no key is
        # watched, and the serve-path hooks need a stable handle.
        from gubernator_tpu.obs.slo import AdmissionWatch

        self.obs = None
        self.slo_watchdog = None
        self.admission_watch = AdmissionWatch()

    def sketch(self):
        if self._sketch is None:
            with self._sketch_lock:
                if self._sketch is None:
                    from gubernator_tpu.ops.sketch import SketchLimiter

                    self._sketch = SketchLimiter(
                        window_ms=getattr(self.conf, "sketch_window_ms", 1000),
                        depth=getattr(self.conf, "sketch_depth", 4),
                        width=getattr(self.conf, "sketch_width", 1 << 20),
                    )
        return self._sketch

    def _apply_sketch(
        self, keys, hits, limit, now_ms: int, key_hashes=None
    ):
        """Run one sketch batch → (status, limit, remaining, reset)
        columns.  remaining = limit - estimate (floored at 0); reset =
        end of the current sketch window."""
        sk = self.sketch()
        over, est = sk.apply(
            keys, np.asarray(hits, dtype=np.int64),
            np.asarray(limit, dtype=np.int64), now_ms,
            key_hashes=key_hashes,
        )
        limit64 = np.asarray(limit, dtype=np.int64)
        remaining = np.maximum(limit64 - est, 0)
        reset = np.full(
            len(est),
            (now_ms // sk.window_ms + 1) * sk.window_ms,
            dtype=np.int64,
        )
        self.counters["sketch"] += len(est)
        return over.astype(np.int32), limit64, remaining, reset

    # ------------------------------------------------------------------
    # Public API (reference: proto/gubernator.proto service V1)

    def get_rate_limits(
        self, requests: Sequence[RateLimitReq]
    ) -> List[RateLimitResp]:
        """reference: gubernator.go:197-317 (GetRateLimits)."""
        from gubernator_tpu.utils.tracing import span

        with span("service.get_rate_limits", batch=len(requests)):
            return self._get_rate_limits(requests)

    def _get_rate_limits(
        self, requests: Sequence[RateLimitReq]
    ) -> List[RateLimitResp]:
        if len(requests) > MAX_BATCH_SIZE:
            self.counters["check_errors"] += 1
            raise ServiceError(
                f"Requests.RateLimits list too large; max size is '{MAX_BATCH_SIZE}'"
            )
        n = len(requests)
        responses: List[Optional[RateLimitResp]] = [None] * n
        now_ms = self.engine.clock.now_ms()

        # 1. validate (reference: gubernator.go:231-243).  Sketch items
        # split off here: the approximate limiter is node-local, so
        # they must not pay the ring lookup below.
        candidates: List[int] = []
        sketch_idx: List[int] = []
        for i, r in enumerate(requests):
            if not r.unique_key:
                self.counters["check_errors"] += 1
                responses[i] = RateLimitResp(error="field 'unique_key' cannot be empty")
            elif not r.name:
                self.counters["check_errors"] += 1
                responses[i] = RateLimitResp(error="field 'namespace' cannot be empty")
            elif int(r.behavior) & _SKETCH_I:
                sketch_idx.append(i)
            else:
                candidates.append(i)

        # 2. one vectorized owner lookup for the batch
        keys = [requests[i].hash_key() for i in candidates]
        if self.hotkeys is not None and keys:
            self.hotkeys.offer_many_params(
                (
                    k.encode(),
                    max(requests[i].hits, 1),
                    # Lease-sizing aux: only rows the lease algebra
                    # could cover stamp their params (the promotion
                    # plane skips keys whose last limit reads 0).
                    requests[i].limit
                    if (
                        int(requests[i].algorithm) == _TOKEN_I
                        and not int(requests[i].behavior) & _LEASE_BREAKERS
                    )
                    else 0,
                    requests[i].duration,
                )
                for k, i in zip(keys, candidates)
            )
        with self._peer_lock:
            if self.local_picker.size() == 0:
                owners: List[Optional[PeerClient]] = [None] * len(candidates)
            else:
                owners = self.local_picker.get_batch(keys)

        # 3. partition
        local_idx: List[int] = []
        forward: Dict[str, Tuple[PeerClient, List[int]]] = {}
        global_items: List[Tuple[int, PeerClient]] = []
        global_miss: List[Tuple[int, PeerClient]] = []
        repl = self.replication
        repl_live = repl is not None and repl.has_leases
        for k, i, owner in zip(keys, candidates, owners):
            r = requests[i]
            if owner is None or owner.info.is_owner:
                local_idx.append(i)
                continue
            if repl_live:
                # Hot-key replication override (cluster/replication.py):
                # a peer-owned key with a live replica lease answers
                # HERE from pre-debited credit — no forward hop, and
                # (for GLOBAL items) no async hit queue either: the
                # owner already debited these hits at grant time.
                # (`k` is the hash key step 2 already built.)
                ans = repl.try_answer(
                    k.encode(), int(r.algorithm),
                    int(r.behavior), r.hits, r.limit, r.duration,
                    now_ms,
                )
                if ans is not None:
                    st, rem, rst = ans
                    self.counters["replicated_local"] += 1
                    responses[i] = RateLimitResp(
                        status=Status(st), limit=r.limit, remaining=rem,
                        reset_time=rst,
                        metadata={
                            "owner": owner.info.grpc_address,
                            "replicated": "true",
                        },
                    )
                    continue
            if int(r.behavior) & _GLOBAL_I:
                # reference: gubernator.go:276-287, 426-466
                global_items.append((i, owner))
            else:
                addr = owner.info.grpc_address
                forward.setdefault(addr, (owner, []))[1].append(i)

        # GLOBAL non-owners: batch the hit queueing and the status-cache
        # lookups (one lock each per wire batch, not per item).
        if global_items:
            self.counters["global"] += len(global_items)
            self.global_mgr.queue_hits_many(
                requests[i] for i, _ in global_items
            )
            cached_list = self.global_cache.get_many(
                [requests[i].hash_key() for i, _ in global_items], now_ms
            )
            for (i, owner), cached in zip(global_items, cached_list):
                if cached is not None:
                    responses[i] = replace(
                        cached,
                        metadata={"owner": owner.info.grpc_address},
                    )
                else:
                    # Cache miss: process locally as a NO_BATCHING copy
                    # (reference: gubernator.go:455-460).
                    global_miss.append((i, owner))
            self.counters["global_miss_local"] += len(global_miss)

        # 3b. sketch items: one approximate-limiter batch (node-local;
        # MULTI_REGION-flagged sketch items still queue region
        # replication so remote DCs' sketches see the hits).
        if sketch_idx:
            s_keys = [requests[i].hash_key().encode() for i in sketch_idx]
            st, lim, rem, rst = self._apply_sketch(
                s_keys,
                [requests[i].hits for i in sketch_idx],
                [requests[i].limit for i in sketch_idx],
                now_ms,
            )
            status_of = {int(s): s for s in Status}
            for j, i in enumerate(sketch_idx):
                responses[i] = RateLimitResp(
                    status=status_of[int(st[j])],
                    limit=int(lim[j]),
                    remaining=int(rem[j]),
                    reset_time=int(rst[j]),
                )
                if int(requests[i].behavior) & _MULTI_REGION_I:
                    self.multi_region_mgr.queue_hits(requests[i])

        # 4. local + global-miss items: ONE engine batch
        engine_items = local_idx + [i for i, _ in global_miss]
        if engine_items:
            engine_reqs = [requests[i] for i in local_idx]
            for i, _ in global_miss:
                engine_reqs.append(
                    replace(requests[i], behavior=int(Behavior.NO_BATCHING))
                )
            self.counters["local"] += len(local_idx)
            engine_resps = self.apply_local_batch(engine_reqs, now_ms=now_ms)
            for j, i in enumerate(engine_items):
                responses[i] = engine_resps[j]
            for i, owner in global_miss:
                responses[i].metadata = {"owner": owner.info.grpc_address}

        # 5. forward the rest (async per peer, 5-retry loop).  The
        # forward pool is another thread, so the caller's span context
        # travels explicitly (tracing.current_context is thread-local).
        if forward:
            from gubernator_tpu.utils import tracing

            fwd_ctx = tracing.current_context()
            futures = []
            for addr, (peer, idxs) in forward.items():
                self.counters["forward"] += len(idxs)
                futures.append(
                    self._forward_pool.submit(
                        self._forward_group, peer, idxs, requests,
                        responses, fwd_ctx,
                    )
                )
            for f in futures:
                f.result()

        aw = self.admission_watch
        if aw.active:
            # Admission-bound invariant feed (obs/slo.py): watched
            # finite-limit keys count their CLIENT-VISIBLE admitted
            # hits here, at the client-facing boundary — local,
            # forwarded, degraded, GLOBAL-cached and replica-lease
            # answers all land in `responses` by now.  Internal
            # re-applies (multiregion delta pushes, GLOBAL hit
            # windows, handoff restores) arrive via the peer routes
            # and are deliberately NOT counted: they re-play hits a
            # client was already answered for, and counting them
            # would double-bill the N×limit bound.
            aw.observe_batch(requests, responses)
        return responses  # type: ignore[return-value]

    def _degraded_answer(
        self,
        ids: List[int],
        requests: Sequence[RateLimitReq],
        responses: List[Optional[RateLimitResp]],
        owner_addr: str,
    ) -> None:
        """Serve forward items from OUR engine because their owner is
        unreachable (circuit open / retries exhausted).  The response
        is flagged (`metadata.degraded`) so callers can tell an
        authoritative answer from a partition-local one.  Availability
        over accuracy, exactly like the reference's design creed
        (architecture.md:5-11): worst case each partition side admits
        up to `limit` independently — N_partitions × limit total, the
        same shape as the GLOBAL broadcast-lag bound (RESILIENCE.md)."""
        from gubernator_tpu.utils import tracing

        tracing.add_event(
            "degraded_answer", owner=owner_addr, items=len(ids)
        )
        resps = self.apply_local_batch([requests[i] for i in ids])
        self.counters["degraded_answers"] += len(ids)
        for i, resp in zip(ids, resps):
            md = dict(resp.metadata) if resp.metadata else {}
            md["degraded"] = "true"
            md["owner"] = owner_addr
            resp.metadata = md
            responses[i] = resp

    def _forward_group(
        self,
        peer: PeerClient,
        idxs: List[int],
        requests: Sequence[RateLimitReq],
        responses: List[Optional[RateLimitResp]],
        parent_ctx=None,
    ) -> None:
        """Span shim re-anchoring the forward-pool thread to the
        caller's trace (tracing.current_context is thread-local); the
        ownership-migration loop lives in _forward_group_traced."""
        from gubernator_tpu.utils.tracing import span

        with span(
            "forward.group", parent_ctx=parent_ctx,
            peer=peer.info.grpc_address, batch=len(idxs),
        ):
            self._forward_group_traced(peer, idxs, requests, responses)

    def _forward_group_traced(
        self,
        peer: PeerClient,
        idxs: List[int],
        requests: Sequence[RateLimitReq],
        responses: List[Optional[RateLimitResp]],
    ) -> None:
        """Forward a same-owner group with the ownership-migration loop.

        reference: gubernator.go:333-422 (asyncRequests) — ≤5 retries on
        NotReady, re-picking the owner each time; if ownership migrated
        to us mid-flight, apply locally.  Beyond the reference (the
        health plane, RESILIENCE.md):

        - re-pick rounds after a REAL dial failure sleep a capped
          exponential backoff with full jitter (the reference's loop
          re-picked with zero delay — the tail-amplifying spin "When
          Two is Worse Than One" warns about);
        - a circuit-open owner fails in one dict probe (no dial); with
          degraded mode on the items are answered locally right away
          instead of burning retries that can only land on the same
          broken peer;
        - exhausted retries answer degraded too (the pre-circuit-open
          window) unless GUBER_DEGRADED_LOCAL=0 restores the
          reference's fail-closed error strings.

        Multi-item groups go as ONE unary GetPeerRateLimits RPC (our
        client batch already coalesced them); singletons ride the
        per-peer batching client so concurrent small requests still
        coalesce across windows (the reference's thundering-herd
        protection, peer_client.go:308-376).
        """
        groups: Dict[str, Tuple[PeerClient, List[int]]] = {
            peer.info.grpc_address: (peer, idxs)
        }
        behaviors = self.conf.behaviors
        degraded_on = behaviors.degraded_local
        attempts = 0
        while groups:
            if attempts > 5:
                for _, (p, ids) in groups.items():
                    if degraded_on:
                        self._degraded_answer(
                            ids, requests, responses, p.info.grpc_address
                        )
                        continue
                    for i in ids:
                        self.counters["check_errors"] += 1
                        responses[i] = RateLimitResp(
                            error=(
                                "GetPeer() keeps returning peers that are not "
                                f"connected for '{requests[i].hash_key()}'"
                            )
                        )
                return
            retry: List[int] = []
            dialed_and_failed = False
            for _, (p, ids) in groups.items():
                if attempts != 0 and p.info.is_owner:
                    # Ownership moved to us (reference: gubernator.go:368-383).
                    resps = self.apply_local_batch([requests[i] for i in ids])
                    for i, resp in zip(ids, resps):
                        responses[i] = resp
                    continue
                try:
                    if len(ids) == 1:
                        resps = [
                            p.get_peer_rate_limit(
                                requests[ids[0]],
                                timeout=behaviors.batch_timeout,
                            )
                        ]
                    else:
                        resps = p.get_peer_rate_limits(
                            [requests[i] for i in ids],
                            timeout=behaviors.batch_timeout,
                        )
                except PeerError as e:
                    if e.circuit_open:
                        from gubernator_tpu.utils import tracing

                        tracing.add_event(
                            "circuit_open", peer=p.info.grpc_address,
                            items=len(ids),
                        )
                    if e.circuit_open and degraded_on:
                        # Broken owner, no probe due: a re-pick hands
                        # back the same peer, so answer locally NOW —
                        # this is the no-connect-timeout-storm path.
                        self._degraded_answer(
                            ids, requests, responses, p.info.grpc_address
                        )
                        continue
                    if e.not_ready:
                        self.counters["async_retries"] += len(ids)
                        retry.extend(ids)
                        if not e.circuit_open:
                            # A real dial burned a timeout — the next
                            # round must wait, not spin.
                            dialed_and_failed = True
                        continue
                    for i in ids:
                        responses[i] = RateLimitResp(
                            error=(
                                "Error while fetching rate limit "
                                f"'{requests[i].hash_key()}' from peer: {e}"
                            )
                        )
                    continue
                for i, resp in zip(ids, resps):
                    resp.metadata = {"owner": p.info.grpc_address}
                    responses[i] = resp
            if not retry:
                return
            attempts += 1
            if dialed_and_failed:
                # Capped exponential + FULL jitter between re-pick
                # rounds (cluster/health.backoff_delay): decorrelates
                # the herd that all picked the same dead owner.
                delay = backoff_delay(
                    attempts - 1,
                    behaviors.forward_backoff,
                    behaviors.forward_backoff_cap,
                )
                if delay > 0:
                    self.counters["backoff_retries"] += len(retry)
                    time.sleep(delay)
            # Re-pick owners for the retried items; they may now map to
            # different peers or to us.
            groups = {}
            for i in retry:
                try:
                    p = self.get_peer(requests[i].hash_key())
                except Exception as pick_err:  # noqa: BLE001
                    responses[i] = RateLimitResp(
                        error=(
                            "Error finding peer that owns rate limit "
                            f"'{requests[i].hash_key()}': {pick_err}"
                        )
                    )
                    continue
                groups.setdefault(p.info.grpc_address, (p, []))[1].append(i)

    # ------------------------------------------------------------------
    # Columnar fast path (the wire-side counterpart of
    # DecisionEngine.apply_columnar — VERDICT r1 item 2: the served path
    # must be the same program as the benched one).

    def _owned_mask(self, dec):
        """Per-row local-ownership bool mask for a decoded wire batch,
        or None when the picker is empty (single-node: everything is
        ours)."""
        with self._peer_lock:
            picker = self.local_picker
        n_peers = picker.size()
        if n_peers == 0:
            return None
        if n_peers == 1:
            return np.full(dec.n, bool(picker.peers()[0].info.is_owner))
        owners = picker.get_batch_dual_hashed(dec.fnv1, dec.fnv1a)
        return np.fromiter((o.info.is_owner for o in owners), bool, dec.n)

    def all_locally_owned(self, dec) -> bool:
        """True when every key in a decoded wire batch is owned by this
        node (the columnar fast paths' gate; shared with the native h2
        front so the ownership semantics cannot drift between them)."""
        owned = self._owned_mask(dec)
        return owned is None or bool(owned.all())

    def _serve_wire_replicated(self, dec) -> Optional[bytes]:
        """Columnar serve of an all-peer-owned batch from replica
        credit leases (cluster/replication.py): every row must have a
        live lease covering it, or the whole batch declines to the pb
        path (which answers leased rows there and forwards the rest).
        The common shape — a flash crowd's single-hot-key RPCs — is
        all-or-nothing by construction."""
        repl = self.replication
        if repl is None or not repl.has_leases:
            return None
        from gubernator_tpu.net import wire_codec

        now_ms = self.engine.clock.now_ms()
        idx = np.arange(dec.n, dtype=np.int64)
        out = repl.try_answer_columns(dec, idx, now_ms)
        if out is None:
            return None
        st, rem, rst = out
        self.counters["replicated_local"] += dec.n
        self.counters["columnar"] += dec.n
        self._offer_hotkeys(dec)
        return wire_codec.encode_resps(
            st.astype(np.int32), np.asarray(dec.limit, dtype=np.int64),
            rem, rst,
        )

    def _offer_hotkeys(self, dec, idx=None) -> None:
        """Columnar hot-key accounting with the lease-sizing aux
        params: rows the lease algebra could never cover stamp limit 0
        so the promotion plane skips them."""
        hk = self.hotkeys
        if hk is None:
            return
        lim = np.asarray(dec.limit)
        elig = (
            (np.asarray(dec.algo) == _TOKEN_I)
            & ((np.asarray(dec.behavior) & _LEASE_BREAKERS) == 0)
            & (lim > 0)
        )
        hk.offer_columns(
            dec.key_buf, dec.key_offsets, dec.hits, idx=idx,
            hashes=dec.fnv1a, limit=np.where(elig, lim, 0),
            duration=dec.duration,
        )

    def serve_decoded_local(self, dec):
        """Shared post-decode columnar serve for the native fronts —
        the h2 fast front's byte windows AND the columnar feeder's
        ring windows both land here, so the ownership gate, hot-key
        accounting, and ledger semantics cannot drift between them.
        Returns (status, limit, remaining, reset) columns, or None to
        decline (caller answers UNIMPLEMENTED / falls to the pb path).
        """
        engine = self.engine
        # Same engine guards as serve_wire_bytes: a write-through
        # store must not be bypassed, and an engine without the
        # columnar entry declines cleanly.
        if getattr(engine, "apply_columnar", None) is None or getattr(
            engine, "store", None
        ) is not None:
            return None
        # The fast fronts must never answer peer-owned keys locally —
        # clustered deployments route those through the full
        # listener's forward path.
        if not self.all_locally_owned(dec):
            return None
        self._offer_hotkeys(dec)
        if self.ledger is not None:
            return self._serve_decoded_ledger(dec)
        from gubernator_tpu.core.engine import PackedKeys

        packed = PackedKeys(dec.key_buf, dec.key_offsets, dec.n)
        if hasattr(engine, "tables"):
            return engine.apply_columnar(
                packed, dec.algo, dec.behavior, dec.hits, dec.limit,
                dec.duration, dec.burst, route_hashes=dec.fnv1a,
            )
        return engine.apply_columnar(
            packed, dec.algo, dec.behavior, dec.hits, dec.limit,
            dec.duration, dec.burst,
        )

    def _serve_decoded_ledger(self, dec):
        """Ledger-aware columnar serve for the native fronts: hot-key
        rows (sticky over-limit, live lease credit) answer without any
        device work — for a fully hot window the engine is never
        dispatched at all, which is the fronts' whole point on a
        dispatch-bound backend."""
        from gubernator_tpu.core.engine import PackedKeys

        engine = self.engine
        plan = self.ledger.plan(dec, engine.clock.now_ms())
        if plan.full:
            return plan.dense_cols()
        lane = plan.build_engine_lane()
        packed = PackedKeys(lane.key_buf, lane.key_offsets, lane.n)
        try:
            if hasattr(engine, "tables"):
                out = engine.apply_columnar(
                    packed, lane.algo, lane.behavior, lane.hits,
                    lane.limit, lane.duration, lane.burst,
                    route_hashes=lane.fnv1a,
                )
            else:
                out = engine.apply_columnar(
                    packed, lane.algo, lane.behavior, lane.hits,
                    lane.limit, lane.duration, lane.burst,
                )
        except Exception:
            plan.rollback()
            raise
        st, lim, rem, rst = out
        plan.learn(st, lim, rem, rst)
        if not plan.answered_rows and lane is dec:
            return out
        return plan.merge_outputs(st, rem, rst)

    def serve_wire_bytes(
        self, raw: bytes, *, check_ownership: bool = True
    ) -> Optional[bytes]:
        """Serve one GetRateLimitsReq/GetPeerRateLimitsReq payload
        entirely through native code + the engine's columnar path:
        C wire decode → packed key schedule → device step → C wire
        encode.  Returns response bytes, or None to decline (codec
        unavailable, slow-path batch, store attached, peer-owned keys)
        — the caller then takes the protobuf path.  No per-item Python
        objects anywhere (PERF.md: the pb path costs ~3.2ms per
        1000-item batch)."""
        engine = self.engine
        if getattr(engine, "apply_columnar", None) is None or getattr(
            engine, "store", None
        ) is not None:
            return None
        from gubernator_tpu.net import wire_codec

        if wire_codec.load() is None:
            return None
        # Decode with GLOBAL/SKETCH allowed: all-GLOBAL and all-SKETCH
        # batches have their own columnar routes below; mixed batches
        # decline to the pb path.
        dec = wire_codec.decode_reqs(
            bytes(raw), MAX_BATCH_SIZE,
            COLUMNAR_DISQUALIFIERS & ~_GLOBAL_I & ~_SKETCH_I,
        )
        if dec is None:
            return None
        s_mask = (dec.behavior & _SKETCH_I) != 0
        if s_mask.any():
            if not s_mask.all():
                return None  # mixed batch → pb path partitions it
            # (MULTI_REGION+SKETCH can't reach here: the decode mask
            # still disqualifies MULTI_REGION → pb path replicates.)
            # Approximate limiter straight off the decoded hashes — no
            # key materialization, no engine dispatch.
            st, lim, rem, rst = self._apply_sketch(
                None, dec.hits, dec.limit,
                self.engine.clock.now_ms(), key_hashes=dec.fnv1a,
            )
            self.counters["columnar"] += dec.n
            if self.hotkeys is not None:
                self.hotkeys.offer_columns(
                    dec.key_buf, dec.key_offsets, dec.hits,
                    hashes=dec.fnv1a,
                )
            return wire_codec.encode_resps(st, lim, rem, rst)
        g_mask = (dec.behavior & _GLOBAL_I) != 0
        if g_mask.any():
            if not g_mask.all():
                return None
            return self._serve_wire_global(dec, check_ownership)
        if check_ownership:
            owned = self._owned_mask(dec)
            if owned is not None and not bool(owned.all()):
                if not owned.any():
                    # Entirely peer-owned: a flash-crowd hot-key batch
                    # may answer from replica leases without touching
                    # the pb path at all.
                    return self._serve_wire_replicated(dec)
                return None  # mixed ownership → pb path partitions it
            self.counters["local"] += dec.n
        self.counters["columnar"] += dec.n
        self._offer_hotkeys(dec)

        if self.ledger is not None:
            return self._serve_columnar_ledger(dec)

        from gubernator_tpu.core.engine import PackedKeys

        if self._wire_window is not None:
            out = self._wire_window.submit(dec)
            if out is None:
                return None
            st, lim, rem, rst = out
            return wire_codec.encode_resps(st, lim, rem, rst)
        packed = PackedKeys(dec.key_buf, dec.key_offsets, dec.n)
        t_serve = time.monotonic()
        if hasattr(engine, "tables"):  # sharded: codec hashes route shards
            st, lim, rem, rst = engine.apply_columnar(
                packed, dec.algo, dec.behavior, dec.hits, dec.limit,
                dec.duration, dec.burst, route_hashes=dec.fnv1a,
            )
        else:
            st, lim, rem, rst = engine.apply_columnar(
                packed, dec.algo, dec.behavior, dec.hits, dec.limit,
                dec.duration, dec.burst,
            )
        self.stage_timers["engine_serve"].observe(
            time.monotonic() - t_serve
        )
        return wire_codec.encode_resps(st, lim, rem, rst)

    def _serve_columnar_ledger(self, dec) -> Optional[bytes]:
        """The local columnar route through the decision ledger: rows
        the ledger can answer exactly (sticky over-limit, live lease
        credit) skip the device entirely; the rest — with any settle
        rows prepended — ride the usual group-commit window / direct
        apply, and the engine's responses teach the ledger (lease
        grants, over-limit inserts)."""
        from gubernator_tpu.net import wire_codec

        engine = self.engine
        plan = self.ledger.plan(dec, engine.clock.now_ms())
        if plan.full:
            st, lim, rem, rst = plan.dense_cols()
            return wire_codec.encode_resps(st, lim, rem, rst)
        lane = plan.build_engine_lane()
        out = self._dispatch_lane(lane)
        if out is None:
            plan.rollback()
            return None
        st, lim, rem, rst = out
        plan.learn(st, lim, rem, rst)
        if not plan.answered_rows and lane is dec:
            return wire_codec.encode_resps(st, lim, rem, rst)
        return wire_codec.encode_resps(*plan.merge_outputs(st, rem, rst))

    def _dispatch_lane(self, lane):
        """Run one engine-lane column set through the group-commit
        window (preferred) or a direct columnar apply; returns the
        (status, limit, remaining, reset) columns or None on failure
        (callers roll the ledger back and fall to the pb path)."""
        from gubernator_tpu.core.engine import PackedKeys

        engine = self.engine
        if self._wire_window is not None:
            out = self._wire_window.submit(lane)
            if out is not None:
                return out
        packed = PackedKeys(lane.key_buf, lane.key_offsets, lane.n)
        t_serve = time.monotonic()
        try:
            if hasattr(engine, "tables"):
                return engine.apply_columnar(
                    packed, lane.algo, lane.behavior, lane.hits,
                    lane.limit, lane.duration, lane.burst,
                    route_hashes=lane.fnv1a,
                )
            return engine.apply_columnar(
                packed, lane.algo, lane.behavior, lane.hits, lane.limit,
                lane.duration, lane.burst,
            )
        except Exception:  # noqa: BLE001 — callers fall back to pb
            from gubernator_tpu.utils.metrics import record_swallowed

            record_swallowed("service.ledger_lane")
            log.exception("ledger engine-lane apply failed")
            return None
        finally:
            self.stage_timers["engine_serve"].observe(
                time.monotonic() - t_serve
            )

    def _serve_wire_global(
        self, dec, check_ownership: bool
    ) -> Optional[bytes]:
        """Columnar GLOBAL route (the cluster tier's hot path): owned
        items run the engine + queue a broadcast chunk; non-owned items
        queue a hits chunk and answer from the status cache (misses run
        locally, eventually consistent) — all with O(batch) numpy and
        zero per-item dataclasses.  Mirrors the pb partitioning at
        _get_rate_limits step 3 (reference: gubernator.go:426-466)."""
        from gubernator_tpu.core.engine import PackedKeys
        from gubernator_tpu.net import wire_codec

        engine = self.engine
        now_ms = engine.clock.now_ms()
        n = dec.n
        if check_ownership:
            with self._peer_lock:
                picker = self.local_picker
            n_peers = picker.size()
            single_addr = None
            if n_peers == 0:
                owned = np.ones(n, dtype=bool)
                owner_objs = None
            elif n_peers == 1:
                me = picker.peers()[0]
                owned = np.full(n, bool(me.info.is_owner))
                owner_objs = None
                single_addr = me.info.grpc_address
            else:
                owner_objs = picker.get_batch_dual_hashed(
                    dec.fnv1, dec.fnv1a
                )
                owned = np.fromiter(
                    (o.info.is_owner for o in owner_objs), bool, n
                )
        else:
            # Peer-forwarded batch: we are the owner of every item.
            owned = np.ones(n, dtype=bool)
            owner_objs = None
            single_addr = None
        owned_idx = np.nonzero(owned)[0]
        non_idx = np.nonzero(~owned)[0]

        status = np.zeros(n, dtype=np.int32)
        limit = np.asarray(dec.limit).copy()
        remaining = np.zeros(n, dtype=np.int64)
        reset = np.zeros(n, dtype=np.int64)
        owner_meta_idx = np.full(n, -1, dtype=np.int32)
        owner_strs: List[bytes] = []

        # Owner-side ledger: sticky over-limit and leased hot keys
        # answer without joining the merged engine apply (the answered
        # columns still ride the broadcast below — the ledger's view IS
        # the authoritative serve-time status).
        led_plan = None
        owned_eng = owned_idx
        if len(owned_idx) and self.ledger is not None:
            led_plan = self.ledger.plan(dec, now_ms, idx=owned_idx)
            aidx = led_plan.answered_idx
            if len(aidx):
                a_st, a_rem, a_rst = led_plan.answered_cols()
                status[aidx] = a_st
                remaining[aidx] = a_rem
                reset[aidx] = a_rst
            owned_eng = led_plan.fall_idx
        eng_parts = [owned_eng] if len(owned_eng) else []
        if len(non_idx):
            self.counters["global"] += len(non_idx)
            self.global_mgr.queue_hits_chunk(dec, non_idx)
            raw_keys = dec.key_buf.tobytes()
            off = dec.key_offsets
            keys = [raw_keys[off[i]:off[i + 1]] for i in non_idx.tolist()]
            hit, c_st, c_lim, c_rem, c_rst = self.global_cache.get_columns(
                keys, now_ms
            )
            hidx = non_idx[hit]
            midx = non_idx[~hit]
            status[hidx] = c_st[hit]
            limit[hidx] = c_lim[hit]
            remaining[hidx] = c_rem[hit]
            reset[hidx] = c_rst[hit]
            if len(midx):
                self.counters["global_miss_local"] += len(midx)
                eng_parts.append(midx)
            # Every non-owned response echoes its owner address
            # (reference: gubernator.go:448-452).
            addr_index: Dict[str, int] = {}
            for i in non_idx.tolist():
                addr = (
                    single_addr if owner_objs is None
                    else owner_objs[i].info.grpc_address
                )
                k = addr_index.get(addr)
                if k is None:
                    k = len(owner_strs)
                    addr_index[addr] = k
                    owner_strs.append(addr.encode())
                owner_meta_idx[i] = k
        if len(owned_idx):
            self.counters["local"] += len(owned_idx)

        if eng_parts:
            eng_idx = (
                eng_parts[0] if len(eng_parts) == 1
                else np.sort(np.concatenate(eng_parts))
            )
            sub_buf, sub_off = _slice_key_columns(
                dec.key_buf, dec.key_offsets, eng_idx
            )
            cols = tuple(
                np.ascontiguousarray(np.asarray(a)[eng_idx])
                for a in (dec.algo, dec.behavior, dec.hits, dec.limit,
                          dec.duration, dec.burst)
            )
            sub = _SubBatch()
            sub.n = len(eng_idx)
            sub.key_buf = sub_buf
            sub.key_offsets = sub_off
            (sub.algo, sub.behavior, sub.hits, sub.limit,
             sub.duration, sub.burst) = cols
            sub.fnv1a = np.ascontiguousarray(dec.fnv1a[eng_idx])
            n_settles = 0
            n_acq = 0
            n_eng = len(eng_idx)
            if led_plan is not None and (
                led_plan.n_settles or led_plan.n_acquires
            ):
                # Revoked leases return their credit IN this dispatch,
                # ahead of the rows that broke their preconditions;
                # lease acquisitions ride the tail.
                from gubernator_tpu.core.ledger import concat_lanes

                n_settles = led_plan.n_settles
                n_acq = led_plan.n_acquires
                pre = led_plan.settle_lane()
                if pre is not None:
                    sub = concat_lanes(pre, sub)
                post = led_plan.acq_lane()
                if post is not None:
                    sub = concat_lanes(sub, post)
            packed = PackedKeys(sub.key_buf, sub.key_offsets, sub.n)
            out = None
            if self._global_window is not None:
                # The window observes engine_serve itself — once per
                # merged dispatch, not once per grouped RPC.
                out = self._global_window.submit(sub)
            if out is not None:
                st, lim, rem, rst = out
            else:
                t_serve = time.monotonic()
                try:
                    if hasattr(engine, "tables"):
                        st, lim, rem, rst = engine.apply_columnar(
                            packed, sub.algo, sub.behavior, sub.hits,
                            sub.limit, sub.duration, sub.burst,
                            now_ms=now_ms, route_hashes=sub.fnv1a,
                        )
                    else:
                        st, lim, rem, rst = engine.apply_columnar(
                            packed, sub.algo, sub.behavior, sub.hits,
                            sub.limit, sub.duration, sub.burst,
                            now_ms=now_ms,
                        )
                except Exception:
                    # The lane never applied: restore consumed credits
                    # and re-queue the pulled return rows, or the
                    # revoked leases' unused credit would stay debited
                    # on the device forever.
                    if led_plan is not None:
                        led_plan.rollback()
                    raise
                finally:
                    self.stage_timers["engine_serve"].observe(
                        time.monotonic() - t_serve
                    )
            if led_plan is not None and (
                len(owned_eng) or n_settles or n_acq
            ):
                # Engine outputs for the return rows + the owned
                # fall-through rows + the acquisition rows teach the
                # ledger (reconciliation, over-limit inserts, lease
                # grants) — learn expects them in [settles..., fall...,
                # acquires...] lane order.
                pos = np.searchsorted(eng_idx, owned_eng) + n_settles
                lidx = np.concatenate(
                    [
                        np.arange(n_settles, dtype=np.int64),
                        pos,
                        np.arange(n_acq, dtype=np.int64)
                        + n_settles + n_eng,
                    ]
                )
                led_plan.learn(st[lidx], lim[lidx], rem[lidx], rst[lidx])
            if n_settles or n_acq:
                sl = slice(n_settles, n_settles + n_eng)
                st, lim, rem, rst = st[sl], lim[sl], rem[sl], rst[sl]
            status[eng_idx] = st
            limit[eng_idx] = lim
            remaining[eng_idx] = rem
            reset[eng_idx] = rst

        # Stamp the apply order as close to the apply as possible
        # (see GlobalManager.next_update_seq).
        apply_seq = (
            self.global_mgr.next_update_seq() if len(owned_idx) else 0
        )
        if len(owned_idx):
            # Owner-side GLOBAL items queue the broadcast (reference:
            # gubernator.go:621-654 via apply_local_batch) — WITH the
            # decision columns just computed: the broadcast window
            # pushes these captured statuses instead of re-reading the
            # engine (the re-read was one extra engine dispatch per
            # window plus a per-key Python materialization pass; the
            # owner's serve IS the authoritative read).  apply_seq
            # orders the capture by engine-apply completion so a
            # racing slower thread cannot broadcast a superseded
            # status last.
            self.global_mgr.queue_updates_chunk(
                dec, owned_idx, status[owned_idx], limit[owned_idx],
                remaining[owned_idx], reset[owned_idx],
                seq=apply_seq,
            )
        self.counters["columnar"] += n
        self._offer_hotkeys(dec)
        if owner_strs:
            return wire_codec.encode_resps_owner(
                status, limit, remaining, reset, owner_meta_idx, owner_strs
            )
        return wire_codec.encode_resps(status, limit, remaining, reset)

    def apply_columnar_local(
        self,
        keys_str: List[str],
        keys_bytes: List[bytes],
        algo,
        behavior,
        hits,
        limit,
        duration,
        burst,
        *,
        check_ownership: bool = True,
    ):
        """Run an all-local batch through the engine's columnar path.

        Returns (status, limit, remaining, reset_time) numpy columns in
        request order, or None to decline (engine can't take columns, a
        write-through Store is attached, or some key is peer-owned) —
        the caller then falls back to the dataclass path.  The caller
        guarantees the batch has no GLOBAL / MULTI_REGION /
        DURATION_IS_GREGORIAN items and no invalid fields.
        """
        engine = self.engine
        apply_columnar = getattr(engine, "apply_columnar", None)
        if apply_columnar is None or getattr(engine, "store", None) is not None:
            return None
        if check_ownership:
            with self._peer_lock:
                picker = self.local_picker
            n_peers = picker.size()
            if n_peers == 1:
                # Single-node: the lone member is us iff marked owner.
                if not picker.peers()[0].info.is_owner:
                    return None
            elif n_peers > 1:
                owners = picker.get_batch(keys_str)
                if not all(o.info.is_owner for o in owners):
                    return None
            # Only the client-facing path counts as "local" traffic;
            # the dataclass peer path never bumps it either.
            self.counters["local"] += len(keys_bytes)
        self.counters["columnar"] += len(keys_bytes)
        if self.ledger is not None:
            # pb-decoded columns carry no fnv1a hashes, so this path
            # cannot consult the ledger — keep it coherent instead.
            self.ledger.invalidate_keys(keys_bytes)
        out = apply_columnar(
            keys_bytes, algo, behavior, hits, limit, duration, burst
        )
        aw = self.admission_watch
        if out is not None and aw.active and check_ownership:
            # Client-facing columnar answers only: the peer-side call
            # (check_ownership=False) serves batches a remote
            # client-facing node already counts from its responses.
            aw.observe_columns(keys_str, hits, out)
        return out

    def get_peer_batch(self, keys: Sequence[str]) -> List:
        """Owner clients for a key list — ONE lock + one vectorized
        ring pass (the GLOBAL hit windows look up every queued key)."""
        with self._peer_lock:
            if self.local_picker.size() == 0:
                return [None] * len(keys)
            return self.local_picker.get_batch(list(keys))

    def get_peer_batch_hashed(self, fnv1, fnv1a) -> Optional[List]:
        """Owner clients from precomputed key hashes (the columnar hit
        windows never materialize keys).  None when the picker is
        empty — callers fall back to local handling."""
        with self._peer_lock:
            picker = self.local_picker
            if picker.size() == 0:
                return None
            return picker.get_batch_dual_hashed(fnv1, fnv1a)

    def get_peer_rate_limits(
        self, requests: Sequence[RateLimitReq]
    ) -> List[RateLimitResp]:
        """Owner side of a forwarded batch — answered authoritatively,
        never re-forwarded.

        reference: gubernator.go:493-559.  The reference fans items over
        a worker pool with an order-restoring collector; here the whole
        batch is one engine call, order preserved by construction.
        """
        from gubernator_tpu.utils.tracing import span

        if len(requests) > MAX_BATCH_SIZE:
            self.counters["check_errors"] += 1
            raise ServiceError(
                f"'PeerRequest.rate_limits' list too large; max size is '{MAX_BATCH_SIZE}'"
            )
        with span("service.get_peer_rate_limits", batch=len(requests)):
            return self.apply_local_batch(list(requests))

    def update_peer_globals(self, globals_: Sequence[UpdatePeerGlobal]) -> None:
        """Owner-broadcast GLOBAL statuses land in the host status cache.

        reference: gubernator.go:470-490.
        """
        self.global_cache.put_many(
            (g.key, g.status, g.algorithm)
            for g in globals_
            if g.status is not None
        )

    def update_peer_globals_columns(self, dec) -> None:
        """Columnar variant (raw wire path — net/server.py)."""
        self.global_cache.put_columns(dec)

    def receive_transfer(self, raw: bytes) -> int:
        """Ownership-handoff receiver (PeersV1/TransferBuckets):
        restore one shipped window of bucket rows into the local
        engine; returns rows applied (cluster/handoff.py documents
        the protocol and its over-admission bound)."""
        from gubernator_tpu.cluster.handoff import receive_transfer

        return receive_transfer(self, raw)

    def receive_replication(self, raw: bytes) -> bytes:
        """Hot-key replication receiver (PeersV1/ReplicateKeys): install
        or revoke replica credit leases granted by a key's owner;
        returns the JSON response bytes carrying superseded leases'
        (consumed, unused) for the owner's reconciliation
        (cluster/replication.py documents the protocol and its
        N_replicas × lease over-admission bound)."""
        repl = self.replication
        if repl is None:
            # No replication plane on this node: the owner reads this
            # as a failed grant and returns the credit immediately.
            return b'{"disabled":true,"returns":[]}'
        return repl.receive(raw)

    def obs_snapshot_raw(self) -> bytes:
        """Fleet rollup scrape receiver (PeersV1/ObsSnapshot): this
        node's metric families as raw JSON (obs/fleet.py documents
        the schema and merge semantics).  A node without the obs
        plane answers its disabled shape so the collector can count
        it instead of erroring."""
        obs = self.obs
        if obs is None:
            return b'{"v":1,"disabled":true}'
        return obs.local_snapshot_raw()

    def health_check(self) -> HealthCheckResp:
        """Aggregate recent peer errors. reference: gubernator.go:562-619."""
        errs: List[str] = []
        with self._peer_lock:
            local_peers = self.local_picker.peers()
            region_peers = self.region_picker.peers()
        for p in local_peers:
            for e in p.last_errs():
                errs.append(f"Error returned from local peer.GetLastErr: {e}")
        for p in region_peers:
            for e in p.last_errs():
                errs.append(f"Error returned from region peer.GetLastErr: {e}")
        resp = HealthCheckResp(
            status=HEALTHY, peer_count=len(local_peers) + len(region_peers)
        )
        if errs:
            resp.status = UNHEALTHY
            resp.message = "|".join(errs)
        return resp

    # ------------------------------------------------------------------
    # Local execution

    def apply_local_batch(
        self, reqs: List[RateLimitReq], now_ms: Optional[int] = None
    ) -> List[RateLimitResp]:
        """Run a batch on the local engine, handling behavior queues.

        reference: gubernator.go:621-654 (getRateLimit): GLOBAL items
        queue an owner broadcast, MULTI_REGION items queue region hits,
        then the algorithm runs (here: one vectorized engine call).
        """
        g_items = [r for r in reqs if int(r.behavior) & _GLOBAL_I]
        if g_items:
            self.global_mgr.queue_updates_many(g_items)
        mr_idx = [
            i for i, r in enumerate(reqs)
            if int(r.behavior) & _MULTI_REGION_I
        ]
        if mr_idx:
            self.multi_region_mgr.queue_hits_many(
                reqs[i] for i in mr_idx
            )
        if self.ledger is not None:
            # This batch runs on the engine outside the ledger: settle
            # and drop any ledger entry for its keys first, so the
            # engine computes on the sequential state (O(1) dict probe
            # per key; almost always a miss).
            self.ledger.invalidate_keys(
                [r.hash_key().encode() for r in reqs]
            )
        resps = self.engine.get_rate_limits(reqs, now_ms=now_ms)
        if mr_idx:
            # Honest degradation hints ("When Two is Worse Than One"):
            # while a remote region's aggregate circuit is OPEN, this
            # answer's cross-region convergence is deferred behind the
            # requeue backlog — flag it so callers can tell a
            # federated answer from a partition-local one.  The drift
            # stays bounded: each region admits at most `limit` from
            # local state, ≤ N_regions × limit cluster-wide
            # (RESILIENCE.md §12).
            open_regions = self.multi_region_mgr.open_regions()
            if open_regions:
                self.counters["degraded_region_answers"] += len(mr_idx)
                joined = ",".join(open_regions)
                for i in mr_idx:
                    resp = resps[i]
                    md = dict(resp.metadata) if resp.metadata else {}
                    md["degraded_region"] = "true"
                    md["degraded_regions"] = joined
                    resp.metadata = md
        return resps

    # ------------------------------------------------------------------
    # Peer management (reference: gubernator.go:657-765)

    def set_peers(self, peer_infos: Sequence[PeerInfo]) -> None:
        """Rebuild pickers from a fresh peer list, reusing existing
        clients and draining dropped ones.

        reference: gubernator.go:657-740 (SetPeers).
        """
        with self._peer_lock:
            # Snapshot INSIDE the lock: two concurrent set_peers calls
            # (discovery push racing a manual static update) must not
            # both build from the same superseded ring and silently
            # drop the other's peers on publish.
            local_picker = self.local_picker.new()
            region_picker = self.region_picker.new()
            creds = self.conf.peer_credentials
            # Our own advertise address (the is_owner entry): stamped
            # on every client as the fault injector's src key.
            me_addr = next(
                (p.grpc_address for p in peer_infos if p.is_owner), ""
            )
            local_members: List[PeerClient] = []
            for info in peer_infos:
                # Strict DC match, like the reference — a node with
                # datacenter="" treats only ""-DC peers as local
                # (reference: gubernator.go:661-676).
                if info.datacenter != self.conf.data_center:
                    existing = self.region_picker.get_by_peer_info(info)
                    peer = existing or PeerClient(
                        info,
                        self.conf.behaviors,
                        credentials=creds,
                        flush_stat=self.flush_duration,
                    )
                    peer.info = info
                    peer.src_addr = me_addr
                    region_picker.add(peer)
                else:
                    existing = self.local_picker.get_by_peer_info(info)
                    peer = existing or PeerClient(
                        info,
                        self.conf.behaviors,
                        credentials=creds,
                        flush_stat=self.flush_duration,
                    )
                    peer.info = info
                    peer.src_addr = me_addr
                    local_members.append(peer)
            local_picker.add_all(local_members)  # one ring rebuild

            old_local = self.local_picker
            old_region = self.region_picker
            self.local_picker = local_picker
            self.region_picker = region_picker

        # Drain peers that fell out of the pool (in the background, like
        # the reference's goroutine at gubernator.go:719-731).
        keep = {p.info.grpc_address for p in local_picker.peers()}
        keep |= {p.info.grpc_address for p in region_picker.peers()}
        dropped = [
            p
            for p in (old_local.peers() + old_region.peers())
            if p.info.grpc_address not in keep
        ]
        for p in dropped:
            # guberlint: ok thread — bounded one-shot drain mirroring
            # the reference's goroutine (gubernator.go:719-731);
            # peer.shutdown() has an internal flush timeout, and the
            # peer object is unreachable afterwards.
            threading.Thread(target=p.shutdown, daemon=True).start()

    def get_peer(self, key: str) -> PeerClient:
        """Owner of one key. reference: gubernator.go:743-765."""
        with self._peer_lock:
            return self.local_picker.get(key)

    def get_peer_list(self) -> List[PeerClient]:
        with self._peer_lock:
            return self.local_picker.peers()

    def get_region_pickers(self):
        with self._peer_lock:
            return self.region_picker.pickers()

    # ------------------------------------------------------------------

    def close(self) -> None:
        """reference: gubernator.go:159-192 (Close)."""
        if self._closed:
            return
        self._closed = True
        if self.ledger is not None:
            self.ledger.close()
        self.global_mgr.close()
        self.multi_region_mgr.close()
        self._forward_pool.shutdown(wait=True)
        with self._peer_lock:
            peers = self.local_picker.peers() + self.region_picker.peers()
        for p in peers:
            p.shutdown(timeout=1.0)
        self.engine.close()

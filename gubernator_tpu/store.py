"""Persistence interfaces: write-through Store + bulk Loader.

reference: store.go — `Store` gets OnChange/Get/Remove called inline by
the algorithms (:49-65, call sites algorithms.go:46-54,164-169,266-269);
`Loader` streams the whole cache in at startup and out at shutdown
(:69-78, driven by gubernator_pool.go:341-531).  The bucket value
structs mirror store.go:29-43.

TPU adaptation: bucket state lives on device, so
- `Store.get` hydrates a freshly interned slot via a batched device
  scatter (`ops.bucket_kernel.load_slots`) instead of a cache insert;
- `Store.on_change` receives values derived from the kernel's response
  (for LEAKY_BUCKET the sub-integer remainder is quantized to the
  response's integer `remaining` — the reference hands the store its
  float64; a restored bucket may therefore leak up to one hit of
  precision per save/restore cycle);
- `Loader.save`/`load` use full-fidelity device snapshots (exact hi/lo
  words, including the leaky fixed-point fraction).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Protocol, Union

from gubernator_tpu.types import Algorithm, RateLimitReq


@dataclass
class TokenBucketItem:
    """reference: store.go:29-35."""

    status: int = 0
    limit: int = 0
    duration: int = 0
    remaining: int = 0
    created_at: int = 0  # unix ms


@dataclass
class LeakyBucketItem:
    """reference: store.go:37-43."""

    limit: int = 0
    duration: int = 0
    remaining: float = 0.0
    updated_at: int = 0  # unix ms
    burst: int = 0
    # Exact 32.32 fixed-point (whole, frac) words of `remaining` — set
    # by engine snapshots so Loader round-trips are bit-exact even when
    # the float64 mirror would round (whole part ≥ 2^21); restores
    # prefer these over `remaining` when present.
    remaining_words: Optional[tuple] = None


@dataclass
class CacheItem:
    """reference: cache.go:30-42."""

    key: str = ""
    value: Union[TokenBucketItem, LeakyBucketItem, None] = None
    expire_at: int = 0  # unix ms
    algorithm: int = Algorithm.TOKEN_BUCKET
    # A store may set this to force the cache to treat the item as
    # invalid after this time (reference: cache.go:37-41).
    invalid_at: int = 0


def words_from_float(v: float) -> tuple:
    """float remaining → exact-as-possible 32.32 fixed-point words."""
    import math

    whole = math.floor(v)
    frac = min((v - whole) * (2.0**32), 2.0**32 - 1)
    return (int(whole), int(frac))


def item_from_record(
    key: str,
    algorithm: int,
    status: int,
    limit: int,
    remaining: int,
    remf_hi: int,
    remf_lo: int,
    duration: int,
    t0: int,
    expire_at: int,
    burst: int,
    invalid_at: int,
) -> CacheItem:
    """Build a CacheItem from raw engine-state words — the ONE place
    that knows how snapshot columns map onto bucket value structs
    (used by both engines' export_items)."""
    if algorithm == int(Algorithm.TOKEN_BUCKET):
        value: Union[TokenBucketItem, LeakyBucketItem] = TokenBucketItem(
            status=status,
            limit=limit,
            duration=duration,
            remaining=remaining,
            created_at=t0,
        )
    else:
        value = LeakyBucketItem(
            limit=limit,
            duration=duration,
            # Float mirror rounds at whole ≥ 2^21; words are exact.
            remaining=float(remf_hi) + float(remf_lo) * 2.0**-32,
            updated_at=t0,
            burst=burst,
            remaining_words=(remf_hi, remf_lo),
        )
    return CacheItem(
        key=key,
        value=value,
        expire_at=expire_at,
        algorithm=algorithm,
        invalid_at=invalid_at,
    )


class Store(Protocol):
    """Write-through hooks, called by the engine per touched key.

    reference: store.go:49-65.
    """

    def on_change(self, req: RateLimitReq, item: CacheItem) -> None: ...

    def get(self, req: RateLimitReq) -> Optional[CacheItem]: ...

    def remove(self, key: str) -> None: ...


class Loader(Protocol):
    """Bulk restore/persist at startup/shutdown.

    reference: store.go:69-78.
    """

    def load(self) -> Iterable[CacheItem]: ...

    def save(self, items: Iterator[CacheItem]) -> None: ...


class MemoryStore:
    """Dict-backed Store (reference: MockStore, store.go:80-112)."""

    def __init__(self) -> None:
        self.data: Dict[str, CacheItem] = {}
        self.on_change_calls = 0
        self.get_calls = 0
        self.remove_calls = 0

    def on_change(self, req: RateLimitReq, item: CacheItem) -> None:
        self.on_change_calls += 1
        self.data[item.key] = item

    def get(self, req: RateLimitReq) -> Optional[CacheItem]:
        self.get_calls += 1
        return self.data.get(req.hash_key())

    def remove(self, key: str) -> None:
        self.remove_calls += 1
        self.data.pop(key, None)


class MemoryLoader:
    """List-backed Loader (reference: MockLoader, store.go:114-150)."""

    def __init__(self, items: Optional[List[CacheItem]] = None) -> None:
        self.items: List[CacheItem] = list(items or [])
        self.load_calls = 0
        self.save_calls = 0

    def load(self) -> Iterable[CacheItem]:
        self.load_calls += 1
        return list(self.items)

    def save(self, items: Iterator[CacheItem]) -> None:
        self.save_calls += 1
        self.items = list(items)

"""Dataclass ↔ protobuf conversion for the wire contract.

The engine and cluster tier work with the plain dataclasses in
`gubernator_tpu.types`; conversion happens once at the RPC boundary.
"""

from __future__ import annotations

from typing import Iterable, List

from gubernator_tpu.net.pb import gubernator_pb2 as pb
from gubernator_tpu.net.pb import peers_pb2 as peers_pb
from gubernator_tpu.types import (
    GetRateLimitsReq,
    GetRateLimitsResp,
    HealthCheckResp,
    RateLimitReq,
    RateLimitResp,
    UpdatePeerGlobal,
)


def rate_limit_req_to_pb(r: RateLimitReq) -> pb.RateLimitReq:
    return pb.RateLimitReq(
        name=r.name,
        unique_key=r.unique_key,
        hits=r.hits,
        limit=r.limit,
        duration=r.duration,
        algorithm=int(r.algorithm),
        behavior=int(r.behavior),
        burst=r.burst,
    )


def rate_limit_req_from_pb(m: pb.RateLimitReq) -> RateLimitReq:
    return RateLimitReq(
        name=m.name,
        unique_key=m.unique_key,
        hits=m.hits,
        limit=m.limit,
        duration=m.duration,
        algorithm=m.algorithm,
        behavior=m.behavior,
        burst=m.burst,
    )


def rate_limit_resp_to_pb(r: RateLimitResp) -> pb.RateLimitResp:
    m = pb.RateLimitResp(
        status=int(r.status),
        limit=r.limit,
        remaining=r.remaining,
        reset_time=r.reset_time,
        error=r.error,
    )
    for k, v in r.metadata.items():
        m.metadata[k] = v
    return m


def rate_limit_resp_from_pb(m: pb.RateLimitResp) -> RateLimitResp:
    return RateLimitResp(
        status=m.status,
        limit=m.limit,
        remaining=m.remaining,
        reset_time=m.reset_time,
        error=m.error,
        metadata=dict(m.metadata),
    )


def get_rate_limits_req_to_pb(reqs: Iterable[RateLimitReq]) -> pb.GetRateLimitsReq:
    return pb.GetRateLimitsReq(requests=[rate_limit_req_to_pb(r) for r in reqs])


def get_rate_limits_req_from_pb(m: pb.GetRateLimitsReq) -> GetRateLimitsReq:
    return GetRateLimitsReq(requests=[rate_limit_req_from_pb(r) for r in m.requests])


def get_rate_limits_resp_to_pb(resps: Iterable[RateLimitResp]) -> pb.GetRateLimitsResp:
    return pb.GetRateLimitsResp(responses=[rate_limit_resp_to_pb(r) for r in resps])


def get_rate_limits_resp_from_pb(m: pb.GetRateLimitsResp) -> GetRateLimitsResp:
    return GetRateLimitsResp(
        responses=[rate_limit_resp_from_pb(r) for r in m.responses]
    )


def health_check_resp_to_pb(r: HealthCheckResp) -> pb.HealthCheckResp:
    return pb.HealthCheckResp(
        status=r.status, message=r.message, peer_count=r.peer_count
    )


def health_check_resp_from_pb(m: pb.HealthCheckResp) -> HealthCheckResp:
    return HealthCheckResp(
        status=m.status, message=m.message, peer_count=m.peer_count
    )


def update_peer_global_to_pb(u: UpdatePeerGlobal) -> peers_pb.UpdatePeerGlobal:
    m = peers_pb.UpdatePeerGlobal(key=u.key, algorithm=int(u.algorithm))
    if u.status is not None:
        m.status.CopyFrom(rate_limit_resp_to_pb(u.status))
    return m


def update_peer_global_from_pb(m: peers_pb.UpdatePeerGlobal) -> UpdatePeerGlobal:
    return UpdatePeerGlobal(
        key=m.key,
        status=rate_limit_resp_from_pb(m.status),
        algorithm=m.algorithm,
    )


def peer_rate_limits_resp_to_pb(
    resps: Iterable[RateLimitResp],
) -> peers_pb.GetPeerRateLimitsResp:
    return peers_pb.GetPeerRateLimitsResp(
        rate_limits=[rate_limit_resp_to_pb(r) for r in resps]
    )


def peer_rate_limits_resp_from_pb(
    m: peers_pb.GetPeerRateLimitsResp,
) -> List[RateLimitResp]:
    return [rate_limit_resp_from_pb(r) for r in m.rate_limits]

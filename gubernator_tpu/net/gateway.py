"""HTTP/JSON gateway: REST facade over the service + /metrics.

reference: gubernator.pb.gw.go + daemon.go:222-268 — grpc-gateway v2
semantics: `POST /v1/GetRateLimits` and `GET /v1/HealthCheck` with
proto-JSON marshaling in snake_case (`UseProtoNames`), int64 as JSON
strings, enums as names; plus the prometheus `/metrics` endpoint and
`/healthz` for probes (reference: daemon.go:279-307 status listener).

Implemented directly on the service core (no loopback gRPC hop — the
reference only dials loopback because grpc-gateway needs a channel).
protobuf's own json_format does the marshaling, so the JSON contract is
byte-compatible with the reference gateway.
"""

from __future__ import annotations

import json
import ssl
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from google.protobuf import json_format

from prometheus_client import generate_latest
from prometheus_client.registry import CollectorRegistry

from gubernator_tpu.net import serde
from gubernator_tpu.net.pb import gubernator_pb2 as pb
from gubernator_tpu.net.pb import peers_pb2 as peers_pb
from gubernator_tpu.service import ServiceError, V1Instance


class _Handler(BaseHTTPRequestHandler):
    # Set by the server factory.
    instance: V1Instance
    registry: Optional[CollectorRegistry] = None
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet by default
        pass

    def _reply(self, code: int, body: bytes, content_type: str = "application/json"):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_error(self, http_code: int, grpc_code: int, message: str):
        # grpc-gateway error shape: {"code": ..., "message": ...}.
        self._reply(
            http_code,
            json.dumps({"code": grpc_code, "message": message}).encode(),
        )

    def do_GET(self):  # noqa: N802 (stdlib naming)
        path, _, query = self.path.partition("?")
        if path == "/v1/HealthCheck" or path == "/healthz":
            resp = serde.health_check_resp_to_pb(self.instance.health_check())
            self._reply(
                200,
                json_format.MessageToJson(
                    resp,
                    preserving_proto_field_name=True,
                    always_print_fields_with_no_presence=True,
                ).encode(),
            )
        elif path == "/metrics" and self.registry is not None:
            self._serve_metrics(query)
        elif path == "/debug/trace":
            self._reply(200, json.dumps(self._debug_trace()).encode())
        elif path == "/debug/hotkeys":
            self._reply(200, json.dumps(self._debug_hotkeys()).encode())
        elif path == "/debug/vars":
            self._reply(200, json.dumps(self._debug_vars()).encode())
        elif path == "/debug/fleet":
            self._reply(200, json.dumps(self._debug_fleet()).encode())
        elif path == "/debug/slo":
            self._reply(200, json.dumps(self._debug_slo()).encode())
        else:
            self._reply_error(404, 5, "not found")

    def _serve_metrics(self, query: str) -> None:
        """The /metrics scrape, with two opt-in extensions:

        - ``?fleet=1`` appends the gubernator_fleet_* rollup families
          (one ObsSnapshot fan-out, merged — any node answers for the
          cluster);
        - ``?exemplars=1`` switches to the OpenMetrics exposition so
          the stage-histogram buckets carry their trace_id exemplars
          (the classic format has no exemplar syntax and drops them).
        """
        from urllib.parse import parse_qs

        qs = parse_qs(query)

        def _flag(name: str) -> bool:
            return (qs.get(name, ["0"])[0] or "0") not in ("0", "false")

        want_exemplars = _flag("exemplars")
        if want_exemplars:
            from prometheus_client.openmetrics.exposition import (
                generate_latest as om_generate_latest,
            )

            gen = om_generate_latest
            ctype = (
                "application/openmetrics-text; version=1.0.0; "
                "charset=utf-8"
            )
        else:
            gen = generate_latest
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        body = gen(self.registry)
        if _flag("fleet"):
            obs = getattr(self.instance, "obs", None)
            if obs is not None:
                from gubernator_tpu.utils.metrics import (
                    build_fleet_registry,
                )

                extra = gen(build_fleet_registry(obs.collect()))
                if want_exemplars and body.endswith(b"# EOF\n"):
                    # OpenMetrics ends every exposition with "# EOF";
                    # splicing two outputs keeps exactly one.
                    body = body[: -len(b"# EOF\n")]
                body += extra
        self._reply(200, body, content_type=ctype)

    # -- /debug fleet/SLO surface (obs/; OBSERVABILITY.md §§9-10) ------

    def _debug_fleet(self) -> dict:
        """One cluster rollup from this node's vantage: the merged
        counters/gauges/quantiles plus the SLO evaluation OVER that
        rollup (read-only — the on-demand view must not pollute the
        watchdog's periodic sample cadence)."""
        obs = getattr(self.instance, "obs", None)
        if obs is None:
            return {"enabled": False}
        rollup = obs.collect()
        out = {"enabled": True}
        out.update(rollup)
        wd = getattr(self.instance, "slo_watchdog", None)
        if wd is not None:
            # Windowed (ratio/drops) burns only when the watchdog's
            # recorded history shares this rollup's FLEET scope — a
            # local-slice history differenced against a fleet rollup
            # would report other nodes' lifetime totals as window
            # traffic (phantom breaches).  Quantile + invariant SLIs
            # always evaluate (no history needed).
            out["slo"] = wd.evaluate(
                rollup, record=False, windowed=wd.fleet_scope
            )
        return out

    def _debug_slo(self) -> dict:
        wd = getattr(self.instance, "slo_watchdog", None)
        if wd is None:
            return {"enabled": False}
        return wd.status()

    # -- /debug introspection surface (OBSERVABILITY.md) ---------------

    def _debug_trace(self) -> dict:
        """Tail flight recorder dump: the retained span trees of
        decisions that exceeded the adaptive threshold."""
        fr = getattr(self.instance, "flight_recorder", None)
        if fr is None:
            return {"enabled": False, "traces": []}
        out = fr.dump()
        out["enabled"] = True
        return out

    def _debug_hotkeys(self) -> dict:
        hk = getattr(self.instance, "hotkeys", None)
        if hk is None:
            return {"enabled": False, "top": []}
        out = hk.stats()
        out["enabled"] = True
        out["top"] = [
            {
                "key": key.decode(errors="replace"),
                "count": count,
                "err": err,
            }
            for key, count, err in hk.top(50)
        ]
        return out

    def _debug_vars(self) -> dict:
        """One JSON snapshot of the node's live internals: counters,
        stage budget (real quantiles), ledger/native/ring stats, peer
        health, membership, and queue depths — the flight recorder's
        companion when attributing a tail."""
        inst = self.instance
        out: dict = {"counters": dict(inst.counters)}
        out["stage_budget"] = {
            stage: stat.snapshot_ms()
            for stage, stat in inst.stage_timers.items()
        }
        led = getattr(inst, "ledger", None)
        if led is not None:
            try:
                out["ledger"] = led.stats()
            except Exception:  # noqa: BLE001 — snapshot best-effort
                out["ledger"] = None
        ev = getattr(inst, "native_events", None)
        if ev is not None:
            out["native_events"] = ev.stats()
        out["peer_health"] = {}
        for p in inst.get_peer_list():
            if p.info.is_owner:
                continue
            out["peer_health"][p.info.grpc_address] = {
                "state": p.health.state(),
                "transitions": p.health.transition_counts(),
                "queue_length": p.queue_length(),
            }
        mem = getattr(inst, "membership", None)
        if mem is not None:
            try:
                out["membership"] = mem.stats()
            except Exception:  # noqa: BLE001 — snapshot best-effort
                out["membership"] = None
        out["handoff"] = dict(inst.handoff_counters)
        # PR 13/14 planes (hot-key replication, multi-region
        # federation): the same numbers /metrics exports as
        # gubernator_replication_* / gubernator_multiregion_*, in the
        # one-stop snapshot the other planes already had.
        repl = getattr(inst, "replication", None)
        if repl is not None:
            try:
                out["replication"] = repl.stats()
            except Exception:  # noqa: BLE001 — snapshot best-effort
                out["replication"] = None
        try:
            out["multiregion"] = inst.multi_region_mgr.stats()
        except Exception:  # noqa: BLE001 — snapshot best-effort
            out["multiregion"] = None
        out["global"] = {
            "hits_pending": inst.global_mgr._hits.pending(),
            "broadcasts_pending": inst.global_mgr._updates.pending(),
            "async_sends": inst.global_mgr.async_sends,
            "broadcasts": inst.global_mgr.broadcasts,
        }
        out["cache_size"] = inst.engine.cache_size()
        return out

    def _read_json(self, msg):
        length = int(self.headers.get("Content-Length", "0"))
        body = self.rfile.read(length)
        return json_format.Parse(body or b"{}", msg, ignore_unknown_fields=True)

    def _reply_json(self, msg):
        self._reply(
            200,
            json_format.MessageToJson(
                msg,
                preserving_proto_field_name=True,
                always_print_fields_with_no_presence=True,
            ).encode(),
        )

    def do_POST(self):  # noqa: N802
        path = self.path.split("?", 1)[0]
        try:
            if path == "/v1/GetRateLimits":
                req = self._read_json(pb.GetRateLimitsReq())
                resps = self.instance.get_rate_limits(
                    [serde.rate_limit_req_from_pb(m) for m in req.requests]
                )
                self._reply_json(serde.get_rate_limits_resp_to_pb(resps))
            elif path == "/pb.gubernator.PeersV1/GetPeerRateLimits":
                # Peer-service REST routes: grpc-gateway's unbound-method
                # default paths (reference: peers.pb.gw.go:108-143).
                req = self._read_json(peers_pb.GetPeerRateLimitsReq())
                resps = self.instance.get_peer_rate_limits(
                    [serde.rate_limit_req_from_pb(m) for m in req.requests]
                )
                self._reply_json(serde.peer_rate_limits_resp_to_pb(resps))
            elif path == "/pb.gubernator.PeersV1/UpdatePeerGlobals":
                req = self._read_json(peers_pb.UpdatePeerGlobalsReq())
                self.instance.update_peer_globals(
                    [serde.update_peer_global_from_pb(g) for g in req.globals]
                )
                self._reply_json(peers_pb.UpdatePeerGlobalsResp())
            else:
                self._reply_error(404, 5, "not found")
        except json_format.ParseError as e:
            self._reply_error(400, 3, str(e))  # INVALID_ARGUMENT
        except ServiceError as e:
            self._reply_error(400, 11, str(e))  # OUT_OF_RANGE


class Gateway:
    """The HTTP listener (gateway + metrics + health probes)."""

    def __init__(
        self,
        instance: V1Instance,
        address: str,
        registry: Optional[CollectorRegistry] = None,
        *,
        ssl_context: Optional[ssl.SSLContext] = None,
        serve_metrics: bool = True,
    ):
        host, _, port = address.rpartition(":")
        handler = type(
            "BoundHandler",
            (_Handler,),
            {
                "instance": instance,
                "registry": registry if serve_metrics else None,
            },
        )
        self._server = ThreadingHTTPServer((host or "0.0.0.0", int(port)), handler)
        self._server.daemon_threads = True
        if ssl_context is not None:
            self._server.socket = ssl_context.wrap_socket(
                self._server.socket, server_side=True
            )
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name=f"guber-gateway-{address}",
            daemon=True,
        )

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        # serve_forever returns after shutdown(); reap the thread so
        # the socket close below never races a final accept.
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)
        self._server.server_close()

"""Group-commit window for client-facing wire batches.

SURVEY §7.1's "batching front-end": the reference batches PEER
forwards over a 500µs window (peer_client.go:380-453) but processes
client requests immediately — fine when a decision costs microseconds
of Go, wrong when each dispatch pays a device round trip.  Under a
thundering herd of small RPCs, every request would otherwise pay its
own dispatch; this window lets concurrent requests share ONE engine
batch (group commit): the first arrival becomes the leader, sleeps
`wait` seconds while followers append, then runs the combined columns
through the engine once and hands each caller its slice.

Opt-in (GUBER_LOCAL_BATCH_WAIT, default 0 = disabled).  Round 6: the
configured wait is a CAP, not a fixed sleep — the window is
load-ADAPTIVE (the reference's interval semantics, peer_client.go:
380-453, applied to the client tier): a window that keeps grouping
only one RPC fires immediately (an isolated caller no longer pays the
window at all, VERDICT r5 weak #2's stacked-window mechanism), and the
wait grows toward the cap only while windows actually group concurrent
RPCs (where the amortization pays).  `adaptive=False` restores the
fixed wait for tests that pin window timing.

Round 7: submissions are ENGINE LANES, not whole RPCs — the decision
ledger (core/ledger.py) splits each batch before it reaches the window
(ledger-answerable rows never enter; the lane may carry prepended
credit-return rows and appended lease-acquisition rows), so a fully
hot-key RPC skips the window — and the dispatch — entirely.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional, Tuple

import numpy as np

log = logging.getLogger("gubernator_tpu.wire_window")


class _Entry:
    __slots__ = ("dec", "event", "result")

    def __init__(self, dec):
        self.dec = dec
        self.event = threading.Event()
        self.result = None  # (status, limit, remaining, reset) slices


class WireWindow:
    """Aggregates DecodedBatch submissions into one columnar engine
    call per window."""

    def __init__(
        self,
        engine,
        wait: float,
        follower_grace: float = 5.0,
        *,
        adaptive: bool = True,
        target_rpcs: int = 2,
        max_items: int = 4096,  # lanes per merged engine apply
        wait_stat=None,  # DurationStat: leader wait per window
        apply_stat=None,  # DurationStat: engine apply per window
    ):
        self.engine = engine
        self.wait = wait  # the window CAP (adaptive) or fixed sleep
        # How long past the expected window a follower waits before
        # concluding the leader died (tests shrink this).
        self.follower_grace = follower_grace
        # Adaptive interval state: EWMA of RPCs grouped per window.
        # Windows of 1 mean no concurrency → wait 0; `target_rpcs`
        # concurrent RPCs per window → the full cap.  The target is
        # LOW (2) on purpose: grouping has positive feedback (a longer
        # wait groups more, which amortizes the dispatch, which raises
        # the arrival a closed-loop herd can sustain), so the window
        # must reach its cap as soon as any steady sharing appears or
        # a slow-RPC host can stick at the ungrouped fixed point.
        self._adaptive = adaptive
        self._target_rpcs = max(2, target_rpcs)
        self._ewma_rpcs = 0.0
        self._wait_stat = wait_stat
        self._apply_stat = apply_stat
        # A merged window's lane count is bounded so its padded width
        # stays inside the daemon's warmed compile ladder — an
        # unbounded merge produced pow-2 widths the ladder never saw,
        # and the mid-serving XLA compile (hundreds of ms) became the
        # p99 tail the window exists to prevent.
        self.max_items = max_items
        self._lock = threading.Lock()
        self._pending: List[_Entry] = []
        self._leader_active = False
        # Windows whose engine apply is still running.  Leadership is
        # released BEFORE the apply (so the next window can form), which
        # means a zero-wait window under engine-serialized concurrency
        # would always swap a batch of ONE — each new arrival leads,
        # drains itself instantly, and queues on the engine lock.  The
        # EWMA would then never see concurrency and the adaptive wait
        # would stay at the ungrouped fixed point.  An in-flight run at
        # claim time IS the concurrency signal, so it seeds the EWMA.
        self._inflight_runs = 0
        # Metrics.
        self.windows = 0
        self.grouped_batches = 0

    def next_wait(self) -> float:
        """The wait the next leader will sleep (metrics + tests)."""
        if not self._adaptive:
            return self.wait
        frac = (self._ewma_rpcs - 1.0) / (self._target_rpcs - 1.0)
        w = self.wait * min(1.0, max(0.0, frac))
        return w if w >= 50e-6 else 0.0

    def _observe(self, n_rpcs: int) -> None:
        self._ewma_rpcs += 0.4 * (n_rpcs - self._ewma_rpcs)

    def submit(self, dec) -> Optional[Tuple]:
        """Run `dec` through a shared window; returns this batch's
        (status, limit, remaining, reset_time) columns."""
        entry = _Entry(dec)
        with self._lock:
            self._pending.append(entry)
            lead = not self._leader_active
            if lead:
                self._leader_active = True
        if not lead:
            # Bounded wait: if the leader dies before completing the
            # window, fall back to the protobuf path instead of
            # hanging the server's wire threads forever.
            if entry.event.wait(timeout=self.wait * 10 + self.follower_grace):
                return entry.result
            with self._lock:
                if entry in self._pending:
                    # Leader never swapped the batch out — this entry
                    # was never applied, so a caller-side fallback
                    # cannot double-count.  The leader is presumed
                    # dead: release leadership so the NEXT submit can
                    # lead instead of every future request eating this
                    # timeout (any still-live slow leader swapping
                    # later just takes whatever remains — the swap is
                    # atomic under the lock, so nothing double-applies).
                    self._pending.remove(entry)
                    self._leader_active = False
                    return None
            # A leader already took the batch: the hits WILL be applied
            # (or failed — _run always signals via its finally), so a
            # caller-side fallback here would double-count.  Wait for
            # the signal however long the apply takes; the only way it
            # never arrives is a hard-killed leader thread, at which
            # point the process is dying anyway.
            entry.event.wait()
            return entry.result
        w = self.next_wait()
        if w > 0:
            try:
                time.sleep(w)
            except BaseException:
                # Injected exception mid-window (interpreter shutdown,
                # etc.): release leadership and fail our batch so no
                # follower blocks on a window that will never run.
                with self._lock:
                    batch = self._pending
                    self._pending = []
                    self._leader_active = False
                for e in batch:
                    e.result = None
                    e.event.set()
                raise
        with self._lock:
            batch = self._pending
            self._pending = []
            self._leader_active = False
            busy = self._inflight_runs > 0
            self._inflight_runs += 1
        # A previous window's apply still in flight counts as a second
        # "RPC" toward the occupancy EWMA (see _inflight_runs above) —
        # it bootstraps the grouping feedback out of the zero-wait
        # fixed point under concurrent load, while an isolated caller
        # (never overlapping itself) still converges to zero wait.
        self._observe(max(len(batch), 2 if busy else 1))
        if self._wait_stat is not None:
            self._wait_stat.observe(w)
        try:
            self._run(batch)
        finally:
            with self._lock:
                self._inflight_runs -= 1
        return entry.result

    def _run(self, batch: List[_Entry]) -> None:
        # Split oversized merges so each apply stays within the warmed
        # width ladder (see max_items above).  Entries are never split
        # — each is ≤ MAX_BATCH_SIZE ≤ max_items.
        if len(batch) > 1:
            total = sum(e.dec.n for e in batch)
            if total > self.max_items:
                part: List[_Entry] = []
                part_n = 0
                for e in batch:
                    if part and part_n + e.dec.n > self.max_items:
                        self._run_group(part)
                        part, part_n = [], 0
                    part.append(e)
                    part_n += e.dec.n
                if part:
                    self._run_group(part)
                return
        self._run_group(batch)

    def _run_group(self, batch: List[_Entry]) -> None:
        from gubernator_tpu.core.engine import PackedKeys

        try:
            if len(batch) == 1:
                e = batch[0]
                d = e.dec
                e.result = self._apply(
                    PackedKeys(d.key_buf, d.key_offsets, d.n), d
                )
                return
            # Concatenate columns (+ key buffers with shifted offsets).
            decs = [e.dec for e in batch]
            key_buf = np.concatenate([d.key_buf for d in decs])
            offsets = [decs[0].key_offsets]
            base = decs[0].key_offsets[-1]
            for d in decs[1:]:
                offsets.append(d.key_offsets[1:] + base)
                base = base + d.key_offsets[-1]
            key_offsets = np.concatenate(offsets)
            n = sum(d.n for d in decs)
            cols = tuple(
                np.concatenate([getattr(d, f) for d in decs])
                for f in (
                    "algo", "behavior", "hits", "limit", "duration",
                    "burst", "fnv1a",
                )
            )

            class _Merged:
                pass

            m = _Merged()
            m.n = n
            (m.algo, m.behavior, m.hits, m.limit, m.duration, m.burst,
             m.fnv1a) = cols
            out = self._apply(PackedKeys(key_buf, key_offsets, n), m)
            self.windows += 1
            self.grouped_batches += len(batch)
            lo = 0
            for e in batch:
                hi = lo + e.dec.n
                e.result = tuple(col[lo:hi] for col in out)
                lo = hi
        except Exception:  # noqa: BLE001
            # Callers fall back to the protobuf path on None.
            from gubernator_tpu.utils.metrics import record_swallowed

            record_swallowed("wire_window.apply")
            log.exception("wire window apply failed; callers fall back")
            for e in batch:
                e.result = None
        finally:
            for e in batch:
                e.event.set()

    def _apply(self, packed, d):
        t0 = time.monotonic()
        try:
            if hasattr(self.engine, "tables"):
                return self.engine.apply_columnar(
                    packed, d.algo, d.behavior, d.hits, d.limit,
                    d.duration, d.burst, route_hashes=d.fnv1a,
                )
            return self.engine.apply_columnar(
                packed, d.algo, d.behavior, d.hits, d.limit, d.duration,
                d.burst,
            )
        finally:
            if self._apply_stat is not None:
                # ONE observation per device dispatch, however many
                # RPCs shared the window (the stage budget's
                # engine_serve term must not scale with grouping).
                self._apply_stat.observe(time.monotonic() - t0)

"""Group-commit window for client-facing wire batches.

SURVEY §7.1's "batching front-end": the reference batches PEER
forwards over a 500µs window (peer_client.go:380-453) but processes
client requests immediately — fine when a decision costs microseconds
of Go, wrong when each dispatch pays a device round trip.  Under a
thundering herd of small RPCs, every request would otherwise pay its
own dispatch; this window lets concurrent requests share ONE engine
batch (group commit): the first arrival becomes the leader, sleeps
`wait` seconds while followers append, then runs the combined columns
through the engine once and hands each caller its slice.

Opt-in (GUBER_LOCAL_BATCH_WAIT, default 0 = disabled) because it adds
`wait` to the latency of isolated requests — the classic throughput/
latency trade the reference exposes as BehaviorConfig.BatchWait for
its peer tier (config.go:113-115).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional, Tuple

import numpy as np

log = logging.getLogger("gubernator_tpu.wire_window")


class _Entry:
    __slots__ = ("dec", "event", "result")

    def __init__(self, dec):
        self.dec = dec
        self.event = threading.Event()
        self.result = None  # (status, limit, remaining, reset) slices


class WireWindow:
    """Aggregates DecodedBatch submissions into one columnar engine
    call per window."""

    def __init__(self, engine, wait: float, follower_grace: float = 5.0):
        self.engine = engine
        self.wait = wait
        # How long past the expected window a follower waits before
        # concluding the leader died (tests shrink this).
        self.follower_grace = follower_grace
        self._lock = threading.Lock()
        self._pending: List[_Entry] = []
        self._leader_active = False
        # Metrics.
        self.windows = 0
        self.grouped_batches = 0

    def submit(self, dec) -> Optional[Tuple]:
        """Run `dec` through a shared window; returns this batch's
        (status, limit, remaining, reset_time) columns."""
        entry = _Entry(dec)
        with self._lock:
            self._pending.append(entry)
            lead = not self._leader_active
            if lead:
                self._leader_active = True
        if not lead:
            # Bounded wait: if the leader dies before completing the
            # window, fall back to the protobuf path instead of
            # hanging the server's wire threads forever.
            if entry.event.wait(timeout=self.wait * 10 + self.follower_grace):
                return entry.result
            with self._lock:
                if entry in self._pending:
                    # Leader never swapped the batch out — this entry
                    # was never applied, so a caller-side fallback
                    # cannot double-count.  The leader is presumed
                    # dead: release leadership so the NEXT submit can
                    # lead instead of every future request eating this
                    # timeout (any still-live slow leader swapping
                    # later just takes whatever remains — the swap is
                    # atomic under the lock, so nothing double-applies).
                    self._pending.remove(entry)
                    self._leader_active = False
                    return None
            # A leader already took the batch: the hits WILL be applied
            # (or failed — _run always signals via its finally), so a
            # caller-side fallback here would double-count.  Wait for
            # the signal however long the apply takes; the only way it
            # never arrives is a hard-killed leader thread, at which
            # point the process is dying anyway.
            entry.event.wait()
            return entry.result
        try:
            time.sleep(self.wait)
        except BaseException:
            # Injected exception mid-window (interpreter shutdown,
            # etc.): release leadership and fail our batch so no
            # follower blocks on a window that will never run.
            with self._lock:
                batch = self._pending
                self._pending = []
                self._leader_active = False
            for e in batch:
                e.result = None
                e.event.set()
            raise
        with self._lock:
            batch = self._pending
            self._pending = []
            self._leader_active = False
        self._run(batch)
        return entry.result

    def _run(self, batch: List[_Entry]) -> None:
        from gubernator_tpu.core.engine import PackedKeys

        try:
            if len(batch) == 1:
                e = batch[0]
                d = e.dec
                e.result = self._apply(
                    PackedKeys(d.key_buf, d.key_offsets, d.n), d
                )
                return
            # Concatenate columns (+ key buffers with shifted offsets).
            decs = [e.dec for e in batch]
            key_buf = np.concatenate([d.key_buf for d in decs])
            offsets = [decs[0].key_offsets]
            base = decs[0].key_offsets[-1]
            for d in decs[1:]:
                offsets.append(d.key_offsets[1:] + base)
                base = base + d.key_offsets[-1]
            key_offsets = np.concatenate(offsets)
            n = sum(d.n for d in decs)
            cols = tuple(
                np.concatenate([getattr(d, f) for d in decs])
                for f in (
                    "algo", "behavior", "hits", "limit", "duration",
                    "burst", "fnv1a",
                )
            )

            class _Merged:
                pass

            m = _Merged()
            m.n = n
            (m.algo, m.behavior, m.hits, m.limit, m.duration, m.burst,
             m.fnv1a) = cols
            out = self._apply(PackedKeys(key_buf, key_offsets, n), m)
            self.windows += 1
            self.grouped_batches += len(batch)
            lo = 0
            for e in batch:
                hi = lo + e.dec.n
                e.result = tuple(col[lo:hi] for col in out)
                lo = hi
        except Exception:  # noqa: BLE001
            # Callers fall back to the protobuf path on None.
            log.exception("wire window apply failed; callers fall back")
            for e in batch:
                e.result = None
        finally:
            for e in batch:
                e.event.set()

    def _apply(self, packed, d):
        if hasattr(self.engine, "tables"):
            return self.engine.apply_columnar(
                packed, d.algo, d.behavior, d.hits, d.limit, d.duration,
                d.burst, route_hashes=d.fnv1a,
            )
        return self.engine.apply_columnar(
            packed, d.algo, d.behavior, d.hits, d.limit, d.duration,
            d.burst,
        )

#!/bin/sh
# Regenerate gubernator_tpu/net/pb from the .proto sources.
# The generated peers_pb2 imports its sibling with a bare top-level
# import; rewrite it package-relative so `import gubernator_tpu` works
# without sys.path games.
set -e
cd "$(dirname "$0")/.."
protoc -Iproto --python_out=pb proto/gubernator.proto proto/peers.proto
sed -i 's/^import gubernator_pb2 as gubernator__pb2$/from gubernator_tpu.net.pb import gubernator_pb2 as gubernator__pb2/' pb/peers_pb2.py

#!/bin/sh
# Regenerate gubernator_tpu/net/pb from the .proto sources.
# The generated peers_pb2 imports its sibling with a bare top-level
# import; rewrite it package-relative so `import gubernator_tpu` works
# without sys.path games.
set -e
cd "$(dirname "$0")/.."
protoc -Iproto --python_out=pb proto/gubernator.proto proto/peers.proto \
    proto/etcd_kv.proto proto/etcd_rpc.proto
sed -i 's/^import gubernator_pb2 as gubernator__pb2$/from gubernator_tpu.net.pb import gubernator_pb2 as gubernator__pb2/' pb/peers_pb2.py
sed -i 's/^import etcd_kv_pb2 as etcd__kv__pb2$/from gubernator_tpu.net.pb import etcd_kv_pb2 as etcd__kv__pb2/' pb/etcd_rpc_pb2.py

"""Network plane: proto contract, gRPC services, HTTP/JSON gateway, TLS.

Client-facing and peer-facing RPC stays a host-level concern (SURVEY.md
§2.3): the TPU data path begins after batches are decoded.  Wire contract
is identical to the reference so its clients work unchanged.
"""

from gubernator_tpu.net.serde import (
    rate_limit_req_from_pb,
    rate_limit_req_to_pb,
    rate_limit_resp_from_pb,
    rate_limit_resp_to_pb,
)

__all__ = [
    "rate_limit_req_from_pb",
    "rate_limit_req_to_pb",
    "rate_limit_resp_from_pb",
    "rate_limit_resp_to_pb",
]

"""gRPC servicer adapters: pb messages ↔ V1Instance.

The service core (gubernator_tpu.service) speaks dataclasses; these
adapters sit at the transport edge, converting once per RPC and mapping
ServiceError to gRPC status codes (the only RPC-level error the
contract allows — oversized batches; reference: gubernator.go:212-216).
"""

from __future__ import annotations

import contextlib

from typing import Optional, Tuple

import grpc
import numpy as np

from gubernator_tpu.net import serde
from gubernator_tpu.net.pb import gubernator_pb2 as pb
from gubernator_tpu.net.pb import peers_pb2 as peers_pb
from gubernator_tpu.service import ServiceError, V1Instance
from gubernator_tpu.types import MAX_BATCH_SIZE, Behavior

_CODE = {
    "OUT_OF_RANGE": grpc.StatusCode.OUT_OF_RANGE,
    "INVALID_ARGUMENT": grpc.StatusCode.INVALID_ARGUMENT,
    "INTERNAL": grpc.StatusCode.INTERNAL,
}

# Behaviors that need the dataclass path (defined next to the service
# core; the native wire codec shares the same mask).
from gubernator_tpu.service import COLUMNAR_DISQUALIFIERS as _COLUMNAR_DISQUALIFIERS  # noqa: E402


def _decode_columns(items) -> Optional[Tuple]:
    """One pass over the pb batch into numpy columns, or None if any
    item needs the dataclass path (special behavior or a field error).

    This skips dataclass materialization entirely for the common case —
    the decoded columns feed DecisionEngine.apply_columnar, the same
    program bench.py measures (reference hot path: gubernator.go:197-317).
    """
    n = len(items)
    if n == 0 or n > MAX_BATCH_SIZE:
        return None
    keys_str = [""] * n
    keys_bytes: list = [b""] * n
    algo = np.empty(n, dtype=np.int32)
    behavior = np.empty(n, dtype=np.int32)
    hits = np.empty(n, dtype=np.int64)
    limit = np.empty(n, dtype=np.int64)
    duration = np.empty(n, dtype=np.int64)
    burst = np.empty(n, dtype=np.int64)
    for i, m in enumerate(items):
        b = m.behavior
        if b & _COLUMNAR_DISQUALIFIERS:
            return None
        name = m.name
        uk = m.unique_key
        if not name or not uk:
            return None
        k = name + "_" + uk  # canonical hash key (reference: client.go:37-39)
        keys_str[i] = k
        keys_bytes[i] = k.encode()
        algo[i] = m.algorithm
        behavior[i] = b
        hits[i] = m.hits
        limit[i] = m.limit
        duration[i] = m.duration
        burst[i] = m.burst
    return keys_str, keys_bytes, algo, behavior, hits, limit, duration, burst


def _fill_rate_limit_resps(field, cols) -> None:
    """Fill a repeated RateLimitResp field from the engine's output
    columns."""
    status, limit, remaining, reset_time = cols
    for st, li, rem, rt in zip(
        status.tolist(), limit.tolist(), remaining.tolist(), reset_time.tolist()
    ):
        field.add(status=st, limit=li, remaining=rem, reset_time=rt)



def _handler_span(name: str, context):
    """Span for one inbound RPC, joined to the caller's trace via the
    ``traceparent`` metadata pair (utils/tracing) — a contextmanager
    that is free when tracing is off (one global check, no metadata
    read)."""
    from gubernator_tpu.utils import tracing

    if not tracing.active():
        return contextlib.nullcontext()
    return tracing.span(
        name,
        remote_parent=tracing.remote_parent_from_metadata(
            context.invocation_metadata()
        ),
    )


class GrpcV1Adapter:
    """Public service (reference: proto/gubernator.proto:27-45)."""

    def __init__(self, instance: V1Instance):
        self.instance = instance

    def GetRateLimits(self, request, context):
        with _handler_span("rpc.get_rate_limits", context):
            return self._get_rate_limits(request, context)

    def _get_rate_limits(self, request, context):
        # The method handler passes RAW request bytes (grpc_service
        # _unary_raw): the native codec path serves the whole RPC in
        # compiled code when it can.
        if isinstance(request, (bytes, memoryview)):
            out_raw = self.instance.serve_wire_bytes(request)
            if out_raw is not None:
                return out_raw
            try:
                request = pb.GetRateLimitsReq.FromString(request)
            except Exception:  # noqa: BLE001 — match the framework
                # deserializer's client-visible INTERNAL status.
                context.abort(
                    grpc.StatusCode.INTERNAL, "Exception deserializing request!"
                )
        cols = _decode_columns(request.requests)
        if cols is not None:
            keys_str, keys_bytes, *columns = cols
            out = self.instance.apply_columnar_local(keys_str, keys_bytes, *columns)
            if out is not None:
                resp = pb.GetRateLimitsResp()
                _fill_rate_limit_resps(resp.responses, out)
                return resp
        reqs = [serde.rate_limit_req_from_pb(m) for m in request.requests]
        try:
            resps = self.instance.get_rate_limits(reqs)
        except ServiceError as e:
            context.abort(_CODE.get(e.code, grpc.StatusCode.INTERNAL), str(e))
        return serde.get_rate_limits_resp_to_pb(resps)

    def HealthCheck(self, request, context):
        return serde.health_check_resp_to_pb(self.instance.health_check())


class GrpcPeersV1Adapter:
    """Peer-only service (reference: proto/peers.proto:28-34)."""

    def __init__(self, instance: V1Instance):
        self.instance = instance

    def GetPeerRateLimits(self, request, context):
        with _handler_span("rpc.get_peer_rate_limits", context):
            return self._get_peer_rate_limits(request, context)

    def _get_peer_rate_limits(self, request, context):
        # Owner side of a forwarded batch: answered authoritatively
        # (never re-forwarded), so no ownership check is needed.
        if isinstance(request, (bytes, memoryview)):
            out_raw = self.instance.serve_wire_bytes(
                request, check_ownership=False
            )
            if out_raw is not None:
                return out_raw
            try:
                request = peers_pb.GetPeerRateLimitsReq.FromString(request)
            except Exception:  # noqa: BLE001 — see GetRateLimits
                context.abort(
                    grpc.StatusCode.INTERNAL, "Exception deserializing request!"
                )
        cols = _decode_columns(request.requests)
        if cols is not None:
            keys_str, keys_bytes, *columns = cols
            out = self.instance.apply_columnar_local(
                keys_str, keys_bytes, *columns, check_ownership=False
            )
            if out is not None:
                resp = peers_pb.GetPeerRateLimitsResp()
                _fill_rate_limit_resps(resp.rate_limits, out)
                return resp
        reqs = [serde.rate_limit_req_from_pb(m) for m in request.requests]
        try:
            resps = self.instance.get_peer_rate_limits(reqs)
        except ServiceError as e:
            context.abort(_CODE.get(e.code, grpc.StatusCode.INTERNAL), str(e))
        return serde.peer_rate_limits_resp_to_pb(resps)

    def UpdatePeerGlobals(self, request, context):
        with _handler_span("rpc.update_peer_globals", context):
            return self._update_peer_globals(request, context)

    def _update_peer_globals(self, request, context):
        # Raw-bytes fast path: the broadcast plane is the cluster
        # tier's highest-rate message; decode straight into status-
        # cache columns (net/wire_codec.decode_globals).
        if isinstance(request, (bytes, memoryview)):
            from gubernator_tpu.net import wire_codec
            from gubernator_tpu.types import MAX_BATCH_SIZE

            dec = wire_codec.decode_globals(
                bytes(request), MAX_BATCH_SIZE
            )
            if dec is not None:
                self.instance.update_peer_globals_columns(dec)
                return b""  # empty UpdatePeerGlobalsResp
            try:
                request = peers_pb.UpdatePeerGlobalsReq.FromString(
                    bytes(request)
                )
            except Exception:  # noqa: BLE001 — see GetRateLimits
                context.abort(
                    grpc.StatusCode.INTERNAL,
                    "Exception deserializing request!",
                )
        self.instance.update_peer_globals(
            [serde.update_peer_global_from_pb(g) for g in request.globals]
        )
        return peers_pb.UpdatePeerGlobalsResp()

    def TransferBuckets(self, request, context):
        with _handler_span("rpc.transfer_buckets", context):
            return self._transfer_buckets(request, context)

    def _transfer_buckets(self, request, context):
        # Ownership handoff (cluster/handoff.py): restore a shipped
        # window of bucket rows into the local engine.  Raw JSON in,
        # empty response out.
        try:
            self.instance.receive_transfer(bytes(request))
        except (ValueError, KeyError, IndexError, TypeError) as e:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"malformed bucket transfer: {e}",
            )
        return b""

    def ReplicateKeys(self, request, context):
        with _handler_span("rpc.replicate_keys", context):
            return self._replicate_keys(request, context)

    def _replicate_keys(self, request, context):
        # Hot-key replication (cluster/replication.py): install/revoke
        # replica credit leases.  Raw JSON in, raw JSON out (the
        # response carries superseded leases' credit accounting for
        # the owner's reconciliation).
        try:
            return self.instance.receive_replication(bytes(request))
        except (ValueError, KeyError, IndexError, TypeError) as e:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"malformed replication message: {e}",
            )

    def ObsSnapshot(self, request, context):
        with _handler_span("rpc.obs_snapshot", context):
            # Fleet rollup scrape (obs/fleet.py): this node's metric
            # families as raw JSON.  The request body is empty by
            # contract; a node without the obs plane answers its
            # disabled shape so the collector can count it.
            return self.instance.obs_snapshot_raw()

"""gRPC servicer adapters: pb messages ↔ V1Instance.

The service core (gubernator_tpu.service) speaks dataclasses; these
adapters sit at the transport edge, converting once per RPC and mapping
ServiceError to gRPC status codes (the only RPC-level error the
contract allows — oversized batches; reference: gubernator.go:212-216).
"""

from __future__ import annotations

import grpc

from gubernator_tpu.net import serde
from gubernator_tpu.net.pb import gubernator_pb2 as pb
from gubernator_tpu.net.pb import peers_pb2 as peers_pb
from gubernator_tpu.service import ServiceError, V1Instance

_CODE = {
    "OUT_OF_RANGE": grpc.StatusCode.OUT_OF_RANGE,
    "INVALID_ARGUMENT": grpc.StatusCode.INVALID_ARGUMENT,
    "INTERNAL": grpc.StatusCode.INTERNAL,
}


class GrpcV1Adapter:
    """Public service (reference: proto/gubernator.proto:27-45)."""

    def __init__(self, instance: V1Instance):
        self.instance = instance

    def GetRateLimits(self, request, context):
        reqs = [serde.rate_limit_req_from_pb(m) for m in request.requests]
        try:
            resps = self.instance.get_rate_limits(reqs)
        except ServiceError as e:
            context.abort(_CODE.get(e.code, grpc.StatusCode.INTERNAL), str(e))
        return serde.get_rate_limits_resp_to_pb(resps)

    def HealthCheck(self, request, context):
        return serde.health_check_resp_to_pb(self.instance.health_check())


class GrpcPeersV1Adapter:
    """Peer-only service (reference: proto/peers.proto:28-34)."""

    def __init__(self, instance: V1Instance):
        self.instance = instance

    def GetPeerRateLimits(self, request, context):
        reqs = [serde.rate_limit_req_from_pb(m) for m in request.requests]
        try:
            resps = self.instance.get_peer_rate_limits(reqs)
        except ServiceError as e:
            context.abort(_CODE.get(e.code, grpc.StatusCode.INTERNAL), str(e))
        return serde.peer_rate_limits_resp_to_pb(resps)

    def UpdatePeerGlobals(self, request, context):
        self.instance.update_peer_globals(
            [serde.update_peer_global_from_pb(g) for g in request.globals]
        )
        return peers_pb.UpdatePeerGlobalsResp()

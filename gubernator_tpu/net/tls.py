"""TLS subsystem: server/client credentials, mTLS, AutoTLS.

reference: tls.go — TLSConfig with file or PEM-buffer pairs for CA,
server cert, and client-auth CA/cert (:46-123); SetupTLS builds
ServerTLS/ClientTLS with system-CA merge (:231-240) and mTLS client
pools (:252-278); AutoTLS generates a self-signed CA (selfCA :384-436)
and a per-host server cert with SANs (selfCert :285-382).

The reference uses ECDSA P-521 for AutoTLS; we use P-384 (P-521 offers
no practical benefit and is slower in the Python `cryptography` stack).
"""

from __future__ import annotations

import datetime
import ipaddress
import socket
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import grpc


@dataclass
class TLSConfig:
    """reference: tls.go:46-123 (TLSConfig struct)."""

    ca_file: str = ""
    ca_key_file: str = ""
    cert_file: str = ""
    key_file: str = ""
    # PEM buffers (take precedence over files when set).
    ca_pem: bytes = b""
    ca_key_pem: bytes = b""
    cert_pem: bytes = b""
    key_pem: bytes = b""
    # Generate a self-signed CA + server cert at startup.
    auto_tls: bool = False
    # "" | "request" | "require-and-verify"
    # (reference: config.go TLS client auth modes).
    client_auth: str = ""
    client_auth_ca_file: str = ""
    client_auth_ca_pem: bytes = b""
    # Client-side identity for peer dials / clients under mTLS.
    client_auth_cert_file: str = ""
    client_auth_key_file: str = ""
    client_auth_cert_pem: bytes = b""
    client_auth_key_pem: bytes = b""
    # Extra SANs for AutoTLS certs.
    auto_tls_hosts: List[str] = field(default_factory=list)

    def _load(self, pem: bytes, path: str) -> bytes:
        if pem:
            return pem
        if path:
            with open(path, "rb") as f:
                return f.read()
        return b""

    def setup(self) -> "TLSBundle":
        """Materialize credentials. reference: tls.go:126-283 (SetupTLS)."""
        ca = self._load(self.ca_pem, self.ca_file)
        ca_key = self._load(self.ca_key_pem, self.ca_key_file)
        cert = self._load(self.cert_pem, self.cert_file)
        key = self._load(self.key_pem, self.key_file)

        if self.auto_tls and not cert:
            if not ca:
                ca, ca_key = generate_self_ca()
            if not ca_key:
                raise ValueError(
                    "AutoTLS needs a CA private key to mint the server cert"
                )
            cert, key = generate_server_cert(ca, ca_key, self.auto_tls_hosts)

        if not cert or not key:
            raise ValueError("TLS enabled but no server cert/key configured")

        client_ca = self._load(self.client_auth_ca_pem, self.client_auth_ca_file)
        if self.client_auth and not client_ca:
            client_ca = ca
        client_cert = self._load(
            self.client_auth_cert_pem, self.client_auth_cert_file
        )
        client_key = self._load(self.client_auth_key_pem, self.client_auth_key_file)

        return TLSBundle(
            ca_pem=ca,
            server_cert_pem=cert,
            server_key_pem=key,
            client_auth=self.client_auth,
            client_ca_pem=client_ca,
            client_cert_pem=client_cert,
            client_key_pem=client_key,
        )


@dataclass
class TLSBundle:
    """Materialized PEMs + gRPC credential builders."""

    ca_pem: bytes
    server_cert_pem: bytes
    server_key_pem: bytes
    client_auth: str = ""
    client_ca_pem: bytes = b""
    client_cert_pem: bytes = b""
    client_key_pem: bytes = b""

    def server_credentials(self) -> grpc.ServerCredentials:
        require = self.client_auth == "require-and-verify"
        return grpc.ssl_server_credentials(
            [(self.server_key_pem, self.server_cert_pem)],
            root_certificates=self.client_ca_pem or self.ca_pem
            if self.client_auth
            else None,
            require_client_auth=require,
        )

    def client_credentials(self) -> grpc.ChannelCredentials:
        if self.client_cert_pem and self.client_key_pem:
            return grpc.ssl_channel_credentials(
                root_certificates=self.ca_pem,
                private_key=self.client_key_pem,
                certificate_chain=self.client_cert_pem,
            )
        return grpc.ssl_channel_credentials(root_certificates=self.ca_pem)


def _key_and_name(common_name: str):
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography import x509
    from cryptography.x509.oid import NameOID

    key = ec.generate_private_key(ec.SECP384R1())
    name = x509.Name(
        [
            x509.NameAttribute(NameOID.ORGANIZATION_NAME, "gubernator_tpu"),
            x509.NameAttribute(NameOID.COMMON_NAME, common_name),
        ]
    )
    return key, name


def _pem(cert, key) -> Tuple[bytes, bytes]:
    from cryptography.hazmat.primitives import serialization

    cert_pem = cert.public_bytes(serialization.Encoding.PEM)
    key_pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )
    return cert_pem, key_pem


def generate_self_ca(valid_days: int = 365) -> Tuple[bytes, bytes]:
    """Mint a self-signed CA. reference: tls.go:384-436 (selfCA)."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes

    key, name = _key_and_name("gubernator_tpu AutoTLS CA")
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=valid_days))
        .add_extension(x509.BasicConstraints(ca=True, path_length=None), critical=True)
        .add_extension(
            x509.KeyUsage(
                digital_signature=True,
                key_cert_sign=True,
                crl_sign=True,
                content_commitment=False,
                key_encipherment=False,
                data_encipherment=False,
                key_agreement=False,
                encipher_only=False,
                decipher_only=False,
            ),
            critical=True,
        )
        .sign(key, hashes.SHA384())
    )
    return _pem(cert, key)


def discover_san_hosts() -> List[str]:
    """Hostname + local interface addresses for AutoTLS SANs.

    reference: net.go:57-122 (interface scan).
    """
    hosts = {"localhost", socket.gethostname(), "127.0.0.1", "::1"}
    try:
        for info in socket.getaddrinfo(socket.gethostname(), None):
            hosts.add(info[4][0])
    except socket.gaierror:
        pass
    return sorted(hosts)


def generate_server_cert(
    ca_pem: bytes,
    ca_key_pem: bytes,
    hosts: Optional[List[str]] = None,
    valid_days: int = 365,
) -> Tuple[bytes, bytes]:
    """Mint a CA-signed server cert with discovered SANs.

    reference: tls.go:285-382 (selfCert).
    """
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.serialization import load_pem_private_key

    ca_cert = x509.load_pem_x509_certificate(ca_pem)
    ca_key = load_pem_private_key(ca_key_pem, password=None)

    all_hosts = list(dict.fromkeys((hosts or []) + discover_san_hosts()))
    sans: List[x509.GeneralName] = []
    for h in all_hosts:
        try:
            sans.append(x509.IPAddress(ipaddress.ip_address(h)))
        except ValueError:
            sans.append(x509.DNSName(h))

    key, name = _key_and_name(socket.gethostname())
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(ca_cert.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=valid_days))
        .add_extension(x509.SubjectAlternativeName(sans), critical=False)
        .add_extension(
            x509.ExtendedKeyUsage(
                [
                    x509.oid.ExtendedKeyUsageOID.SERVER_AUTH,
                    x509.oid.ExtendedKeyUsageOID.CLIENT_AUTH,
                ]
            ),
            critical=False,
        )
        .sign(ca_key, hashes.SHA384())
    )
    return _pem(cert, key)

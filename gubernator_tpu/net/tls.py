"""TLS subsystem: server/client credentials, mTLS, AutoTLS.

reference: tls.go — TLSConfig with file or PEM-buffer pairs for CA,
server cert, and client-auth CA/cert (:46-123); SetupTLS builds
ServerTLS/ClientTLS with system-CA merge (:231-240) and mTLS client
pools (:252-278); AutoTLS generates a self-signed CA (selfCA :384-436)
and a per-host server cert with SANs (selfCert :285-382).

The reference uses ECDSA P-521 for AutoTLS; we use P-384 (P-521 offers
no practical benefit and is slower in the Python `cryptography` stack).

Cert minting backends: the Python `cryptography` package when
importable, otherwise the `openssl` CLI (present in every image this
repo targets; the grpc wheel itself links OpenSSL, so the CLI is a
strictly weaker dependency than the wheel already carries).  Both
produce the same shape — P-384 key, CA with basicConstraints+keyUsage,
server cert with discovered SANs — and the TLS tests exercise
whichever backend the environment has.
"""

from __future__ import annotations

import datetime
import ipaddress
import os
import socket
import subprocess
import tempfile
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import grpc


def _have_cryptography() -> bool:
    try:
        import cryptography  # noqa: F401

        return True
    except ImportError:
        return False


@dataclass
class TLSConfig:
    """reference: tls.go:46-123 (TLSConfig struct)."""

    ca_file: str = ""
    ca_key_file: str = ""
    cert_file: str = ""
    key_file: str = ""
    # PEM buffers (take precedence over files when set).
    ca_pem: bytes = b""
    ca_key_pem: bytes = b""
    cert_pem: bytes = b""
    key_pem: bytes = b""
    # Generate a self-signed CA + server cert at startup.
    auto_tls: bool = False
    # "" | "request" | "require-and-verify"
    # (reference: config.go TLS client auth modes).
    client_auth: str = ""
    client_auth_ca_file: str = ""
    client_auth_ca_pem: bytes = b""
    # Client-side identity for peer dials / clients under mTLS.
    client_auth_cert_file: str = ""
    client_auth_key_file: str = ""
    client_auth_cert_pem: bytes = b""
    client_auth_key_pem: bytes = b""
    # Extra SANs for AutoTLS certs.
    auto_tls_hosts: List[str] = field(default_factory=list)

    def _load(self, pem: bytes, path: str) -> bytes:
        if pem:
            return pem
        if path:
            with open(path, "rb") as f:
                return f.read()
        return b""

    def setup(self) -> "TLSBundle":
        """Materialize credentials. reference: tls.go:126-283 (SetupTLS)."""
        ca = self._load(self.ca_pem, self.ca_file)
        ca_key = self._load(self.ca_key_pem, self.ca_key_file)
        cert = self._load(self.cert_pem, self.cert_file)
        key = self._load(self.key_pem, self.key_file)

        if self.auto_tls and not cert:
            if not ca:
                ca, ca_key = generate_self_ca()
            if not ca_key:
                raise ValueError(
                    "AutoTLS needs a CA private key to mint the server cert"
                )
            cert, key = generate_server_cert(ca, ca_key, self.auto_tls_hosts)

        if not cert or not key:
            raise ValueError("TLS enabled but no server cert/key configured")

        client_ca = self._load(self.client_auth_ca_pem, self.client_auth_ca_file)
        if self.client_auth and not client_ca:
            client_ca = ca
        client_cert = self._load(
            self.client_auth_cert_pem, self.client_auth_cert_file
        )
        client_key = self._load(self.client_auth_key_pem, self.client_auth_key_file)

        return TLSBundle(
            ca_pem=ca,
            server_cert_pem=cert,
            server_key_pem=key,
            client_auth=self.client_auth,
            client_ca_pem=client_ca,
            client_cert_pem=client_cert,
            client_key_pem=client_key,
        )


@dataclass
class TLSBundle:
    """Materialized PEMs + gRPC credential builders."""

    ca_pem: bytes
    server_cert_pem: bytes
    server_key_pem: bytes
    client_auth: str = ""
    client_ca_pem: bytes = b""
    client_cert_pem: bytes = b""
    client_key_pem: bytes = b""

    def server_credentials(self) -> grpc.ServerCredentials:
        require = self.client_auth == "require-and-verify"
        return grpc.ssl_server_credentials(
            [(self.server_key_pem, self.server_cert_pem)],
            root_certificates=self.client_ca_pem or self.ca_pem
            if self.client_auth
            else None,
            require_client_auth=require,
        )

    def client_credentials(self) -> grpc.ChannelCredentials:
        if self.client_cert_pem and self.client_key_pem:
            return grpc.ssl_channel_credentials(
                root_certificates=self.ca_pem,
                private_key=self.client_key_pem,
                certificate_chain=self.client_cert_pem,
            )
        return grpc.ssl_channel_credentials(root_certificates=self.ca_pem)


def _key_and_name(common_name: str):
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography import x509
    from cryptography.x509.oid import NameOID

    key = ec.generate_private_key(ec.SECP384R1())
    name = x509.Name(
        [
            x509.NameAttribute(NameOID.ORGANIZATION_NAME, "gubernator_tpu"),
            x509.NameAttribute(NameOID.COMMON_NAME, common_name),
        ]
    )
    return key, name


def _pem(cert, key) -> Tuple[bytes, bytes]:
    from cryptography.hazmat.primitives import serialization

    cert_pem = cert.public_bytes(serialization.Encoding.PEM)
    key_pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )
    return cert_pem, key_pem


def _run_openssl(args: List[str], cwd: str) -> None:
    subprocess.run(
        ["openssl"] + args, cwd=cwd, check=True, capture_output=True,
        timeout=30,
    )


def _openssl_key(tmp: str, name: str) -> bytes:
    """Mint a P-384 key via the openssl CLI, returned as PKCS8 PEM
    (the format grpc's SSL credentials and the cryptography backend
    both emit)."""
    sec1 = os.path.join(tmp, f"{name}.sec1.pem")
    pk8 = os.path.join(tmp, f"{name}.pem")
    _run_openssl(
        ["ecparam", "-name", "secp384r1", "-genkey", "-noout",
         "-out", sec1], tmp,
    )
    _run_openssl(
        ["pkcs8", "-topk8", "-nocrypt", "-in", sec1, "-out", pk8], tmp,
    )
    with open(pk8, "rb") as f:
        return f.read()


def _openssl_self_ca(valid_days: int) -> Tuple[bytes, bytes]:
    with tempfile.TemporaryDirectory() as tmp:
        key_pem = _openssl_key(tmp, "ca_key")
        # Explicit -config: `req -x509` otherwise ALSO applies the
        # system config's default extension section, and duplicated
        # basicConstraints makes chain building reject the CA.
        with open(os.path.join(tmp, "ca.cnf"), "w") as f:
            f.write(
                "[req]\n"
                "distinguished_name = dn\n"
                "x509_extensions = v3_ca\n"
                "prompt = no\n"
                "[dn]\n"
                "O = gubernator_tpu\n"
                "CN = gubernator_tpu AutoTLS CA\n"
                "[v3_ca]\n"
                "basicConstraints = critical,CA:TRUE\n"
                "keyUsage = critical,digitalSignature,keyCertSign,cRLSign\n"
                "subjectKeyIdentifier = hash\n"
            )
        _run_openssl(
            [
                "req", "-new", "-x509", "-key",
                os.path.join(tmp, "ca_key.pem"), "-sha384",
                "-days", str(valid_days),
                "-config", os.path.join(tmp, "ca.cnf"),
                "-out", os.path.join(tmp, "ca.pem"),
            ],
            tmp,
        )
        with open(os.path.join(tmp, "ca.pem"), "rb") as f:
            return f.read(), key_pem


def _openssl_server_cert(
    ca_pem: bytes, ca_key_pem: bytes, hosts: Optional[List[str]],
    valid_days: int,
) -> Tuple[bytes, bytes]:
    all_hosts = list(dict.fromkeys((hosts or []) + discover_san_hosts()))
    sans = []
    for h in all_hosts:
        try:
            ipaddress.ip_address(h)
            sans.append(f"IP:{h}")
        except ValueError:
            sans.append(f"DNS:{h}")
    with tempfile.TemporaryDirectory() as tmp:
        with open(os.path.join(tmp, "ca.pem"), "wb") as f:
            f.write(ca_pem)
        with open(os.path.join(tmp, "ca_key.pem"), "wb") as f:
            f.write(ca_key_pem)
        key_pem = _openssl_key(tmp, "key")
        _run_openssl(
            [
                "req", "-new", "-key", os.path.join(tmp, "key.pem"),
                "-sha384",
                "-subj", f"/O=gubernator_tpu/CN={socket.gethostname()}",
                "-out", os.path.join(tmp, "csr.pem"),
            ],
            tmp,
        )
        with open(os.path.join(tmp, "ext.cnf"), "w") as f:
            f.write(f"subjectAltName={','.join(sans)}\n")
            f.write("extendedKeyUsage=serverAuth,clientAuth\n")
            f.write("authorityKeyIdentifier=keyid,issuer\n")
        _run_openssl(
            [
                "x509", "-req", "-in", os.path.join(tmp, "csr.pem"),
                "-CA", os.path.join(tmp, "ca.pem"),
                "-CAkey", os.path.join(tmp, "ca_key.pem"),
                "-CAcreateserial", "-sha384", "-days", str(valid_days),
                "-extfile", os.path.join(tmp, "ext.cnf"),
                "-out", os.path.join(tmp, "cert.pem"),
            ],
            tmp,
        )
        with open(os.path.join(tmp, "cert.pem"), "rb") as f:
            return f.read(), key_pem


def generate_self_ca(valid_days: int = 365) -> Tuple[bytes, bytes]:
    """Mint a self-signed CA. reference: tls.go:384-436 (selfCA)."""
    if not _have_cryptography():
        return _openssl_self_ca(valid_days)
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes

    key, name = _key_and_name("gubernator_tpu AutoTLS CA")
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=valid_days))
        .add_extension(x509.BasicConstraints(ca=True, path_length=None), critical=True)
        .add_extension(
            x509.KeyUsage(
                digital_signature=True,
                key_cert_sign=True,
                crl_sign=True,
                content_commitment=False,
                key_encipherment=False,
                data_encipherment=False,
                key_agreement=False,
                encipher_only=False,
                decipher_only=False,
            ),
            critical=True,
        )
        .sign(key, hashes.SHA384())
    )
    return _pem(cert, key)


def discover_san_hosts() -> List[str]:
    """Hostname + local interface addresses for AutoTLS SANs.

    reference: net.go:57-122 (interface scan).
    """
    hosts = {"localhost", socket.gethostname(), "127.0.0.1", "::1"}
    try:
        for info in socket.getaddrinfo(socket.gethostname(), None):
            hosts.add(info[4][0])
    except socket.gaierror:
        pass
    return sorted(hosts)


def generate_server_cert(
    ca_pem: bytes,
    ca_key_pem: bytes,
    hosts: Optional[List[str]] = None,
    valid_days: int = 365,
) -> Tuple[bytes, bytes]:
    """Mint a CA-signed server cert with discovered SANs.

    reference: tls.go:285-382 (selfCert).
    """
    if not _have_cryptography():
        return _openssl_server_cert(ca_pem, ca_key_pem, hosts, valid_days)
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.serialization import load_pem_private_key

    ca_cert = x509.load_pem_x509_certificate(ca_pem)
    ca_key = load_pem_private_key(ca_key_pem, password=None)

    all_hosts = list(dict.fromkeys((hosts or []) + discover_san_hosts()))
    sans: List[x509.GeneralName] = []
    for h in all_hosts:
        try:
            sans.append(x509.IPAddress(ipaddress.ip_address(h)))
        except ValueError:
            sans.append(x509.DNSName(h))

    key, name = _key_and_name(socket.gethostname())
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(ca_cert.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=valid_days))
        .add_extension(x509.SubjectAlternativeName(sans), critical=False)
        .add_extension(
            x509.ExtendedKeyUsage(
                [
                    x509.oid.ExtendedKeyUsageOID.SERVER_AUTH,
                    x509.oid.ExtendedKeyUsageOID.CLIENT_AUTH,
                ]
            ),
            critical=False,
        )
        .sign(ca_key, hashes.SHA384())
    )
    return _pem(cert, key)

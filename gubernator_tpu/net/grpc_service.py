"""gRPC service registration and client stubs.

No grpc_python_plugin exists in this image, so instead of generated
service classes the two services are registered with
`grpc.method_handlers_generic_handler` and clients use
`channel.unary_unary` with the generated message (de)serializers —
byte-identical on the wire to the reference's generated stubs
(reference: gubernator_grpc.pb.go, peers_grpc.pb.go).
"""

from __future__ import annotations

from typing import Callable, Optional, Protocol

import grpc

from gubernator_tpu.net.pb import gubernator_pb2 as pb
from gubernator_tpu.net.pb import peers_pb2 as peers_pb

V1_SERVICE = "pb.gubernator.V1"
PEERS_SERVICE = "pb.gubernator.PeersV1"


class V1Servicer(Protocol):
    """The public service (reference: proto/gubernator.proto:27-45)."""

    def GetRateLimits(
        self, request: pb.GetRateLimitsReq, context: grpc.ServicerContext
    ) -> pb.GetRateLimitsResp: ...

    def HealthCheck(
        self, request: pb.HealthCheckReq, context: grpc.ServicerContext
    ) -> pb.HealthCheckResp: ...


class PeersV1Servicer(Protocol):
    """The peer-only service (reference: proto/peers.proto:28-34)."""

    def GetPeerRateLimits(
        self, request: peers_pb.GetPeerRateLimitsReq, context: grpc.ServicerContext
    ) -> peers_pb.GetPeerRateLimitsResp: ...

    def UpdatePeerGlobals(
        self, request: peers_pb.UpdatePeerGlobalsReq, context: grpc.ServicerContext
    ) -> peers_pb.UpdatePeerGlobalsResp: ...

    def TransferBuckets(self, request, context) -> bytes: ...

    def ReplicateKeys(self, request, context) -> bytes: ...

    def ObsSnapshot(self, request, context) -> bytes: ...


def _unary(fn: Callable, req_cls, resp_cls) -> grpc.RpcMethodHandler:
    return grpc.unary_unary_rpc_method_handler(
        fn,
        request_deserializer=req_cls.FromString,
        response_serializer=resp_cls.SerializeToString,
    )


def _unary_raw(fn: Callable) -> grpc.RpcMethodHandler:
    """Handler that receives the UNDESERIALIZED request bytes and may
    return either raw response bytes (native wire-codec fast path) or
    a protobuf message (slow path) — see net/server.py."""
    return grpc.unary_unary_rpc_method_handler(
        fn,
        request_deserializer=lambda raw: raw,
        response_serializer=lambda resp: (
            resp if isinstance(resp, bytes) else resp.SerializeToString()
        ),
    )


def add_v1_to_server(servicer: V1Servicer, server: grpc.Server) -> None:
    server.add_generic_rpc_handlers(
        (
            grpc.method_handlers_generic_handler(
                V1_SERVICE,
                {
                    "GetRateLimits": _unary_raw(servicer.GetRateLimits),
                    "HealthCheck": _unary(
                        servicer.HealthCheck,
                        pb.HealthCheckReq,
                        pb.HealthCheckResp,
                    ),
                },
            ),
        )
    )


def add_peers_v1_to_server(servicer: PeersV1Servicer, server: grpc.Server) -> None:
    server.add_generic_rpc_handlers(
        (
            grpc.method_handlers_generic_handler(
                PEERS_SERVICE,
                {
                    "GetPeerRateLimits": _unary_raw(
                        servicer.GetPeerRateLimits
                    ),
                    "UpdatePeerGlobals": _unary_raw(
                        servicer.UpdatePeerGlobals
                    ),
                    # Ownership-transfer protocol (cluster/handoff.py):
                    # raw JSON windows of bucket rows — no generated
                    # messages (no grpc_python_plugin in this image).
                    "TransferBuckets": _unary_raw(
                        servicer.TransferBuckets
                    ),
                    # Hot-key replication protocol
                    # (cluster/replication.py): raw JSON grant/revoke
                    # messages for replica credit leases, same wire
                    # idiom as the handoff plane.
                    "ReplicateKeys": _unary_raw(
                        servicer.ReplicateKeys
                    ),
                    # Fleet rollup scrape (obs/fleet.py): one node's
                    # metric families — counters, gauges, raw
                    # 36-bucket histograms — as raw JSON for the
                    # cluster rollup merge.  Scrape-rate traffic.
                    "ObsSnapshot": _unary_raw(
                        servicer.ObsSnapshot
                    ),
                },
            ),
        )
    )


class V1Stub:
    """Client stub for the public service."""

    def __init__(self, channel: grpc.Channel):
        self.GetRateLimits = channel.unary_unary(
            f"/{V1_SERVICE}/GetRateLimits",
            request_serializer=pb.GetRateLimitsReq.SerializeToString,
            response_deserializer=pb.GetRateLimitsResp.FromString,
        )
        self.HealthCheck = channel.unary_unary(
            f"/{V1_SERVICE}/HealthCheck",
            request_serializer=pb.HealthCheckReq.SerializeToString,
            response_deserializer=pb.HealthCheckResp.FromString,
        )


class PeersV1Stub:
    """Client stub for the peer-only service."""

    def __init__(self, channel: grpc.Channel):
        self.GetPeerRateLimits = channel.unary_unary(
            f"/{PEERS_SERVICE}/GetPeerRateLimits",
            request_serializer=peers_pb.GetPeerRateLimitsReq.SerializeToString,
            response_deserializer=peers_pb.GetPeerRateLimitsResp.FromString,
        )
        self.UpdatePeerGlobals = channel.unary_unary(
            f"/{PEERS_SERVICE}/UpdatePeerGlobals",
            request_serializer=peers_pb.UpdatePeerGlobalsReq.SerializeToString,
            response_deserializer=peers_pb.UpdatePeerGlobalsResp.FromString,
        )


def dial(
    address: str,
    *,
    credentials: Optional[grpc.ChannelCredentials] = None,
    options: Optional[list] = None,
) -> grpc.Channel:
    """Open a channel to a daemon or peer.

    reference: client.go:42-64 (DialV1Server).
    """
    opts = options or []
    if credentials is not None:
        return grpc.secure_channel(address, credentials, options=opts)
    return grpc.insecure_channel(address, options=opts)

"""Native h2 serving front: one method, zero per-RPC Python.

`H2FastFront` runs the C server (core/native/h2_server.cpp) on a
dedicated cleartext port serving exactly
/pb.gubernator.V1/GetRateLimits.  The C side owns accept/framing/
group-commit/response-encode; Python is entered ONCE per window with
the concatenated request bodies (protobuf repeated-field semantics
make the concatenation of N GetRateLimitsReq messages one valid
GetRateLimitsReq), runs the columnar engine path, and hands decision
columns back.

Scope, documented for operators: the front answers plain rate-limit
checks — requests that decode on the columnar path and whose
responses carry no error/metadata fields.  Batches containing
behaviors the columnar route declines (GLOBAL and friends) or any
per-item validation error are answered with grpc-status
UNIMPLEMENTED(12); point such traffic at the full gRPC listener
(`GUBER_GRPC_ADDRESS`).  The grpc-python wall this removes is
~160 µs/RPC of framework Python (PERF.md §13).

Enable with GUBER_H2_FAST_ADDRESS=127.0.0.1:<port> (0 = ephemeral);
GUBER_H2_FAST_WINDOW tunes the C-side group-commit window (default
2 ms, the §13 knee).

Native decision plane (GUBER_NATIVE_LEDGER, default on when the
decision ledger runs): the ledger's exact fast path — sticky
over-limit answers and credit-lease drains — delegated into a C table
(core/native/decision_plane.cpp) probed inside the connection threads,
so hot-key RPCs complete with zero GIL acquisitions and zero Python
frames; only cold/fall-through traffic enters the per-window Python
path.  The plane anchors to CLOCK_REALTIME, so it only attaches when
the engine runs on the live SYSTEM_CLOCK (frozen test clocks keep the
Python-only ledger).

Event front (GUBER_H2_EVENT_FRONT, default on; PERF.md §26): the C
side multiplexes ALL connections over a small pool of epoll reactor
threads (GUBER_H2_REACTORS, default ncpu−1 — one core stays reserved
for the Python serve plane) instead of one detached thread per
connection, with writev-batched egress and idle-connection reaping
(GUBER_H2_IDLE_TIMEOUT; GOAWAY + close).  GUBER_H2_EVENT_FRONT=0
restores the thread-per-connection plane, where GUBER_H2_LANES
(default: CPU count) shards the listener across SO_REUSEPORT accept
lanes.
"""

from __future__ import annotations

import ctypes
import logging
import os
import threading
from typing import Optional

import numpy as np

from gubernator_tpu.core.native_build import ensure_built

log = logging.getLogger("gubernator_tpu.h2_fast")

_CALLBACK = ctypes.CFUNCTYPE(
    ctypes.c_int64,
    ctypes.c_void_p,  # concat bodies
    ctypes.c_int64,  # len
    ctypes.c_void_p,  # item_counts [n_rpcs]
    ctypes.c_void_p,  # body_lens [n_rpcs]
    ctypes.c_int64,  # n_rpcs
    ctypes.c_int64,  # total_items
    ctypes.c_void_p,  # out_cols [4 * total]
    ctypes.c_void_p,  # out_rpc_status [n_rpcs]
)

_lib = None


def load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    so = ensure_built("h2_server")
    if so is None:
        return None
    lib = ctypes.CDLL(str(so))
    lib.h2s_start.restype = ctypes.c_void_p
    lib.h2s_start.argtypes = [
        ctypes.c_int32, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
        _CALLBACK,
    ]
    lib.h2s_port.restype = ctypes.c_int32
    lib.h2s_port.argtypes = [ctypes.c_void_p]
    lib.h2s_lanes.restype = ctypes.c_int32
    lib.h2s_lanes.argtypes = [ctypes.c_void_p]
    lib.h2s_reactors.restype = ctypes.c_int32
    lib.h2s_reactors.argtypes = [ctypes.c_void_p]
    lib.h2s_stats.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.h2s_attach_plane.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.h2s_attach_ring.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.h2s_attach_feeder.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.h2s_stop.argtypes = [ctypes.c_void_p]
    # Event ring (core/native/event_ring.cpp, same .so).
    lib.evr_create.restype = ctypes.c_void_p
    lib.evr_create.argtypes = [ctypes.c_int64]
    lib.evr_free.argtypes = [ctypes.c_void_p]
    lib.evr_drain.restype = ctypes.c_int64
    lib.evr_drain.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
    ]
    lib.evr_stats.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.evr_record.restype = ctypes.c_int64
    lib.evr_record.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64,
    ]
    _lib = lib
    return _lib


def native_events_capacity() -> int:
    """GUBER_NATIVE_EVENTS / GUBER_NATIVE_EVENTS_CAP: 0 disables the
    event ring; otherwise the ring's record capacity (rounded up to a
    power of two by the C side; default 65536)."""
    if os.environ.get("GUBER_NATIVE_EVENTS", "1").strip().lower() in (
        "0", "false", "no", "off"
    ):
        return 0
    v = os.environ.get("GUBER_NATIVE_EVENTS_CAP", "").strip()
    try:
        return int(v) if v else 65536
    except ValueError:
        log.warning("GUBER_NATIVE_EVENTS_CAP=%r not an integer", v)
        return 65536


def default_lanes() -> int:
    """GUBER_H2_LANES, defaulting to the CPU count — the SO_REUSEPORT
    sharding only helps while there are cores to spread accept/framing/
    decide across.  0 (config.py's documented auto value) and
    malformed values mean auto, not one lane."""
    v = os.environ.get("GUBER_H2_LANES", "").strip()
    try:
        n = int(v) if v else 0
    except ValueError:
        log.warning("GUBER_H2_LANES=%r not an integer; using CPU count", v)
        n = 0
    if n > 0:
        return n
    return max(1, os.cpu_count() or 1)


def event_front_enabled() -> bool:
    """GUBER_H2_EVENT_FRONT (default on): epoll reactor connection
    multiplexing instead of thread-per-connection (PERF.md §26)."""
    return os.environ.get("GUBER_H2_EVENT_FRONT", "1").strip().lower() not in (
        "0", "false", "no", "off"
    )


def default_reactors() -> int:
    """GUBER_H2_REACTORS: epoll reactor threads for the event front.
    0 (default) = auto, resolved by the C side to ncpu−1 (min 1) so
    one core stays reserved for the serve/dispatch plane — the §25
    starvation fix."""
    v = os.environ.get("GUBER_H2_REACTORS", "").strip()
    try:
        n = int(v) if v else 0
    except ValueError:
        log.warning("GUBER_H2_REACTORS=%r not an integer; using auto", v)
        n = 0
    return max(0, n)


def idle_timeout_ms() -> int:
    """GUBER_H2_IDLE_TIMEOUT (event front): reap connections silent
    this long (GOAWAY + close; Go-style duration or float seconds).
    Default 300s; 0 disables — the threaded front (and the pre-§26
    event front) held dead client connections forever."""
    raw = os.environ.get("GUBER_H2_IDLE_TIMEOUT", "").strip()
    if not raw:
        return 300_000
    try:
        from gubernator_tpu.config import parse_duration

        return max(0, int(parse_duration(raw) * 1000))
    except ValueError:
        log.warning(
            "GUBER_H2_IDLE_TIMEOUT=%r is not a duration; using 300s", raw
        )
        return 300_000


def native_ledger_enabled() -> bool:
    """GUBER_NATIVE_LEDGER (default on): delegate the ledger fast path
    to the C decision plane."""
    return os.environ.get("GUBER_NATIVE_LEDGER", "1").strip().lower() not in (
        "0", "false", "no", "off"
    )


def native_feeder_enabled() -> bool:
    """GUBER_NATIVE_FEEDER (default on): pack fall-through RPCs into
    the columnar feeder ring inside the C connection threads instead
    of queueing wire bytes for the Python window path."""
    return os.environ.get("GUBER_NATIVE_FEEDER", "1").strip().lower() not in (
        "0", "false", "no", "off"
    )


def retry_hints_enabled() -> bool:
    """GUBER_RETRY_HINTS (default on): retry_after_ms metadata on
    natively answered OVER_LIMIT items (reset_time-derived), so herds
    back off instead of hammering."""
    return os.environ.get("GUBER_RETRY_HINTS", "1").strip().lower() not in (
        "0", "false", "no", "off"
    )


def _int_knob(env: str, default: int) -> int:
    v = os.environ.get(env, "").strip()
    try:
        return int(v) if v else default
    except ValueError:
        log.warning("%s=%r not an integer; using %d", env, v, default)
        return default


def _feeder_ring_params() -> dict:
    """GUBER_FEEDER_RING_SLOTS / _ROWS / _KEYBYTES — the ring's window
    count, per-window row capacity, and per-window key-byte capacity
    (clamped by the C side's cursor field widths)."""
    return {
        "n_slots": _int_knob("GUBER_FEEDER_RING_SLOTS", 4),
        "max_rows": _int_knob("GUBER_FEEDER_RING_ROWS", 8192),
        "key_cap": _int_knob("GUBER_FEEDER_RING_KEYBYTES", 1 << 20),
    }


class H2FastFront:
    """The native front bound to a V1Instance's columnar serve path."""

    def __init__(
        self,
        instance,
        *,
        port: int = 0,
        window_s: float = 0.002,
        max_batch: int = 16384,
        flush_items: int = 4096,  # early-flush: an engine-batch-worth
        lanes: Optional[int] = None,
        native_ledger: Optional[bool] = None,
        native_feeder: Optional[bool] = None,
        event_front: Optional[bool] = None,
        reactors: Optional[int] = None,
        idle_timeout_s: Optional[float] = None,
    ):
        lib = load()
        if lib is None:
            raise RuntimeError("native h2 server unavailable")
        self._lib = lib
        self.instance = instance
        # Serializes conn_stats() (the metrics collector's scrape
        # thread) against close(): the handle must not be freed while
        # an FFI stats call is in flight.
        self._teardown_mu = threading.Lock()
        if event_front is None:
            event_front = event_front_enabled()
        if reactors is None:
            reactors = default_reactors()
        idle_ms = (
            idle_timeout_ms()
            if idle_timeout_s is None
            else max(0, int(idle_timeout_s * 1000))
        )
        # The ctypes callback object must outlive the server.
        self._cb = _CALLBACK(self._window)
        self._handle = lib.h2s_start(
            port, int(window_s * 1e6), max_batch, flush_items,
            default_lanes() if lanes is None else max(1, int(lanes)),
            1 if event_front else 0, int(reactors), idle_ms,
            self._cb,
        )
        if not self._handle:
            raise RuntimeError("h2 fast front failed to bind")
        self.port = int(lib.h2s_port(self._handle))
        self.address = f"127.0.0.1:{self.port}"
        self.lanes = int(lib.h2s_lanes(self._handle))
        self.reactors = int(lib.h2s_reactors(self._handle))
        self.event_front = bool(event_front)
        self.plane = None
        self._attach_plane(native_ledger)
        # Columnar feeder plane (core/native/columnar_feeder.cpp):
        # fall-through RPCs pack into device-ready column windows in
        # the C connection threads; Python enters once per window with
        # zero-copy views and the C side scatters the responses.
        # GUBER_NATIVE_FEEDER=0 restores the byte window path exactly.
        self.feeder = None
        if native_feeder is None:
            native_feeder = native_feeder_enabled()
        if native_feeder and not self._engine_columnar_ok():
            # An engine that can never serve columnar (write-through
            # store, or no apply_columnar entry) would make every ring
            # window a futile decode+decline round trip — don't build
            # the ring at all; the byte path's cheap guard-first
            # decline handles such fronts.
            native_feeder = False
        if native_feeder:
            try:
                import gubernator_tpu.service as svc
                from gubernator_tpu.core.native_plane import (
                    NativeColumnarFeeder,
                )

                self.feeder = NativeColumnarFeeder(
                    disqualify_mask=svc.COLUMNAR_DISQUALIFIERS,
                    window_s=window_s,
                    flush_rows=flush_items,
                    hints=retry_hints_enabled(),
                    window_handler=self._feeder_window,
                    **_feeder_ring_params(),
                )
                lib.h2s_attach_feeder(self._handle, self.feeder.handle)
            except (RuntimeError, OSError) as e:
                log.warning("native columnar feeder unavailable: %s", e)
        # Event ring: the C threads publish per-stage latency events
        # (utils/native_events.py drains them).  Created unless
        # GUBER_NATIVE_EVENTS=0 — an unattached front pays nothing,
        # an attached one pays two clock reads + one lock-free write
        # per event.
        self._ring = None
        cap = native_events_capacity()
        if cap > 0:
            ring = lib.evr_create(cap)
            if ring:
                self._ring = ctypes.c_void_p(ring)
                lib.h2s_attach_ring(self._handle, self._ring)
                if self.feeder is not None:
                    # The feeder publishes feeder.pack/ring_wait/serve
                    # stages into the same ring.
                    self.feeder.attach_ring(self._ring)

    def _attach_plane(self, native_ledger: Optional[bool]) -> None:
        """Create and attach the native decision plane when the ledger
        runs on a live clock.  `native_ledger` False = off, True = on,
        None = GUBER_NATIVE_LEDGER (the direct-construction default);
        either way frozen/managed clocks refuse the plane — it
        compares entry deadlines against CLOCK_REALTIME, and a clock
        racing ahead of realtime would let stale leases answer (tests
        that manage the clock themselves attach via
        ledger.attach_native directly)."""
        ledger = getattr(self.instance, "ledger", None)
        if ledger is None:
            return
        if native_ledger is None:
            native_ledger = native_ledger_enabled()
        if not native_ledger:
            return
        from gubernator_tpu.clock import SYSTEM_CLOCK

        clock = self.instance.engine.clock
        if clock is not SYSTEM_CLOCK or clock.frozen:
            log.info(
                "native decision plane disabled: engine clock is "
                "not the live system clock"
            )
            return
        try:
            import gubernator_tpu.service as svc
            from gubernator_tpu.core.native_plane import NativeDecisionPlane

            self.plane = NativeDecisionPlane(
                max_keys=getattr(ledger, "max_keys", 65536),
                disqualify_mask=svc.COLUMNAR_DISQUALIFIERS,
            )
        except (RuntimeError, OSError) as e:
            log.warning("native decision plane unavailable: %s", e)
            return
        ledger.attach_native(self.plane)
        # reset_time-derived retry hints on OVER answers served by the
        # plane (the feeder's scatter applies the same knob).
        self.plane.set_hints(retry_hints_enabled())
        self._lib.h2s_attach_plane(self._handle, self.plane.handle)

    # -- the per-window entry ------------------------------------------

    def _window(
        self, buf, length, counts_ptr, lens_ptr, n_rpcs, total, out_ptr,
        status_ptr,
    ) -> int:
        try:
            n = int(total)
            nr = int(n_rpcs)
            if n == 0:
                # A zero-item window (e.g. one empty GetRateLimitsReq)
                # is a valid request and answers empty-OK, like the
                # reference's zero-request batches.  out_ptr (and
                # possibly buf) back empty C vectors whose data() may
                # be NULL — touching them through np.ctypeslib raises
                # and would fail the window INTERNAL(13) (ADVICE r5).
                if nr > 0 and status_ptr:
                    np.ctypeslib.as_array(
                        ctypes.cast(
                            status_ptr, ctypes.POINTER(ctypes.c_int64)
                        ),
                        shape=(nr,),
                    )[:] = 0
                return 0
            payload = ctypes.string_at(buf, length)
            cols = np.ctypeslib.as_array(
                ctypes.cast(out_ptr, ctypes.POINTER(ctypes.c_int64)),
                shape=(4 * n,),
            )
            rpc_status = np.ctypeslib.as_array(
                ctypes.cast(status_ptr, ctypes.POINTER(ctypes.c_int64)),
                shape=(nr,),
            )
            out = self._serve(payload, n)
            if out is not None:
                st, lim, rem, rst = out
                cols[0 * n : 0 * n + n] = np.asarray(st, dtype=np.int64)
                cols[1 * n : 1 * n + n] = np.asarray(lim, dtype=np.int64)
                cols[2 * n : 2 * n + n] = np.asarray(rem, dtype=np.int64)
                cols[3 * n : 3 * n + n] = np.asarray(rst, dtype=np.int64)
                rpc_status[:] = 0
                return 0
            # The combined window declined (one RPC out of scope must
            # not fail its window-mates): re-serve each RPC alone and
            # mark only the decliners UNIMPLEMENTED.
            counts = np.ctypeslib.as_array(
                ctypes.cast(counts_ptr, ctypes.POINTER(ctypes.c_int64)),
                shape=(nr,),
            )
            lens = np.ctypeslib.as_array(
                ctypes.cast(lens_ptr, ctypes.POINTER(ctypes.c_int64)),
                shape=(nr,),
            )
            b_off = 0
            i_off = 0
            for r in range(nr):
                body = payload[b_off : b_off + int(lens[r])]
                k = int(counts[r])
                one = self._serve(body, k)
                if one is None:
                    rpc_status[r] = 12  # UNIMPLEMENTED
                else:
                    st, lim, rem, rst = one
                    cols[0 * n + i_off : 0 * n + i_off + k] = np.asarray(
                        st, dtype=np.int64
                    )
                    cols[1 * n + i_off : 1 * n + i_off + k] = np.asarray(
                        lim, dtype=np.int64
                    )
                    cols[2 * n + i_off : 2 * n + i_off + k] = np.asarray(
                        rem, dtype=np.int64
                    )
                    cols[3 * n + i_off : 3 * n + i_off + k] = np.asarray(
                        rst, dtype=np.int64
                    )
                    rpc_status[r] = 0
                b_off += int(lens[r])
                i_off += k
            return 0
        except Exception:  # noqa: BLE001 — never unwind into C
            from gubernator_tpu.utils.metrics import record_swallowed

            record_swallowed("h2_fast.window")
            log.exception("h2 fast window failed")
            return 13  # INTERNAL

    def _engine_columnar_ok(self) -> bool:
        """The engine guards serve_decoded_local re-checks — hoisted
        here so both ingest paths can decline BEFORE paying a decode
        (a write-through store or a stub engine makes every window
        UNIMPLEMENTED; the decode would be pure waste)."""
        engine = self.instance.engine
        return (
            getattr(engine, "apply_columnar", None) is not None
            and getattr(engine, "store", None) is None
        )

    def _serve(self, payload: bytes, total: int):
        """Columnar decode + engine apply for one byte window; None if
        the batch needs the pb path (caller answers UNIMPLEMENTED).
        The post-decode serve is service.serve_decoded_local — shared
        with the feeder's ring windows so the ownership gate and
        ledger semantics cannot drift between the two ingest paths."""
        import gubernator_tpu.service as svc
        from gubernator_tpu.net import wire_codec

        if not self._engine_columnar_ok():
            return None  # guard-first: decline before decoding
        mask = svc.COLUMNAR_DISQUALIFIERS
        dec = wire_codec.decode_reqs(payload, max(total, 1), mask)
        if dec is None or dec.n != total:
            return None
        return self.instance.serve_decoded_local(dec)

    # -- the per-window feeder entry (columnar_feeder.cpp) --------------

    def _feeder_window(self, slot, n_rows, n_rpcs, key_bytes) -> int:
        """Serve one sealed ring window: build a DecodedBatch of
        ZERO-COPY views over the slot's C-resident columns (no decode,
        no allocation — the C conn threads already packed them), run
        the shared columnar serve, write the verdict lanes in place.
        The feeder thread then encodes + scatters the responses in C.
        """
        from gubernator_tpu.net.wire_codec import DecodedBatch

        # Engine-domain "now" for the scatter's retry-hint encode:
        # reset_time verdicts are written in the ENGINE clock domain,
        # so the hint math must subtract the same domain's now (a raw
        # wall clock in C would skew every hint by the engine/host
        # offset — frozen test clocks included).
        slot.hint_now_ms[0] = self.instance.engine.clock.now_ms()
        dec = DecodedBatch(
            n=n_rows,
            key_buf=slot.key_buf[:key_bytes],
            key_offsets=slot.key_offsets[: n_rows + 1],
            algo=slot.algo[:n_rows],
            behavior=slot.behavior[:n_rows],
            hits=slot.hits[:n_rows],
            limit=slot.limit[:n_rows],
            duration=slot.duration[:n_rows],
            burst=slot.burst[:n_rows],
            fnv1=slot.fnv1[:n_rows],
            fnv1a=slot.fnv1a[:n_rows],
            name_len=slot.name_lens[:n_rows],
        )
        out = self.instance.serve_decoded_local(dec)
        if out is not None:
            st, lim, rem, rst = out
            slot.out_status[:n_rows] = st
            slot.out_limit[:n_rows] = lim
            slot.out_remaining[:n_rows] = rem
            slot.out_reset[:n_rows] = rst
            slot.rpc_status[:n_rpcs] = 0
            return 0
        # The combined window declined (ownership, engine guards): one
        # RPC out of scope must not fail its window-mates — re-serve
        # each RPC alone off the same views and mark only the
        # decliners UNIMPLEMENTED.  Rare path: per-RPC slicing may
        # allocate the rebased offsets.
        rows = slot.rpc_row
        counts = slot.rpc_items
        for r in range(n_rpcs):
            row0 = int(rows[r])
            k = int(counts[r])
            off0 = int(slot.key_offsets[row0])
            offk = int(slot.key_offsets[row0 + k])
            sub = DecodedBatch(
                n=k,
                key_buf=slot.key_buf[off0:offk],
                key_offsets=slot.key_offsets[row0 : row0 + k + 1] - off0,
                algo=slot.algo[row0 : row0 + k],
                behavior=slot.behavior[row0 : row0 + k],
                hits=slot.hits[row0 : row0 + k],
                limit=slot.limit[row0 : row0 + k],
                duration=slot.duration[row0 : row0 + k],
                burst=slot.burst[row0 : row0 + k],
                fnv1=slot.fnv1[row0 : row0 + k],
                fnv1a=slot.fnv1a[row0 : row0 + k],
                name_len=slot.name_lens[row0 : row0 + k],
            )
            one = self.instance.serve_decoded_local(sub)
            if one is None:
                slot.rpc_status[r] = 12  # UNIMPLEMENTED
            else:
                st, lim, rem, rst = one
                slot.out_status[row0 : row0 + k] = st
                slot.out_limit[row0 : row0 + k] = lim
                slot.out_remaining[row0 : row0 + k] = rem
                slot.out_reset[row0 : row0 + k] = rst
                slot.rpc_status[r] = 0
        return 0

    # -- event ring (core/native/event_ring.cpp) ------------------------

    def drain_events(self, out) -> int:
        """Drain ring records into `out` (int64 numpy array, 4 slots
        per record: kind, t_end_ns, dur_ns, items); returns records
        read.  SINGLE consumer by contract — only the
        NativeEventCollector thread calls this."""
        if self._ring is None:
            return 0
        return int(
            self._lib.evr_drain(
                self._ring, out.ctypes.data_as(ctypes.c_void_p),
                len(out) // 4,
            )
        )

    def ring_stats(self) -> dict:
        if self._ring is None:
            return {"written": 0, "dropped": 0, "enabled": False}
        out = np.zeros(2, dtype=np.int64)
        self._lib.evr_stats(
            self._ring, out.ctypes.data_as(ctypes.c_void_p)
        )
        return {
            "written": int(out[0]),
            "dropped": int(out[1]),
            "enabled": True,
        }

    def abandon_ring(self) -> None:
        """Detach the ring and forget it WITHOUT freeing: the
        collector's drain thread outlived its join, and a freed ring
        under a live consumer is a native use-after-free — leak over
        UAF (same rule as h2s_stop's conn-thread bound)."""
        if self._ring is not None:
            if self._handle:
                self._lib.h2s_attach_ring(self._handle, None)
            self._ring = None

    # -- lifecycle ------------------------------------------------------

    def conn_stats(self) -> dict:
        """The connection-plane slice alone (cheap: one FFI call) —
        the gubernator_h2_conns gauge scrapes this per collect.
        Serialized against close() by _teardown_mu: a bare truthiness
        check would be check-then-use (the argument re-read could see
        None → NULL deref in C, or a captured handle could be freed
        mid-call)."""
        out = np.zeros(16, dtype=np.int64)
        with self._teardown_mu:
            handle = self._handle
            if handle:
                self._lib.h2s_stats(
                    handle, out.ctypes.data_as(ctypes.c_void_p)
                )
        return {
            "conns_open": int(out[7]),
            "conns_idle_reaped": int(out[8]),
            "reactors": int(out[9]),
            "event_front": bool(out[10]),
        }

    def stats(self) -> dict:
        out = np.zeros(16, dtype=np.int64)
        with self._teardown_mu:
            handle = self._handle
            if handle:
                self._lib.h2s_stats(
                    handle, out.ctypes.data_as(ctypes.c_void_p)
                )
        stats = {
            "rpcs": int(out[0]),
            "windows": int(out[1]),
            "errors": int(out[2]),
            "native_rpcs": int(out[3]),
            "native_items": int(out[4]),
            "feeder_front_rpcs": int(out[5]),
            "feeder_front_items": int(out[6]),
            "conns_open": int(out[7]),
            "conns_idle_reaped": int(out[8]),
            "reactors": int(out[9]),
            "event_front": bool(out[10]),
            "lanes": self.lanes,
        }
        if self.plane is not None:
            stats.update(self.plane.stats())
        if self.feeder is not None:
            stats.update(self.feeder.stats())
        return stats

    def close(self) -> None:
        if self._handle:
            # Null the public handle under _teardown_mu: the metrics
            # collector's conn_stats() can race this teardown from the
            # gateway thread, and h2s_stats on a freed server is a
            # native use-after-free.  After this block any scrape sees
            # None and reports zeros; an in-flight one finished before
            # the handle is stopped/freed below.
            with self._teardown_mu:
                handle, self._handle = self._handle, None
            if self.plane is not None:
                # Detach before stop: conn threads re-read the plane
                # pointer per RPC, so no new native serves start; stop
                # then joins/drains them before the ledger pulls its
                # credit back and the table is freed.
                self._lib.h2s_attach_plane(handle, None)
            if self.feeder is not None:
                # Feeder teardown is drain-then-close: detach (conn
                # threads stop packing at the next RPC), stop (the
                # serve thread drains every claimed window — pending
                # RPCs answer UNAVAILABLE through still-live conns —
                # then joins), and free only after h2s_stop below has
                # also joined the conn threads.
                self._lib.h2s_attach_feeder(handle, None)
                self.feeder.stop()
            if self._ring is not None:
                # Same contract as the plane: detach first, free only
                # after h2s_stop joined/drained the writer threads.
                self._lib.h2s_attach_ring(handle, None)
            self._lib.h2s_stop(handle)
            if self.plane is not None:
                ledger = getattr(self.instance, "ledger", None)
                if ledger is not None:
                    ledger.detach_native()
                self.plane.close()
                self.plane = None
            if self.feeder is not None:
                self.feeder.close()
                self.feeder = None
            if self._ring is not None:
                self._lib.evr_free(self._ring)
                self._ring = None

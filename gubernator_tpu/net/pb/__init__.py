"""Generated protobuf modules (see ../proto/regen.sh)."""

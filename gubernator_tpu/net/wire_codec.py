"""ctypes wrapper over the native wire codec (wire_codec.cpp).

`decode_reqs(raw)` turns one GetRateLimitsReq/GetPeerRateLimitsReq
payload into engine-ready columns — the concatenated key buffer +
offsets that the native intern table's schedule() consumes directly,
plus per-key FNV ring hashes — and `encode_resps(...)` assembles the
response bytes straight from the engine's output columns.  Together
they remove every per-item protobuf object from the served hot path
(profiled ~3.2ms per 1000-item batch in Python; see PERF.md).

Falls back cleanly: `load()` returns None when the native toolchain is
unavailable (GUBERNATOR_TPU_NATIVE=0 or g++ missing), and decode
returns None for any batch the columnar path can't serve (disqualifying
behaviors, empty name/key, malformed bytes) — callers then use the
protobuf path, so behavior is identical, just slower.
"""

from __future__ import annotations

import ctypes
import threading
from typing import NamedTuple, Optional

import numpy as np

from gubernator_tpu.core.native_build import ensure_built

_lib = None
_lib_lock = threading.Lock()


class DecodedBatch(NamedTuple):
    n: int
    key_buf: np.ndarray  # uint8 [total_key_bytes]
    key_offsets: np.ndarray  # int64 [n+1]
    algo: np.ndarray  # int32 [n]
    behavior: np.ndarray  # int32 [n]
    hits: np.ndarray  # int64 [n]
    limit: np.ndarray  # int64 [n]
    duration: np.ndarray  # int64 [n]
    burst: np.ndarray  # int64 [n]
    fnv1: np.ndarray  # uint64 [n]
    fnv1a: np.ndarray  # uint64 [n]
    name_len: np.ndarray  # int32 [n] — key_buf item = name + b"_" + key


def load():
    """Load (building if needed) the codec library; None if unavailable."""
    global _lib
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        so = ensure_built("wire_codec")
        if so is None:
            return None
        lib = ctypes.CDLL(str(so))
        lib.wire_decode_reqs.restype = ctypes.c_int64
        # (buf, len, max_items, disqualify_mask, key_buf, key_cap,
        #  key_offsets, algo, behavior, hits, limit, duration, burst,
        #  fnv1, fnv1a) — key_cap is an int64 BETWEEN pointers; the
        # full 15-entry list must match the C signature exactly.
        lib.wire_decode_reqs.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64,
        ] + [ctypes.c_void_p] * 10
        lib.wire_encode_resps.restype = ctypes.c_int64
        lib.wire_encode_resps.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.wire_encode_resps_hint.restype = ctypes.c_int64
        # (status, limit, remaining, reset, n, over_status, now_ms,
        #  out, out_cap)
        lib.wire_encode_resps_hint.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.wire_encode_resps_owner.restype = ctypes.c_int64
        # (status, limit, remaining, reset, owner_idx, owner_buf,
        #  owner_offsets, n, out, out_cap)
        lib.wire_encode_resps_owner.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_int64,
        ]
        lib.wire_encode_reqs.restype = ctypes.c_int64
        # (key_buf, key_offsets, name_lens, algo, behavior, hits,
        #  limit, duration, burst, n, out, out_cap)
        lib.wire_encode_reqs.argtypes = (
            [ctypes.c_void_p] * 9 + [ctypes.c_int64, ctypes.c_void_p,
                                     ctypes.c_int64]
        )
        lib.wire_encode_globals.restype = ctypes.c_int64
        # (key_buf, key_offsets, algo, status, limit, remaining,
        #  reset, n, out, out_cap)
        lib.wire_encode_globals.argtypes = (
            [ctypes.c_void_p] * 7 + [ctypes.c_int64, ctypes.c_void_p,
                                     ctypes.c_int64]
        )
        lib.wire_decode_globals.restype = ctypes.c_int64
        # (buf, len, max_items, key_buf, key_cap, key_offsets, algo,
        #  status, limit, remaining, reset, has_status)
        lib.wire_decode_globals.argtypes = (
            [ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
             ctypes.c_void_p, ctypes.c_int64] + [ctypes.c_void_p] * 7
        )
        _lib = lib
    return _lib


def gather_key_slices(key_buf: np.ndarray, starts: np.ndarray,
                      lens: np.ndarray):
    """Gather variable-length key slices out of a (possibly shared)
    byte buffer into a contiguous buffer: returns (sub_buf,
    sub_offsets) with sub_offsets[0] == 0.  One vectorized pass — the
    per-output-byte source index is repeat(starts - dest_starts, lens)
    + arange(total).  Shared by the serving partition, the hits
    fan-out and the broadcast encode (the same offset/gather math must
    not fork)."""
    n = len(starts)
    off = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(lens, out=off[1:])
    total = int(off[-1])
    pos = (
        np.repeat(starts - off[:-1], lens)
        + np.arange(total, dtype=np.int64)
    )
    return key_buf[pos], off


def encode_peer_reqs(
    key_buf: np.ndarray,
    key_offsets: np.ndarray,
    name_len: np.ndarray,
    algo: np.ndarray,
    behavior: np.ndarray,
    hits: np.ndarray,
    limit: np.ndarray,
    duration: np.ndarray,
    burst: np.ndarray,
) -> bytes:
    """Columns → GetPeerRateLimitsReq bytes (hits-forward plane)."""
    lib = load()
    assert lib is not None
    n = len(algo)
    key_buf = np.ascontiguousarray(key_buf, dtype=np.uint8)
    key_offsets = np.ascontiguousarray(key_offsets, dtype=np.int64)
    name_len = np.ascontiguousarray(name_len, dtype=np.int32)
    algo = np.ascontiguousarray(algo, dtype=np.int32)
    behavior = np.ascontiguousarray(behavior, dtype=np.int32)
    hits = np.ascontiguousarray(hits, dtype=np.int64)
    limit = np.ascontiguousarray(limit, dtype=np.int64)
    duration = np.ascontiguousarray(duration, dtype=np.int64)
    burst = np.ascontiguousarray(burst, dtype=np.int64)
    out = np.empty(int(key_offsets[-1]) + n * 80 + 16, dtype=np.uint8)
    written = lib.wire_encode_reqs(
        _ptr(key_buf), _ptr(key_offsets), _ptr(name_len), _ptr(algo),
        _ptr(behavior), _ptr(hits), _ptr(limit), _ptr(duration),
        _ptr(burst), n, _ptr(out), len(out),
    )
    assert written >= 0
    return out[:written].tobytes()


class DecodedGlobals(NamedTuple):
    n: int
    key_buf: np.ndarray  # uint8
    key_offsets: np.ndarray  # int64 [n+1]
    algo: np.ndarray  # int32 [n]
    status: np.ndarray  # int32 [n]
    limit: np.ndarray  # int64 [n]
    remaining: np.ndarray  # int64 [n]
    reset_time: np.ndarray  # int64 [n]
    has_status: np.ndarray  # int32 [n]


def encode_globals(
    key_buf: np.ndarray,
    key_offsets: np.ndarray,
    algo: np.ndarray,
    status: np.ndarray,
    limit: np.ndarray,
    remaining: np.ndarray,
    reset_time: np.ndarray,
) -> bytes:
    """Columns → UpdatePeerGlobalsReq bytes (broadcast plane)."""
    lib = load()
    assert lib is not None
    n = len(algo)
    key_buf = np.ascontiguousarray(key_buf, dtype=np.uint8)
    key_offsets = np.ascontiguousarray(key_offsets, dtype=np.int64)
    algo = np.ascontiguousarray(algo, dtype=np.int32)
    status = np.ascontiguousarray(status, dtype=np.int32)
    limit = np.ascontiguousarray(limit, dtype=np.int64)
    remaining = np.ascontiguousarray(remaining, dtype=np.int64)
    reset_time = np.ascontiguousarray(reset_time, dtype=np.int64)
    out = np.empty(int(key_offsets[-1]) + n * 64 + 16, dtype=np.uint8)
    written = lib.wire_encode_globals(
        _ptr(key_buf), _ptr(key_offsets), _ptr(algo), _ptr(status),
        _ptr(limit), _ptr(remaining), _ptr(reset_time), n,
        _ptr(out), len(out),
    )
    assert written >= 0
    return out[:written].tobytes()


def decode_globals(raw: bytes, max_items: int) -> Optional[DecodedGlobals]:
    """UpdatePeerGlobalsReq bytes → columns; None ⇒ pb fallback."""
    lib = load()
    if lib is None or not raw:
        return None
    max_items = min(max_items, len(raw) // 2 + 1)
    key_cap = len(raw)
    key_buf = np.empty(key_cap, dtype=np.uint8)
    key_offsets = np.empty(max_items + 1, dtype=np.int64)
    algo = np.empty(max_items, dtype=np.int32)
    status = np.empty(max_items, dtype=np.int32)
    limit = np.empty(max_items, dtype=np.int64)
    remaining = np.empty(max_items, dtype=np.int64)
    reset_time = np.empty(max_items, dtype=np.int64)
    has_status = np.empty(max_items, dtype=np.int32)
    n = lib.wire_decode_globals(
        raw, len(raw), max_items, _ptr(key_buf), key_cap,
        _ptr(key_offsets), _ptr(algo), _ptr(status), _ptr(limit),
        _ptr(remaining), _ptr(reset_time), _ptr(has_status),
    )
    if n < 0:
        return None
    return DecodedGlobals(
        n=int(n),
        key_buf=key_buf[: key_offsets[n] if n else 0],
        key_offsets=key_offsets[: n + 1],
        algo=algo[:n],
        status=status[:n],
        limit=limit[:n],
        remaining=remaining[:n],
        reset_time=reset_time[:n],
        has_status=has_status[:n],
    )


def _ptr(a: np.ndarray):
    # Bare data address (int) — ctypes passes it as c_void_p.  The
    # data_as(c_void_p) form costs 3.2µs per array (it builds a ctypes
    # view object); at 16 pointer extractions per decoded RPC that was
    # the single largest glue cost on the serve path.
    return a.ctypes.data


def decode_reqs(
    raw: bytes, max_items: int, disqualify_mask: int
) -> Optional[DecodedBatch]:
    """Decode or decline.  None ⇒ caller takes the protobuf path
    (malformed input included — the pb parser then produces the proper
    error)."""
    lib = load()
    if lib is None or not raw:
        return None
    # Each item costs ≥4 wire bytes (outer tag+len + ≥2 content), so
    # len(raw)//2 bounds the item count — a 1-item herd RPC allocates
    # ~tens of bytes per column instead of MAX_BATCH_SIZE-sized arrays
    # (profiled ~15µs/RPC of pure allocation at batch=1).
    max_items = min(max_items, len(raw) // 2 + 1)
    # Key bytes + one '_' per item always fit in len(raw): each item's
    # wire framing alone costs more than the added separator byte.
    key_cap = len(raw)
    key_buf = np.empty(key_cap, dtype=np.uint8)
    key_offsets = np.empty(max_items + 1, dtype=np.int64)
    algo = np.empty(max_items, dtype=np.int32)
    behavior = np.empty(max_items, dtype=np.int32)
    hits = np.empty(max_items, dtype=np.int64)
    limit = np.empty(max_items, dtype=np.int64)
    duration = np.empty(max_items, dtype=np.int64)
    burst = np.empty(max_items, dtype=np.int64)
    fnv1 = np.empty(max_items, dtype=np.uint64)
    fnv1a = np.empty(max_items, dtype=np.uint64)
    name_len = np.empty(max_items, dtype=np.int32)
    n = lib.wire_decode_reqs(
        raw, len(raw), max_items, disqualify_mask,
        _ptr(key_buf), key_cap, _ptr(key_offsets), _ptr(algo),
        _ptr(behavior), _ptr(hits), _ptr(limit), _ptr(duration),
        _ptr(burst), _ptr(fnv1), _ptr(fnv1a), _ptr(name_len),
    )
    if n <= 0:
        # -2 (too many items) must surface as the RPC-level batch error;
        # the pb path re-parses and raises it.  All other declines are
        # equivalent fallbacks.
        return None
    return DecodedBatch(
        n=int(n),
        key_buf=key_buf[: key_offsets[n]],
        key_offsets=key_offsets[: n + 1],
        algo=algo[:n],
        behavior=behavior[:n],
        hits=hits[:n],
        limit=limit[:n],
        duration=duration[:n],
        burst=burst[:n],
        fnv1=fnv1[:n],
        fnv1a=fnv1a[:n],
        name_len=name_len[:n],
    )


def encode_resps(
    status: np.ndarray,
    limit: np.ndarray,
    remaining: np.ndarray,
    reset_time: np.ndarray,
) -> bytes:
    """Columns → GetRateLimitsResp/GetPeerRateLimitsResp bytes."""
    lib = load()
    assert lib is not None, "encode_resps requires the native codec"
    n = len(status)
    status = np.ascontiguousarray(status, dtype=np.int32)
    limit = np.ascontiguousarray(limit, dtype=np.int64)
    remaining = np.ascontiguousarray(remaining, dtype=np.int64)
    reset_time = np.ascontiguousarray(reset_time, dtype=np.int64)
    # Worst case per item: tag+len (6) + 4 fields × (1 tag + 10 varint).
    out = np.empty(n * 52 + 16, dtype=np.uint8)
    written = lib.wire_encode_resps(
        _ptr(status), _ptr(limit), _ptr(remaining), _ptr(reset_time),
        n, _ptr(out), len(out),
    )
    assert written >= 0
    return out[:written].tobytes()


def encode_resps_hint(
    status: np.ndarray,
    limit: np.ndarray,
    remaining: np.ndarray,
    reset_time: np.ndarray,
    over_status: int,
    now_ms: int,
) -> bytes:
    """Columns → response bytes with retry_after_ms metadata on
    OVER_LIMIT items (the native tier's herd-backoff hint — the same
    C encoder the decision plane and the columnar feeder scatter use)."""
    lib = load()
    assert lib is not None, "encode_resps_hint requires the native codec"
    n = len(status)
    status = np.ascontiguousarray(status, dtype=np.int32)
    limit = np.ascontiguousarray(limit, dtype=np.int64)
    remaining = np.ascontiguousarray(remaining, dtype=np.int64)
    reset_time = np.ascontiguousarray(reset_time, dtype=np.int64)
    out = np.empty(n * 96 + 16, dtype=np.uint8)
    written = lib.wire_encode_resps_hint(
        _ptr(status), _ptr(limit), _ptr(remaining), _ptr(reset_time),
        n, int(over_status), int(now_ms), _ptr(out), len(out),
    )
    assert written >= 0
    return out[:written].tobytes()


def encode_resps_owner(
    status: np.ndarray,
    limit: np.ndarray,
    remaining: np.ndarray,
    reset_time: np.ndarray,
    owner_idx: np.ndarray,  # int32 [n]; -1 = no metadata
    owners: list,  # list[bytes] — owner grpc addresses
) -> bytes:
    """Columns → response bytes with per-item {"owner": addr} metadata
    (the GLOBAL non-owner responses — reference: gubernator.go:448-452)."""
    lib = load()
    assert lib is not None, "encode_resps_owner requires the native codec"
    n = len(status)
    status = np.ascontiguousarray(status, dtype=np.int32)
    limit = np.ascontiguousarray(limit, dtype=np.int64)
    remaining = np.ascontiguousarray(remaining, dtype=np.int64)
    reset_time = np.ascontiguousarray(reset_time, dtype=np.int64)
    owner_idx = np.ascontiguousarray(owner_idx, dtype=np.int32)
    owner_buf = np.frombuffer(b"".join(owners), dtype=np.uint8) if owners \
        else np.empty(0, dtype=np.uint8)
    owner_offsets = np.zeros(len(owners) + 1, dtype=np.int64)
    if owners:
        owner_offsets[1:] = np.cumsum([len(o) for o in owners])
    max_owner = max((len(o) for o in owners), default=0)
    out = np.empty(n * (52 + 24 + max_owner) + 16, dtype=np.uint8)
    written = lib.wire_encode_resps_owner(
        _ptr(status), _ptr(limit), _ptr(remaining), _ptr(reset_time),
        _ptr(owner_idx), _ptr(owner_buf), _ptr(owner_offsets),
        n, _ptr(out), len(out),
    )
    assert written >= 0
    return out[:written].tobytes()

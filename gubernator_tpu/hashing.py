"""Key hashing: FNV-1 / FNV-1a 64-bit, scalar and numpy-vectorized.

The consistent-hash ring hashes keys with fnv1 by default
(reference: replicated_hash.go:31-33, config.go:395-417 allows
fnv1/fnv1a).  The reference's intra-node worker ring uses xxhash
truncated to 63 bits (reference: gubernator_pool.go:155-157); our
device-shard routing reuses fnv1a instead — the worker ring is replaced
by device sharding so only the distribution property matters.

`fnv1_64_batch` hashes a padded uint8 matrix of keys in one vectorized
numpy pass — the host hot path feeding the batch router.  A compiled
C++ path (gubernator_tpu.core.native) supersedes it at high QPS.
"""

from __future__ import annotations

import numpy as np

FNV1_OFFSET = 0xCBF29CE484222325
FNV1_PRIME = 0x100000001B3
_MASK = 0xFFFFFFFFFFFFFFFF


def fnv1_64(data: bytes) -> int:
    """FNV-1 (multiply then xor). reference: segmentio/fasthash fnv1."""
    h = FNV1_OFFSET
    for b in data:
        h = ((h * FNV1_PRIME) & _MASK) ^ b
    return h


def fnv1a_64(data: bytes) -> int:
    """FNV-1a (xor then multiply)."""
    h = FNV1_OFFSET
    for b in data:
        h = ((h ^ b) * FNV1_PRIME) & _MASK
    return h


def fnv1_64_batch(padded: np.ndarray, lengths: np.ndarray) -> np.ndarray:
    """Vectorized FNV-1 over a [N, max_len] uint8 matrix of padded keys.

    Scans column-by-column (max_len passes over N lanes), updating only
    lanes whose key extends to that column — O(N * max_len) numpy work
    instead of a per-key Python loop.
    """
    n, max_len = padded.shape
    h = np.full(n, FNV1_OFFSET, dtype=np.uint64)
    prime = np.uint64(FNV1_PRIME)
    for col in range(max_len):
        active = lengths > col
        if not active.any():
            break
        nh = (h * prime) ^ padded[:, col].astype(np.uint64)
        h = np.where(active, nh, h)
    return h


def fnv1a_64_batch(padded: np.ndarray, lengths: np.ndarray) -> np.ndarray:
    """Vectorized FNV-1a (xor then multiply) — see fnv1_64_batch."""
    n, max_len = padded.shape
    h = np.full(n, FNV1_OFFSET, dtype=np.uint64)
    prime = np.uint64(FNV1_PRIME)
    for col in range(max_len):
        active = lengths > col
        if not active.any():
            break
        nh = (h ^ padded[:, col].astype(np.uint64)) * prime
        h = np.where(active, nh, h)
    return h


def pack_keys(keys: list[bytes]) -> tuple[np.ndarray, np.ndarray]:
    """Pack variable-length byte keys into a padded uint8 matrix."""
    n = len(keys)
    lengths = np.fromiter((len(k) for k in keys), count=n, dtype=np.int64)
    max_len = int(lengths.max()) if n else 0
    padded = np.zeros((n, max_len), dtype=np.uint8)
    for i, k in enumerate(keys):
        padded[i, : len(k)] = np.frombuffer(k, dtype=np.uint8)
    return padded, lengths

"""Gregorian calendar interval math for DURATION_IS_GREGORIAN.

reference: interval.go:74-148.  When the behavior flag is set, the
request `duration` field is an interval enum (minutes/hours/days/weeks/
months/years) and limits reset at the end of the civil-calendar interval.

All host-side: the device kernel receives the precomputed
(gregorian_duration, gregorian_expiration) per request and never does
calendar math (SURVEY.md §7.1).

Deliberate divergences from the reference, both documented reference
bugs that its own tests never reach:

* `gregorian_duration` for months/years: interval.go:99,105 computes
  ``end.UnixNano() - begin.UnixNano()/1000000`` — an operator-precedence
  bug yielding ~1.7e18.  We return the true interval length in ms.
* Weeks are supported here (ISO weeks ending Sunday 23:59:59.999) rather
  than returning an error (interval.go:92-93 "not yet supported").
"""

from __future__ import annotations

from calendar import monthrange
from datetime import datetime, timedelta

GREGORIAN_MINUTES = 0
GREGORIAN_HOURS = 1
GREGORIAN_DAYS = 2
GREGORIAN_WEEKS = 3
GREGORIAN_MONTHS = 4
GREGORIAN_YEARS = 5

_MS = 1


class GregorianError(ValueError):
    """Raised for a non-Gregorian `duration` under DURATION_IS_GREGORIAN.

    reference: interval.go:107 — the error string is propagated into the
    per-item `RateLimitResp.error` field, not a transport error.
    """


def _to_ms(dt: datetime) -> int:
    return int(dt.timestamp() * 1000)


def dt_from_ms(now_ms: int) -> datetime:
    """Civil UTC time for a unix-ms timestamp.

    The engines derive the Gregorian civil time from the same `now_ms`
    the kernel receives — a second clock read could land in a different
    calendar interval and create buckets already expired relative to
    the kernel's `now` (engine time-source invariant)."""
    from datetime import timezone

    return datetime.fromtimestamp(now_ms / 1000.0, tz=timezone.utc)


def gregorian_duration(now: datetime, d: int) -> int:
    """Total length in ms of the Gregorian interval containing `now`.

    reference: interval.go:83-109 (GregorianDuration), with the
    months/years precedence bug fixed (see module docstring).
    """
    if d == GREGORIAN_MINUTES:
        return 60_000
    if d == GREGORIAN_HOURS:
        return 3_600_000
    if d == GREGORIAN_DAYS:
        return 86_400_000
    if d == GREGORIAN_WEEKS:
        return 7 * 86_400_000
    if d == GREGORIAN_MONTHS:
        days = monthrange(now.year, now.month)[1]
        return days * 86_400_000
    if d == GREGORIAN_YEARS:
        begin = now.replace(month=1, day=1, hour=0, minute=0, second=0, microsecond=0)
        end = begin.replace(year=begin.year + 1)
        return _to_ms(end) - _to_ms(begin)
    raise GregorianError(
        "behavior DURATION_IS_GREGORIAN is set; but `Duration` is not a valid gregorian interval"
    )


def gregorian_expiration(now: datetime, d: int) -> int:
    """End of the current Gregorian interval, unix-ms.

    Returns `start_of_next_interval - 1ms`, matching the reference's
    `boundary - 1ns` truncated to ms (reference: interval.go:117-148).
    """
    if d == GREGORIAN_MINUTES:
        begin = now.replace(second=0, microsecond=0)
        return _to_ms(begin + timedelta(minutes=1)) - _MS
    if d == GREGORIAN_HOURS:
        begin = now.replace(minute=0, second=0, microsecond=0)
        return _to_ms(begin + timedelta(hours=1)) - _MS
    if d == GREGORIAN_DAYS:
        begin = now.replace(hour=0, minute=0, second=0, microsecond=0)
        return _to_ms(begin + timedelta(days=1)) - _MS
    if d == GREGORIAN_WEEKS:
        begin = now.replace(hour=0, minute=0, second=0, microsecond=0)
        # End of the ISO week (Sunday night).
        return _to_ms(begin + timedelta(days=7 - now.weekday())) - _MS
    if d == GREGORIAN_MONTHS:
        begin = now.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
        if begin.month == 12:
            nxt = begin.replace(year=begin.year + 1, month=1)
        else:
            nxt = begin.replace(month=begin.month + 1)
        return _to_ms(nxt) - _MS
    if d == GREGORIAN_YEARS:
        begin = now.replace(month=1, day=1, hour=0, minute=0, second=0, microsecond=0)
        return _to_ms(begin.replace(year=begin.year + 1)) - _MS
    raise GregorianError(
        "behavior DURATION_IS_GREGORIAN is set; but `Duration` is not a valid gregorian interval"
    )

"""Daemon — process bootstrap: engine + service + listeners + discovery.

reference: daemon.go.  `spawn_daemon(conf)` builds the TPU decision
engine (single-device or mesh-sharded), wires the V1 service, starts
the gRPC server + HTTP gateway (+ optional plain status listener when
mTLS is on), hooks up peer discovery, and exposes `set_peers` for
membership pushes (daemon.go:370-380 marks self by address match).
"""

from __future__ import annotations

import logging
import os
import ssl
import tempfile
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Sequence

import grpc

from gubernator_tpu.clock import SYSTEM_CLOCK, Clock
from gubernator_tpu.config import Config, DaemonConfig, resolve_advertise_address
from gubernator_tpu.net.gateway import Gateway
from gubernator_tpu.net.grpc_service import (
    V1Stub,
    add_peers_v1_to_server,
    add_v1_to_server,
    dial,
)
from gubernator_tpu.net.server import GrpcPeersV1Adapter, GrpcV1Adapter
from gubernator_tpu.service import V1Instance
from gubernator_tpu.types import PeerInfo
from gubernator_tpu.utils.metrics import build_registry

log = logging.getLogger("gubernator_tpu.daemon")


class Daemon:
    """One gubernator_tpu process. reference: daemon.go:56-80."""

    def __init__(
        self,
        conf: DaemonConfig,
        *,
        clock: Clock = SYSTEM_CLOCK,
        engine=None,
        store=None,  # write-through Store (reference: config.go Store field)
        loader=None,  # bulk Loader (reference: config.go Loader field)
    ):
        self.conf = conf
        self.clock = clock
        self._engine = engine
        self._store = store
        self._loader = loader
        self.instance: Optional[V1Instance] = None
        self.grpc_server: Optional[grpc.Server] = None
        self.gateway: Optional[Gateway] = None
        self.status_gateway: Optional[Gateway] = None
        self.registry = None
        self.grpc_address = conf.grpc_listen_address
        self.http_address = conf.http_listen_address
        self._tls_bundle = None
        self._discovery = None
        self.membership = None
        self.replication = None
        self.obs = None
        self.slo = None
        self._closed = False

    # ------------------------------------------------------------------

    def _probe_backend(self) -> None:
        """Apply the operator platform escape hatch and fail FAST when
        the accelerator plugin is wedged, instead of hanging backend
        init forever.

        GUBER_PLATFORM=cpu (honored HERE so every entry point —
        binary, spawn_daemon, harness — gets it, not just
        cmd/daemon.py) forces the host backend before any backend
        touch.  Otherwise, when no backend is initialized yet, probe
        it in a throwaway subprocess with a hard timeout
        (platform_guard.probe_backend_subprocess — process-group kill)
        and raise a clear error naming the escape hatch on failure.
        GUBER_BACKEND_PROBE=0 disables the probe;
        GUBER_BACKEND_PROBE_TIMEOUT takes Go-style durations."""
        import sys

        if os.environ.get("GUBER_PLATFORM", "").lower() == "cpu":
            from gubernator_tpu.platform_guard import force_cpu_platform

            force_cpu_platform(self.conf.device_count or None)
            return
        if os.environ.get("GUBER_BACKEND_PROBE", "1") == "0":
            return
        if "jax" in sys.modules:
            # Importing jax does NOT initialize a backend (the package
            # __init__ pulls jax in), so module presence alone must not
            # skip the probe — but a forced-CPU platform or an
            # already-initialized backend means there is nothing left
            # to hang on.
            import jax
            from jax._src import xla_bridge

            if (jax.config.jax_platforms or "") == "cpu":
                return
            if getattr(xla_bridge, "_backends", None):
                return
        from gubernator_tpu.config import _env_float_seconds
        from gubernator_tpu.platform_guard import probe_backend_subprocess

        timeout = _env_float_seconds(
            {}, "GUBER_BACKEND_PROBE_TIMEOUT", 120.0
        )
        ok, detail = probe_backend_subprocess(timeout)
        if not ok:
            raise RuntimeError(
                f"accelerator backend failed to initialize: {detail}; "
                "set GUBER_PLATFORM=cpu to serve on the host backend, "
                "or GUBER_BACKEND_PROBE=0 to wait indefinitely"
            )

    def _build_engine(self):
        if self._engine is not None:
            return self._engine
        import jax

        devices = jax.devices()
        n = self.conf.device_count or len(devices)
        if n > 1:
            from gubernator_tpu.parallel.mesh import make_mesh
            from gubernator_tpu.parallel.sharded_engine import ShardedDecisionEngine

            mesh = make_mesh(devices[:n])
            return ShardedDecisionEngine(
                shard_capacity=max(1, self.conf.cache_size // n),
                mesh=mesh,
                clock=self.clock,
                store=self._store,
            )
        from gubernator_tpu.core.engine import DecisionEngine

        return DecisionEngine(
            capacity=self.conf.cache_size,
            clock=self.clock,
            device=devices[0],
            store=self._store,
        )

    def start(self) -> None:
        """reference: daemon.go:82-339 (Daemon.Start)."""
        conf = self.conf
        # Count XLA compiles from before the first engine build so the
        # gubernator_jit_recompiles metric covers warmup too; a healthy
        # daemon's count is flat after start() returns.
        from gubernator_tpu.utils import jit_guard

        jit_guard.install()
        self._probe_backend()
        engine = self._build_engine()
        self._warmup(engine)
        if self._loader is not None:
            # Restore persisted buckets before serving
            # (reference: gubernator.go:146-152).
            engine.load(self._loader)

        creds = None
        if conf.tls is not None:
            self._tls_bundle = conf.tls.setup()
            creds = self._tls_bundle.client_credentials()

        service_conf = Config(
            behaviors=conf.behaviors,
            cache_size=conf.cache_size,
            hash_algorithm=conf.hash_algorithm,
            peer_picker=conf.peer_picker,
            picker_replicas=conf.picker_replicas,
            data_center=conf.data_center,
            peer_credentials=creds,
            local_batch_wait=conf.local_batch_wait,
            global_serve_window=conf.global_serve_window,
            sketch_window_ms=conf.sketch_window_ms,
            sketch_depth=conf.sketch_depth,
            sketch_width=conf.sketch_width,
            ledger=conf.ledger,
            ledger_lease=conf.ledger_lease,
            ledger_lease_ttl=conf.ledger_lease_ttl,
            ledger_hot_threshold=conf.ledger_hot_threshold,
            ledger_keys=conf.ledger_keys,
            ledger_settle_interval=conf.ledger_settle_interval,
        )
        self.instance = V1Instance(service_conf, engine)
        # Elastic membership plane (cluster/membership.py): every peer
        # list this daemon observes — discovery pushes, static config,
        # harness — flows through set_peers into the manager, which
        # drives epoch transitions and ownership handoff.
        from gubernator_tpu.cluster.membership import MembershipManager

        self.membership = MembershipManager(
            self,
            epoch_timeout=conf.membership_epoch_timeout,
            handoff_window=conf.handoff_window,
            drain_deadline=conf.drain_deadline,
        )
        self.instance.membership = self.membership
        # Hot-key replication plane (cluster/replication.py): observed
        # load reshapes ownership — the hottest measured keys promote
        # to replicated credit leases, demote on cooldown.  Needs the
        # hot-key sketch for its rate source; inert without it.
        if conf.replication and self.instance.hotkeys is not None:
            from gubernator_tpu.cluster.replication import (
                ReplicationManager,
            )

            self.replication = ReplicationManager(
                self,
                promote_rate=conf.repl_promote_rate,
                cooldown=conf.repl_cooldown,
                lease=conf.repl_lease,
                lease_ttl=conf.repl_lease_ttl,
                interval=conf.repl_interval,
                max_keys=conf.repl_max_keys,
                max_replicas=conf.repl_max_replicas,
            )
            self.instance.replication = self.replication
            self.replication.start()
        # Tail flight recorder (utils/flight_recorder.py): when the
        # in-memory tracer is live (GUBER_TRACING=memory or a harness
        # set_tracer), retain full span trees of tail decisions for
        # /debug/trace.  OTel backends do their own tail sampling
        # upstream; disabled tracing costs nothing here.
        from gubernator_tpu.utils import tracing as _tracing
        from gubernator_tpu.utils.tracing import InMemoryTracer

        tracer = _tracing.current_tracer()
        if isinstance(tracer, InMemoryTracer):
            from gubernator_tpu.utils.flight_recorder import FlightRecorder

            # One recorder per tracer: in-process multi-daemon
            # harnesses share the global tracer, and each daemon
            # re-hooking on_root_finish would orphan its siblings'
            # recorders.
            fr = getattr(tracer, "_flight_recorder", None)
            if fr is None:
                fr = FlightRecorder.from_env(tracer)
                tracer._flight_recorder = fr
            self.instance.flight_recorder = fr
        self.registry = build_registry(
            self.instance, metric_flags=conf.metric_flags
        )
        # gRPC request counts/durations (reference: grpc_stats.go).
        from gubernator_tpu.utils.grpc_stats import GrpcStats

        grpc_stats = GrpcStats()
        self.registry.register(grpc_stats)

        # gRPC server (both services on one listener; the reference's
        # second loopback server exists only for grpc-gateway's dial,
        # which our native gateway doesn't need).
        self.grpc_server = grpc.server(
            ThreadPoolExecutor(
                max_workers=max(1, conf.grpc_workers),
                thread_name_prefix="guber-grpc",
            ),
            interceptors=[grpc_stats],
            options=[
                ("grpc.max_receive_message_length", 1024 * 1024),  # daemon.go:103
            ]
            + (
                # Only when configured, like the reference
                # (GUBER_GRPC_MAX_CONN_AGE_SEC; daemon.go:110-115).
                [("grpc.max_connection_age_ms", conf.grpc_max_conn_age_sec * 1000)]
                if conf.grpc_max_conn_age_sec > 0
                else []
            ),
        )
        add_v1_to_server(GrpcV1Adapter(self.instance), self.grpc_server)
        add_peers_v1_to_server(GrpcPeersV1Adapter(self.instance), self.grpc_server)
        if self._tls_bundle is not None:
            port = self.grpc_server.add_secure_port(
                conf.grpc_listen_address, self._tls_bundle.server_credentials()
            )
        else:
            port = self.grpc_server.add_insecure_port(conf.grpc_listen_address)
        if port == 0:
            raise RuntimeError(f"failed to bind gRPC on {conf.grpc_listen_address}")
        host = conf.grpc_listen_address.rpartition(":")[0]
        self.grpc_address = f"{host}:{port}"
        self.grpc_server.start()

        # HTTP gateway (+ /metrics).  Under TLS the gateway serves HTTPS
        # (reference: daemon.go:311-328).
        ssl_ctx = None
        if self._tls_bundle is not None:
            ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            with tempfile.NamedTemporaryFile(suffix=".pem") as cf, tempfile.NamedTemporaryFile(
                suffix=".pem"
            ) as kf:
                cf.write(self._tls_bundle.server_cert_pem)
                cf.flush()
                kf.write(self._tls_bundle.server_key_pem)
                kf.flush()
                ssl_ctx.load_cert_chain(cf.name, kf.name)
        self.gateway = Gateway(
            self.instance,
            conf.http_listen_address,
            self.registry,
            ssl_context=ssl_ctx,
        )
        self.gateway.start()
        host = conf.http_listen_address.rpartition(":")[0]
        self.http_address = f"{host}:{self.gateway.port}"

        # Optional native h2 fast front: one-method serving with zero
        # per-RPC Python (net/h2_fast.py documents the scope).
        self.h2_fast = None
        if conf.h2_fast_address:
            from gubernator_tpu.net.h2_fast import H2FastFront

            port = int(conf.h2_fast_address.rpartition(":")[2] or 0)
            self.h2_fast = H2FastFront(
                self.instance,
                port=port,
                window_s=conf.h2_fast_window,
                lanes=conf.h2_lanes or None,
                # The config field is authoritative (setup_daemon_config
                # parsed GUBER_NATIVE_LEDGER once); the front still
                # applies its live-clock gate.
                native_ledger=conf.native_ledger,
            )
            self.h2_fast_address = self.h2_fast.address
            # Connection-plane gauge source (gubernator_h2_conns):
            # the collector scrapes conn_stats() off the instance.
            self.instance.h2_front = self.h2_fast
            # Native event collector: drain the C front's event ring
            # into histograms/metrics/span stubs (utils/native_events;
            # GUBER_NATIVE_EVENTS=0 disables the ring entirely).
            if self.h2_fast._ring is not None:
                from gubernator_tpu.utils.native_events import (
                    NativeEventCollector,
                )

                self.instance.native_events = NativeEventCollector.from_env(
                    self.h2_fast
                )

        # Fleet observability plane (obs/; OBSERVABILITY.md §§9-10):
        # the cluster rollup collector behind /debug/fleet +
        # /metrics?fleet=1 + PeersV1/ObsSnapshot, and the SLO/
        # invariant burn-rate watchdog behind /debug/slo and the
        # gubernator_slo_* gauges.  GUBER_OBS=0 removes the whole
        # plane (the fleetobs bench's A/B arm).
        self.obs = None
        self.slo = None
        if os.environ.get("GUBER_OBS", "1").strip().lower() not in (
            "0", "false", "no", "off",
        ):
            from gubernator_tpu.obs.fleet import FleetCollector
            from gubernator_tpu.obs.slo import (
                SLOWatchdog,
                watch_keys_from_env,
            )

            self.obs = FleetCollector.from_env(
                self.instance,
                addr=resolve_advertise_address(
                    self.grpc_address, conf.advertise_address
                ),
                region=conf.data_center,
            )
            self.instance.obs = self.obs
            watch_keys_from_env(self.instance.admission_watch)
            self.slo = SLOWatchdog.from_env(
                self.obs, self.instance.admission_watch
            )
            self.instance.slo_watchdog = self.slo

        # Optional plain-HTTP status listener for probes when mTLS
        # would block them (reference: daemon.go:279-307).
        if conf.http_status_listen_address:
            self.status_gateway = Gateway(
                self.instance,
                conf.http_status_listen_address,
                self.registry,
                serve_metrics=True,
            )
            self.status_gateway.start()

        self._start_discovery()

        # Periodic device expiry sweep reclaiming slots of expired
        # buckets (the reference's cache drops expired items on read,
        # lrucache.go:112-138; device-resident state needs an explicit
        # sweep kernel — SURVEY.md §7.3 item 6).
        if self.conf.sweep_interval > 0:
            self._sweep_stop = threading.Event()
            self._sweeper = threading.Thread(
                target=self._sweep_loop, name="guber-sweep", daemon=True
            )
            self._sweeper.start()

    # Windows swept per tick: bounds how long each periodic sweep holds
    # the engine lock (a full pass at 100M slots is ~763 windows of
    # device round-trips — serving p99 would spike for its whole
    # duration).  The cursor resumes next tick, so full coverage still
    # happens, just spread across ticks.
    SWEEP_WINDOWS_PER_TICK = 16

    def _sweep_loop(self) -> None:
        while not self._sweep_stop.wait(self.conf.sweep_interval):
            try:
                self.instance.engine.sweep(
                    max_windows=self.SWEEP_WINDOWS_PER_TICK
                )
            except Exception:  # noqa: BLE001 — sweeping must not die
                from gubernator_tpu.utils.metrics import record_swallowed

                record_swallowed("daemon.sweep")
                log.exception("expiry sweep failed")

    def _warmup(self, engine) -> None:
        """Pay the kernel jit compiles before serving, not on the first
        client requests (an XLA compile can exceed the peer batch
        timeout).  The default ladder (64..1024) covers every width the
        wire can produce — MAX_BATCH_SIZE=1000 pads to 1024 — for BOTH
        serving programs (dataclass + columnar); engine-level callers
        that exceed it (bench harnesses) warm their own widths.
        Group-commit windows MERGE wire batches, so with a window
        enabled the ladder extends to the window's merge bound (4096)
        — a mid-serving compile of an unseen merged width was a
        measured multi-second p99 spike.
        tests/test_warmup.py pins zero compile-cache misses."""
        conf = self.conf
        if conf.global_serve_window > 0 or conf.local_batch_wait > 0:
            engine.warmup(max_width=4096)
        else:
            engine.warmup()

    # ------------------------------------------------------------------

    def _start_discovery(self) -> None:
        """reference: daemon.go:185-220 (discovery selection switch)."""
        kind = self.conf.peer_discovery_type
        if kind == "none":
            if self.conf.static_peers:
                # Fixed-topology cluster (GUBER_STATIC_PEERS): the full
                # membership is configuration, not discovery.  set_peers
                # marks whichever entry matches our advertise address
                # as self.
                self.set_peers(
                    [
                        PeerInfo(
                            grpc_address=a,
                            http_address="",
                            datacenter=self.conf.data_center,
                        )
                        for a in self.conf.static_peers
                    ]
                )
            else:
                self.set_peers([self.peer_info()])
            return
        from gubernator_tpu.discovery import create_discovery

        self._discovery = create_discovery(self.conf, self)
        self._discovery.start()

    def peer_info(self) -> PeerInfo:
        advertise = resolve_advertise_address(
            self.grpc_address, self.conf.advertise_address
        )
        return PeerInfo(
            grpc_address=advertise,
            http_address=self.http_address,
            datacenter=self.conf.data_center,
        )

    def set_peers(self, peers: Sequence[PeerInfo]) -> None:
        """Mark ourselves in the list, then hand to the service.

        reference: daemon.go:370-380 (SetPeers).
        """
        me = self.peer_info()
        marked: List[PeerInfo] = []
        for p in peers:
            marked.append(
                PeerInfo(
                    grpc_address=p.grpc_address,
                    http_address=p.http_address,
                    datacenter=p.datacenter,
                    is_owner=p.grpc_address == me.grpc_address,
                )
            )
        if not any(p.is_owner for p in marked):
            me.is_owner = True
            marked.append(me)
        assert self.instance is not None
        self.instance.set_peers(marked)
        # New routing is live; now let the membership plane observe
        # the view — on a real change it bumps the epoch, opens the
        # dual-ring window, and ships moved buckets to their new
        # owners in the background (cluster/membership.py).
        if self.membership is not None:
            self.membership.apply_view(marked)

    # ------------------------------------------------------------------

    def wait_for_connect(self, timeout: float = 10.0) -> None:
        """Block until our own gRPC endpoint answers HealthCheck.

        reference: daemon.go:330-337, 398-437 (WaitForConnect).
        """
        from gubernator_tpu.net.pb import gubernator_pb2 as pb

        deadline = time.monotonic() + timeout
        creds = (
            self._tls_bundle.client_credentials() if self._tls_bundle else None
        )
        addr = self.grpc_address
        if addr.startswith("0.0.0.0:") or addr.startswith(":::"):
            addr = "127.0.0.1:" + addr.rpartition(":")[2]
        last_err: Optional[Exception] = None
        while time.monotonic() < deadline:
            try:
                channel = dial(addr, credentials=creds)
                V1Stub(channel).HealthCheck(pb.HealthCheckReq(), timeout=1.0)
                channel.close()
                return
            except grpc.RpcError as e:  # pragma: no cover - timing
                last_err = e
                time.sleep(0.05)
        raise TimeoutError(f"daemon at {addr} never became ready: {last_err}")

    def peer_health(self) -> dict:
        """This node's view of every peer's circuit state + transition
        counts (cluster/health.py) — the operator/bench entry for the
        same numbers /metrics exports as gubernator_peer_state and
        gubernator_circuit_transitions (bench artifacts embed it)."""
        assert self.instance is not None
        out = {}
        for p in self.instance.get_peer_list():
            if p.info.is_owner:
                continue
            out[p.info.grpc_address] = {
                "state": p.health.state(),
                "transitions": p.health.transition_counts(),
            }
        return out

    def membership_stats(self) -> dict:
        """This node's membership-plane view: epoch, phase
        (stable|dual), cumulative dual-window seconds, and handoff
        row counters — the same numbers /metrics exports as
        gubernator_membership_epoch / gubernator_handoff_keys /
        gubernator_ring_dual_window_seconds (bench artifacts embed
        it, like peer_health())."""
        if self.membership is None:
            return {}
        return self.membership.stats()

    def replication_stats(self) -> dict:
        """This node's hot-key replication view: promotion/demotion
        counters, grant traffic, credit accounting, and the live
        promoted/replica-lease key counts — the same numbers /metrics
        exports as gubernator_replication_* (bench artifacts embed
        it, like membership_stats())."""
        if self.replication is None:
            return {}
        return self.replication.stats()

    def multiregion_stats(self) -> dict:
        """This node's cross-region federation view: window/push
        counters, per-region sends and circuit states, the retry
        backlog, and the window-wait / region-RPC hop budget — the
        same numbers /metrics exports as gubernator_multiregion_*
        (bench artifacts embed it, like peer_health())."""
        if self.instance is None:
            return {}
        return self.instance.multi_region_mgr.stats()

    def fleet_stats(self, peers: bool = True) -> dict:
        """One cluster rollup from this node's vantage (obs/fleet.py)
        — the same merged view /debug/fleet and /metrics?fleet=1
        serve (bench artifacts embed it, like peer_health())."""
        if self.obs is None:
            return {}
        return self.obs.collect(peers=peers)

    def slo_status(self) -> dict:
        """The SLO watchdog's live view: declared SLIs, current burn
        rates, invariant headroom, and the bounded breach log — the
        same shape /debug/slo serves."""
        if self.slo is None:
            return {}
        return self.slo.status()

    def drain(self, deadline: Optional[float] = None) -> dict:
        """Planned leave: ship EVERY held bucket to its owner under
        the ring-without-self (cluster/membership.py), bounded by
        `deadline` seconds (default GUBER_DRAIN_DEADLINE).  Returns
        {"shipped", "forfeited", "targets"}; the caller then removes
        this node from the cluster (deregister / peer push) and calls
        close() — state first, then topology."""
        if self.membership is None:
            return {"shipped": 0, "forfeited": 0, "targets": 0}
        return self.membership.drain(deadline)

    def stage_budget(self) -> dict:
        """The measured GLOBAL-path latency budget on this node:
        per-stage {count, mean_ms, p50_ms, p99_ms, max_ms} for the
        five pipeline stages (client window wait, engine serve,
        hit-window wait, owner RPC, broadcast age).  p50/p99 are REAL
        streaming quantiles from DurationStat's histogram — earlier
        rounds advertised a "p50 budget" while reporting means, which
        is exactly how the lease-TTL-churn tail stayed hidden
        (PERF.md §23).  The same numbers /metrics exports as
        gubernator_stage_duration + gubernator_stage_quantile_seconds;
        /debug/vars serves them live."""
        assert self.instance is not None
        return {
            stage: stat.snapshot_ms()
            for stage, stat in self.instance.stage_timers.items()
        }

    def close(self) -> None:
        """Graceful stop. reference: daemon.go:342-367 (Close)."""
        if self._closed:
            return
        self._closed = True
        if getattr(self, "_sweep_stop", None) is not None:
            self._sweep_stop.set()
            # A sweep tick may be mid-flight inside engine.sweep();
            # join before tearing the engine down under it.
            self._sweeper.join(timeout=5.0)
        if self._discovery is not None:
            self._discovery.close()
        if self.membership is not None:
            # Join any in-flight epoch transition before tearing the
            # engine down under its snapshot/ship pass.
            self.membership.close()
        if self.replication is not None:
            # Demote what we promoted (returns replica credit while
            # peers are still up) and drop replica leases BEFORE the
            # native front frees the decision plane below.
            self.replication.close()
        if getattr(self, "slo", None) is not None:
            # Watchdog before the obs collector: a tick mid-teardown
            # must not fan out through a closed scrape pool.
            self.slo.close()
        if getattr(self, "obs", None) is not None:
            self.obs.close()
        if self.instance is not None and self.instance.native_events is not None:
            # Stop the drain thread BEFORE the front frees the ring
            # (single-consumer contract; a drain into a freed ring is
            # a use-after-free).  If the thread outlived the join,
            # leak the ring instead of freeing it.
            if not self.instance.native_events.close():
                if getattr(self, "h2_fast", None) is not None:
                    self.h2_fast.abandon_ring()
        if getattr(self, "h2_fast", None) is not None:
            self.h2_fast.close()
        if self.gateway is not None:
            self.gateway.close()
        if self.status_gateway is not None:
            self.status_gateway.close()
        if self.grpc_server is not None:
            self.grpc_server.stop(grace=1.0).wait()
        if self.instance is not None:
            if self._loader is not None:
                # Persist the cache on shutdown
                # (reference: gubernator.go:159-192 → Loader.Save).
                self.instance.engine.save(self._loader)
            self.instance.close()


def spawn_daemon(
    conf: DaemonConfig,
    *,
    clock: Clock = SYSTEM_CLOCK,
    engine=None,
    store=None,
    loader=None,
) -> Daemon:
    """Start a daemon and wait for readiness.

    reference: daemon.go:66-80 (SpawnDaemon).
    """
    d = Daemon(conf, clock=clock, engine=engine, store=store, loader=loader)
    d.start()
    d.wait_for_connect()
    return d

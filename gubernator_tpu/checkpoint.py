"""Checkpoint/resume: file-backed Loader for device-state snapshots.

The Loader interface (store.py) IS the checkpoint system, exactly as in
the reference (SURVEY.md §5.4): `engine.save(loader)` streams a
full-fidelity device→host snapshot out, `engine.load(loader)` streams
it back in before serving.  `NpzFileLoader` persists the stream as one
compressed npz of columnar arrays — the struct-of-arrays layout on
disk mirrors the layout in HBM, so save/restore is a single
device↔host transfer plus one numpy write/read, not a per-key walk.
"""

from __future__ import annotations

import os
from typing import Iterable, Iterator, List

import numpy as np

from gubernator_tpu.store import CacheItem, LeakyBucketItem, TokenBucketItem
from gubernator_tpu.types import Algorithm


class NpzFileLoader:
    """Loader that persists CacheItems to an .npz file."""

    def __init__(self, path: str):
        self.path = path

    def save(self, items: Iterator[CacheItem]) -> None:
        keys: List[str] = []
        algo: List[int] = []
        status: List[int] = []
        limit: List[int] = []
        remaining_i: List[int] = []
        remaining_f: List[float] = []
        remf_hi: List[int] = []
        remf_lo: List[int] = []
        duration: List[int] = []
        t0: List[int] = []
        expire: List[int] = []
        burst: List[int] = []
        invalid: List[int] = []
        for it in items:
            v = it.value
            if v is None:
                continue
            keys.append(it.key)
            algo.append(int(it.algorithm))
            expire.append(it.expire_at)
            invalid.append(it.invalid_at)
            if isinstance(v, TokenBucketItem):
                status.append(v.status)
                limit.append(v.limit)
                remaining_i.append(v.remaining)
                remaining_f.append(0.0)
                remf_hi.append(0)
                remf_lo.append(0)
                duration.append(v.duration)
                t0.append(v.created_at)
                burst.append(0)
            else:
                status.append(0)
                limit.append(v.limit)
                remaining_i.append(0)
                remaining_f.append(v.remaining)
                # Exact 32.32 words when present — the float64 mirror
                # rounds once whole parts exceed 2^21.  Items built from
                # the float field only derive their words from it.
                from gubernator_tpu.store import words_from_float

                w = (
                    v.remaining_words
                    if v.remaining_words is not None
                    else words_from_float(v.remaining)
                )
                remf_hi.append(w[0])
                remf_lo.append(w[1])
                duration.append(v.duration)
                t0.append(v.updated_at)
                burst.append(v.burst)
        # .npz-suffixed temp name (savez would append the suffix
        # otherwise), swapped in atomically so a crash mid-save never
        # clobbers the previous checkpoint.
        tmp = self.path + ".tmp.npz"
        np.savez_compressed(
            tmp,
            keys=np.asarray(keys, dtype=object),
            algo=np.asarray(algo, dtype=np.int32),
            status=np.asarray(status, dtype=np.int32),
            limit=np.asarray(limit, dtype=np.int64),
            remaining_i=np.asarray(remaining_i, dtype=np.int64),
            remaining_f=np.asarray(remaining_f, dtype=np.float64),
            remf_hi=np.asarray(remf_hi, dtype=np.int32),
            remf_lo=np.asarray(remf_lo, dtype=np.uint32),
            duration=np.asarray(duration, dtype=np.int64),
            t0=np.asarray(t0, dtype=np.int64),
            expire=np.asarray(expire, dtype=np.int64),
            burst=np.asarray(burst, dtype=np.int64),
            invalid=np.asarray(invalid, dtype=np.int64),
        )
        os.replace(tmp, self.path)

    def load(self) -> Iterable[CacheItem]:
        if not os.path.exists(self.path):
            return
        with np.load(self.path, allow_pickle=True) as z:
            keys = z["keys"]
            algo = z["algo"]
            status = z["status"]
            limit = z["limit"]
            remaining_i = z["remaining_i"]
            remaining_f = z["remaining_f"]
            duration = z["duration"]
            t0 = z["t0"]
            expire = z["expire"]
            burst = z["burst"]
            invalid = z["invalid"]
            remf_hi = z["remf_hi"] if "remf_hi" in z else None
            remf_lo = z["remf_lo"] if "remf_lo" in z else None
            for i in range(len(keys)):
                if algo[i] == int(Algorithm.TOKEN_BUCKET):
                    value = TokenBucketItem(
                        status=int(status[i]),
                        limit=int(limit[i]),
                        duration=int(duration[i]),
                        remaining=int(remaining_i[i]),
                        created_at=int(t0[i]),
                    )
                else:
                    value = LeakyBucketItem(
                        limit=int(limit[i]),
                        duration=int(duration[i]),
                        remaining=float(remaining_f[i]),
                        updated_at=int(t0[i]),
                        burst=int(burst[i]),
                        remaining_words=(
                            (int(remf_hi[i]), int(remf_lo[i]))
                            if remf_hi is not None
                            else None
                        ),
                    )
                yield CacheItem(
                    key=str(keys[i]),
                    value=value,
                    expire_at=int(expire[i]),
                    algorithm=int(algo[i]),
                    invalid_at=int(invalid[i]),
                )

"""FleetCollector — the cluster rollup scrape (OBSERVABILITY.md §9).

An operator of the PR-13/14 cluster had N×/metrics + N×/debug
endpoints and no rollup; the crossregion/flashcrowd benches hand-fold
counters per node — exactly the fleet-level accounting gap "Designing
Scalable Rate Limiting Systems" (PAPERS.md) calls out.  This module
gives any node a one-scrape cluster view:

* **Pull, not push**: `collect()` fans one raw-JSON
  ``PeersV1/ObsSnapshot`` RPC out to every peer (local ring + every
  region picker — the same topology surface the decision planes
  route over).  The fan-out is health-gated (circuit-open peers are
  SKIPPED counted, never probed — a rollup must not perturb the
  breakers chaos tests assert on), every RPC carries an explicit
  timeout, and the whole fan-out sits under one total barrier budget
  — the multiregion push's shape (GUBER_OBS_RPC_TIMEOUT /
  GUBER_OBS_FANOUT_DEADLINE).

* **Merge semantics**: counters SUM (per region and fleet-wide,
  regions from the nodes' DC tags); gauges label-join by peer/region
  (a cache size does not sum); ``DurationStat`` histograms merge
  bucket-for-bucket via ``merge_snapshot`` so the fleet p50/p99 are
  REAL quantiles of the union of observations — never
  means-of-means.

Served as ``/debug/fleet`` and ``/metrics?fleet=1`` on any node
(net/gateway.py), and consumed by the SLO watchdog (obs/slo.py).
"""

from __future__ import annotations

import json
import logging
import time
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutTimeout
from typing import Dict, List, Tuple

log = logging.getLogger("gubernator_tpu.obs.fleet")

SNAPSHOT_VERSION = 1


class FleetCollector:
    """One node's rollup plane: local snapshot + peer fan-out merge."""

    def __init__(
        self,
        instance,
        *,
        addr: str = "",
        region: str = "",
        rpc_timeout: float = 0.5,
        fanout_deadline: float = 2.0,
    ) -> None:
        self.instance = instance
        self.addr = addr
        self.region = region
        self.rpc_timeout = rpc_timeout
        self.fanout_deadline = fanout_deadline
        # Small persistent pool: rollups are scrape-rate, and a pool
        # per collect() would leak thread churn into every scrape.
        self._pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="guber-obs-scrape"
        )
        self._closed = False

    @classmethod
    def from_env(
        cls, instance, *, addr: str = "", region: str = ""
    ) -> "FleetCollector":
        from gubernator_tpu.config import _env_float_seconds

        return cls(
            instance,
            addr=addr,
            region=region,
            rpc_timeout=_env_float_seconds(
                {}, "GUBER_OBS_RPC_TIMEOUT", 0.5
            ),
            fanout_deadline=_env_float_seconds(
                {}, "GUBER_OBS_FANOUT_DEADLINE", 2.0
            ),
        )

    # -- the local snapshot (what ObsSnapshot serves) -------------------

    def local_snapshot(self) -> dict:
        """This node's metric families in wire shape: summable
        counters, per-node gauges, and raw 36-bucket histograms."""
        inst = self.instance
        eng = inst.engine
        counters: Dict[str, float] = {
            "checks": getattr(eng, "requests_total", 0),
            "over_limit": getattr(eng, "over_limit_total", 0),
        }
        for k in (
            "check_errors", "local", "forward", "global", "sketch",
            "replicated_local", "global_miss_local",
            "degraded_answers", "degraded_region_answers",
            "backoff_retries", "async_retries",
        ):
            counters[k] = inst.counters.get(k, 0)
        gm = getattr(inst, "global_mgr", None)
        if gm is not None:
            counters["global_async_sends"] = gm.async_sends
            counters["global_broadcasts"] = gm.broadcasts
            counters["global_hits_requeued"] = gm.hits_requeued
            counters["global_hits_requeue_dropped"] = (
                gm.hits_requeue_dropped
            )
        mr = getattr(inst, "multi_region_mgr", None)
        if mr is not None:
            mrs = mr.stats()
            counters["multiregion_windows"] = mrs["windows"]
            counters["multiregion_region_sends"] = mrs["region_sends"]
            counters["multiregion_hits_requeued"] = mrs["hits_requeued"]
            counters["multiregion_hits_dropped"] = mrs["hits_dropped"]
        hoff = getattr(inst, "handoff_counters", None)
        if hoff is not None:
            for k in ("shipped", "forfeited", "received"):
                counters[f"handoff_{k}"] = hoff[k]
        led = getattr(inst, "ledger", None)
        if led is not None:
            counters["ledger_answered"] = led.answered
            counters["ledger_native_answered"] = led.native_answered()
        ev = getattr(inst, "native_events", None)
        if ev is not None:
            rs = ev.ring_stats()
            counters["native_ring_dropped"] = rs.get("dropped", 0)
            counters["native_events"] = sum(
                ev.event_counts().values()
            )

        gauges: Dict[str, float] = {
            "cache_size": eng.cache_size()
            if hasattr(eng, "cache_size") else 0,
        }
        if gm is not None:
            gauges["global_hits_pending"] = gm._hits.pending()
            gauges["global_broadcasts_pending"] = gm._updates.pending()
        mem = getattr(inst, "membership", None)
        if mem is not None:
            gauges["membership_epoch"] = mem.epoch()
        front = getattr(inst, "h2_front", None)
        if front is not None:
            try:
                gauges["h2_conns_open"] = front.conn_stats()[
                    "conns_open"
                ]
            except Exception:  # noqa: BLE001 — front mid-teardown
                pass
        repl = getattr(inst, "replication", None)
        if repl is not None:
            rs = repl.stats()
            gauges["replication_promoted"] = rs["promoted_keys"]
            gauges["replication_replica_leases"] = rs["replica_leases"]

        hists = {
            stage: stat.bucket_snapshot()
            for stage, stat in inst.stage_timers.items()
        }
        if ev is not None:
            for stage, stat in ev.histograms().items():
                hists[stage] = stat.bucket_snapshot()

        aw = getattr(inst, "admission_watch", None)
        admitted = aw.snapshot() if aw is not None else {}
        return {
            "v": SNAPSHOT_VERSION,
            "addr": self.addr,
            "region": self.region,
            "counters": counters,
            "gauges": gauges,
            "hists": hists,
            "admitted": admitted,
        }

    def local_snapshot_raw(self) -> bytes:
        return json.dumps(self.local_snapshot()).encode()

    # -- the fan-out ---------------------------------------------------

    def _peers(self) -> List:
        """Every dialable peer: the local ring plus every region
        picker's members (self excluded — the local snapshot is taken
        in-process)."""
        inst = self.instance
        peers = [
            p for p in inst.get_peer_list() if not p.info.is_owner
        ]
        for _dc, ring in inst.get_region_pickers().items():
            peers.extend(ring.peers())
        return peers

    @staticmethod
    def _scrape_peer(peer, timeout: float) -> dict:
        raw = peer.obs_snapshot_raw(timeout=timeout)
        snap = json.loads(bytes(raw) or b"{}")
        if not isinstance(snap, dict):
            raise ValueError("malformed obs snapshot")
        snap.setdefault("addr", peer.info.grpc_address)
        snap.setdefault("region", peer.info.datacenter)
        return snap

    def collect(self, peers: bool = True) -> dict:
        """One rollup: local snapshot (+ the peer fan-out unless
        `peers` is False) merged into the fleet view."""
        from gubernator_tpu.utils.metrics import record_swallowed
        from gubernator_tpu.utils.tracing import span

        t0 = time.monotonic()
        snaps = [self.local_snapshot()]
        ok, failed, skipped = 1, 0, 0
        if peers and not self._closed:
            targets = self._peers()
            with span("obs.fleet_scrape", peers=len(targets)):
                futs = []
                for p in targets:
                    # Peek-only gate: a broken peer is skipped without
                    # consuming a half-open probe slot — the rollup
                    # must observe the health plane, not drive it.
                    if not p.health.would_allow():
                        skipped += 1
                        continue
                    futs.append(
                        self._pool.submit(
                            self._scrape_peer, p, self.rpc_timeout
                        )
                    )
                deadline = t0 + max(0.05, self.fanout_deadline)
                for f in futs:
                    try:
                        snaps.append(
                            f.result(
                                timeout=max(
                                    0.0, deadline - time.monotonic()
                                )
                            )
                        )
                        ok += 1
                    except FutTimeout:
                        # A not-yet-started scrape is cancelled so it
                        # does not burn a pool slot (and a peer RPC)
                        # after the barrier already gave up on it.
                        f.cancel()
                        failed += 1
                        record_swallowed("obs.fanout_deadline")
                    except Exception:  # noqa: BLE001 — one peer must
                        # not sink the rollup; the count is the signal.
                        failed += 1
                        record_swallowed("obs.scrape")
        rollup = self.merge(snaps)
        rollup["scrape"] = {
            "ok": ok,
            "failed": failed,
            "skipped": skipped,
            "elapsed_ms": round((time.monotonic() - t0) * 1e3, 3),
        }
        return rollup

    # -- the merge -----------------------------------------------------

    @staticmethod
    def merge(snaps: List[dict]) -> dict:
        """Merge node snapshots: counters sum (per region + total),
        gauges label-join, histograms merge exactly."""
        from gubernator_tpu.utils.metrics import DurationStat

        nodes = []
        counters: Dict[str, float] = {}
        regions: Dict[str, dict] = {}
        gauges: Dict[str, Dict[str, Tuple[str, float]]] = {}
        hists: Dict[str, DurationStat] = {}
        admitted: Dict[str, dict] = {}
        for snap in snaps:
            addr = snap.get("addr", "")
            region = snap.get("region", "")
            nodes.append({"addr": addr, "region": region})
            sub = regions.setdefault(
                region, {"nodes": 0, "counters": {}}
            )
            sub["nodes"] += 1
            for name, v in (snap.get("counters") or {}).items():
                counters[name] = counters.get(name, 0) + v
                sub["counters"][name] = (
                    sub["counters"].get(name, 0) + v
                )
            for name, v in (snap.get("gauges") or {}).items():
                gauges.setdefault(name, {})[addr] = (region, v)
            for stage, hsnap in (snap.get("hists") or {}).items():
                hists.setdefault(stage, DurationStat()).merge_snapshot(
                    hsnap
                )
            for key, ent in (snap.get("admitted") or {}).items():
                agg = admitted.setdefault(
                    key, {"admitted": 0, "limit": 0, "nodes": 0}
                )
                agg["admitted"] += int(ent.get("admitted", 0))
                agg["limit"] = max(
                    agg["limit"], int(ent.get("limit", 0))
                )
                agg["nodes"] += 1
        return {
            "v": SNAPSHOT_VERSION,
            "nodes": nodes,
            "regions": regions,
            "counters": counters,
            "gauges": gauges,
            "quantiles": {
                stage: h.snapshot_ms() for stage, h in hists.items()
            },
            "admitted": admitted,
        }

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._pool.shutdown(wait=True)

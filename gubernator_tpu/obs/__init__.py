"""Fleet observability plane (OBSERVABILITY.md §§9-11).

- ``obs.fleet.FleetCollector`` — the cluster rollup: every peer's
  metric families pulled over the raw-JSON ``PeersV1/ObsSnapshot``
  RPC (health-gated, per-RPC timeouts under a total fan-out deadline)
  and merged so counters SUM, gauges label-join by peer/region, and
  ``DurationStat`` histograms merge bucket-for-bucket — cluster
  p50/p99 are real quantiles, not means-of-means.
- ``obs.slo`` — declared SLIs evaluated as multi-window multi-burn-
  rate alerts over the rollup, plus the admission-bound invariant
  (RESILIENCE.md's N×limit proofs as a live gauge).

Pure-Python and jax-free by design: the smoke harness and the
guberlint drift ``slo`` sub-rule both load this package without a
backend.
"""

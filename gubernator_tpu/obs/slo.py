"""SLO/invariant watchdog: declared SLIs, multi-window burn rates,
and the admission-bound invariant as a live gauge.

Until this plane, the bounded-drift invariants the resilience tiers
are built on (over-admission ≤ N_partitions / N_replicas / N_regions
× limit — RESILIENCE.md §§10-12) were proven in tests and bench
canaries only; nothing watched them on a live cluster.  This module
turns them, plus the serving SLOs, into continuously evaluated
gauges:

* **SLIs are declared data** (`SLI` rows in `DEFAULT_SLIS`): each
  names the documented metric backing it — guberlint's drift ``slo``
  sub-rule pins the link, so an SLI can never reference a series the
  registry stopped exporting.

* **Multi-window multi-burn-rate** (the SRE-workbook shape): each SLI
  evaluates over a FAST pair (5m / 1h, factor 14.4 — pages) and a
  SLOW pair (6h / 3d, factor 1.0 — tickets); a breach needs BOTH
  windows of a pair over the factor, which kills both blips (short
  window alone) and stale alerts (long window alone).  Window lengths
  shrink via GUBER_SLO_FAST_WINDOWS / GUBER_SLO_SLOW_WINDOWS for the
  test timescale.  Window history is the watchdog's own sample ring;
  windows longer than the retained history evaluate against the
  oldest sample (reported as the actual span).

* **The admission-bound invariant**: watched finite-limit keys
  (AdmissionWatch) count their cluster-wide ADMITTED hits per
  duration window; the watchdog derives the applicable bound
  (N_regions × limit on a federated cluster, N_nodes × limit
  otherwise) and exports ``gubernator_invariant_headroom{key,bound}``
  = bound − admitted.  Negative headroom is a violated RESILIENCE.md
  proof — on a healthy cluster it never goes below zero, and a new
  duration window restores it to the full bound.

Breaches are recorded as span events (``slo_breach`` inside
``slo.evaluate``) and in a bounded breach log served at /debug/slo.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

log = logging.getLogger("gubernator_tpu.obs.slo")

_OFF_VALUES = ("0", "false", "no", "off")


@dataclass(frozen=True)
class SLI:
    """One declared service-level indicator.

    `metric` names the DOCUMENTED metric family backing the SLI (the
    drift ``slo`` sub-rule checks it against utils/metrics.py).  The
    kind selects the evaluation:

    - ``ratio``: burn = (Δ`bad` / Δ`total` over the window) / budget,
      budget = 1 − objective;
    - ``quantile``: burn = merged-histogram p99 of `stage` /
      `threshold_ms` (a cluster tail SLO — the rollup's histogram
      merge makes this a real quantile);
    - ``drops``: like ratio, but `bad` counts shed work (silent-loss
      SLIs: ring drops, requeue age-cap drops);
    - ``invariant``: burn = max over watched keys of admitted/bound
      (the admission-bound SLI; headroom rides its own gauge).
    """

    name: str
    metric: str
    kind: str
    bad: str = ""
    total: str = ""
    stage: str = ""
    threshold_ms: float = 0.0
    objective: float = 0.999


DEFAULT_SLIS: Tuple[SLI, ...] = (
    SLI(
        name="error_rate",
        metric="gubernator_check_error_counter",
        kind="ratio", bad="check_errors", total="checks",
        objective=0.999,
    ),
    SLI(
        name="degraded_fraction",
        metric="gubernator_degraded_answers",
        kind="ratio", bad="degraded_answers", total="checks",
        objective=0.99,
    ),
    SLI(
        name="degraded_region_fraction",
        metric="gubernator_multiregion_degraded_answers",
        kind="ratio", bad="degraded_region_answers", total="checks",
        objective=0.99,
    ),
    SLI(
        name="window_wait_p99",
        metric="gubernator_stage_seconds",
        kind="quantile", stage="window_wait", threshold_ms=50.0,
    ),
    SLI(
        name="feeder_ring_wait_p99",
        metric="gubernator_native_stage_duration",
        kind="quantile", stage="feeder_ring_wait", threshold_ms=25.0,
    ),
    SLI(
        name="reactor_wake_p99",
        metric="gubernator_native_events",
        kind="quantile", stage="reactor_wake", threshold_ms=25.0,
    ),
    SLI(
        name="ring_drops",
        metric="gubernator_native_ring_dropped",
        kind="drops", bad="native_ring_dropped", total="checks",
        objective=0.999,
    ),
    SLI(
        name="requeue_drops",
        metric="gubernator_multiregion_hits_dropped",
        kind="drops", bad="multiregion_hits_dropped", total="checks",
        objective=0.999,
    ),
    SLI(
        name="admission_bound",
        metric="gubernator_invariant_headroom",
        kind="invariant",
    ),
)


class AdmissionWatch:
    """Bounded per-key ADMITTED-hit counters for watched finite-limit
    keys — the local half of the admission-bound invariant.

    Zero steady-state cost: serve paths peek one attribute (`active`)
    and return when nothing is watched.  Counts accrue at the
    CLIENT-FACING boundary only — get_rate_limits' final responses
    (local, forwarded, degraded, GLOBAL-cached and replica-lease
    answers alike) and the client-facing pb-columnar route.  Internal
    re-applies (multiregion delta pushes, GLOBAL hit windows, handoff
    restores) replay hits a client was already answered for and are
    deliberately NOT counted — they would double-bill the N×limit
    bound; the zero-Python raw-wire front under-counts by design
    (safe direction, documented in OBSERVABILITY.md).  A response's
    `reset_time` advancing past the stored one means a NEW duration
    window: the count resets, so headroom recovers once a
    partition-era window expires."""

    _MAX_KEYS = 64

    # guberlint: guard _keys by _lock

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._keys: Dict[str, dict] = {}
        # Lock-free fast-path peek; written only under the lock.
        self.active = False

    def watch(self, key: str, limit: int = 0) -> bool:
        """Start counting `key` (a hash key, "name_uniquekey");
        bounded at _MAX_KEYS — refusals return False, never evict."""
        with self._lock:
            if key not in self._keys and len(self._keys) >= self._MAX_KEYS:
                return False
            self._keys.setdefault(
                key,
                {"admitted": 0, "limit": int(limit), "reset_time": 0},
            )
            self.active = True
            return True

    def unwatch(self, key: str) -> None:
        with self._lock:
            self._keys.pop(key, None)
            self.active = bool(self._keys)

    def _observe_locked(
        self, ent: dict, hits: int, status: int, limit: int, reset: int
    ) -> None:
        if reset > ent["reset_time"]:
            # A new duration window: the bound re-arms.
            ent["reset_time"] = int(reset)
            ent["admitted"] = 0
        if status == 0 and hits > 0:  # UNDER_LIMIT ⇒ the hits landed
            ent["admitted"] += int(hits)
        if limit > 0:
            ent["limit"] = int(limit)

    def observe_batch(self, reqs, resps) -> None:
        """Client-facing dataclass route (get_rate_limits' final
        responses — every answer shape funnels through there)."""
        with self._lock:
            if not self._keys:
                return
            for r, resp in zip(reqs, resps):
                ent = self._keys.get(r.hash_key())
                if ent is None or resp is None or resp.error:
                    continue
                self._observe_locked(
                    ent, int(r.hits), int(resp.status), int(r.limit),
                    int(resp.reset_time),
                )

    def observe_columns(self, keys_str, hits, cols) -> None:
        """pb-columnar serve route (apply_columnar_local): `cols` is
        the engine's (status, limit, remaining, reset_time) tuple."""
        status, limit, _remaining, reset = cols
        with self._lock:
            if not self._keys:
                return
            for i, k in enumerate(keys_str):
                ent = self._keys.get(k)
                if ent is None:
                    continue
                self._observe_locked(
                    ent, int(hits[i]), int(status[i]), int(limit[i]),
                    int(reset[i]),
                )

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._keys.items()}


def watch_keys_from_env(watch: AdmissionWatch) -> None:
    """Seed the admission watch from GUBER_SLO_WATCH_KEYS: comma-
    separated hash keys, each optionally ``key:limit``."""
    raw = os.environ.get("GUBER_SLO_WATCH_KEYS", "")
    for entry in raw.split(","):
        entry = entry.strip()
        if not entry:
            continue
        key, _, lim = entry.rpartition(":")
        if key and lim.isdigit():
            watch.watch(key, int(lim))
        else:
            watch.watch(entry)


def _windows_env(env_key: str, default: str) -> Tuple[float, float]:
    raw = os.environ.get(env_key, "") or default
    try:
        short_s, long_s = (float(x) for x in raw.split(",")[:2])
        if short_s <= 0 or long_s <= 0:
            raise ValueError(raw)
        return (short_s, long_s)
    except (ValueError, TypeError):
        log.warning("%s=%r is not 'short,long' seconds; using %s",
                    env_key, raw, default)
        short_s, long_s = (float(x) for x in default.split(","))
        return (short_s, long_s)


class SLOWatchdog:
    """Evaluates the declared SLIs against fleet rollups on a
    background cadence; /debug/fleet calls `evaluate` on demand.

    Scope: with GUBER_SLO_FLEET=1 each tick scrapes the whole fleet
    (the rollup-node posture — the bench and smoke run this); the
    default ticks evaluate this node's LOCAL slice only, so a large
    cluster is not all-pairs scraping itself every interval, and the
    fleet view stays an on-demand (or single-designated-node)
    fan-out."""

    _HISTORY_CAP = 4096
    _BREACH_CAP = 256

    # guberlint: guard _history, _breaches, _burn, _headroom by _lock

    def __init__(
        self,
        fleet,
        admission: Optional[AdmissionWatch],
        *,
        slis: Tuple[SLI, ...] = DEFAULT_SLIS,
        interval: float = 5.0,
        fleet_scope: bool = False,
        fast_windows: Tuple[float, float] = (300.0, 3600.0),
        slow_windows: Tuple[float, float] = (21600.0, 259200.0),
        fast_factor: float = 14.4,
        slow_factor: float = 1.0,
    ) -> None:
        self._fleet = fleet
        self._admission = admission
        self.slis = slis
        self.interval = interval
        self.fleet_scope = fleet_scope
        # (label, short_s, long_s, factor)
        self.pairs = (
            ("fast", fast_windows[0], fast_windows[1], fast_factor),
            ("slow", slow_windows[0], slow_windows[1], slow_factor),
        )
        self._lock = threading.Lock()
        self._history: deque = deque(maxlen=self._HISTORY_CAP)
        self._breaches: deque = deque(maxlen=self._BREACH_CAP)
        self._burn: Dict[Tuple[str, str], float] = {}
        self._headroom: Dict[Tuple[str, str], float] = {}
        self._paused = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if interval > 0:
            self._thread = threading.Thread(
                target=self._run, name="guber-slo-watchdog", daemon=True
            )
            self._thread.start()

    @classmethod
    def from_env(
        cls, fleet, admission: Optional[AdmissionWatch]
    ) -> "SLOWatchdog":
        from gubernator_tpu.config import parse_duration

        raw = os.environ.get("GUBER_SLO_INTERVAL", "").strip()
        interval = 5.0
        if raw:
            try:
                interval = parse_duration(raw)
            except ValueError:
                log.warning(
                    "GUBER_SLO_INTERVAL=%r is not a duration; using 5s",
                    raw,
                )
        fleet_scope = os.environ.get(
            "GUBER_SLO_FLEET", "0"
        ).strip().lower() not in _OFF_VALUES
        return cls(
            fleet,
            admission,
            interval=interval,
            fleet_scope=fleet_scope,
            fast_windows=_windows_env(
                "GUBER_SLO_FAST_WINDOWS", "300,3600"
            ),
            slow_windows=_windows_env(
                "GUBER_SLO_SLOW_WINDOWS", "21600,259200"
            ),
        )

    # -- the tick loop -------------------------------------------------

    def _run(self) -> None:
        from gubernator_tpu.utils.metrics import record_swallowed

        while not self._stop.wait(self.interval):
            if self._paused:
                continue
            try:
                rollup = self._fleet.collect(peers=self.fleet_scope)
                self.evaluate(rollup)
            except Exception:  # noqa: BLE001 — the watchdog must not die
                record_swallowed("slo.tick")
                log.exception("SLO watchdog tick failed")

    def pause(self) -> None:
        """Stop evaluating without tearing the thread down (the
        fleetobs bench's GUBER_OBS=0 arm)."""
        self._paused = True

    def resume(self) -> None:
        self._paused = False

    # -- evaluation ----------------------------------------------------

    @staticmethod
    def inputs_from_rollup(rollup: dict) -> dict:
        """Flatten one rollup into the counter/quantile/admitted
        inputs the SLI evaluations consume."""
        counters = dict(rollup.get("counters") or {})
        quantiles = {
            stage: q.get("p99_ms", 0.0)
            for stage, q in (rollup.get("quantiles") or {}).items()
        }
        return {
            "counters": counters,
            "p99_ms": quantiles,
            "admitted": dict(rollup.get("admitted") or {}),
            "regions": sorted((rollup.get("regions") or {}).keys()),
            "nodes": len(rollup.get("nodes") or ()) or 1,
        }

    def _sample_at_locked(self, now: float, age_s: float) -> Tuple[float, dict]:
        """The NEWEST history sample at least `age_s` old, else the
        oldest retained one (reported span may be shorter than the
        window — honest, and inevitable right after start)."""
        chosen = None
        for t, inputs in self._history:  # oldest → newest
            if now - t >= age_s:
                chosen = (t, inputs)
            else:
                break
        if chosen is None and self._history:
            chosen = self._history[0]
        return chosen if chosen is not None else (now, {})

    @staticmethod
    def _delta(now_in: dict, then_in: dict, key: str) -> float:
        return float((now_in.get("counters") or {}).get(key, 0.0)) - float(
            (then_in.get("counters") or {}).get(key, 0.0)
        )

    def _burn_for(
        self, sli: SLI, now_in: dict, then_in: dict
    ) -> Optional[float]:
        if sli.kind in ("ratio", "drops"):
            dbad = self._delta(now_in, then_in, sli.bad)
            dtotal = self._delta(now_in, then_in, sli.total)
            budget = max(1e-9, 1.0 - sli.objective)
            if dtotal <= 0:
                return 0.0 if dbad <= 0 else dbad / budget
            return (dbad / dtotal) / budget
        if sli.kind == "quantile":
            p99 = (now_in.get("p99_ms") or {}).get(sli.stage)
            if p99 is None or sli.threshold_ms <= 0:
                return None
            return p99 / sli.threshold_ms
        if sli.kind == "invariant":
            worst = 0.0
            for _key, ent in (now_in.get("admitted") or {}).items():
                bound = ent.get("bound", 0)
                if bound:
                    worst = max(worst, ent.get("admitted", 0) / bound)
            return worst
        return None

    def _derive_bounds(self, inputs: dict) -> None:
        """Attach the derived admission bound to each watched key:
        N_regions × limit on a federated cluster (each region answers
        locally from its own ring — RESILIENCE.md §12), N_nodes ×
        limit otherwise (the degraded-answering partition bound,
        §§5/10)."""
        regions = [r for r in inputs.get("regions") or []]
        n_regions = len(regions)
        n = n_regions if n_regions > 1 else max(1, inputs.get("nodes", 1))
        kind = "regions" if n_regions > 1 else "nodes"
        for _key, ent in (inputs.get("admitted") or {}).items():
            limit = int(ent.get("limit", 0))
            ent["bound"] = n * limit
            ent["bound_label"] = f"{n}_{kind}_x_{limit}"

    def evaluate(
        self, rollup: dict, record: bool = True, windowed: bool = True
    ) -> dict:
        """Evaluate every SLI against `rollup` (+ the retained
        history for windowed burns).  With `record`, the sample joins
        the history, the gauges update, and breaches log; without, it
        is a read-only view (the /debug/fleet on-demand path must not
        pollute the watchdog's periodic sample cadence).  With
        `windowed=False` the history-backed SLIs (ratio/drops) are
        SKIPPED: a caller whose rollup scope differs from the
        recorded samples' scope (a fleet rollup on a local-slice
        watchdog) must not difference across scopes — the "delta"
        would be other nodes' lifetime totals masquerading as window
        traffic, breach-level burn for errors that happened hours
        ago.  Quantile and invariant SLIs need no history and always
        evaluate."""
        from gubernator_tpu.utils import tracing
        from gubernator_tpu.utils.tracing import span

        now = time.monotonic()
        inputs = self.inputs_from_rollup(rollup)
        self._derive_bounds(inputs)
        burn: Dict[Tuple[str, str], float] = {}
        breaches: List[dict] = []
        with self._lock:
            for label, short_s, long_s, factor in self.pairs:
                t_short, in_short = self._sample_at_locked(now, short_s)
                t_long, in_long = self._sample_at_locked(now, long_s)
                for sli in self.slis:
                    if not windowed and sli.kind in ("ratio", "drops"):
                        continue
                    b_short = self._burn_for(sli, inputs, in_short)
                    if b_short is None:
                        continue
                    b_long = self._burn_for(sli, inputs, in_long)
                    burn[(sli.name, f"{label}_{short_s:g}s")] = round(
                        b_short, 4
                    )
                    burn[(sli.name, f"{label}_{long_s:g}s")] = round(
                        b_long if b_long is not None else 0.0, 4
                    )
                    if b_short > factor and (b_long or 0.0) > factor:
                        breaches.append(
                            {
                                "sli": sli.name,
                                "pair": label,
                                "burn_short": round(b_short, 4),
                                "burn_long": round(b_long or 0.0, 4),
                                "factor": factor,
                                "window_actual_s": (
                                    round(now - t_short, 3),
                                    round(now - t_long, 3),
                                ),
                            }
                        )
            headroom = {
                (key, ent.get("bound_label", "")): float(
                    ent.get("bound", 0) - ent.get("admitted", 0)
                )
                for key, ent in (inputs.get("admitted") or {}).items()
            }
            if record:
                self._history.append((now, inputs))
                self._burn = dict(burn)
                self._headroom = dict(headroom)
                for b in breaches:
                    self._breaches.append({"t": round(now, 3), **b})
        if record and breaches and tracing.active():
            with span("slo.evaluate", breaches=len(breaches)):
                for b in breaches:
                    tracing.add_event(
                        "slo_breach", sli=b["sli"], pair=b["pair"],
                        burn=b["burn_short"],
                    )
        return {
            "slis": {
                f"{name}@{window}": v
                for (name, window), v in sorted(burn.items())
            },
            "headroom": {
                key: {"bound": bound, "headroom": v}
                for (key, bound), v in sorted(headroom.items())
            },
            "breaches": breaches,
        }

    # -- read side -----------------------------------------------------

    def metrics_snapshot(self) -> dict:
        """The gauge feed for utils/metrics (gubernator_slo_burn_rate
        + gubernator_invariant_headroom)."""
        with self._lock:
            return {
                "burn": dict(self._burn),
                "headroom": dict(self._headroom),
            }

    def status(self) -> dict:
        """/debug/slo: declared SLIs, current burns, headroom, and
        the bounded breach log."""
        with self._lock:
            burn = dict(self._burn)
            headroom = dict(self._headroom)
            breach_log = list(self._breaches)
            samples = len(self._history)
        return {
            "enabled": True,
            "interval_s": self.interval,
            "fleet_scope": self.fleet_scope,
            "pairs": [
                {
                    "label": label, "short_s": s, "long_s": l,
                    "factor": f,
                }
                for label, s, l, f in self.pairs
            ],
            "slis": [
                {
                    "name": s.name, "metric": s.metric, "kind": s.kind,
                    "objective": s.objective,
                    "threshold_ms": s.threshold_ms or None,
                }
                for s in self.slis
            ],
            "burn": {
                f"{name}@{window}": v
                for (name, window), v in sorted(burn.items())
            },
            "headroom": {
                key: {"bound": bound, "headroom": v}
                for (key, bound), v in sorted(headroom.items())
            },
            "samples": samples,
            "breaches": breach_log,
        }

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

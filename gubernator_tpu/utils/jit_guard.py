"""JIT recompile guard: count XLA backend compiles at runtime.

A steady-state serving process must not recompile: every serve-path
program is precompiled by warmup (daemon._warmup, tests/test_warmup.py)
and batch shapes are pinned by the columnar layout (pad ladders).  A
recompile in the serve path is a multi-second p99 spike — the exact
failure mode guberlint's trace pass exists to keep out of the code.
This module closes the loop at RUNTIME: it counts actual backend
compiles via jax's monitoring events and exports the count as the
``gubernator_jit_recompiles`` metric, so a soak (tests/
test_recompile_guard.py) or a production scrape can assert the count
stays flat after warmup.

The hook is jax's semi-private ``jax._src.monitoring`` listener API
(the '/jax/core/compile/backend_compile_duration' duration event fires
once per backend compile, never on cache hits — pinned by a test).
If the API moves, install() degrades to unavailable and the metric
reports 0; the guard test skips.
"""

from __future__ import annotations

import threading

_lock = threading.Lock()
_count = 0  # guberlint: guarded-by _lock
_installed = False
_available = False

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def _on_event_duration(event: str, duration: float, **kwargs) -> None:
    global _count
    if event == _COMPILE_EVENT:
        with _lock:
            _count += 1


def install() -> bool:
    """Register the compile-event listener (idempotent).  Returns
    whether the counter is live."""
    global _installed, _available
    with _lock:
        if _installed:
            return _available
        _installed = True
    try:
        from jax._src import monitoring
    except Exception:  # noqa: BLE001 — private API moved; degrade
        from gubernator_tpu.utils.metrics import record_swallowed

        record_swallowed("jit_guard.install")
        return False
    monitoring.register_event_duration_secs_listener(_on_event_duration)
    with _lock:
        _available = True
    return True


def available() -> bool:
    with _lock:
        return _available


def compile_count() -> int:
    """Backend compiles observed since install() (0 if unavailable)."""
    with _lock:
        return _count

"""Cross-cutting utilities: metrics, logging, tracing."""

"""gRPC server request metrics.

reference: grpc_stats.go:41-131 — a stats.Handler counting requests and
observing durations per method, exported as
`gubernator_grpc_request_counts` / `gubernator_grpc_request_duration`.
Implemented as a grpc.ServerInterceptor feeding a prometheus Collector.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, List

import grpc
from prometheus_client.core import CounterMetricFamily, SummaryMetricFamily
from prometheus_client.registry import Collector


class GrpcStats(Collector, grpc.ServerInterceptor):
    """Counts + duration sums per gRPC method, with a failed counter."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        self._failed: Dict[str, int] = {}
        self._dur_sum: Dict[str, float] = {}

    # -- grpc.ServerInterceptor ---------------------------------------

    def intercept_service(self, continuation, handler_call_details):
        method = handler_call_details.method
        handler = continuation(handler_call_details)
        if handler is None or not handler.unary_unary:
            return handler
        inner = handler.unary_unary

        def wrapper(request, context):
            start = time.perf_counter()
            ok = True
            try:
                return inner(request, context)
            except Exception:
                ok = False
                raise
            finally:
                dt = time.perf_counter() - start
                with self._lock:
                    self._counts[method] = self._counts.get(method, 0) + 1
                    self._dur_sum[method] = self._dur_sum.get(method, 0.0) + dt
                    if not ok or context.code() not in (None, grpc.StatusCode.OK):
                        self._failed[method] = self._failed.get(method, 0) + 1

        return grpc.unary_unary_rpc_method_handler(
            wrapper,
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )

    # -- prometheus Collector -----------------------------------------

    def collect(self) -> Iterable:
        with self._lock:
            counts = dict(self._counts)
            failed = dict(self._failed)
            dur = dict(self._dur_sum)
        c = CounterMetricFamily(
            "gubernator_grpc_request_counts",
            "The count of gRPC requests.",
            labels=["method", "failed"],
        )
        for m, n in counts.items():
            c.add_metric([m, "0"], n - failed.get(m, 0))
        for m, n in failed.items():
            c.add_metric([m, "1"], n)
        yield c
        s = SummaryMetricFamily(
            "gubernator_grpc_request_duration",
            "Duration of gRPC requests in seconds.",
            labels=["method"],
        )
        for m, total in dur.items():
            s.add_metric([m], counts.get(m, 0), total)
        yield s

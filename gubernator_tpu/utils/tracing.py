"""Tracing: spans woven through the hot path, no-op when disabled.

reference: the reference weaves holster tracing through every function
(SURVEY.md §5.1 — e.g. gubernator.go:198-202, algorithms.go:32-44) and
exports via OTEL_* env configuration (cmd/gubernator/main.go:57-69).

Three backends, selected by `init_tracing()`:

- disabled (default): `span()` is one global check — the decision hot
  path never pays for tracing that is off.
- OTel (when OTEL_EXPORTER_OTLP_ENDPOINT / OTEL_TRACES_EXPORTER is set
  and the opentelemetry SDK is importable): real OTLP export.
- in-memory recorder (`InMemoryTracer`, or
  GUBER_TRACING=memory): dependency-free span capture with parent
  links, attributes, and events — the test oracle
  (tests/test_tracing.py) and a flight-recorder for debugging.

Span sites (matching the reference's observability depth):
service entry points, engine batches/rounds/sweeps, peer batch
flushes, GLOBAL hit/broadcast windows — each with batch-size/round
attributes.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Iterator, List, Optional

log = logging.getLogger("gubernator_tpu.tracing")

_tracer = None
_initialized = False


@dataclass
class RecordedSpan:
    """One finished span in the in-memory recorder."""

    name: str
    attributes: dict = field(default_factory=dict)
    events: List[tuple] = field(default_factory=list)  # (name, attrs)
    parent: Optional[str] = None  # parent span name (None = root)
    start_ns: int = 0
    end_ns: int = 0

    def set_attribute(self, key: str, value) -> None:
        self.attributes[key] = value

    def add_event(self, name: str, **attrs) -> None:
        self.events.append((name, attrs))


class InMemoryTracer:
    """Thread-safe span recorder with a per-thread active-span stack
    (parent links come from nesting, like OTel's context)."""

    def __init__(self) -> None:
        self.finished: List[RecordedSpan] = []
        self._lock = threading.Lock()
        self._local = threading.local()

    def _stack(self) -> List[RecordedSpan]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    @contextlib.contextmanager
    def start_span(self, name: str, **attributes) -> Iterator[RecordedSpan]:
        stack = self._stack()
        s = RecordedSpan(
            name=name,
            attributes=dict(attributes),
            parent=stack[-1].name if stack else None,
            start_ns=time.monotonic_ns(),
        )
        stack.append(s)
        try:
            yield s
        finally:
            stack.pop()
            s.end_ns = time.monotonic_ns()
            with self._lock:
                self.finished.append(s)

    # Test helpers -----------------------------------------------------

    def spans(self, name: Optional[str] = None) -> List[RecordedSpan]:
        with self._lock:
            out = list(self.finished)
        return [s for s in out if name is None or s.name == name]

    def clear(self) -> None:
        with self._lock:
            self.finished.clear()


class _OtelTracer:
    """Adapter presenting the start_span interface over an OTel tracer."""

    def __init__(self, tracer) -> None:
        self._tracer = tracer

    @contextlib.contextmanager
    def start_span(self, name: str, **attributes) -> Iterator[object]:
        with self._tracer.start_as_current_span(name) as s:
            for k, v in attributes.items():
                s.set_attribute(k, v)
            yield s


def init_tracing(service_name: str = "gubernator_tpu") -> bool:
    """Configure the global tracer from OTEL_*/GUBER_TRACING env;
    returns whether tracing is active.
    reference: cmd/gubernator/main.go:57-69."""
    global _tracer, _initialized
    if _initialized:
        return _tracer is not None
    _initialized = True
    if os.environ.get("GUBER_TRACING", "") == "memory":
        _tracer = InMemoryTracer()
        log.info("in-memory tracing active")
        return True
    want = os.environ.get("OTEL_EXPORTER_OTLP_ENDPOINT") or os.environ.get(
        "OTEL_TRACES_EXPORTER"
    )
    if not want:
        return False
    try:
        from opentelemetry import trace
        from opentelemetry.sdk.resources import Resource
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.export import BatchSpanProcessor
        from opentelemetry.exporter.otlp.proto.grpc.trace_exporter import (
            OTLPSpanExporter,
        )
    except ImportError as e:
        log.warning("tracing requested but exporter unavailable: %s", e)
        return False
    provider = TracerProvider(
        resource=Resource.create({"service.name": service_name})
    )
    provider.add_span_processor(BatchSpanProcessor(OTLPSpanExporter()))
    trace.set_tracer_provider(provider)
    _tracer = _OtelTracer(trace.get_tracer("gubernator_tpu"))
    log.info("OTel tracing active (service=%s)", service_name)
    return True


def set_tracer(tracer) -> None:
    """Install a tracer directly (tests: an InMemoryTracer); None
    disables tracing."""
    global _tracer, _initialized
    _tracer = tracer
    _initialized = True


def current_tracer():
    return _tracer


@contextlib.contextmanager
def span(name: str, **attributes) -> Iterator[Optional[object]]:
    """Start a span when tracing is active, else a no-op context."""
    if _tracer is None:
        yield None
        return
    with _tracer.start_span(name, **attributes) as s:
        yield s


def shutdown_tracing() -> None:
    global _tracer, _initialized
    if isinstance(_tracer, _OtelTracer):
        try:
            from opentelemetry import trace

            trace.get_tracer_provider().shutdown()  # type: ignore[attr-defined]
        except Exception:  # noqa: BLE001
            log.exception("tracing shutdown failed")
    _tracer = None
    _initialized = False

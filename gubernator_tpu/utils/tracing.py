"""Tracing: spans woven through the hot path, no-op when disabled.

reference: the reference weaves holster tracing through every function
(SURVEY.md §5.1 — e.g. gubernator.go:198-202, algorithms.go:32-44) and
exports via OTEL_* env configuration (cmd/gubernator/main.go:57-69).

Three backends, selected by `init_tracing()`:

- disabled (default): `span()` is one global check — the decision hot
  path never pays for tracing that is off.
- OTel (when OTEL_EXPORTER_OTLP_ENDPOINT / OTEL_TRACES_EXPORTER is set
  and the opentelemetry SDK is importable): real OTLP export.
- in-memory recorder (`InMemoryTracer`, or
  GUBER_TRACING=memory): dependency-free span capture with parent
  links, attributes, and events — the test oracle
  (tests/test_tracing.py) and the tail flight recorder's feed
  (utils/flight_recorder.py).

Cross-tier context (OBSERVABILITY.md):

Every span carries a W3C-traceparent-shaped context — (trace_id,
span_id, sampled) — and spans can be parented three ways:

- nesting (same thread, like OTel's implicit context);
- ``parent_ctx=`` — an explicit LOCAL parent, for work handed to
  another thread (forward pool, flush workers, fan-out pools);
- ``remote_parent=`` — a context extracted from an incoming RPC's
  ``traceparent`` metadata: the span joins the caller's trace across
  the process boundary (``remote=True`` on the recorded span).

`grpc_metadata()` injects the current context into outgoing gRPC
metadata; `remote_parent_from_metadata()` extracts it server-side.
Both are None/no-op while tracing is disabled, so the wire paths pay
one global check and nothing else.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Iterator, List, Optional, Tuple

log = logging.getLogger("gubernator_tpu.tracing")

_tracer = None
_initialized = False


@dataclass(frozen=True)
class TraceContext:
    """W3C-traceparent-shaped span identity: 32-hex trace_id, 16-hex
    span_id, sampled flag — what travels on the wire."""

    trace_id: str
    span_id: str
    sampled: bool = True


def _new_trace_id() -> str:
    return os.urandom(16).hex()


def _new_span_id() -> str:
    return os.urandom(8).hex()


def format_traceparent(ctx: TraceContext) -> str:
    """``00-<trace_id>-<span_id>-<flags>`` (W3C Trace Context)."""
    return f"00-{ctx.trace_id}-{ctx.span_id}-{'01' if ctx.sampled else '00'}"


def parse_traceparent(value: str) -> Optional[TraceContext]:
    """Inverse of format_traceparent; None on anything malformed (a
    bad header must never fail the RPC carrying it)."""
    try:
        parts = value.strip().split("-")
        if len(parts) != 4:
            return None
        version, trace_id, span_id, flags = parts
        if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16:
            return None
        int(trace_id, 16)
        int(span_id, 16)
        return TraceContext(
            trace_id=trace_id,
            span_id=span_id,
            sampled=bool(int(flags, 16) & 1),
        )
    except (ValueError, AttributeError):
        return None


@dataclass
class RecordedSpan:
    """One finished span in the in-memory recorder."""

    name: str
    attributes: dict = field(default_factory=dict)
    events: List[tuple] = field(default_factory=list)  # (name, attrs)
    parent: Optional[str] = None  # parent span name (None = root)
    start_ns: int = 0
    end_ns: int = 0
    # Cross-tier identity (TraceContext-shaped).
    trace_id: str = ""
    span_id: str = ""
    parent_span_id: Optional[str] = None
    # True when the parent lives in another process (the context came
    # in via RPC metadata).
    remote: bool = False

    @property
    def context(self) -> TraceContext:
        return TraceContext(trace_id=self.trace_id, span_id=self.span_id)

    def set_attribute(self, key: str, value) -> None:
        self.attributes[key] = value

    def add_event(self, name: str, **attrs) -> None:
        self.events.append((name, attrs))


class InMemoryTracer:
    """Thread-safe span recorder with a per-thread active-span stack
    (parent links come from nesting, like OTel's context) plus
    explicit local/remote parenting for cross-thread and cross-process
    stitching.  Bounded: the oldest finished spans are shed past
    `max_spans` (a long-lived daemon must not grow without bound)."""

    def __init__(self, max_spans: int = 100_000) -> None:
        from collections import deque

        self.finished = deque(maxlen=max(1, max_spans))
        self._lock = threading.Lock()
        self._local = threading.local()
        # Live trace ids by refcount: a metric exemplar links a
        # histogram bucket to a trace_id (utils/metrics.DurationStat),
        # and an exemplar pointing at a trace the deque has fully
        # evicted is a dead link — has_trace() answers membership in
        # O(1) so the exporter can prune instead of publishing it.
        # Every OPEN span holds one ref (acquired at start, released
        # at finish) and every RETAINED finished span holds one: an
        # exemplar is captured while its span is still open, so a
        # scrape racing the span's finish must still see the trace as
        # live — pruning there would drop the link moments before the
        # trace lands in the deque.
        self._trace_refs: dict = {}  # guberlint: guarded-by _lock
        # Root-finish hook (utils/flight_recorder.py): called with the
        # outermost span of a thread's stack right after it finishes.
        self.on_root_finish = None

    def _acquire_ref_locked(self, trace_id: str) -> None:
        self._trace_refs[trace_id] = (
            self._trace_refs.get(trace_id, 0) + 1
        )

    def _release_ref_locked(self, trace_id: str) -> None:
        n = self._trace_refs.get(trace_id, 0) - 1
        if n <= 0:
            self._trace_refs.pop(trace_id, None)
        else:
            self._trace_refs[trace_id] = n

    def _append_finished_locked(self, s: "RecordedSpan") -> None:
        """Append under self._lock, accounting trace-id refcounts
        through the deque's eviction (popleft explicitly — an implicit
        maxlen eviction would be invisible to the refcount table)."""
        if len(self.finished) == self.finished.maxlen:
            old = self.finished.popleft()
            self._release_ref_locked(old.trace_id)
        self.finished.append(s)
        self._acquire_ref_locked(s.trace_id)

    def has_trace(self, trace_id: str) -> bool:
        """Whether any open or retained finished span of this trace
        is still live (exemplar liveness — see _trace_refs above)."""
        with self._lock:
            return trace_id in self._trace_refs

    def _stack(self) -> List[RecordedSpan]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def current_context(self) -> Optional[TraceContext]:
        st = getattr(self._local, "stack", None)
        return st[-1].context if st else None

    @contextlib.contextmanager
    def start_span(
        self,
        name: str,
        remote_parent: Optional[TraceContext] = None,
        parent_ctx: Optional[TraceContext] = None,
        **attributes,
    ) -> Iterator[RecordedSpan]:
        stack = self._stack()
        if remote_parent is not None:
            trace_id = remote_parent.trace_id
            parent_span_id: Optional[str] = remote_parent.span_id
            remote = True
            parent_name = None
        elif parent_ctx is not None:
            trace_id = parent_ctx.trace_id
            parent_span_id = parent_ctx.span_id
            remote = False
            parent_name = None
        elif stack:
            trace_id = stack[-1].trace_id
            parent_span_id = stack[-1].span_id
            remote = False
            parent_name = stack[-1].name
        else:
            trace_id = _new_trace_id()
            parent_span_id = None
            remote = False
            parent_name = None
        s = RecordedSpan(
            name=name,
            attributes=dict(attributes),
            parent=parent_name,
            start_ns=time.monotonic_ns(),
            trace_id=trace_id,
            span_id=_new_span_id(),
            parent_span_id=parent_span_id,
            remote=remote,
        )
        stack.append(s)
        # The open span holds a trace ref so an exemplar captured
        # inside it survives a scrape racing the span's finish — but
        # only the thread's STACK ROOT (or a span re-anchored to a
        # different trace) needs one: children share the root's
        # trace_id, so its ref already keeps has_trace() true for
        # exemplars captured in descendants, and skipping them avoids
        # a global-lock acquisition per child span start.
        own_ref = len(stack) == 1 or s.trace_id != stack[0].trace_id
        if own_ref:
            with self._lock:
                self._acquire_ref_locked(s.trace_id)
        try:
            yield s
        finally:
            stack.pop()
            s.end_ns = time.monotonic_ns()
            with self._lock:
                # Retained-ref first, open-ref release second: the
                # trace must never read dead between the two.
                self._append_finished_locked(s)
                if own_ref:
                    self._release_ref_locked(s.trace_id)
            # Fire for this PROCESS's trace roots: spans with no
            # parent anywhere, plus remote-parented handler spans —
            # on an owner node every root is rpc.* with a remote
            # parent, and excluding those would leave its flight
            # recorder permanently empty.  Locally re-anchored pool
            # spans (parent_ctx: global.owner_rpc, forward.group,
            # broadcast pushes) stay excluded — they belong to a
            # local decision's trace, and feeding them would inflate
            # the rolling-p99 threshold with RPC-timeout-scale
            # durations and duplicate their trace's trees.
            if (
                not stack
                and (s.parent_span_id is None or s.remote)
                and self.on_root_finish is not None
            ):
                try:
                    self.on_root_finish(s)
                except Exception:  # noqa: BLE001 — recording must not
                    # fail the traced operation.
                    log.exception("root-finish hook failed")

    def record_span(
        self,
        name: str,
        start_ns: int,
        end_ns: int,
        parent_ctx: Optional[TraceContext] = None,
        **attributes,
    ) -> RecordedSpan:
        """Record an already-finished span from externally measured
        timestamps (monotonic ns) — the native event collector's span
        stubs (utils/native_events.py)."""
        if parent_ctx is not None:
            trace_id, parent_span_id = parent_ctx.trace_id, parent_ctx.span_id
        else:
            trace_id, parent_span_id = _new_trace_id(), None
        s = RecordedSpan(
            name=name,
            attributes=dict(attributes),
            start_ns=start_ns,
            end_ns=end_ns,
            trace_id=trace_id,
            span_id=_new_span_id(),
            parent_span_id=parent_span_id,
        )
        with self._lock:
            self._append_finished_locked(s)
        return s

    def add_event(self, name: str, **attrs) -> None:
        """Attach an event to this thread's current span (no-op when
        none is open)."""
        st = getattr(self._local, "stack", None)
        if st:
            st[-1].add_event(name, **attrs)

    # Test helpers -----------------------------------------------------

    def spans(self, name: Optional[str] = None) -> List[RecordedSpan]:
        with self._lock:
            out = list(self.finished)
        return [s for s in out if name is None or s.name == name]

    def trace(
        self, trace_id: str, max_scan: Optional[int] = None
    ) -> List[RecordedSpan]:
        """Finished spans of one trace.  `max_scan` bounds the walk to
        the NEWEST that many spans (the flight recorder captures at
        root finish, when the trace's spans are by construction the
        most recent — an unbounded filter of a 100k-span deque under
        this lock would stall every concurrent span finish)."""
        import itertools

        with self._lock:
            if max_scan is None or len(self.finished) <= max_scan:
                return [s for s in self.finished if s.trace_id == trace_id]
            # islice actually STOPS the walk at max_scan (a filtering
            # comprehension over the whole deque would still iterate
            # every element under this lock).
            out = [
                s
                for s in itertools.islice(
                    reversed(self.finished), max_scan
                )
                if s.trace_id == trace_id
            ]
            out.reverse()
            return out

    def clear(self) -> None:
        with self._lock:
            self.finished.clear()
            self._trace_refs.clear()


class _OtelTracer:
    """Adapter presenting the start_span interface over an OTel tracer
    (remote parents become OTel remote SpanContexts)."""

    def __init__(self, tracer) -> None:
        self._tracer = tracer

    @contextlib.contextmanager
    def start_span(
        self,
        name: str,
        remote_parent: Optional[TraceContext] = None,
        parent_ctx: Optional[TraceContext] = None,
        **attributes,
    ) -> Iterator[object]:
        from opentelemetry import context as otel_context
        from opentelemetry import trace as otel_trace

        ctx = None
        parent = remote_parent or parent_ctx
        if parent is not None:
            span_ctx = otel_trace.SpanContext(
                trace_id=int(parent.trace_id, 16),
                span_id=int(parent.span_id, 16),
                is_remote=remote_parent is not None,
                trace_flags=otel_trace.TraceFlags(
                    otel_trace.TraceFlags.SAMPLED if parent.sampled else 0
                ),
            )
            ctx = otel_trace.set_span_in_context(
                otel_trace.NonRecordingSpan(span_ctx),
                otel_context.get_current(),
            )
        with self._tracer.start_as_current_span(name, context=ctx) as s:
            for k, v in attributes.items():
                s.set_attribute(k, v)
            yield s

    def current_context(self) -> Optional[TraceContext]:
        from opentelemetry import trace as otel_trace

        sc = otel_trace.get_current_span().get_span_context()
        if not sc.is_valid:
            return None
        return TraceContext(
            trace_id=format(sc.trace_id, "032x"),
            span_id=format(sc.span_id, "016x"),
            sampled=bool(sc.trace_flags & 1),
        )

    def add_event(self, name: str, **attrs) -> None:
        from opentelemetry import trace as otel_trace

        s = otel_trace.get_current_span()
        if s.get_span_context().is_valid:
            s.add_event(name, attributes=attrs)


def init_tracing(service_name: str = "gubernator_tpu") -> bool:
    """Configure the global tracer from OTEL_*/GUBER_TRACING env;
    returns whether tracing is active.
    reference: cmd/gubernator/main.go:57-69."""
    global _tracer, _initialized
    if _initialized:
        return _tracer is not None
    _initialized = True
    if os.environ.get("GUBER_TRACING", "") == "memory":
        _tracer = InMemoryTracer()
        log.info("in-memory tracing active")
        return True
    want = os.environ.get("OTEL_EXPORTER_OTLP_ENDPOINT") or os.environ.get(
        "OTEL_TRACES_EXPORTER"
    )
    if not want:
        return False
    try:
        from opentelemetry import trace
        from opentelemetry.sdk.resources import Resource
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.export import BatchSpanProcessor
        from opentelemetry.exporter.otlp.proto.grpc.trace_exporter import (
            OTLPSpanExporter,
        )
    except ImportError as e:
        log.warning("tracing requested but exporter unavailable: %s", e)
        return False
    provider = TracerProvider(
        resource=Resource.create({"service.name": service_name})
    )
    provider.add_span_processor(BatchSpanProcessor(OTLPSpanExporter()))
    trace.set_tracer_provider(provider)
    _tracer = _OtelTracer(trace.get_tracer("gubernator_tpu"))
    log.info("OTel tracing active (service=%s)", service_name)
    return True


def set_tracer(tracer) -> None:
    """Install a tracer directly (tests: an InMemoryTracer); None
    disables tracing."""
    global _tracer, _initialized
    _tracer = tracer
    _initialized = True


def current_tracer():
    return _tracer


def active() -> bool:
    """One global check — what the disabled hot path pays."""
    return _tracer is not None


def current_context() -> Optional[TraceContext]:
    """The active span's context on THIS thread (None when tracing is
    off or no span is open) — capture it before handing work to
    another thread, then re-anchor with span(..., parent_ctx=ctx)."""
    if _tracer is None:
        return None
    try:
        return _tracer.current_context()
    except Exception:  # noqa: BLE001 — a custom tracer without contexts
        return None


def current_trace_id() -> str:
    """Hex trace id of the active span ('' when none) — what the
    structured log lines carry (utils/logging_setup.py)."""
    ctx = current_context()
    return ctx.trace_id if ctx is not None else ""


def grpc_metadata() -> Optional[Tuple[Tuple[str, str], ...]]:
    """Outgoing gRPC metadata carrying the current trace context as a
    W3C ``traceparent`` pair, or None when tracing is off / no span is
    active (grpc accepts metadata=None)."""
    ctx = current_context()
    if ctx is None:
        return None
    return (("traceparent", format_traceparent(ctx)),)


def remote_parent_from_metadata(metadata) -> Optional[TraceContext]:
    """Extract a ``traceparent`` context from incoming RPC metadata
    (server side).  None when tracing is off or no valid header is
    present."""
    if _tracer is None or metadata is None:
        return None
    for k, v in metadata:
        if k == "traceparent":
            return parse_traceparent(v)
    return None


@contextlib.contextmanager
def span(
    name: str,
    remote_parent: Optional[TraceContext] = None,
    parent_ctx: Optional[TraceContext] = None,
    **attributes,
) -> Iterator[Optional[object]]:
    """Start a span when tracing is active, else a no-op context.
    `remote_parent` joins an RPC caller's trace; `parent_ctx` anchors
    to a local span on another thread."""
    if _tracer is None:
        yield None
        return
    with _tracer.start_span(
        name, remote_parent=remote_parent, parent_ctx=parent_ctx,
        **attributes,
    ) as s:
        yield s


def add_event(name: str, **attrs) -> None:
    """Attach an event to the current span (no-op when tracing is off
    or no span is open) — degraded answers and circuit-open refusals
    mark themselves this way so the flight recorder can show WHY a
    tail request took the path it took.  Delegates to the backend
    (both the in-memory recorder and the OTel adapter implement
    add_event), so the events reach real exporters, not just tests."""
    if _tracer is None:
        return
    hook = getattr(_tracer, "add_event", None)
    if hook is not None:
        hook(name, **attrs)


def shutdown_tracing() -> None:
    global _tracer, _initialized
    if isinstance(_tracer, _OtelTracer):
        try:
            from opentelemetry import trace

            trace.get_tracer_provider().shutdown()  # type: ignore[attr-defined]
        except Exception:  # noqa: BLE001
            log.exception("tracing shutdown failed")
    _tracer = None
    _initialized = False

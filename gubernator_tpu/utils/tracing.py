"""OpenTelemetry tracing (optional, env-driven).

reference: the reference weaves holster tracing through every function
(SURVEY.md §5.1 — e.g. gubernator.go:198-202, algorithms.go:32-36) and
exports via OTEL_* env configuration (cmd/gubernator/main.go:57-69).

Here tracing is opt-in: `init_tracing()` configures a tracer provider
when OTEL_EXPORTER_OTLP_ENDPOINT or OTEL_TRACES_EXPORTER is set (and
the exporter package is importable); otherwise every span helper is a
cheap no-op — the decision hot path never pays for disabled tracing.
"""

from __future__ import annotations

import contextlib
import logging
import os
from typing import Iterator, Optional

log = logging.getLogger("gubernator_tpu.tracing")

_tracer = None
_initialized = False


def init_tracing(service_name: str = "gubernator_tpu") -> bool:
    """Configure the global tracer from OTEL_* env; returns whether
    tracing is active.  reference: cmd/gubernator/main.go:57-69."""
    global _tracer, _initialized
    if _initialized:
        return _tracer is not None
    _initialized = True
    want = os.environ.get("OTEL_EXPORTER_OTLP_ENDPOINT") or os.environ.get(
        "OTEL_TRACES_EXPORTER"
    )
    if not want:
        return False
    try:
        from opentelemetry import trace
        from opentelemetry.sdk.resources import Resource
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.export import BatchSpanProcessor
        from opentelemetry.exporter.otlp.proto.grpc.trace_exporter import (
            OTLPSpanExporter,
        )
    except ImportError as e:
        log.warning("tracing requested but exporter unavailable: %s", e)
        return False
    provider = TracerProvider(
        resource=Resource.create({"service.name": service_name})
    )
    provider.add_span_processor(BatchSpanProcessor(OTLPSpanExporter()))
    trace.set_tracer_provider(provider)
    _tracer = trace.get_tracer("gubernator_tpu")
    log.info("OTel tracing active (service=%s)", service_name)
    return True


@contextlib.contextmanager
def span(name: str, **attributes) -> Iterator[Optional[object]]:
    """Start a span when tracing is active, else a no-op context."""
    if _tracer is None:
        yield None
        return
    with _tracer.start_as_current_span(name) as s:
        for k, v in attributes.items():
            s.set_attribute(k, v)
        yield s


def shutdown_tracing() -> None:
    global _tracer, _initialized
    if _tracer is not None:
        try:
            from opentelemetry import trace

            trace.get_tracer_provider().shutdown()  # type: ignore[attr-defined]
        except Exception:  # noqa: BLE001
            log.exception("tracing shutdown failed")
    _tracer = None
    _initialized = False

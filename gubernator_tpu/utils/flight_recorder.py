"""Tail flight recorder: keep full span trees ONLY for tail decisions.

Always-on tracing of every decision would drown the interesting 1% in
the boring 99% (and the InMemoryTracer's bounded deque would shed the
tail spans first under load).  The recorder hooks the tracer's
root-finish callback and retains the COMPLETE span tree of any trace
whose root exceeded an adaptive threshold:

    threshold = max(GUBER_TRACE_TAIL_MIN_MS,
                    rolling_p99(root durations) × GUBER_TRACE_TAIL_FACTOR)

so "tail" self-calibrates to the workload — under a healthy herd the
p99 is ~1ms and a 5ms decision records; under a degraded cluster the
p99 grows and only the genuinely anomalous trees are kept.  Retention
is a bounded ring of GUBER_TRACE_TAIL_CAP trees, dumpable live via the
gateway's ``/debug/trace`` endpoint (OBSERVABILITY.md documents the
shape).

Scope note: a tree is captured when its ROOT finishes; async children
that outlive the root (a broadcast window flushing later) appear in
the tree only if they finished first.  That is the right trade — the
recorder answers "where did THIS request's milliseconds go", and the
async tail has its own spans under the same trace id in the tracer.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from gubernator_tpu.utils.metrics import DurationStat
from gubernator_tpu.utils.tracing import InMemoryTracer, RecordedSpan


def _span_dict(s: RecordedSpan) -> dict:
    return {
        "name": s.name,
        "span_id": s.span_id,
        "parent_span_id": s.parent_span_id,
        "remote": s.remote,
        "start_ns": s.start_ns,
        "duration_ms": round((s.end_ns - s.start_ns) / 1e6, 3),
        "attributes": dict(s.attributes),
        "events": [
            {"name": name, **attrs} for name, attrs in s.events
        ],
    }


class FlightRecorder:
    """Bounded ring of tail span trees over an InMemoryTracer."""

    def __init__(
        self,
        tracer: InMemoryTracer,
        *,
        factor: float = 4.0,
        min_ms: float = 5.0,
        cap: int = 64,
    ) -> None:
        self._tracer = tracer
        self.factor = factor
        self.min_s = min_ms / 1e3
        self._lock = threading.Lock()
        # guberlint: guard _traces, recorded, considered by _lock
        self._traces = deque(maxlen=max(1, cap))
        self.recorded = 0
        self.considered = 0
        # Rolling root-duration distribution: the adaptive threshold's
        # p99 source (DurationStat's log2-bucket histogram).
        self.root_durations = DurationStat()
        tracer.on_root_finish = self._root_finished

    @classmethod
    def from_env(cls, tracer: InMemoryTracer) -> "FlightRecorder":
        import os

        def _f(name: str, default: float) -> float:
            try:
                return float(os.environ.get(name, "") or default)
            except ValueError:
                return default

        return cls(
            tracer,
            factor=_f("GUBER_TRACE_TAIL_FACTOR", 4.0),
            min_ms=_f("GUBER_TRACE_TAIL_MIN_MS", 5.0),
            cap=int(_f("GUBER_TRACE_TAIL_CAP", 64)),
        )

    # Rolling-p99 warmup: with an empty histogram the adaptive term is
    # zero and the threshold is just the min_ms floor, so a workload
    # whose NORMAL latency exceeds the floor would record every early
    # decision (each capture costs a tracer scan + tree serialization
    # on the request thread).  Until this many roots have calibrated
    # the p99, the adaptive term uses the rolling MAX instead — the
    # first anomalous-looking root still records, but the steady
    # stream right behind it does not.
    WARMUP_ROOTS = 32
    # Capture scans only the newest this-many spans: the trace's spans
    # are the most recent by construction (children finish before the
    # root), and an unbounded filter of the tracer's 100k-span deque
    # under its lock would stall concurrent span finishes.
    MAX_TRACE_SCAN = 4096

    def threshold_s(self) -> float:
        ref = (
            self.root_durations.p99()
            if self.root_durations.count >= self.WARMUP_ROOTS
            else self.root_durations.max
        )
        return max(self.min_s, ref * self.factor)

    def _root_finished(self, root: RecordedSpan) -> None:
        dur_s = (root.end_ns - root.start_ns) / 1e9
        thresh = self.threshold_s()
        self.root_durations.observe(dur_s)
        with self._lock:
            self.considered += 1
        if dur_s < thresh:
            return
        spans = self._tracer.trace(
            root.trace_id, max_scan=self.MAX_TRACE_SCAN
        )
        entry = {
            "trace_id": root.trace_id,
            "root": root.name,
            "captured_at": time.time(),
            "duration_ms": round(dur_s * 1e3, 3),
            "threshold_ms": round(thresh * 1e3, 3),
            "spans": [_span_dict(s) for s in spans],
        }
        with self._lock:
            self.recorded += 1
            self._traces.append(entry)

    def dump(self, limit: Optional[int] = None) -> dict:
        with self._lock:
            traces = list(self._traces)
            recorded, considered = self.recorded, self.considered
        if limit is not None:
            traces = traces[-limit:]
        return {
            "threshold_ms": round(self.threshold_s() * 1e3, 3),
            "factor": self.factor,
            "min_ms": self.min_s * 1e3,
            "considered": considered,
            "recorded": recorded,
            "root_p50_ms": round(self.root_durations.p50() * 1e3, 3),
            "root_p99_ms": round(self.root_durations.p99() * 1e3, 3),
            "traces": traces,
        }

    def close(self) -> None:
        # Bound-method identity: compare the receiver, not the method
        # object (each attribute access builds a fresh bound method).
        hook = self._tracer.on_root_finish
        if getattr(hook, "__self__", None) is self:
            self._tracer.on_root_finish = None

"""Native event collector: drain the C front's event ring into
histograms, metrics, and span stubs.

The C h2 front (core/native/h2_server.cpp) publishes per-stage latency
events into a lock-free ring (core/native/event_ring.cpp) from its
connection/dispatch threads — zero mutex, zero Py* calls on the serve
side.  This module's ONE background thread drains the ring every
``GUBER_NATIVE_EVENTS_INTERVAL`` seconds and turns the records into:

- per-stage DurationStat histograms (count/sum/max + streaming
  p50/p99), exported as ``gubernator_native_stage_duration`` and the
  ``native_*`` rows of ``gubernator_stage_quantile_seconds``;
- event counts per stage (``gubernator_native_events{stage}``) and the
  ring's overflow drops (``gubernator_native_ring_dropped``);
- when in-memory tracing is active, bounded NATIVE SPAN STUBS
  (``native.decide``) reconstructed from the records' monotonic
  timestamps — the first spans ever emitted for decisions that never
  touch Python.  The fast front skips header decoding entirely (the
  port is the route), so there is no traceparent to join: stubs are
  roots grouped per drain, attributed by stage/items, and the flight
  recorder's window-path traces carry the cross-process stitching
  (OBSERVABILITY.md documents the split).

Stage ids mirror h2_server.cpp's kEv* constants.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict

from gubernator_tpu.utils.metrics import DurationStat, record_swallowed

log = logging.getLogger("gubernator_tpu.native_events")

# kind -> stage name (h2_server.cpp kEvNativeServe/kEvWindowWait/
# kEvWindowServe; columnar_feeder.cpp kEvFeederPack/kEvFeederRingWait/
# kEvFeederServe).
STAGES = {
    1: "native_serve",
    2: "window_wait",
    3: "window_serve",
    # Columnar feeder plane: per-RPC wire→columns pack (conn thread),
    # pack → window-callback queue wait (the feeder's analog of
    # window_wait — the stage the §23 p99 tail lived in), and the
    # per-window columnar serve wall.
    4: "feeder_pack",
    5: "feeder_ring_wait",
    6: "feeder_serve",
    # Event front (PERF.md §26): one epoll wake's processing wall
    # (items = ready events), one connection's budgeted read drain
    # (items = bytes), and one EPOLLOUT writev resumption (items =
    # bytes moved) — the egress backpressure path, not the common
    # inline flush.
    7: "reactor_wake",
    8: "reactor_read",
    9: "reactor_write",
}

# Span stubs recorded per drain tick, bounded: under a 9k/s native
# herd an unbounded stub stream would evict every interesting span
# from the tracer's deque.
_MAX_STUBS_PER_DRAIN = 32


class NativeEventCollector:
    """One daemon's ring-drain thread + the derived stats."""

    def __init__(
        self,
        front,
        *,
        interval: float = 0.05,
        max_drain: int = 8192,
    ) -> None:
        import numpy as np

        self._front = front
        self.interval = interval
        self._max_drain = max_drain
        self._out = np.zeros(4 * max_drain, dtype=np.int64)
        self._hists: Dict[str, DurationStat] = {
            name: DurationStat() for name in STAGES.values()
        }
        self._counts: Dict[str, int] = {name: 0 for name in STAGES.values()}
        self._lock = threading.Lock()  # guberlint: guards _counts
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="guber-native-events", daemon=True
        )
        self._thread.start()

    @classmethod
    def from_env(cls, front) -> "NativeEventCollector":
        import os

        raw = os.environ.get("GUBER_NATIVE_EVENTS_INTERVAL", "").strip()
        interval = 0.05
        if raw:
            try:
                # Go-style duration strings ("50ms") or float seconds —
                # the same surface every other GUBER_* duration speaks.
                from gubernator_tpu.config import parse_duration

                interval = parse_duration(raw)
            except ValueError:
                log.warning(
                    "GUBER_NATIVE_EVENTS_INTERVAL=%r is not a duration;"
                    " using 0.05s", raw,
                )
        return cls(front, interval=max(0.005, interval))

    # -- the drain loop ------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.drain_once()
            except Exception:  # noqa: BLE001 — the tap must not die
                record_swallowed("native_events.drain")
                log.exception("native event drain failed")
        # Final drain so short-lived runs (benches, tests) keep the
        # tail events published just before close.
        try:
            self.drain_once()
        except Exception:  # noqa: BLE001 — teardown best-effort
            record_swallowed("native_events.drain")

    def drain_once(self) -> int:
        """One ring drain: bin durations into the per-stage histograms
        (vectorized), count events, emit bounded span stubs."""
        import numpy as np

        n = self._front.drain_events(self._out)
        if n <= 0:
            return 0
        rec = self._out[: 4 * n].reshape(n, 4)
        kinds = rec[:, 0]
        dur_s = rec[:, 2].astype(np.float64) / 1e9
        # Vectorized log2 binning, matching DurationStat.bucket_of.
        idx = np.floor(
            np.log2(np.maximum(dur_s, DurationStat._BASE) / DurationStat._BASE)
        ).astype(np.int64)
        np.clip(idx, 0, DurationStat.N_BUCKETS - 1, out=idx)
        for kind, stage in STAGES.items():
            mask = kinds == kind
            m = int(mask.sum())
            if not m:
                continue
            counts = np.bincount(
                idx[mask], minlength=DurationStat.N_BUCKETS
            )
            self._hists[stage].observe_bucket_counts(counts.tolist())
            with self._lock:
                self._counts[stage] += m
        self._emit_stubs(rec)
        return n

    def _emit_stubs(self, rec) -> None:
        from gubernator_tpu.utils import tracing

        tracer = tracing.current_tracer()
        if tracer is None or not hasattr(tracer, "record_span"):
            return
        native = rec[rec[:, 0] == 1][:_MAX_STUBS_PER_DRAIN]
        for kind, t_end, dur, items in native.tolist():
            tracer.record_span(
                "native.decide",
                start_ns=int(t_end - dur),
                end_ns=int(t_end),
                items=int(items),
                stage=STAGES[int(kind)],
            )

    # -- read side (metrics / debug vars / bench artifacts) ------------

    def histograms(self) -> Dict[str, DurationStat]:
        return self._hists

    def event_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def ring_stats(self) -> dict:
        return self._front.ring_stats()

    def stats(self) -> dict:
        """Bench-artifact / /debug/vars shape: counts, drops, and
        per-stage latency summaries."""
        out = {"events": self.event_counts(), "ring": self.ring_stats()}
        out["stages"] = {
            stage: h.snapshot_ms(digits=4)
            for stage, h in self._hists.items()
        }
        return out

    def close(self) -> bool:
        """Stop the drain thread; returns False if it outlived the
        join — the caller must then LEAK the ring instead of freeing
        it (H2FastFront.abandon_ring), or the straggler's next
        evr_drain is a native use-after-free."""
        self._stop.set()
        self._thread.join(timeout=5.0)
        return not self._thread.is_alive()

"""Hot-key attribution: a space-saving top-K sketch over decision keys.

Metwally's space-saving algorithm with a fixed capacity of counters:
every offered (key, hits) either bumps its existing counter or evicts
the minimum counter, inheriting its count as the new entry's error
bound.  Guarantees: any key with true count > count_min is IN the
table, and each reported count over-estimates by at most its recorded
`err`.  That is exactly the contract /debug/hotkeys needs — "which
keys are the load" with an honest error bar — in O(capacity) memory
regardless of key cardinality.

Windowed decay: alongside the cumulative counters, every tracked key
carries a two-window hit counter (current + previous window of
`window_s` seconds, rotated lazily on touch/read), so `top_rates()`
reports the *current* offered rate — a key hot an hour ago reads ~0
even though its cumulative count still ranks it.  The replication
plane (cluster/replication.py) promotes and — crucially — demotes off
these rates; demotion on the cumulative counts would never happen.
Rates come with the last observed (limit, duration) when the offering
path carries them, which is what lets the promotion path split a hot
key's limit into replica leases without an engine export sweep.

Batch entry points pre-aggregate with numpy on the decoded wire
columns (one np.unique per batch, dict work only per UNIQUE key), so
the serving paths pay O(batch log batch) numpy + O(unique) Python —
the same amortization shape as the GLOBAL window aggregation.  The
whole surface is gated by GUBER_HOTKEYS; disabled costs one attribute
check per batch.  GUBER_HOTKEYS_WINDOW sets the decay window.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

# _items value layout (a list, not a class: the offer path is the
# serving tier's highest-rate per-unique-key loop).
_COUNT = 0   # cumulative estimated count (space-saving)
_ERR = 1     # over-estimate bound inherited at eviction
_WID = 2     # window id of the _WIN counter
_WIN = 3     # hits offered in window _WID
_PREV = 4    # hits offered in window _WID - 1
_LIMIT = 5   # last observed request limit (0 = never seen)
_DUR = 6     # last observed request duration ms (0 = never seen)


class SpaceSaving:
    """Fixed-capacity top-K counter table (thread-safe).

    Eviction uses a LAZY MIN-HEAP of (count-at-push, key) entries
    instead of an O(capacity) min() scan: counts only grow, so a heap
    entry is either current (evictable) or stale (its key was bumped
    or already evicted — pop and, if live, re-push at the current
    count).  Amortized O(log K) per eviction; the table is on
    default-enabled serve paths where a full scan per new key would
    be a per-batch tax on high-cardinality workloads."""

    def __init__(
        self,
        capacity: int = 1024,
        *,
        window_s: float = 5.0,
        now=time.monotonic,
    ) -> None:
        self.capacity = max(1, capacity)
        # Decay window (seconds) for top_rates(); mutable so the bench
        # and the replication plane can tune responsiveness live.
        self.window_s = max(1e-3, window_s)
        self._now = now
        # key -> [count, err, wid, win, prev, limit, duration]
        self._items: Dict[bytes, List[int]] = {}
        # guberlint: guard _heap by _lock
        self._heap: list = []  # lazy (count_at_push, key) min-heap
        self._lock = threading.Lock()  # guberlint: guards _items
        self.offered = 0  # guberlint: guarded-by _lock

    def _wid(self) -> int:
        return int(self._now() / self.window_s)

    @staticmethod
    def _rotate(it: List[int], wid: int) -> None:
        """Lazily shift the two-window counters to window `wid`."""
        gap = wid - it[_WID]
        if gap == 0:
            return
        it[_PREV] = it[_WIN] if gap == 1 else 0
        it[_WIN] = 0
        it[_WID] = wid

    def _pop_min_locked(self) -> tuple:
        """(min_key, min_count) via the lazy heap; stale entries are
        dropped or refreshed on the way down."""
        import heapq

        while True:
            count, key = heapq.heappop(self._heap)
            it = self._items.get(key)
            if it is None:
                continue  # evicted earlier; stale entry
            if it[_COUNT] != count:
                # Bumped since pushed: refresh at the current count.
                heapq.heappush(self._heap, (it[_COUNT], key))
                continue
            return key, count

    def _offer_locked(
        self, key: bytes, n: int, wid: int, lim: int = 0, dur: int = 0
    ) -> None:
        import heapq

        it = self._items.get(key)
        if it is not None:
            it[_COUNT] += n  # heap entry goes stale; refreshed lazily
            self._rotate(it, wid)
            it[_WIN] += n
            if lim:
                it[_LIMIT] = lim
                it[_DUR] = dur
            return
        if len(self._items) < self.capacity:
            self._items[key] = [n, 0, wid, n, 0, lim, dur]
            heapq.heappush(self._heap, (n, key))
            return
        # Evict the minimum counter; the newcomer inherits its count
        # as the over-estimate bound (Metwally et al. 2005).  The
        # window counters start fresh — rates carry no inherited
        # error, only the cumulative count does.
        min_key, min_count = self._pop_min_locked()
        del self._items[min_key]
        self._items[key] = [min_count + n, min_count, wid, n, 0, lim, dur]
        heapq.heappush(self._heap, (min_count + n, key))

    def offer(self, key: bytes, n: int = 1) -> None:
        wid = self._wid()
        with self._lock:
            self.offered += n
            self._offer_locked(key, n, wid)

    def offer_many(self, pairs) -> None:
        """(key bytes, hits) iterable under ONE lock acquisition."""
        wid = self._wid()
        with self._lock:
            for key, n in pairs:
                self.offered += n
                self._offer_locked(key, n, wid)

    def offer_many_params(self, rows) -> None:
        """(key bytes, hits, limit, duration) iterable under ONE lock
        — the dataclass serving path's entry, carrying the request
        params the promotion plane sizes leases from."""
        wid = self._wid()
        with self._lock:
            for key, n, lim, dur in rows:
                self.offered += n
                self._offer_locked(key, n, wid, lim, dur)

    def offer_columns(
        self, key_buf, key_offsets, hits, idx=None, hashes=None,
        limit=None, duration=None,
    ) -> None:
        """Decoded-wire-batch entry: with `hashes` (the decode's
        per-row fnv1a), rows group by hash in ONE np.unique pass and
        key bytes materialize only per UNIQUE key — a 1000-occurrence
        hot-key batch costs one slice, which is what lets the
        zero-per-key-Python serve paths afford this hook.  (Hash
        identity: a 64-bit collision merges two keys' counts — noise
        far below the sketch's own error bound.)  Without hashes the
        per-row fallback runs.  `idx` restricts to a subset of rows
        (the GLOBAL serve route's owned/non-owned splits reuse the
        same decode).  `limit`/`duration` columns, when given, stamp
        each unique key's last-seen request params (lease sizing)."""
        import numpy as np

        offs = np.asarray(key_offsets)
        h = np.asarray(hits, dtype=np.int64)
        starts = offs[:-1]
        lens = offs[1:] - starts
        lim = np.asarray(limit) if limit is not None else None
        dur = np.asarray(duration) if duration is not None else None
        if idx is not None:
            starts, lens, h = starts[idx], lens[idx], h[idx]
            if lim is not None:
                lim, dur = lim[idx], dur[idx]
        if len(starts) == 0:
            return
        # Decisions with hits=0 are status reads; count them as one
        # observation each so read-hot keys still surface.
        weight = np.maximum(h, 1)
        if hashes is not None:
            hh = np.asarray(hashes)
            if idx is not None:
                hh = hh[idx]
            _u, first, inv = np.unique(
                hh, return_index=True, return_inverse=True
            )
            weight = np.bincount(inv, weights=weight).astype(np.int64)
            starts, lens = starts[first], lens[first]
            if lim is not None:
                lim, dur = lim[first], dur[first]
        buf = np.asarray(key_buf)
        if lim is None:
            self.offer_many(
                (buf[a:a + l].tobytes(), w)
                for a, l, w in zip(
                    starts.tolist(), lens.tolist(), weight.tolist()
                )
            )
        else:
            self.offer_many_params(
                (buf[a:a + l].tobytes(), w, li, du)
                for a, l, w, li, du in zip(
                    starts.tolist(), lens.tolist(), weight.tolist(),
                    lim.tolist(), dur.tolist(),
                )
            )

    def top(self, n: int = 20) -> List[Tuple[bytes, int, int]]:
        """[(key, estimated count, error bound)] sorted descending."""
        with self._lock:
            rows = sorted(
                ((k, v[_COUNT], v[_ERR]) for k, v in self._items.items()),
                key=lambda r: r[1],
                reverse=True,
            )
        return rows[:n]

    def top_rates(
        self, n: int = 20
    ) -> List[Tuple[bytes, float, int, int]]:
        """[(key, current offered hits/sec, last limit, last duration)]
        sorted by rate descending.  The rate is the sliding two-window
        estimate: the previous window's count weighted by its remaining
        overlap plus the current window's count, over one window — so a
        key that stopped being offered decays to ~0 within two windows
        regardless of its cumulative count (the demotion contract)."""
        now = self._now()
        wid = int(now / self.window_s)
        frac = (now / self.window_s) - wid  # elapsed fraction of wid
        w = self.window_s
        out: List[Tuple[bytes, float, int, int]] = []
        with self._lock:
            for k, it in self._items.items():
                self._rotate(it, wid)
                rate = (it[_PREV] * (1.0 - frac) + it[_WIN]) / w
                if rate > 0.0:
                    out.append((k, rate, it[_LIMIT], it[_DUR]))
        out.sort(key=lambda r: r[1], reverse=True)
        return out[:n]

    def rate(self, key: bytes) -> float:
        """Current offered rate (hits/sec) for one tracked key; 0.0
        when untracked or idle."""
        now = self._now()
        wid = int(now / self.window_s)
        frac = (now / self.window_s) - wid
        with self._lock:
            it = self._items.get(key)
            if it is None:
                return 0.0
            self._rotate(it, wid)
            return (it[_PREV] * (1.0 - frac) + it[_WIN]) / self.window_s

    def stats(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "tracked": len(self._items),
                "offered": self.offered,
            }


def from_env() -> Optional[SpaceSaving]:
    """Build the instance-level sketch from GUBER_HOTKEYS /
    GUBER_HOTKEYS_K / GUBER_HOTKEYS_WINDOW (None when disabled)."""
    import os

    if os.environ.get("GUBER_HOTKEYS", "1").strip().lower() in (
        "0", "false", "no", "off"
    ):
        return None
    try:
        k = int(os.environ.get("GUBER_HOTKEYS_K", "1024"))
    except ValueError:
        k = 1024
    try:
        window = float(os.environ.get("GUBER_HOTKEYS_WINDOW", "5.0"))
    except ValueError:
        window = 5.0
    return SpaceSaving(capacity=k, window_s=window)

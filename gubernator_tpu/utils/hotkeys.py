"""Hot-key attribution: a space-saving top-K sketch over decision keys.

Metwally's space-saving algorithm with a fixed capacity of counters:
every offered (key, hits) either bumps its existing counter or evicts
the minimum counter, inheriting its count as the new entry's error
bound.  Guarantees: any key with true count > count_min is IN the
table, and each reported count over-estimates by at most its recorded
`err`.  That is exactly the contract /debug/hotkeys needs — "which
keys are the load" with an honest error bar — in O(capacity) memory
regardless of key cardinality.

Batch entry points pre-aggregate with numpy on the decoded wire
columns (one np.unique per batch, dict work only per UNIQUE key), so
the serving paths pay O(batch log batch) numpy + O(unique) Python —
the same amortization shape as the GLOBAL window aggregation.  The
whole surface is gated by GUBER_HOTKEYS; disabled costs one attribute
check per batch.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple


class SpaceSaving:
    """Fixed-capacity top-K counter table (thread-safe).

    Eviction uses a LAZY MIN-HEAP of (count-at-push, key) entries
    instead of an O(capacity) min() scan: counts only grow, so a heap
    entry is either current (evictable) or stale (its key was bumped
    or already evicted — pop and, if live, re-push at the current
    count).  Amortized O(log K) per eviction; the table is on
    default-enabled serve paths where a full scan per new key would
    be a per-batch tax on high-cardinality workloads."""

    def __init__(self, capacity: int = 1024) -> None:
        self.capacity = max(1, capacity)
        # key -> [count, err]
        self._items: Dict[bytes, List[int]] = {}
        # guberlint: guard _heap by _lock
        self._heap: list = []  # lazy (count_at_push, key) min-heap
        self._lock = threading.Lock()  # guberlint: guards _items
        self.offered = 0  # guberlint: guarded-by _lock

    def _pop_min_locked(self) -> tuple:
        """(min_key, min_count) via the lazy heap; stale entries are
        dropped or refreshed on the way down."""
        import heapq

        while True:
            count, key = heapq.heappop(self._heap)
            it = self._items.get(key)
            if it is None:
                continue  # evicted earlier; stale entry
            if it[0] != count:
                # Bumped since pushed: refresh at the current count.
                heapq.heappush(self._heap, (it[0], key))
                continue
            return key, count

    def _offer_locked(self, key: bytes, n: int) -> None:
        import heapq

        it = self._items.get(key)
        if it is not None:
            it[0] += n  # heap entry goes stale; refreshed lazily
            return
        if len(self._items) < self.capacity:
            self._items[key] = [n, 0]
            heapq.heappush(self._heap, (n, key))
            return
        # Evict the minimum counter; the newcomer inherits its count
        # as the over-estimate bound (Metwally et al. 2005).
        min_key, min_count = self._pop_min_locked()
        del self._items[min_key]
        self._items[key] = [min_count + n, min_count]
        heapq.heappush(self._heap, (min_count + n, key))

    def offer(self, key: bytes, n: int = 1) -> None:
        with self._lock:
            self.offered += n
            self._offer_locked(key, n)

    def offer_many(self, pairs) -> None:
        """(key bytes, hits) iterable under ONE lock acquisition."""
        with self._lock:
            for key, n in pairs:
                self.offered += n
                self._offer_locked(key, n)

    def offer_columns(
        self, key_buf, key_offsets, hits, idx=None, hashes=None
    ) -> None:
        """Decoded-wire-batch entry: with `hashes` (the decode's
        per-row fnv1a), rows group by hash in ONE np.unique pass and
        key bytes materialize only per UNIQUE key — a 1000-occurrence
        hot-key batch costs one slice, which is what lets the
        zero-per-key-Python serve paths afford this hook.  (Hash
        identity: a 64-bit collision merges two keys' counts — noise
        far below the sketch's own error bound.)  Without hashes the
        per-row fallback runs.  `idx` restricts to a subset of rows
        (the GLOBAL serve route's owned/non-owned splits reuse the
        same decode)."""
        import numpy as np

        offs = np.asarray(key_offsets)
        h = np.asarray(hits, dtype=np.int64)
        starts = offs[:-1]
        lens = offs[1:] - starts
        if idx is not None:
            starts, lens, h = starts[idx], lens[idx], h[idx]
        if len(starts) == 0:
            return
        # Decisions with hits=0 are status reads; count them as one
        # observation each so read-hot keys still surface.
        weight = np.maximum(h, 1)
        if hashes is not None:
            hh = np.asarray(hashes)
            if idx is not None:
                hh = hh[idx]
            _u, first, inv = np.unique(
                hh, return_index=True, return_inverse=True
            )
            weight = np.bincount(inv, weights=weight).astype(np.int64)
            starts, lens = starts[first], lens[first]
        buf = np.asarray(key_buf)
        self.offer_many(
            (buf[a:a + l].tobytes(), w)
            for a, l, w in zip(
                starts.tolist(), lens.tolist(), weight.tolist()
            )
        )

    def top(self, n: int = 20) -> List[Tuple[bytes, int, int]]:
        """[(key, estimated count, error bound)] sorted descending."""
        with self._lock:
            rows = sorted(
                ((k, v[0], v[1]) for k, v in self._items.items()),
                key=lambda r: r[1],
                reverse=True,
            )
        return rows[:n]

    def stats(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "tracked": len(self._items),
                "offered": self.offered,
            }


def from_env() -> Optional[SpaceSaving]:
    """Build the instance-level sketch from GUBER_HOTKEYS /
    GUBER_HOTKEYS_K (None when disabled)."""
    import os

    if os.environ.get("GUBER_HOTKEYS", "1").strip().lower() in (
        "0", "false", "no", "off"
    ):
        return None
    try:
        k = int(os.environ.get("GUBER_HOTKEYS_K", "1024"))
    except ValueError:
        k = 1024
    return SpaceSaving(capacity=k)

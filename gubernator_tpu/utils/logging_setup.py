"""Logging configuration: GUBER_LOG_LEVEL / GUBER_LOG_FORMAT.

reference: config.go:255-280 — the reference switches logrus level and
text/json formatting from these variables; here the stdlib logging
layer gets the same surface (json lines carry time/level/logger/msg,
matching the reference's machine-readable intent).
"""

from __future__ import annotations

import json
import logging
import os


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "time": self.formatTime(record, "%Y-%m-%dT%H:%M:%S%z"),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        # Structured logs join the trace that emitted them: a log line
        # inside an active span carries its trace_id, so the flight
        # recorder's tail trees and the logs correlate on one id
        # (utils/tracing.current_trace_id; '' when tracing is off —
        # one global check).
        from gubernator_tpu.utils.tracing import current_trace_id

        trace_id = current_trace_id()
        if trace_id:
            out["trace_id"] = trace_id
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out)


def configure_logging(debug: bool = False) -> None:
    """Apply GUBER_LOG_LEVEL (trace/debug/info/warn/error; -debug flag
    wins) and GUBER_LOG_FORMAT (text|json)."""
    level_name = os.environ.get("GUBER_LOG_LEVEL", "").lower()
    level = {
        "trace": logging.DEBUG,
        "debug": logging.DEBUG,
        "info": logging.INFO,
        "warn": logging.WARNING,
        "warning": logging.WARNING,
        "error": logging.ERROR,
    }.get(level_name, logging.INFO)
    if debug:
        level = logging.DEBUG
    handler = logging.StreamHandler()
    if os.environ.get("GUBER_LOG_FORMAT", "text").lower() == "json":
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s %(message)s")
        )
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(level)

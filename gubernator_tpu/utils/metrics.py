"""Prometheus metrics for the daemon's /metrics endpoint.

Mirrors the reference's metric catalog (reference: prometheus.md:17-36;
series defined across gubernator.go:59-113, lrucache.go:48-59,
global.go:41-57, grpc_stats.go:41-131).  Counters are kept as plain
ints on the hot-path objects (engine/service/managers) — zero
contention on the decision path — and exported through one custom
Collector at scrape time, which also serves as the test oracle
(SURVEY.md §4.2: metrics-as-oracle tests).

This file is the metric REGISTRY guberlint's drift pass anchors on:
every ``*MetricFamily`` name constructed here must appear in the
README catalog (or PERF/RESILIENCE/STATIC_ANALYSIS/bench_trend), and
every documented ``gubernator_*`` series must still be constructed
here — registering a metric without documenting it fails CI.
"""

from __future__ import annotations

import os
import threading
from typing import TYPE_CHECKING, Iterable, Sequence

from prometheus_client.core import (
    CounterMetricFamily,
    GaugeMetricFamily,
    HistogramMetricFamily,
    SummaryMetricFamily,
)
from prometheus_client.registry import Collector, CollectorRegistry
from prometheus_client.samples import Exemplar

if TYPE_CHECKING:
    from gubernator_tpu.service import V1Instance

_OFF_VALUES = ("0", "false", "no", "off")


_exemplars_enabled = None


def exemplars_enabled() -> bool:
    """GUBER_METRICS_EXEMPLARS (default on): retain the last sampled
    trace_id per histogram bucket and export it as an OpenMetrics
    exemplar — the metrics→traces link.  Costs nothing while tracing
    is disabled (the tracing.active() check short-circuits first).
    Parsed once and cached: DurationStat.observe runs at wire-batch
    rate and must not pay an environment read + string normalization
    per observation (every other knob reads once at construction)."""
    global _exemplars_enabled
    if _exemplars_enabled is None:
        _exemplars_enabled = os.environ.get(
            "GUBER_METRICS_EXEMPLARS", "1"
        ).strip().lower() not in _OFF_VALUES
    return _exemplars_enabled


# Swallowed-exception visibility (guberlint thread pass): background
# threads that catch-and-continue MUST count the swallow here so a
# failing loop is a metric spike, not silence.  Module-level because
# the swallow sites span discovery/cluster/core objects with no shared
# instance.
_swallowed_lock = threading.Lock()
_swallowed: dict = {}  # guberlint: guarded-by _swallowed_lock


def record_swallowed(site: str) -> None:
    """Count one swallowed exception for the
    ``gubernator_swallowed_exceptions{site=...}`` counter."""
    with _swallowed_lock:
        _swallowed[site] = _swallowed.get(site, 0) + 1


def swallowed_counts() -> dict:
    with _swallowed_lock:
        return dict(_swallowed)


class DurationStat:
    """Duration summary (count + sum + max seconds) PLUS a streaming
    fixed-bucket histogram for real quantiles — a mean-only stat let
    call sites advertise a "p50 budget" while reporting means, which
    hides exactly the tail the flight recorder exists to attribute.
    Buckets are log2-spaced from 1µs: bucket i covers
    [2^i µs, 2^(i+1) µs), 36 buckets reaching ~19h, so one observe is
    a frexp + an increment.  Observations happen on flush/round
    boundaries (ms-scale work), so a tiny lock is fine; the
    per-decision hot path never touches one."""

    __slots__ = ("count", "total", "max", "buckets", "exemplars", "_lock")

    N_BUCKETS = 36
    _BASE = 1e-6  # bucket 0 lower bound: 1µs

    # guberlint: guard count, total, max, buckets, exemplars by _lock

    def __init__(self) -> None:
        self.count = 0
        self.total = 0.0
        self.max = 0.0
        self.buckets = [0] * self.N_BUCKETS
        # bucket index -> (trace_id, seconds): the LAST sampled trace
        # that landed in the bucket (bounded by N_BUCKETS entries by
        # construction; populated only while tracing is live AND
        # GUBER_METRICS_EXEMPLARS is on) — what turns a cluster p99
        # bucket into a link to a flight-recorder trace.
        self.exemplars: dict = {}
        self._lock = threading.Lock()

    @classmethod
    def bucket_of(cls, seconds: float) -> int:
        import math

        if seconds <= cls._BASE:
            return 0
        # frexp is exact and ~3x cheaper than log2 here: for
        # m * 2^e with m in [0.5, 1), floor(log2(x)) == e - 1.
        _m, e = math.frexp(seconds / cls._BASE)
        return min(cls.N_BUCKETS - 1, max(0, e - 1))

    @classmethod
    def bucket_bounds(cls, i: int) -> tuple:
        return (cls._BASE * (1 << i), cls._BASE * (1 << (i + 1)))

    def observe(self, seconds: float) -> None:
        b = self.bucket_of(seconds)
        ex = None
        # Exemplar capture: observations happen at flush/window
        # boundaries (see class docstring), so the context lookup is
        # off the per-decision path; a disabled tracer short-circuits
        # at one global check.
        if exemplars_enabled():
            from gubernator_tpu.utils import tracing

            if tracing.active():
                ctx = tracing.current_context()
                if ctx is not None and ctx.sampled:
                    ex = (ctx.trace_id, seconds)
        with self._lock:
            self.count += 1
            self.total += seconds
            if seconds > self.max:
                self.max = seconds
            self.buckets[b] += 1
            if ex is not None:
                self.exemplars[b] = ex

    def observe_bucket_counts(self, counts) -> None:
        """Merge pre-bucketed counts (index-aligned with N_BUCKETS) —
        the native event collector drains per-stage C histograms this
        way, one lock per drain instead of one per event."""
        n = total = 0.0
        top = 0.0
        for i, c in enumerate(counts):
            if c:
                n += c
                lo, hi = self.bucket_bounds(i)
                total += c * (lo + hi) / 2.0
                top = (lo * hi) ** 0.5
        if not n:
            return
        with self._lock:
            self.count += int(n)
            self.total += total
            # Max at bucket resolution (the geometric midpoint of the
            # highest occupied bucket) — pre-bucketed merges lose the
            # exact extremum by construction.
            if top > self.max:
                self.max = top
            for i, c in enumerate(counts):
                if c:
                    self.buckets[i] += int(c)

    def bucket_snapshot(self) -> dict:
        """One consistent {count, total, max, buckets} view — the
        wire shape of the fleet rollup (obs/fleet.py): a peer ships
        this and the collector merges it exactly."""
        with self._lock:
            return {
                "count": self.count,
                "total": self.total,
                "max": self.max,
                "buckets": list(self.buckets),
            }

    def merge_snapshot(self, snap: dict) -> None:
        """EXACT merge of another DurationStat's bucket_snapshot():
        counts/totals/max add, buckets add index-aligned — unlike
        observe_bucket_counts there is no midpoint approximation, so
        a fleet-merged mean is the true cluster mean and the merged
        quantiles are real histogram quantiles, not means-of-means."""
        buckets = snap.get("buckets") or []
        with self._lock:
            self.count += int(snap.get("count", 0))
            self.total += float(snap.get("total", 0.0))
            m = float(snap.get("max", 0.0))
            if m > self.max:
                self.max = m
            for i, c in enumerate(buckets[: self.N_BUCKETS]):
                if c:
                    self.buckets[i] += int(c)

    def exemplar_snapshot(self) -> dict:
        """{bucket index: (trace_id, seconds)} of live exemplars.
        Exemplars whose trace the in-memory tracer has fully evicted
        are pruned HERE (from the snapshot and the retained table):
        a metrics→trace link must never point at a trace that no
        longer exists."""
        with self._lock:
            out = dict(self.exemplars)
        if not out:
            return out
        from gubernator_tpu.utils import tracing

        has = getattr(tracing.current_tracer(), "has_trace", None)
        if has is None:
            return out
        for b, (tid, _v) in list(out.items()):
            if not has(tid):
                del out[b]
                with self._lock:
                    cur = self.exemplars.get(b)
                    if cur is not None and cur[0] == tid:
                        del self.exemplars[b]
        return out

    def mean(self) -> float:
        # Under the lock so count/total come from the same observation
        # (a torn pair between two observes skews the scrape).
        with self._lock:
            return self.total / self.count if self.count else 0.0

    def quantile(self, q: float) -> float:
        """Streaming quantile from the histogram (geometric bucket
        midpoint; resolution is a factor of 2 — honest for budget
        attribution, not for micro-benchmarks)."""
        with self._lock:
            n = self.count
            if not n:
                return 0.0
            rank = q * (n - 1)
            seen = 0
            for i, c in enumerate(self.buckets):
                seen += c
                if seen > rank:
                    lo, hi = self.bucket_bounds(i)
                    return (lo * hi) ** 0.5
            return self.max

    def p50(self) -> float:
        return self.quantile(0.50)

    def p99(self) -> float:
        return self.quantile(0.99)

    def snapshot_ms(self, digits: int = 3) -> dict:
        """The canonical {count, mean_ms, p50_ms, p99_ms, max_ms}
        rendering — shared by stage_budget(), /debug/vars, and the
        bench artifacts so the shape cannot drift between them."""
        with self._lock:
            count = self.count
            mean_s = self.total / count if count else 0.0
            max_s = self.max
        # The quantiles take the lock themselves; an observation
        # landing between the reads skews one scrape by one event.
        return {
            "count": count,
            "mean_ms": round(mean_s * 1e3, digits),
            "p50_ms": round(self.p50() * 1e3, digits),
            "p99_ms": round(self.p99() * 1e3, digits),
            "max_ms": round(max_s * 1e3, digits),
        }


class InstanceCollector(Collector):
    """Exports engine + service + manager counters.

    reference: V1Instance itself implements prometheus.Collector
    (gubernator.go:780-809).
    """

    def __init__(self, instance: "V1Instance"):
        self.instance = instance

    def collect(self) -> Iterable:
        inst = self.instance
        eng = inst.engine

        c = CounterMetricFamily(
            "gubernator_check_counter",
            "The number of rate limits checked.",
        )
        c.add_metric([], eng.requests_total)
        yield c

        c = CounterMetricFamily(
            "gubernator_over_limit_counter",
            "The number of rate limit checks that are over the limit.",
        )
        c.add_metric([], eng.over_limit_total)
        yield c

        c = CounterMetricFamily(
            "gubernator_check_error_counter",
            "The number of errors while checking rate limits.",
        )
        c.add_metric([], inst.counters["check_errors"])
        yield c

        c = CounterMetricFamily(
            "gubernator_getratelimit_counter",
            "The count of getRateLimit() calls by calltype.",
            labels=["calltype"],
        )
        c.add_metric(["local"], inst.counters["local"])
        c.add_metric(["forward"], inst.counters["forward"])
        c.add_metric(["global"], inst.counters["global"])
        c.add_metric(["sketch"], inst.counters.get("sketch", 0))
        c.add_metric(
            ["replicated"], inst.counters.get("replicated_local", 0)
        )
        yield c

        c = CounterMetricFamily(
            "gubernator_global_miss_local",
            "GLOBAL items served by a LOCAL eventually-consistent copy "
            "(status-cache miss on a non-owner) — the source of "
            "GLOBAL's bounded over-admission (<= n_nodes * limit per "
            "broadcast lag window).",
        )
        c.add_metric([], inst.counters.get("global_miss_local", 0))
        yield c

        c = CounterMetricFamily(
            "gubernator_asyncrequest_retries",
            "The count of retries in the forward path.",
        )
        c.add_metric([], inst.counters["async_retries"])
        yield c

        # ---- peer health plane (cluster/health.py; RESILIENCE.md) ----
        c = CounterMetricFamily(
            "gubernator_degraded_answers",
            "Requests answered by THIS node's engine because every "
            "owner candidate was circuit-open/unreachable "
            "(GUBER_DEGRADED_LOCAL).  Availability bought with bounded "
            "over-admission: <= N_partitions * limit per key.",
        )
        c.add_metric([], inst.counters.get("degraded_answers", 0))
        yield c

        c = CounterMetricFamily(
            "gubernator_backoff_retries",
            "Forward retries that waited out a capped-exponential "
            "backoff window before re-picking an owner.",
        )
        c.add_metric([], inst.counters.get("backoff_retries", 0))
        yield c

        g = GaugeMetricFamily(
            "gubernator_peer_state",
            "Per-peer circuit state (1 on the current state's series): "
            "healthy | suspect | broken | half-open.",
            labels=["peer", "state"],
        )
        transitions = CounterMetricFamily(
            "gubernator_circuit_transitions",
            "Circuit state transitions per peer, by to-state.",
            labels=["peer", "to"],
        )
        for peer in inst.get_peer_list():
            try:
                if peer.info.is_owner:
                    # The self-peer is never dialed; every other
                    # health surface (Daemon.peer_health, harness
                    # health_states) filters it, and the scrape must
                    # agree with them.
                    continue
                addr = peer.info.grpc_address
                g.add_metric([addr, peer.health.state()], 1)
                for to, n in sorted(peer.health.transition_counts().items()):
                    transitions.add_metric([addr, to], n)
            except Exception:  # noqa: BLE001 — peer mid-shutdown
                record_swallowed("metrics.peer_health_scrape")
                continue
        yield g
        yield transitions

        # ---- elastic membership (cluster/membership.py; RESILIENCE
        # §10): epoch counter, handoff row traffic, dual-window time.
        mem = getattr(inst, "membership", None)
        if mem is not None:
            g = GaugeMetricFamily(
                "gubernator_membership_epoch",
                "This node's membership epoch (bumps on every observed "
                "view change; equal across nodes once a transition "
                "settles).",
            )
            g.add_metric([], mem.epoch())
            yield g
            g = GaugeMetricFamily(
                "gubernator_membership_dual",
                "1 while a dual-ring cutover window is open (old + new "
                "rings both valid), else 0.",
            )
            g.add_metric([], 1 if mem.phase() == "dual" else 0)
            yield g
            c = CounterMetricFamily(
                "gubernator_ring_dual_window_seconds",
                "Cumulative seconds this node has spent inside "
                "dual-ring cutover windows.",
            )
            c.add_metric([], mem.dual_seconds())
            yield c
        hoff = getattr(inst, "handoff_counters", None)
        if hoff is not None:
            c = CounterMetricFamily(
                "gubernator_handoff_keys",
                "Ownership-handoff bucket rows by event: shipped to a "
                "new owner, forfeited at the epoch deadline (bounded "
                "over-admission, RESILIENCE.md §10), or received and "
                "restored here.",
                labels=["event"],
            )
            c.add_metric(["shipped"], hoff["shipped"])
            c.add_metric(["forfeited"], hoff["forfeited"])
            c.add_metric(["received"], hoff["received"])
            yield c

        # ---- hot-key replication plane (cluster/replication.py;
        # RESILIENCE.md §11): promotion/demotion lifecycle, grant
        # traffic, replica-answered decisions, and credit accounting
        # under the N_replicas × lease bound.
        repl = getattr(inst, "replication", None)
        if repl is not None:
            rs = repl.stats()
            g = GaugeMetricFamily(
                "gubernator_replication_keys",
                "Live hot-key replication state by role: promoted = "
                "keys THIS node (as owner) currently replicates; "
                "replica_leases = remote credit leases held here.",
                labels=["role"],
            )
            g.add_metric(["promoted"], rs["promoted_keys"])
            g.add_metric(["replica_leases"], rs["replica_leases"])
            yield g
            c = CounterMetricFamily(
                "gubernator_replication_events",
                "Hot-key replication lifecycle events by kind "
                "(promoted | demoted | grants_sent | grants_failed | "
                "grants_received | revokes_received | stale_dropped | "
                "expired).",
                labels=["event"],
            )
            for ev_name in (
                "promoted", "demoted", "grants_sent", "grants_failed",
                "grants_received", "revokes_received", "stale_dropped",
                "expired",
            ):
                c.add_metric([ev_name], rs[ev_name])
            yield c
            c = CounterMetricFamily(
                "gubernator_replication_answered",
                "Peer-owned decisions answered locally from a replica "
                "credit lease (the forward hops replication removed; "
                "natively answered drains fold in at pull time).",
            )
            c.add_metric([], rs["answered"])
            yield c
            c = CounterMetricFamily(
                "gubernator_replication_credit",
                "Replication credit flow in hits, by event: granted "
                "(pre-debited onto replica leases), returned (unused "
                "credit settled back), forfeited (lost to unreachable "
                "replicas — bounded by N_replicas × lease per window).",
                labels=["event"],
            )
            c.add_metric(["granted"], rs["credit_granted"])
            c.add_metric(["returned"], rs["credit_returned"])
            c.add_metric(["forfeited"], rs["credit_forfeited"])
            yield c

        c = CounterMetricFamily(
            "gubernator_hits_requeue",
            "GLOBAL hit-window re-queue traffic toward unreachable "
            "owners, by event (requeued | dropped at the age cap).",
            labels=["event"],
        )
        c.add_metric(["requeued"], inst.global_mgr.hits_requeued)
        c.add_metric(["dropped"], inst.global_mgr.hits_requeue_dropped)
        yield c

        c = CounterMetricFamily(
            "gubernator_broadcasts_skipped",
            "Per-peer broadcast pushes skipped, by reason: "
            "circuit_open (the peer is broken) or inflight (its "
            "previous push outlived the fan-out deadline — slow but "
            "healthy).  Supersedable traffic; the peer catches up "
            "from later windows.",
            labels=["reason"],
        )
        c.add_metric(["circuit_open"], inst.global_mgr.broadcasts_skipped)
        c.add_metric(
            ["inflight"], inst.global_mgr.broadcasts_skipped_inflight
        )
        yield c

        g = GaugeMetricFamily(
            "gubernator_cache_size",
            "The number of bucket slots currently interned.",
        )
        g.add_metric([], eng.cache_size())
        yield g

        c = CounterMetricFamily(
            "gubernator_global_async_sends",
            "The count of GLOBAL async hit windows flushed to owners.",
        )
        c.add_metric([], inst.global_mgr.async_sends)
        yield c

        c = CounterMetricFamily(
            "gubernator_global_broadcasts",
            "The count of GLOBAL broadcast windows pushed to peers.",
        )
        c.add_metric([], inst.global_mgr.broadcasts)
        yield c

        # ---- multi-region federation (cluster/multiregion.py;
        # RESILIENCE.md §12): window/push traffic, per-region circuit
        # state, requeue-and-converge accounting, degraded answers.
        mrs = inst.multi_region_mgr.stats()
        c = CounterMetricFamily(
            "gubernator_multiregion_windows",
            "Cross-region hit windows flushed (each window fans out "
            "to every remote region under the fan-out barrier).",
        )
        c.add_metric([], mrs["windows"])
        yield c
        c = CounterMetricFamily(
            "gubernator_multiregion_region_sends",
            "Successful per-region delta pushes, by remote region.",
            labels=["region"],
        )
        for region, n in sorted(mrs["region_sends_by"].items()):
            c.add_metric([region], n)
        yield c
        c = CounterMetricFamily(
            "gubernator_multiregion_hits_requeued",
            "Cross-region deltas re-queued toward an unreachable "
            "region (bounded, age-capped, delivered after heal).",
        )
        c.add_metric([], mrs["hits_requeued"])
        yield c
        c = CounterMetricFamily(
            "gubernator_multiregion_hits_dropped",
            "Cross-region deltas dropped at the requeue age/key cap "
            "or toward a departed region — counted, never silent; the "
            "drift bound covers what they would have reconciled.",
        )
        c.add_metric([], mrs["hits_dropped"])
        yield c
        g = GaugeMetricFamily(
            "gubernator_multiregion_region_state",
            "Aggregate circuit state per remote region (1 on the "
            "current state's series): healthy | degraded | open.",
            labels=["region", "state"],
        )
        for region, st in sorted(mrs["region_states"].items()):
            g.add_metric([region, st], 1)
        yield g
        c = CounterMetricFamily(
            "gubernator_multiregion_degraded_answers",
            "MULTI_REGION answers served while a remote region's "
            "circuit was open (metadata.degraded_region=true; "
            "over-admission bounded at N_regions x limit per window).",
        )
        c.add_metric([], inst.counters.get("degraded_region_answers", 0))
        yield c

        c = CounterMetricFamily(
            "gubernator_engine_batches",
            "Engine batches applied (device step groups).",
        )
        c.add_metric([], eng.batches_total)
        yield c

        c = CounterMetricFamily(
            "gubernator_engine_rounds",
            "Device kernel rounds executed (≥1 per batch; >1 when a "
            "batch repeats keys).",
        )
        c.add_metric([], eng.rounds_total)
        yield c

        # Paged device state (GUBER_PAGED; core/paging.py, PERF.md
        # §30).  Absent on dense engines — the scrape stays drift-free
        # both ways because the whole family is gated on the plane.
        paging = getattr(eng, "paging", None)
        if paging is not None:
            g = GaugeMetricFamily(
                "gubernator_paged_pages_resident",
                "Device frames resident (pages the clock hand ranks); "
                "total pages = ceil(logical capacity / page size).",
            )
            g.add_metric([], paging.frames)
            yield g

            c = CounterMetricFamily(
                "gubernator_paged_faults",
                "Page faults: batches touching a non-resident key "
                "paid a spill+refill before their round dispatched.",
            )
            c.add_metric([], paging.faults)
            yield c

            c = CounterMetricFamily(
                "gubernator_paged_spills",
                "Cold pages spilled to the host store (one d2h gather "
                "of the page's raw words each).",
            )
            c.add_metric([], paging.spills)
            yield c

            s = SummaryMetricFamily(
                "gubernator_paged_refill_wait",
                "Seconds a faulting batch waited for its page refill "
                "scatter (h2d + donated update).",
                count_value=paging.refill_wait.count,
                sum_value=paging.refill_wait.total,
            )
            yield s

        # Queue-depth gauges (reference: guber_queue_length /
        # guber_pool_queue_length, gubernator.go:70-84).
        g = GaugeMetricFamily(
            "gubernator_queue_length",
            "Per-peer batch queue depth (requests awaiting a flush).",
            labels=["peer"],
        )
        for peer in inst.get_peer_list():
            try:
                g.add_metric([peer.info.grpc_address], peer.queue_length())
            except Exception:  # noqa: BLE001 — peer mid-shutdown
                record_swallowed("metrics.peer_queue_scrape")
                continue
        yield g

        g = GaugeMetricFamily(
            "gubernator_global_queue_length",
            "GLOBAL manager queue depths by queue.",
            labels=["queue"],
        )
        g.add_metric(["hits"], inst.global_mgr._hits.pending())
        g.add_metric(["broadcasts"], inst.global_mgr._updates.pending())
        yield g

        # Backlog age: seconds the oldest queued item has waited.  A
        # healthy batcher stays near sync_wait; sustained growth means
        # the flush pipeline cannot drain the enqueue rate (the GLOBAL
        # tail mechanism — PERF.md §15).
        g = GaugeMetricFamily(
            "gubernator_global_backlog_age_seconds",
            "Age of the oldest queued GLOBAL item by queue.",
            labels=["queue"],
        )
        g.add_metric(["hits"], inst.global_mgr._hits.backlog_age())
        g.add_metric(["broadcasts"], inst.global_mgr._updates.backlog_age())
        yield g

        c = CounterMetricFamily(
            "gubernator_global_dropped",
            "GLOBAL queue items shed under overload (supersedable "
            "broadcasts only; hits block instead of dropping).",
            labels=["queue"],
        )
        c.add_metric(["hits"], inst.global_mgr._hits.dropped)
        c.add_metric(["broadcasts"], inst.global_mgr._updates.dropped)
        yield c

        # Batch-duration summaries (reference: guber_batch_send_duration
        # gubernator.go:100-106; guber_async_durations /
        # guber_broadcast_durations global.go:41-57;
        # guber_grpc_request_duration analog for engine rounds).
        s = SummaryMetricFamily(
            "gubernator_batch_send_duration",
            "Seconds spent flushing peer request batches.",
            count_value=inst.flush_duration.count,
            sum_value=inst.flush_duration.total,
        )
        yield s

        s = SummaryMetricFamily(
            "gubernator_global_send_duration",
            "Seconds spent sending GLOBAL hit windows to owners.",
            count_value=inst.global_mgr.hits_duration.count,
            sum_value=inst.global_mgr.hits_duration.total,
        )
        yield s

        s = SummaryMetricFamily(
            "gubernator_broadcast_duration",
            "Seconds spent broadcasting GLOBAL statuses to peers.",
            count_value=inst.global_mgr.broadcast_duration.count,
            sum_value=inst.global_mgr.broadcast_duration.total,
        )
        yield s

        s = SummaryMetricFamily(
            "gubernator_engine_round_duration",
            "Seconds of host-side dispatch per device kernel round.",
            count_value=eng.round_duration.count,
            sum_value=eng.round_duration.total,
        )
        yield s

        # The cluster-tier p50 budget, stage by stage (VERDICT r5
        # next-round #3): client window wait, engine serve, hit-window
        # wait, owner RPC, and broadcast enqueue→delivered age.  The
        # serial sum of these stage means IS the GLOBAL path's median
        # budget; PERF.md §10 publishes the measured table.
        s = SummaryMetricFamily(
            "gubernator_stage_duration",
            "Seconds per GLOBAL-path pipeline stage.",
            labels=["stage"],
        )
        for stage, stat in inst.stage_timers.items():
            s.add_metric([stage], count_value=stat.count, sum_value=stat.total)
        yield s

        # Streaming stage quantiles (DurationStat's fixed-bucket
        # histogram): the p50/p99 the budget tables used to fake with
        # means.  One series per (stage, quantile); native stages (the
        # event-ring histograms) join under a native_ prefix.
        g = GaugeMetricFamily(
            "gubernator_stage_quantile_seconds",
            "Streaming per-stage latency quantiles (log2-bucket "
            "histogram; resolution one octave).  Stages: the pipeline "
            "stage timers plus the event-ring stages under their own "
            "names (native_serve / window_wait / window_serve).",
            labels=["stage", "quantile"],
        )
        quantile_stats = dict(inst.stage_timers)
        ev = getattr(inst, "native_events", None)
        if ev is not None:
            # The collector's stage names (native_serve / window_wait /
            # window_serve) are already distinct from the stage-timer
            # keys and must match gubernator_native_events' labels —
            # joins on the stage label depend on it.
            quantile_stats.update(ev.histograms())
        for stage, stat in quantile_stats.items():
            g.add_metric([stage, "0.5"], stat.p50())
            g.add_metric([stage, "0.99"], stat.p99())
        yield g

        # The RAW per-stage histograms behind the quantile gauge: a
        # cross-node scraper (obs/fleet.py, bench.py's multi-node
        # stage budgets) needs the bucket counts to MERGE histograms
        # into real cluster quantiles — averaging per-node p99s is
        # the means-of-means lie the rollup exists to retire.  Tail
        # buckets carry OpenMetrics exemplars (last sampled trace_id)
        # when tracing is live, so a p99 bucket links straight to a
        # flight-recorder trace (classic exposition drops them;
        # /metrics?exemplars=1 serves the OpenMetrics rendering).
        h = HistogramMetricFamily(
            "gubernator_stage_seconds",
            "Per-stage latency histogram (36 log2 buckets from 1µs; "
            "the raw counts behind gubernator_stage_quantile_seconds, "
            "mergeable across nodes into real cluster quantiles).",
            labels=["stage"],
        )
        for stage, stat in quantile_stats.items():
            snap = stat.bucket_snapshot()
            exs = stat.exemplar_snapshot()
            cum = 0
            buckets = []
            for i, c in enumerate(snap["buckets"]):
                cum += c
                _lo, hi = DurationStat.bucket_bounds(i)
                ex = exs.get(i)
                if ex is not None:
                    buckets.append(
                        (
                            f"{hi:.9g}", float(cum),
                            Exemplar({"trace_id": ex[0]}, float(ex[1])),
                        )
                    )
                else:
                    buckets.append((f"{hi:.9g}", float(cum)))
            buckets.append(("+Inf", float(snap["count"])))
            h.add_metric([stage], buckets, sum_value=snap["total"])
        yield h

        # SLO watchdog gauges (obs/slo.py, attached by the daemon):
        # the continuously-evaluated burn rates of the declared SLIs
        # and the live admission-bound headroom — RESILIENCE.md's
        # N×limit proofs as a gauge instead of a bench-only assert.
        wd = getattr(inst, "slo_watchdog", None)
        if wd is not None:
            snap = wd.metrics_snapshot()
            g = GaugeMetricFamily(
                "gubernator_slo_burn_rate",
                "Error-budget burn rate per declared SLI and window "
                "(>1 = burning budget faster than the SLO allows; "
                "multi-window multi-burn-rate alerting, obs/slo.py).",
                labels=["sli", "window"],
            )
            for (sli, window), v in sorted(snap["burn"].items()):
                g.add_metric([sli, window], v)
            yield g
            g = GaugeMetricFamily(
                "gubernator_invariant_headroom",
                "Per watched finite-limit key: derived admission "
                "bound minus observed admitted hits in the current "
                "window (negative = a RESILIENCE.md invariant was "
                "violated; the bound label names the derivation).",
                labels=["key", "bound"],
            )
            for (key, bound), v in sorted(snap["headroom"].items()):
                g.add_metric([key, bound], v)
            yield g

        # Native event ring (core/native/event_ring.cpp, drained by
        # utils/native_events.py): per-stage C-front latency events and
        # the ring's overflow drops — the first per-decision visibility
        # inside the native plane.
        if ev is not None:
            c = CounterMetricFamily(
                "gubernator_native_events",
                "Event-ring records drained from the C front, by "
                "stage (native_serve | window_wait | window_serve).",
                labels=["stage"],
            )
            for stage, n in sorted(ev.event_counts().items()):
                c.add_metric([stage], n)
            yield c
            rs = ev.ring_stats()
            c = CounterMetricFamily(
                "gubernator_native_ring_dropped",
                "Event-ring writes dropped because the ring was full "
                "(the C front never blocks on observability).",
            )
            c.add_metric([], rs.get("dropped", 0))
            yield c
            s = SummaryMetricFamily(
                "gubernator_native_stage_duration",
                "Seconds per native-front stage, from the event ring.",
                labels=["stage"],
            )
            for stage, stat in ev.histograms().items():
                s.add_metric(
                    [stage], count_value=stat.count, sum_value=stat.total
                )
            yield s

        # Connection plane of the native h2 front (h2_server.cpp):
        # open connections and the idle reaper's cumulative kills —
        # the C100K surface the event front exists for (PERF.md §26).
        front = getattr(inst, "h2_front", None)
        if front is not None:
            cs = front.conn_stats()
            g = GaugeMetricFamily(
                "gubernator_h2_conns",
                "Native h2 front connections by state: open = currently "
                "held fds; idle_reaped = cumulative idle-timeout kills "
                "(GUBER_H2_IDLE_TIMEOUT; GOAWAY + close).",
                labels=["state"],
            )
            g.add_metric(["open"], float(cs["conns_open"]))
            g.add_metric(["idle_reaped"], float(cs["conns_idle_reaped"]))
            yield g

        # Hot-key attribution (utils/hotkeys.py space-saving sketch):
        # the top-K decision keys by estimated hit count, so load and
        # the p99 tail can be attributed to specific keys
        # (/debug/hotkeys serves the same table with error bounds).
        hk = getattr(inst, "hotkeys", None)
        if hk is not None:
            g = GaugeMetricFamily(
                "gubernator_hotkeys",
                "Estimated hits for the top-K decision keys "
                "(space-saving sketch; over-estimate bounded by the "
                "reported error).",
                labels=["key"],
            )
            for key, count, _err in hk.top(10):
                g.add_metric(
                    [key.decode(errors="replace")], float(count)
                )
            yield g

        # Decision-ledger counters (core/ledger.py): decisions answered
        # on the host without a device dispatch, rows that fell through
        # to the engine, lease lifecycle, and settle traffic.
        led = getattr(inst, "ledger", None)
        if led is not None:
            c = CounterMetricFamily(
                "gubernator_ledger_answered",
                "Decisions answered by the host decision ledger "
                "(sticky over-limit + lease credit) with zero device "
                "work.",
            )
            c.add_metric([], led.answered)
            yield c
            c = CounterMetricFamily(
                "gubernator_ledger_fallthrough",
                "Ledger-considered rows that fell through to the "
                "engine.",
            )
            c.add_metric([], led.fallthrough)
            yield c
            c = CounterMetricFamily(
                "gubernator_ledger_leases",
                "Lease lifecycle events by kind.",
                labels=["event"],
            )
            c.add_metric(["granted"], led.leases_granted)
            c.add_metric(["revoked"], led.leases_revoked)
            yield c
            c = CounterMetricFamily(
                "gubernator_ledger_settles",
                "Settle rows applied back to the device (consumed "
                "lease credits reconciled).",
            )
            c.add_metric([], led.settles)
            yield c
            s = SummaryMetricFamily(
                "gubernator_ledger_settle_lag",
                "Seconds from lease revocation to the settle apply.",
                count_value=led.settle_lag.count,
                sum_value=led.settle_lag.total,
            )
            yield s
        # One dp_stats round trip per scrape — the value feeds both the
        # counter and the dispatches-per-decision denominator.
        native_answered = led.native_answered() if led else 0
        if led is not None:
            c = CounterMetricFamily(
                "gubernator_ledger_native_answered",
                "Decisions answered by the native decision plane "
                "(C-resident ledger fast path: zero GIL, zero Python "
                "frames, zero device work).",
            )
            c.add_metric([], native_answered)
            yield c
        # Device dispatches per decision: the number the ledger exists
        # to push below 1 on hot-key traffic.  Decisions = engine rows
        # + ledger answers (Python AND native); dispatches = engine
        # kernel rounds.
        decisions = eng.requests_total + (
            led.answered + native_answered if led else 0
        )
        g = GaugeMetricFamily(
            "gubernator_dispatches_per_decision",
            "Engine kernel rounds per rate-limit decision "
            "(cumulative ratio).",
        )
        g.add_metric([], eng.rounds_total / decisions if decisions else 0.0)
        yield g

        # Window-size gauges: what the adaptive batching windows are
        # actually waiting right now (0 when idle, the configured cap
        # under sustained fill).
        g = GaugeMetricFamily(
            "gubernator_adaptive_window_seconds",
            "Current load-adaptive batching window by queue.",
            labels=["queue"],
        )
        g.add_metric(["hits"], inst.global_mgr._hits.current_wait())
        g.add_metric(["broadcasts"], inst.global_mgr._updates.current_wait())
        if inst._wire_window is not None:
            g.add_metric(["wire_window"], inst._wire_window.next_wait())
        if inst._global_window is not None:
            g.add_metric(["global_serve"], inst._global_window.next_wait())
        yield g

        # Swallowed exceptions by site: background threads that catch
        # and continue count here (guberlint thread pass) — a failing
        # loop shows as a rate spike instead of silence.
        c = CounterMetricFamily(
            "gubernator_swallowed_exceptions",
            "Exceptions swallowed by catch-and-continue sites, by site.",
            labels=["site"],
        )
        for site, n in sorted(swallowed_counts().items()):
            c.add_metric([site], n)
        yield c

        # XLA backend compiles observed at runtime (utils/jit_guard).
        # Flat after warmup in a healthy steady-state server; growth
        # means an unpinned shape/dtype reached a jit program in the
        # serve path (the trace pass + recompile-guard soak).
        from gubernator_tpu.utils import jit_guard

        c = CounterMetricFamily(
            "gubernator_jit_recompiles",
            "XLA backend compiles observed since process start "
            "(0 when the jax monitoring hook is unavailable).",
        )
        c.add_metric([], jit_guard.compile_count())
        yield c


class FleetRollupCollector(Collector):
    """Exports ONE merged fleet rollup (obs/fleet.FleetCollector
    .collect()) as gubernator_fleet_* families — served by any node
    at /metrics?fleet=1 so a single scrape answers for the cluster:
    counters SUM, gauges label-join by peer/region, and stage
    histograms merge via the 36-bucket path so the fleet p50/p99 are
    real quantiles.  Registered into a throwaway registry per scrape
    (the rollup is a point-in-time fan-out, not node state)."""

    def __init__(self, rollup: dict):
        self.rollup = rollup

    def collect(self) -> Iterable:
        r = self.rollup
        regions = r.get("regions") or {}
        g = GaugeMetricFamily(
            "gubernator_fleet_nodes",
            "Nodes merged into this fleet rollup, by region.",
            labels=["region"],
        )
        for region, sub in sorted(regions.items()):
            g.add_metric([region or "default"], sub.get("nodes", 0))
        yield g
        c = CounterMetricFamily(
            "gubernator_fleet_counter",
            "Fleet-summed node counters by name and region (the "
            "per-region subtotals come from the nodes' DC tags; the "
            "cluster total is the sum over regions).",
            labels=["counter", "region"],
        )
        for region, sub in sorted(regions.items()):
            for name, v in sorted((sub.get("counters") or {}).items()):
                c.add_metric([name, region or "default"], v)
        yield c
        g = GaugeMetricFamily(
            "gubernator_fleet_gauge",
            "Per-node gauges label-joined by peer and region (gauges "
            "do not sum — cache sizes and queue depths are per-node "
            "facts).",
            labels=["gauge", "peer", "region"],
        )
        for name, by_peer in sorted((r.get("gauges") or {}).items()):
            for peer, (region, v) in sorted(by_peer.items()):
                g.add_metric([name, peer, region or "default"], v)
        yield g
        g = GaugeMetricFamily(
            "gubernator_fleet_stage_quantile_seconds",
            "REAL cluster-wide per-stage quantiles from histogram "
            "merge (DurationStat.merge_snapshot over every node's "
            "36-bucket histogram) — not means of per-node quantiles.",
            labels=["stage", "quantile"],
        )
        for stage, q in sorted((r.get("quantiles") or {}).items()):
            g.add_metric([stage, "0.5"], q.get("p50_ms", 0.0) / 1e3)
            g.add_metric([stage, "0.99"], q.get("p99_ms", 0.0) / 1e3)
        yield g
        scrape = r.get("scrape") or {}
        g = GaugeMetricFamily(
            "gubernator_fleet_scrape",
            "The rollup fan-out's own health, by outcome: peers that "
            "answered (ok), failed inside the budget (failed), or "
            "were skipped because their circuit was open (skipped).",
            labels=["outcome"],
        )
        for outcome in ("ok", "failed", "skipped"):
            g.add_metric([outcome], scrape.get(outcome, 0))
        yield g


def build_fleet_registry(rollup: dict) -> CollectorRegistry:
    """Throwaway registry for one /metrics?fleet=1 scrape."""
    reg = CollectorRegistry()
    reg.register(FleetRollupCollector(rollup))
    return reg


def build_registry(
    instance: "V1Instance", metric_flags: Sequence[str] = ()
) -> CollectorRegistry:
    """Fresh registry per daemon (reference: daemon.go:85-99).

    `metric_flags` mirrors GUBER_METRIC_FLAGS (reference:
    flags.go:19-57, daemon.go:251-263): "os" adds the process
    CPU/RSS/fd collector; "python" adds the GC + platform collectors
    (the Go-runtime collector analog); "all" adds both."""
    reg = CollectorRegistry()
    reg.register(InstanceCollector(instance))
    flags = {f.strip().lower() for f in metric_flags if f.strip()}
    if flags & {"os", "all"}:
        from prometheus_client import ProcessCollector

        ProcessCollector(registry=reg)
    if flags & {"python", "golang", "all"}:
        from prometheus_client import GCCollector, PlatformCollector

        GCCollector(registry=reg)
        PlatformCollector(registry=reg)
    return reg

"""Prometheus metrics for the daemon's /metrics endpoint.

Mirrors the reference's metric catalog (reference: prometheus.md:17-36;
series defined across gubernator.go:59-113, lrucache.go:48-59,
global.go:41-57, grpc_stats.go:41-131).  Counters are kept as plain
ints on the hot-path objects (engine/service/managers) — zero
contention on the decision path — and exported through one custom
Collector at scrape time, which also serves as the test oracle
(SURVEY.md §4.2: metrics-as-oracle tests).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Iterable

from prometheus_client.core import (
    CounterMetricFamily,
    GaugeMetricFamily,
)
from prometheus_client.registry import Collector, CollectorRegistry

if TYPE_CHECKING:
    from gubernator_tpu.service import V1Instance


class InstanceCollector(Collector):
    """Exports engine + service + manager counters.

    reference: V1Instance itself implements prometheus.Collector
    (gubernator.go:780-809).
    """

    def __init__(self, instance: "V1Instance"):
        self.instance = instance

    def collect(self) -> Iterable:
        inst = self.instance
        eng = inst.engine

        c = CounterMetricFamily(
            "gubernator_check_counter",
            "The number of rate limits checked.",
        )
        c.add_metric([], eng.requests_total)
        yield c

        c = CounterMetricFamily(
            "gubernator_over_limit_counter",
            "The number of rate limit checks that are over the limit.",
        )
        c.add_metric([], eng.over_limit_total)
        yield c

        c = CounterMetricFamily(
            "gubernator_check_error_counter",
            "The number of errors while checking rate limits.",
        )
        c.add_metric([], inst.counters["check_errors"])
        yield c

        c = CounterMetricFamily(
            "gubernator_getratelimit_counter",
            "The count of getRateLimit() calls by calltype.",
            labels=["calltype"],
        )
        c.add_metric(["local"], inst.counters["local"])
        c.add_metric(["forward"], inst.counters["forward"])
        c.add_metric(["global"], inst.counters["global"])
        yield c

        c = CounterMetricFamily(
            "gubernator_asyncrequest_retries",
            "The count of retries in the forward path.",
        )
        c.add_metric([], inst.counters["async_retries"])
        yield c

        g = GaugeMetricFamily(
            "gubernator_cache_size",
            "The number of bucket slots currently interned.",
        )
        g.add_metric([], eng.cache_size())
        yield g

        c = CounterMetricFamily(
            "gubernator_global_async_sends",
            "The count of GLOBAL async hit windows flushed to owners.",
        )
        c.add_metric([], inst.global_mgr.async_sends)
        yield c

        c = CounterMetricFamily(
            "gubernator_global_broadcasts",
            "The count of GLOBAL broadcast windows pushed to peers.",
        )
        c.add_metric([], inst.global_mgr.broadcasts)
        yield c

        c = CounterMetricFamily(
            "gubernator_engine_batches",
            "Engine batches applied (device step groups).",
        )
        c.add_metric([], eng.batches_total)
        yield c

        c = CounterMetricFamily(
            "gubernator_engine_rounds",
            "Device kernel rounds executed (≥1 per batch; >1 when a "
            "batch repeats keys).",
        )
        c.add_metric([], eng.rounds_total)
        yield c


def build_registry(instance: "V1Instance") -> CollectorRegistry:
    """Fresh registry per daemon (reference: daemon.go:85-99)."""
    reg = CollectorRegistry()
    reg.register(InstanceCollector(instance))
    return reg

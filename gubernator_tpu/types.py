"""Wire-level types of the rate-limit API.

These mirror the reference proto contract exactly
(reference: proto/gubernator.proto:48-192, proto/peers.proto:36-57) so a
client of the reference can switch without changing request shapes.  The
actual protobuf/gRPC marshaling lives in `gubernator_tpu.net`; these
dataclasses are the in-process representation used by the engine and the
cluster tier.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional


class Algorithm(enum.IntEnum):
    """reference: proto/gubernator.proto:57-62"""

    TOKEN_BUCKET = 0
    LEAKY_BUCKET = 1


class Behavior(enum.IntFlag):
    """Bit flags controlling rate-limit behavior.

    reference: proto/gubernator.proto:65-131.  BATCHING is 0 (the proto
    requires a zero member); it is the default and has no effect when set.
    """

    BATCHING = 0
    NO_BATCHING = 1
    GLOBAL = 2
    DURATION_IS_GREGORIAN = 4
    RESET_REMAINING = 8
    MULTI_REGION = 16
    # Extension (no reference counterpart): route to the node-local
    # count-min-sketch approximate limiter — O(1) memory at unbounded
    # key cardinality, one-sided (never-under-count) error
    # (ops/sketch.py; BASELINE config 5).  Approximate and node-local
    # by design: no ownership routing, no peer forwarding.
    SKETCH = 32


class Status(enum.IntEnum):
    """reference: proto/gubernator.proto:164-167"""

    UNDER_LIMIT = 0
    OVER_LIMIT = 1


def has_behavior(behavior: int, flag: int) -> bool:
    """reference: gubernator.go:812-817 (HasBehavior)"""
    return (int(behavior) & int(flag)) != 0


@dataclass
class RateLimitReq:
    """One rate-limit check; config is carried in the request.

    reference: proto/gubernator.proto:133-162
    """

    name: str = ""
    unique_key: str = ""
    hits: int = 0
    limit: int = 0
    duration: int = 0  # milliseconds (or a Gregorian interval enum)
    algorithm: int = Algorithm.TOKEN_BUCKET
    behavior: int = Behavior.BATCHING
    burst: int = 0

    def hash_key(self) -> str:
        """The canonical cache/routing key.

        reference: client.go:37-39 (HashKey = Name + "_" + UniqueKey)
        """
        return self.name + "_" + self.unique_key


@dataclass
class RateLimitResp:
    """reference: proto/gubernator.proto:169-182"""

    status: int = Status.UNDER_LIMIT
    limit: int = 0
    remaining: int = 0
    reset_time: int = 0
    error: str = ""
    metadata: Dict[str, str] = field(default_factory=dict)


@dataclass
class GetRateLimitsReq:
    """reference: proto/gubernator.proto:48-50"""

    requests: List[RateLimitReq] = field(default_factory=list)


@dataclass
class GetRateLimitsResp:
    """reference: proto/gubernator.proto:53-55"""

    responses: List[RateLimitResp] = field(default_factory=list)


@dataclass
class HealthCheckReq:
    """reference: proto/gubernator.proto:184"""


@dataclass
class HealthCheckResp:
    """reference: proto/gubernator.proto:185-192"""

    status: str = ""
    message: str = ""
    peer_count: int = 0


@dataclass
class UpdatePeerGlobal:
    """reference: proto/peers.proto:52-56"""

    key: str = ""
    status: Optional[RateLimitResp] = None
    algorithm: int = Algorithm.TOKEN_BUCKET


@dataclass
class PeerInfo:
    """Identity of one cluster peer.

    reference: config.go (PeerInfo struct) — GRPCAddress is the canonical
    peer identity used by the consistent-hash ring
    (reference: replicated_hash.go:78-91).
    """

    grpc_address: str = ""
    http_address: str = ""
    datacenter: str = ""
    is_owner: bool = False

    def hash_key(self) -> str:
        return self.grpc_address


# Max number of requests in one GetRateLimits / GetPeerRateLimits batch.
# reference: gubernator.go:41 (maxBatchSize = 1000)
MAX_BATCH_SIZE = 1000

"""Wire-level etcd v3 client (no `etcd3` package needed) and a
protocol-faithful in-process mini-etcd for integration tests.

`EtcdWireClient` speaks the real etcd gRPC API — the same service
paths (/etcdserverpb.KV/Range, /etcdserverpb.Lease/LeaseKeepAlive,
/etcdserverpb.Watch/Watch) and message numbering a real cluster
expects (net/proto/etcd_rpc.proto) — through hand-rolled stubs, and
exposes the etcd3-client-shaped surface EtcdPool consumes (lease/
put/get_prefix/watch/delete).  With it, etcd discovery works in this
image without the optional dependency: point GUBER_ETCD_ENDPOINT at a
real cluster and the same bytes flow.

`MiniEtcdServer` implements the same API subset with real semantics —
revisions, lease TTL expiry revoking attached keys, keep-alive
extension, half-open [key, range_end) ranges, watch streams with
created/canceled responses and PUT/DELETE events — so the integration
test (tests/test_etcd_wire.py) exercises EtcdPool end-to-end over
real gRPC framing rather than API-shaped fakes.

reference: etcd.go:110-316 (clientv3 usage this mirrors on the wire).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import grpc

from gubernator_tpu.net.pb import etcd_kv_pb2 as kvpb
from gubernator_tpu.net.pb import etcd_rpc_pb2 as rpc

KV_SERVICE = "etcdserverpb.KV"
LEASE_SERVICE = "etcdserverpb.Lease"
WATCH_SERVICE = "etcdserverpb.Watch"


def prefix_range_end(prefix: bytes) -> bytes:
    """etcd's half-open prefix upper bound: last byte + 1 (with 0xff
    carry); all-0xff prefixes watch to the end of keyspace (b"\\0")."""
    end = bytearray(prefix)
    while end:
        if end[-1] < 0xFF:
            end[-1] += 1
            return bytes(end)
        end.pop()
    return b"\x00"


class _WireLease:
    """etcd3.Lease-shaped handle over the wire client."""

    def __init__(self, client: "EtcdWireClient", lease_id: int, ttl: int):
        self._client = client
        self.id = lease_id
        self.ttl = ttl

    def refresh(self):
        resp = self._client.lease_keepalive_once(self.id)
        if resp.TTL <= 0:
            raise RuntimeError(f"lease {self.id} expired on the server")
        return [resp]

    def revoke(self) -> None:
        self._client.lease_revoke(self.id)


class EtcdWireClient:
    """The etcd3-client API surface EtcdPool needs, over raw gRPC."""

    def __init__(
        self,
        target: str = "localhost:2379",
        *,
        credentials: Optional[grpc.ChannelCredentials] = None,
        timeout: float = 5.0,
    ):
        self.timeout = timeout
        if credentials is not None:
            self._channel = grpc.secure_channel(target, credentials)
        else:
            self._channel = grpc.insecure_channel(target)
        ch = self._channel
        self._range = ch.unary_unary(
            f"/{KV_SERVICE}/Range",
            request_serializer=rpc.RangeRequest.SerializeToString,
            response_deserializer=rpc.RangeResponse.FromString,
        )
        self._put = ch.unary_unary(
            f"/{KV_SERVICE}/Put",
            request_serializer=rpc.PutRequest.SerializeToString,
            response_deserializer=rpc.PutResponse.FromString,
        )
        self._delete_range = ch.unary_unary(
            f"/{KV_SERVICE}/DeleteRange",
            request_serializer=rpc.DeleteRangeRequest.SerializeToString,
            response_deserializer=rpc.DeleteRangeResponse.FromString,
        )
        self._lease_grant = ch.unary_unary(
            f"/{LEASE_SERVICE}/LeaseGrant",
            request_serializer=rpc.LeaseGrantRequest.SerializeToString,
            response_deserializer=rpc.LeaseGrantResponse.FromString,
        )
        self._lease_revoke = ch.unary_unary(
            f"/{LEASE_SERVICE}/LeaseRevoke",
            request_serializer=rpc.LeaseRevokeRequest.SerializeToString,
            response_deserializer=rpc.LeaseRevokeResponse.FromString,
        )
        self._lease_keepalive = ch.stream_stream(
            f"/{LEASE_SERVICE}/LeaseKeepAlive",
            request_serializer=rpc.LeaseKeepAliveRequest.SerializeToString,
            response_deserializer=rpc.LeaseKeepAliveResponse.FromString,
        )
        self._watch = ch.stream_stream(
            f"/{WATCH_SERVICE}/Watch",
            request_serializer=rpc.WatchRequest.SerializeToString,
            response_deserializer=rpc.WatchResponse.FromString,
        )
        self._watches: Dict[int, "_WatchStream"] = {}
        self._next_watch = 0
        self._lock = threading.Lock()

    # -- etcd3-shaped surface ------------------------------------------

    def lease(self, ttl: int) -> _WireLease:
        resp = self._lease_grant(
            rpc.LeaseGrantRequest(TTL=ttl), timeout=self.timeout
        )
        if resp.error:
            raise RuntimeError(f"LeaseGrant: {resp.error}")
        return _WireLease(self, resp.ID, resp.TTL)

    def put(self, key, value, lease=None) -> None:
        lease_id = getattr(lease, "id", lease) or 0
        self._put(
            rpc.PutRequest(
                key=_b(key), value=_b(value), lease=int(lease_id)
            ),
            timeout=self.timeout,
        )

    def get_prefix(self, prefix):
        resp = self._range(
            rpc.RangeRequest(
                key=_b(prefix), range_end=prefix_range_end(_b(prefix))
            ),
            timeout=self.timeout,
        )
        for kv in resp.kvs:
            yield kv.value, kv

    def delete(self, key) -> bool:
        resp = self._delete_range(
            rpc.DeleteRangeRequest(key=_b(key)), timeout=self.timeout
        )
        return resp.deleted > 0

    def add_watch_prefix_callback(
        self, prefix, callback: Callable
    ) -> int:
        with self._lock:
            watch_id = self._next_watch
            self._next_watch += 1
        ws = _WatchStream(self._watch, _b(prefix), callback)
        ws.start()
        with self._lock:
            self._watches[watch_id] = ws
        return watch_id

    def cancel_watch(self, watch_id: int) -> None:
        with self._lock:
            ws = self._watches.pop(watch_id, None)
        if ws is not None:
            ws.stop()

    # -- lower-level helpers -------------------------------------------

    def lease_keepalive_once(self, lease_id: int):
        """One keep-alive exchange on a short-lived stream (what
        etcd3.Lease.refresh does per call)."""

        def reqs():
            yield rpc.LeaseKeepAliveRequest(ID=lease_id)

        for resp in self._lease_keepalive(reqs(), timeout=self.timeout):
            return resp
        raise RuntimeError("LeaseKeepAlive stream yielded no response")

    def lease_revoke(self, lease_id: int) -> None:
        self._lease_revoke(
            rpc.LeaseRevokeRequest(ID=lease_id), timeout=self.timeout
        )

    def close(self) -> None:
        with self._lock:
            watches = list(self._watches.values())
            self._watches.clear()
        for ws in watches:
            ws.stop()
        self._channel.close()


def _b(v) -> bytes:
    return v.encode() if isinstance(v, str) else bytes(v)


class _WatchStream:
    """One Watch bidi stream delivering events to a callback from a
    background thread (resumes from the last seen revision on stream
    failure — reference: etcd.go:110-220's watch-retry loop)."""

    def __init__(self, stub, prefix: bytes, callback: Callable):
        self._stub = stub
        self._prefix = prefix
        self._callback = callback
        self._stopped = threading.Event()
        self._call = None
        self._last_rev = 0
        self._thread = threading.Thread(
            target=self._run, name="guber-etcd-watch", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()
        call = self._call
        if call is not None:
            call.cancel()
        self._thread.join(timeout=5.0)

    def _run(self) -> None:
        while not self._stopped.is_set():
            try:
                self._watch_once()
            except grpc.RpcError:
                if self._stopped.is_set():
                    return
                time.sleep(0.2)  # transient; resume from _last_rev

    def _watch_once(self) -> None:
        create = rpc.WatchRequest(
            create_request=rpc.WatchCreateRequest(
                key=self._prefix,
                range_end=prefix_range_end(self._prefix),
                start_revision=(
                    self._last_rev + 1 if self._last_rev else 0
                ),
            )
        )
        hold = threading.Event()

        def reqs():
            yield create
            hold.wait()  # keep the send side open until cancelled

        self._call = self._stub(reqs())
        try:
            for resp in self._call:
                if resp.canceled or self._stopped.is_set():
                    return
                for ev in resp.events:
                    # Advance the resume point ONLY past revisions whose
                    # events were actually delivered — taking it from an
                    # event-less response header (the `created` ack) can
                    # skip events the broken stream never sent, silently
                    # losing a dead peer's DELETE on reconnect.
                    if ev.kv.mod_revision:
                        self._last_rev = max(
                            self._last_rev, ev.kv.mod_revision
                        )
                    self._callback(ev)
        finally:
            hold.set()


# ---------------------------------------------------------------------
# In-process mini etcd (integration-test server).


class MiniEtcdServer:
    """etcd v3 API subset with real semantics, served over real gRPC.

    Supported: revisions, Range/Put/DeleteRange over [key, range_end),
    leases with TTL expiry that revokes attached keys, keep-alive
    extension, watch streams (created/canceled responses, PUT/DELETE
    events, start_revision replay is NOT kept — events are delivered
    from subscription time, which is what the discovery client needs).
    """

    def __init__(self, *, sweep_interval: float = 0.25):
        self._lock = threading.Lock()
        self._kv: Dict[bytes, kvpb.KeyValue] = {}
        self._rev = 0
        self._leases: Dict[int, dict] = {}
        self._next_lease = 1000
        self._watchers: List[dict] = []
        self._sweep_interval = sweep_interval
        self._closed = threading.Event()
        self._server = grpc.server(
            __import__("concurrent.futures", fromlist=["ThreadPoolExecutor"])
            .ThreadPoolExecutor(max_workers=16, thread_name_prefix="mini-etcd")
        )
        self._register_services()
        self.port = self._server.add_insecure_port("127.0.0.1:0")
        self.address = f"127.0.0.1:{self.port}"
        self._sweeper = threading.Thread(
            target=self._sweep_loop, name="mini-etcd-sweep", daemon=True
        )

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "MiniEtcdServer":
        self._server.start()
        self._sweeper.start()
        return self

    def stop(self) -> None:
        self._closed.set()
        self._server.stop(grace=0.5).wait()
        # The sweeper wakes on the _closed event; reap it so tests
        # can assert no mini-etcd threads outlive stop().
        if self._sweeper.is_alive():
            self._sweeper.join(timeout=2.0)

    # -- core state ----------------------------------------------------

    def _header(self) -> rpc.ResponseHeader:
        return rpc.ResponseHeader(
            cluster_id=1, member_id=1, revision=self._rev, raft_term=1
        )

    def _in_range(self, key: bytes, start: bytes, end: bytes) -> bool:
        if not end:
            return key == start
        if end == b"\x00":
            return key >= start
        return start <= key < end

    def _notify_locked(self, event: kvpb.Event) -> None:
        for w in self._watchers:
            if self._in_range(event.kv.key, w["key"], w["range_end"]):
                w["queue"].put(
                    rpc.WatchResponse(
                        header=self._header(),
                        watch_id=w["watch_id"],
                        events=[event],
                    )
                )

    def _put_locked(self, key: bytes, value: bytes, lease_id: int) -> None:
        self._rev += 1
        old = self._kv.get(key)
        kv = kvpb.KeyValue(
            key=key,
            value=value,
            create_revision=(
                old.create_revision if old is not None else self._rev
            ),
            mod_revision=self._rev,
            version=(old.version + 1 if old is not None else 1),
            lease=lease_id,
        )
        self._kv[key] = kv
        if lease_id:
            self._leases[lease_id]["keys"].add(key)
        self._notify_locked(kvpb.Event(type=kvpb.Event.PUT, kv=kv))

    def _delete_locked(self, key: bytes) -> bool:
        old = self._kv.pop(key, None)
        if old is None:
            return False
        self._rev += 1
        if old.lease and old.lease in self._leases:
            self._leases[old.lease]["keys"].discard(key)
        tomb = kvpb.KeyValue(key=key, mod_revision=self._rev)
        self._notify_locked(
            kvpb.Event(type=kvpb.Event.DELETE, kv=tomb, prev_kv=old)
        )
        return True

    def _revoke_locked(self, lease_id: int) -> None:
        lease = self._leases.pop(lease_id, None)
        if lease is None:
            return
        for key in sorted(lease["keys"]):
            self._delete_locked(key)

    def _sweep_loop(self) -> None:
        while not self._closed.wait(self._sweep_interval):
            now = time.monotonic()
            with self._lock:
                expired = [
                    lid
                    for lid, lease in self._leases.items()
                    if lease["expires"] <= now
                ]
                for lid in expired:
                    self._revoke_locked(lid)

    # -- RPC handlers --------------------------------------------------

    def _range(self, req: rpc.RangeRequest, ctx) -> rpc.RangeResponse:
        with self._lock:
            kvs = [
                kv
                for key, kv in sorted(self._kv.items())
                if self._in_range(key, req.key, req.range_end)
            ]
            return rpc.RangeResponse(
                header=self._header(), kvs=kvs, count=len(kvs)
            )

    def _put_rpc(self, req: rpc.PutRequest, ctx) -> rpc.PutResponse:
        with self._lock:
            if req.lease and req.lease not in self._leases:
                ctx.abort(
                    grpc.StatusCode.NOT_FOUND,
                    "etcdserver: requested lease not found",
                )
            self._put_locked(req.key, req.value, req.lease)
            return rpc.PutResponse(header=self._header())

    def _delete_rpc(
        self, req: rpc.DeleteRangeRequest, ctx
    ) -> rpc.DeleteRangeResponse:
        with self._lock:
            keys = [
                key
                for key in sorted(self._kv)
                if self._in_range(key, req.key, req.range_end)
            ]
            deleted = sum(1 for key in keys if self._delete_locked(key))
            return rpc.DeleteRangeResponse(
                header=self._header(), deleted=deleted
            )

    def _lease_grant(
        self, req: rpc.LeaseGrantRequest, ctx
    ) -> rpc.LeaseGrantResponse:
        with self._lock:
            lid = req.ID or self._next_lease
            self._next_lease = max(self._next_lease, lid) + 1
            ttl = max(int(req.TTL), 1)
            self._leases[lid] = {
                "ttl": ttl,
                "expires": time.monotonic() + ttl,
                "keys": set(),
            }
            return rpc.LeaseGrantResponse(
                header=self._header(), ID=lid, TTL=ttl
            )

    def _lease_revoke(
        self, req: rpc.LeaseRevokeRequest, ctx
    ) -> rpc.LeaseRevokeResponse:
        with self._lock:
            self._revoke_locked(req.ID)
            return rpc.LeaseRevokeResponse(header=self._header())

    def _lease_keepalive(self, request_iterator, ctx):
        for req in request_iterator:
            # Build the response under the lock, yield OUTSIDE it — a
            # client stalled on flow control would otherwise suspend
            # the generator with the server-wide lock held.
            with self._lock:
                lease = self._leases.get(req.ID)
                if lease is None:
                    # Real etcd answers TTL=0 for unknown leases.
                    resp = rpc.LeaseKeepAliveResponse(
                        header=self._header(), ID=req.ID, TTL=0
                    )
                else:
                    lease["expires"] = time.monotonic() + lease["ttl"]
                    resp = rpc.LeaseKeepAliveResponse(
                        header=self._header(), ID=req.ID, TTL=lease["ttl"]
                    )
            yield resp

    def _watch_rpc(self, request_iterator, ctx):
        out: "queue.Queue" = queue.Queue()
        my_watches: List[dict] = []
        next_id = [1]
        done = threading.Event()

        def reader() -> None:
            try:
                for req in request_iterator:
                    which = req.WhichOneof("request_union")
                    if which == "create_request":
                        cr = req.create_request
                        w = {
                            "key": cr.key,
                            "range_end": cr.range_end,
                            "watch_id": next_id[0],
                            "queue": out,
                        }
                        next_id[0] += 1
                        with self._lock:
                            self._watchers.append(w)
                        my_watches.append(w)
                        out.put(
                            rpc.WatchResponse(
                                header=self._header(),
                                watch_id=w["watch_id"],
                                created=True,
                            )
                        )
                    elif which == "cancel_request":
                        wid = req.cancel_request.watch_id
                        for w in my_watches:
                            if w["watch_id"] == wid:
                                with self._lock:
                                    if w in self._watchers:
                                        self._watchers.remove(w)
                                out.put(
                                    rpc.WatchResponse(
                                        header=self._header(),
                                        watch_id=wid,
                                        canceled=True,
                                    )
                                )
            except Exception:  # noqa: BLE001 — client went away
                from gubernator_tpu.utils.metrics import record_swallowed

                record_swallowed("etcd_wire.watch_reader")
            finally:
                done.set()
                out.put(None)

        # guberlint: ok thread — reader exits when the client's request
        # stream ends; completion is signaled via `done` + the None
        # sentinel, and the generator's finally deregisters watchers.
        t = threading.Thread(
            target=reader, name="mini-etcd-watch-reader", daemon=True
        )
        t.start()
        try:
            while True:
                item = out.get()
                if item is None:
                    if done.is_set():
                        return
                    continue
                yield item
        finally:
            with self._lock:
                for w in my_watches:
                    if w in self._watchers:
                        self._watchers.remove(w)

    # -- registration --------------------------------------------------

    def _register_services(self) -> None:
        def unary(fn, req_cls, resp_cls):
            return grpc.unary_unary_rpc_method_handler(
                fn,
                request_deserializer=req_cls.FromString,
                response_serializer=resp_cls.SerializeToString,
            )

        self._server.add_generic_rpc_handlers(
            (
                grpc.method_handlers_generic_handler(
                    KV_SERVICE,
                    {
                        "Range": unary(
                            self._range, rpc.RangeRequest, rpc.RangeResponse
                        ),
                        "Put": unary(
                            self._put_rpc, rpc.PutRequest, rpc.PutResponse
                        ),
                        "DeleteRange": unary(
                            self._delete_rpc,
                            rpc.DeleteRangeRequest,
                            rpc.DeleteRangeResponse,
                        ),
                    },
                ),
                grpc.method_handlers_generic_handler(
                    LEASE_SERVICE,
                    {
                        "LeaseGrant": unary(
                            self._lease_grant,
                            rpc.LeaseGrantRequest,
                            rpc.LeaseGrantResponse,
                        ),
                        "LeaseRevoke": unary(
                            self._lease_revoke,
                            rpc.LeaseRevokeRequest,
                            rpc.LeaseRevokeResponse,
                        ),
                        "LeaseKeepAlive": grpc.stream_stream_rpc_method_handler(
                            self._lease_keepalive,
                            request_deserializer=(
                                rpc.LeaseKeepAliveRequest.FromString
                            ),
                            response_serializer=(
                                rpc.LeaseKeepAliveResponse.SerializeToString
                            ),
                        ),
                    },
                ),
                grpc.method_handlers_generic_handler(
                    WATCH_SERVICE,
                    {
                        "Watch": grpc.stream_stream_rpc_method_handler(
                            self._watch_rpc,
                            request_deserializer=rpc.WatchRequest.FromString,
                            response_serializer=(
                                rpc.WatchResponse.SerializeToString
                            ),
                        ),
                    },
                ),
            )
        )

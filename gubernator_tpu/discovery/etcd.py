"""etcd peer discovery.

reference: etcd.go — lease-TTL registration (30s) with keep-alive and
re-register (etcd.go:222-316), prefix watch with revision resume
(:110-220), delete+revoke on shutdown (:298-311).

The transport is the built-in wire-level client
(discovery/etcd_wire.EtcdWireClient — hand-rolled stubs over etcd's
published gRPC API, no extra dependency); when the optional `etcd3`
package IS installed it is preferred, as it covers more of the API
surface (auth, TLS client certs)."""

from __future__ import annotations

import json
import threading
from typing import TYPE_CHECKING, Dict

from gubernator_tpu.discovery.base import DiscoveryBase, log
from gubernator_tpu.types import PeerInfo

if TYPE_CHECKING:
    from gubernator_tpu.config import DaemonConfig
    from gubernator_tpu.daemon import Daemon

LEASE_TTL_S = 30  # reference: etcd.go:35 (etcdTTL)


class EtcdPool(DiscoveryBase):
    def __init__(
        self,
        conf: "DaemonConfig",
        daemon: "Daemon",
        *,
        client=None,  # injectable for tests (any etcd3-shaped client)
        keepalive_interval: float = LEASE_TTL_S / 3,
    ):
        super().__init__(daemon)
        if client is None:
            try:
                import etcd3
            except ImportError:
                etcd3 = None

            endpoint = (conf.etcd_endpoints or ["localhost:2379"])[0]
            host, _, port = endpoint.rpartition(":")
            if etcd3 is None:
                client = self._wire_client(conf, endpoint)
            else:
                # Auth/TLS block (GUBER_ETCD_USER/_PASSWORD/_TLS_*;
                # reference: config.go:363-370, 440-496).
                kwargs = {
                    "host": host or "localhost",
                    "port": int(port or 2379),
                    "timeout": getattr(conf, "etcd_dial_timeout", 5.0),
                }
                if getattr(conf, "etcd_user", ""):
                    kwargs["user"] = conf.etcd_user
                    kwargs["password"] = conf.etcd_password
                if getattr(conf, "etcd_tls_ca", ""):
                    kwargs["ca_cert"] = conf.etcd_tls_ca
                if getattr(conf, "etcd_tls_cert", ""):
                    kwargs["cert_cert"] = conf.etcd_tls_cert
                    kwargs["cert_key"] = conf.etcd_tls_key
                client = etcd3.client(**kwargs)
        self._client = client
        self.keepalive_interval = keepalive_interval
        self.key_prefix = conf.etcd_key_prefix
        # Optional registration overrides (GUBER_ETCD_ADVERTISE_ADDRESS
        # / GUBER_ETCD_DATA_CENTER; reference: config.go:369-370).
        self._advertise_address = getattr(conf, "etcd_advertise_address", "")
        self._advertise_dc = getattr(conf, "etcd_data_center", "")
        self._lease = None
        self._watch_id = None
        self._peers: Dict[str, PeerInfo] = {}
        self._keepalive = threading.Thread(
            target=self._keepalive_loop, name="guber-etcd-lease", daemon=True
        )

    @staticmethod
    def _wire_client(conf: "DaemonConfig", endpoint: str):
        """Built-in wire-level client (no etcd3 dependency).  TLS via
        channel credentials; username/password auth is an etcd3-package
        feature (the wire client documents the limitation)."""
        from gubernator_tpu.discovery.etcd_wire import EtcdWireClient

        credentials = None
        if getattr(conf, "etcd_tls_ca", ""):
            import grpc

            with open(conf.etcd_tls_ca, "rb") as f:
                ca = f.read()
            chain = key = None
            if getattr(conf, "etcd_tls_cert", ""):
                with open(conf.etcd_tls_cert, "rb") as f:
                    chain = f.read()
                with open(conf.etcd_tls_key, "rb") as f:
                    key = f.read()
            credentials = grpc.ssl_channel_credentials(ca, key, chain)
        if getattr(conf, "etcd_user", ""):
            # Fail fast (the pre-wire-client behavior): connecting
            # unauthenticated to an auth-enabled cluster would start a
            # "healthy" daemon whose discovery fails on every RPC.
            raise RuntimeError(
                "GUBER_ETCD_USER is set but etcd username/password auth "
                "requires the optional 'etcd3' package (the built-in "
                "wire client supports TLS but not etcd auth tokens); "
                "install etcd3 or unset the credentials"
            )
        return EtcdWireClient(
            endpoint,
            credentials=credentials,
            timeout=getattr(conf, "etcd_dial_timeout", 5.0),
        )

    def _advertised(self):
        me = self.daemon.peer_info()
        grpc = self._advertise_address or me.grpc_address
        dc = self._advertise_dc or me.datacenter
        return grpc, me.http_address, dc

    def _my_key(self) -> str:
        return self.key_prefix + self._advertised()[0]

    def _register(self) -> None:
        """reference: etcd.go:222-316 (register + keep-alive loop)."""
        grpc, http, dc = self._advertised()
        self._lease = self._client.lease(LEASE_TTL_S)
        self._client.put(
            self._my_key(),
            json.dumps({"grpc": grpc, "http": http, "dc": dc}),
            lease=self._lease,
        )

    def _keepalive_loop(self) -> None:
        while not self._closed.wait(self.keepalive_interval):
            try:
                if self._lease is not None:
                    self._lease.refresh()
            except Exception:  # noqa: BLE001
                from gubernator_tpu.utils.metrics import record_swallowed

                record_swallowed("discovery.etcd_keepalive")
                log.exception("etcd lease refresh failed; re-registering")
                try:
                    self._register()
                except Exception:  # noqa: BLE001
                    log.exception("etcd re-register failed")

    def _sync(self) -> None:
        peers: Dict[str, PeerInfo] = {}
        for value, meta in self._client.get_prefix(self.key_prefix):
            try:
                obj = json.loads(value)
                peers[obj["grpc"]] = PeerInfo(
                    grpc_address=obj["grpc"],
                    http_address=obj.get("http", ""),
                    datacenter=obj.get("dc", ""),
                )
            except (ValueError, KeyError):
                continue
        # Watch events fire for every keepalive refresh and value
        # rewrite, not just membership changes; only a CHANGED view may
        # reach set_peers — each push rebuilds the consistent-hash
        # rings, and the membership plane treats a changed view as an
        # epoch transition (cluster/membership.py double-checks, but
        # the rebuild cost is saved here).  http_address participates:
        # a node re-registering with a new gateway port must propagate
        # even though its ring identity (grpc, dc) is unchanged.
        changed = {
            (a, p.datacenter, p.http_address) for a, p in peers.items()
        } != {
            (a, p.datacenter, p.http_address)
            for a, p in self._peers.items()
        }
        self._peers = peers
        if changed:
            self.on_update(list(peers.values()))

    def _on_event(self, event) -> None:
        self._sync()

    def start(self) -> None:
        self._register()
        self._sync()
        self._watch_id = self._client.add_watch_prefix_callback(
            self.key_prefix, self._on_event
        )
        self._keepalive.start()

    def close(self) -> None:
        super().close()
        # The keepalive loop wakes on the _closed event; reap it so a
        # lease refresh can't race the deregister below.
        if self._keepalive.is_alive():
            self._keepalive.join(timeout=2.0)
        try:
            if self._watch_id is not None:
                self._client.cancel_watch(self._watch_id)
            # Delete our key + revoke lease (reference: etcd.go:298-311).
            self._client.delete(self._my_key())
            if self._lease is not None:
                self._lease.revoke()
        except Exception:  # noqa: BLE001
            log.exception("etcd deregister failed")

"""Peer discovery backends (L0).

reference: memberlist.go / etcd.go / kubernetes.go / dns.go — each
backend watches a membership source and pushes the full peer list to
`Daemon.set_peers` via an on-update callback (reference: config.go:165,
daemon.go:185-220).
"""

from __future__ import annotations

from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from gubernator_tpu.config import DaemonConfig
    from gubernator_tpu.daemon import Daemon


def create_discovery(conf: "DaemonConfig", daemon: "Daemon"):
    """Build the configured backend (reference: daemon.go:185-220)."""
    kind = conf.peer_discovery_type
    if kind == "member-list":
        from gubernator_tpu.discovery.memberlist import MemberListPool

        return MemberListPool(conf, daemon)
    if kind == "dns":
        from gubernator_tpu.discovery.dns import DNSPool

        return DNSPool(conf, daemon)
    if kind == "etcd":
        from gubernator_tpu.discovery.etcd import EtcdPool

        return EtcdPool(conf, daemon)
    if kind == "k8s":
        from gubernator_tpu.discovery.kubernetes import K8sPool

        return K8sPool(conf, daemon)
    raise ValueError(f"unknown peer discovery type {kind!r}")

"""Kubernetes peer discovery (gated on the optional kubernetes client).

reference: kubernetes.go — SharedIndexInformer watch on Endpoints or
Pods selected by label (:48-65,103-188); peers built from ready pod IPs
(:190-244); in-cluster REST config (kubernetesconfig.go).

The `kubernetes` package is not part of this image; the backend raises
a clear error at construction when unavailable and implements a
pod-label watch when it is.
"""

from __future__ import annotations

import threading
from typing import TYPE_CHECKING

from gubernator_tpu.discovery.base import DiscoveryBase, log
from gubernator_tpu.types import PeerInfo

if TYPE_CHECKING:
    from gubernator_tpu.config import DaemonConfig
    from gubernator_tpu.daemon import Daemon


class K8sPool(DiscoveryBase):
    def __init__(
        self,
        conf: "DaemonConfig",
        daemon: "Daemon",
        *,
        core_api=None,  # injectable for tests (CoreV1Api-shaped)
        watch_factory=None,  # injectable for tests (kubernetes.watch.Watch-shaped)
    ):
        super().__init__(daemon)
        if core_api is None:
            try:
                import kubernetes  # noqa: F401
            except ImportError as e:
                raise RuntimeError(
                    "k8s discovery requires the 'kubernetes' package, which "
                    "is not installed in this environment; use member-list "
                    "or dns discovery instead"
                ) from e
            from kubernetes import client, config as k8s_config

            k8s_config.load_incluster_config()
            core_api = client.CoreV1Api()
        if watch_factory is None:
            # Resolve here, not in the watch thread — an ImportError
            # there would kill the loop silently with no peer pushes.
            try:
                from kubernetes import watch as k8s_watch
            except ImportError as e:
                raise RuntimeError(
                    "k8s discovery requires the 'kubernetes' package "
                    "(watch); inject watch_factory= for tests"
                ) from e
            watch_factory = k8s_watch.Watch
        import os

        self._core = core_api
        self._watch_factory = watch_factory
        self.namespace = os.environ.get("GUBER_K8S_NAMESPACE", "default")
        self.selector = os.environ.get("GUBER_K8S_POD_SELECTOR", "app=gubernator")
        self.grpc_port = daemon.grpc_address.rpartition(":")[2]
        self.http_port = daemon.http_address.rpartition(":")[2]
        self.datacenter = conf.data_center
        self._thread = threading.Thread(
            target=self._watch_loop, name="guber-k8s", daemon=True
        )

    def _list_peers(self):
        pods = self._core.list_namespaced_pod(
            self.namespace, label_selector=self.selector
        )
        peers = []
        for pod in pods.items:
            ip = pod.status.pod_ip
            ready = any(
                c.type == "Ready" and c.status == "True"
                for c in (pod.status.conditions or [])
            )
            if ip and ready:  # reference: kubernetes.go:190-244
                peers.append(
                    PeerInfo(
                        grpc_address=f"{ip}:{self.grpc_port}",
                        http_address=f"{ip}:{self.http_port}",
                        datacenter=self.datacenter,
                    )
                )
        return peers

    def _watch_loop(self) -> None:
        while not self._closed.is_set():
            try:
                self.on_update(self._list_peers())
                w = self._watch_factory()
                for _ in w.stream(
                    self._core.list_namespaced_pod,
                    self.namespace,
                    label_selector=self.selector,
                    timeout_seconds=30,
                ):
                    if self._closed.is_set():
                        return
                    self.on_update(self._list_peers())
            except Exception:  # noqa: BLE001
                from gubernator_tpu.utils.metrics import record_swallowed

                record_swallowed("discovery.k8s_watch")
                log.exception("k8s watch failed; retrying")
                self._closed.wait(2.0)

    def start(self) -> None:
        self._thread.start()

    def close(self) -> None:
        super().close()
        self._thread.join(timeout=2.0)

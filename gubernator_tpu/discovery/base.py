"""Shared scaffolding for discovery backends."""

from __future__ import annotations

import logging
import threading
from typing import TYPE_CHECKING, List

from gubernator_tpu.types import PeerInfo

if TYPE_CHECKING:
    from gubernator_tpu.daemon import Daemon

log = logging.getLogger("gubernator_tpu.discovery")


class DiscoveryBase:
    """A backend pushes full peer lists to the daemon on change.

    reference: config.go:165 (OnUpdate UpdateFunc) → daemon.SetPeers.
    """

    def __init__(self, daemon: "Daemon"):
        self.daemon = daemon
        self._closed = threading.Event()

    def on_update(self, peers: List[PeerInfo]) -> None:
        if self._closed.is_set():
            return
        try:
            self.daemon.set_peers(peers)
        except Exception:  # noqa: BLE001 — discovery must survive pushes
            from gubernator_tpu.utils.metrics import record_swallowed

            record_swallowed("discovery.set_peers")
            log.exception("SetPeers from discovery failed")

    def start(self) -> None:
        raise NotImplementedError

    def close(self) -> None:
        self._closed.set()

"""Gossip peer discovery (memberlist-style, self-contained).

reference: memberlist.go — the reference embeds hashicorp/memberlist
(SWIM gossip over UDP+TCP) and carries each node's PeerInfo as JSON
metadata (memberlist.go:126-151); join/leave/update events rebuild the
peer map (:187-233).

This backend reproduces the capability without the dependency: an
anti-entropy heartbeat gossip over UDP.  Each node keeps a member map
`addr -> (incarnation, heartbeat, PeerInfo, last_seen)`; every interval
it bumps its own heartbeat and sends its full map (JSON, one datagram)
to `fanout` random members plus any configured seed.  `last_seen` only
refreshes when a member's (incarnation, heartbeat) RISES — second-hand
gossip cannot keep a dead member alive — so members whose heartbeat
stalls for `suspect_after` are dropped.  A drop leaves a *death
certificate* (tombstone at the dead incarnation) that is itself
gossiped; without it, peers that haven't expired the member yet would
re-introduce it and the pool would oscillate.  Incarnations (startup
timestamps) resolve restarts: a restarted node's fresh incarnation
exceeds its tombstone and rejoins cleanly.

Wire format: the member map is SEGMENTED into datagrams of at most
`max_datagram` bytes (default 1200 — safely under any path MTU, like
hashicorp memberlist's packet budget).  Each datagram is a
self-contained partial map — merging is per-member idempotent
anti-entropy, so segmentation needs no reassembly protocol, and losing
a datagram only delays convergence of the members it carried.  The
sender's own entry rides in every segment so liveness never depends on
which segment survives.  Member-count envelope: a segment holds ~10
members, a 1000-member map is ~100 datagrams per target per interval
(~120KB/s at the defaults) — fine for hundreds of members, and the
soak test pins 50 members converging through loss
(tests/test_gossip_hardening.py); the data plane scales via the device
mesh, not host count.
"""

from __future__ import annotations

import json
import random
import socket
import threading
import time
from dataclasses import dataclass
from typing import TYPE_CHECKING, Dict, List, Tuple

from gubernator_tpu.discovery.base import DiscoveryBase, log
from gubernator_tpu.types import PeerInfo

if TYPE_CHECKING:
    from gubernator_tpu.config import DaemonConfig
    from gubernator_tpu.daemon import Daemon


@dataclass
class _Member:
    incarnation: int
    heartbeat: int
    info: PeerInfo
    last_seen: float


class MemberListPool(DiscoveryBase):
    """reference: memberlist.go:40-233 (MemberListPool)."""

    def __init__(
        self,
        conf: "DaemonConfig",
        daemon: "Daemon",
        *,
        interval: float = 1.0,
        suspect_after: float = 5.0,
        fanout: int = 3,
        max_datagram: int = 1200,
    ):
        super().__init__(daemon)
        self.interval = interval
        self.suspect_after = suspect_after
        self.fanout = fanout
        self.max_datagram = max_datagram
        bind = conf.member_list_address or f"0.0.0.0:{conf.advertise_port}"
        host, _, port = bind.rpartition(":")
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind((host or "0.0.0.0", int(port)))
        self._sock.settimeout(0.25)
        self.gossip_address = (
            f"{self._advertise_host(host)}:{self._sock.getsockname()[1]}"
        )
        self.seeds = [s for s in conf.known_hosts if s != self.gossip_address]
        self.incarnation = time.time_ns()
        self.heartbeat = 0
        self._members: Dict[str, _Member] = {}
        # Death certificates: addr -> (incarnation, recorded_at).
        self._dead: Dict[str, Tuple[int, float]] = {}
        self._dead_ttl = max(suspect_after * 4, 30.0)
        self._lock = threading.Lock()
        self._threads = [
            threading.Thread(target=self._recv_loop, name="guber-gossip-rx", daemon=True),
            threading.Thread(target=self._gossip_loop, name="guber-gossip-tx", daemon=True),
        ]

    @staticmethod
    def _advertise_host(bind_host: str) -> str:
        if bind_host in ("", "0.0.0.0", "::"):
            return "127.0.0.1"
        return bind_host

    # -- wire ------------------------------------------------------------

    def _snapshot(self) -> Dict[str, dict]:
        me = self.daemon.peer_info()
        with self._lock:
            out = {
                addr: {
                    "inc": m.incarnation,
                    "hb": m.heartbeat,
                    "grpc": m.info.grpc_address,
                    "http": m.info.http_address,
                    "dc": m.info.datacenter,
                }
                for addr, m in self._members.items()
            }
        out[self.gossip_address] = {
            "inc": self.incarnation,
            "hb": self.heartbeat,
            "grpc": me.grpc_address,
            "http": me.http_address,
            "dc": me.datacenter,
        }
        now = time.monotonic()
        with self._lock:
            for addr, (inc, recorded_at) in self._dead.items():
                if addr not in out:
                    # Certificates carry their age so every node's TTL
                    # clock agrees and retirement converges cluster-wide
                    # (re-learning a cert must not reset its age).
                    out[addr] = {
                        "inc": inc,
                        "dead": True,
                        "age": round(now - recorded_at, 3),
                    }
        return out

    def _merge(self, payload: Dict[str, dict]) -> bool:
        """Merge a received member map; True if membership changed."""
        changed = False
        now = time.monotonic()
        with self._lock:
            for addr, meta in payload.items():
                inc = int(meta.get("inc", 0))
                if addr == self.gossip_address:
                    # Refutation (the SWIM alive-message analog): if the
                    # cluster certified US dead (e.g. after a long GC
                    # pause), adopt a fresh incarnation — it exceeds the
                    # tombstone, so the next gossip round rejoins us.
                    if meta.get("dead") and inc >= self.incarnation:
                        self.incarnation = time.time_ns()
                        self.heartbeat = 0
                    continue
                cur = self._members.get(addr)
                if meta.get("dead"):
                    # Death certificate: kills any entry at or below
                    # the certified incarnation.
                    if cur is not None and cur.incarnation <= inc:
                        del self._members[addr]
                        changed = True
                    recorded_at = now - float(meta.get("age", 0.0))
                    prev = self._dead.get(addr)
                    if prev is None or prev[0] < inc:
                        self._dead[addr] = (inc, recorded_at)
                    elif prev[0] == inc and recorded_at < prev[1]:
                        # Same certificate, older clock — keep the older
                        # age so TTL retirement converges.
                        self._dead[addr] = (inc, recorded_at)
                    continue
                tomb = self._dead.get(addr)
                if tomb is not None and inc <= tomb[0]:
                    continue  # certified dead at this incarnation
                hb = int(meta.get("hb", 0))
                if cur is None or (inc, hb) > (cur.incarnation, cur.heartbeat):
                    self._members[addr] = _Member(
                        incarnation=inc,
                        heartbeat=hb,
                        info=PeerInfo(
                            grpc_address=meta.get("grpc", ""),
                            http_address=meta.get("http", ""),
                            datacenter=meta.get("dc", ""),
                        ),
                        last_seen=now,
                    )
                    changed = changed or cur is None
        return changed

    def _expire(self) -> bool:
        now = time.monotonic()
        cutoff = now - self.suspect_after
        with self._lock:
            dead = [a for a, m in self._members.items() if m.last_seen < cutoff]
            for a in dead:
                self._dead[a] = (self._members[a].incarnation, now)
                del self._members[a]
            # Retire old certificates so the map stays bounded.
            for a in [
                a
                for a, (_, t) in self._dead.items()
                if t < now - self._dead_ttl
            ]:
                del self._dead[a]
        return bool(dead)

    def _push_peers(self) -> None:
        me = self.daemon.peer_info()
        with self._lock:
            peers = [m.info for m in self._members.values()]
        peers.append(me)
        self.on_update(sorted(peers, key=lambda p: p.grpc_address))

    # -- loops -----------------------------------------------------------

    def _recv_loop(self) -> None:
        while not self._closed.is_set():
            try:
                data, _ = self._sock.recvfrom(256 * 1024)
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                payload = json.loads(data)
            except ValueError:
                continue
            if self._merge(payload):
                self._push_peers()

    def _encode_segments(self, snapshot: Dict[str, dict]) -> List[bytes]:
        """Split the member map into standalone datagrams ≤ max_datagram.

        The sender's own entry is repeated in every segment (liveness
        must not depend on which segment survives a lossy network); the
        remaining entries are packed greedily.  An entry that alone
        exceeds the budget still ships (the OS may fragment it)."""
        me_key = self.gossip_address
        me_entry = {me_key: snapshot[me_key]}
        base = len(json.dumps(me_entry).encode())
        segments: List[bytes] = []
        pending: Dict[str, dict] = dict(me_entry)
        size = base
        for addr, meta in snapshot.items():
            if addr == me_key:
                continue
            entry_len = len(json.dumps({addr: meta}).encode())
            if size + entry_len > self.max_datagram and len(pending) > 1:
                segments.append(json.dumps(pending).encode())
                pending = dict(me_entry)
                size = base
            pending[addr] = meta
            size += entry_len
        segments.append(json.dumps(pending).encode())
        return segments

    def _send(self, blob: bytes, addr: str) -> None:
        """One datagram to one member — the fault-injection seam
        (tests drop a fraction of sends here to model lossy networks)."""
        host, _, port = addr.rpartition(":")
        try:
            self._sock.sendto(blob, (host, int(port)))
        except OSError as e:
            log.debug("gossip send to %s failed: %s", addr, e)

    def _gossip_loop(self) -> None:
        # Announce immediately so joins propagate fast.
        self._push_peers()
        while not self._closed.wait(self.interval):
            self.heartbeat += 1
            segments = self._encode_segments(self._snapshot())
            with self._lock:
                members = list(self._members)
            targets = set(random.sample(members, min(self.fanout, len(members))))
            targets.update(self.seeds)
            for addr in targets:
                for blob in segments:
                    self._send(blob, addr)
            if self._expire():
                self._push_peers()

    def start(self) -> None:
        for t in self._threads:
            t.start()

    def close(self) -> None:
        super().close()
        for t in self._threads:
            t.join(timeout=2.0)
        self._sock.close()

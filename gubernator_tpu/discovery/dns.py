"""DNS peer discovery: poll A/AAAA records of an FQDN.

reference: dns.go:34-214 — resolve the FQDN at a TTL-driven interval
(min 300s default) and push the address set as the peer list; ports are
fixed for discovered peers (reference hardcodes :81/:80,
dns.go:155-168).  Uses the stdlib resolver (no raw-DNS dependency in
this image); poll interval comes from config instead of record TTLs.
"""

from __future__ import annotations

import socket
import threading
from typing import TYPE_CHECKING, List

from gubernator_tpu.discovery.base import DiscoveryBase, log
from gubernator_tpu.types import PeerInfo

if TYPE_CHECKING:
    from gubernator_tpu.config import DaemonConfig
    from gubernator_tpu.daemon import Daemon


class DNSPool(DiscoveryBase):
    def __init__(self, conf: "DaemonConfig", daemon: "Daemon"):
        super().__init__(daemon)
        if not conf.dns_fqdn:
            raise ValueError("GUBER_DNS_FQDN is required for dns discovery")
        self.fqdn = conf.dns_fqdn
        self.interval = max(conf.dns_poll_interval, 1.0)
        self.grpc_port = daemon.grpc_address.rpartition(":")[2]
        self.http_port = daemon.http_address.rpartition(":")[2]
        self.datacenter = conf.data_center
        self._thread = threading.Thread(
            target=self._poll_loop, name="guber-dns", daemon=True
        )

    def _resolve(self) -> List[PeerInfo]:
        addrs = set()
        for info in socket.getaddrinfo(self.fqdn, None, proto=socket.IPPROTO_TCP):
            addrs.add(info[4][0])
        return [
            PeerInfo(
                grpc_address=f"{a}:{self.grpc_port}",
                http_address=f"{a}:{self.http_port}",
                datacenter=self.datacenter,
            )
            for a in sorted(addrs)
        ]

    def _poll_loop(self) -> None:
        last: List[PeerInfo] = []
        while not self._closed.wait(0 if not last else self.interval):
            try:
                peers = self._resolve()
            except socket.gaierror as e:
                log.warning("dns resolve %s failed: %s", self.fqdn, e)
                continue
            if peers != last:
                last = peers
                self.on_update(peers)

    def start(self) -> None:
        self._thread.start()

    def close(self) -> None:
        super().close()
        self._thread.join(timeout=2.0)

"""Injectable millisecond clock with freeze/advance support.

The reference tests freeze and manually advance time
(reference: functional_test.go:160,215; holster/clock).  Everything in
this framework that needs "now" reads it from a `Clock` instance — and
the device kernel takes `now_ms` as an explicit input array (it never
reads time on-device), which is what makes frozen-clock conformance
tests possible (SURVEY.md §4.5).
"""

from __future__ import annotations

import threading
import time
from datetime import datetime, timezone


class Clock:
    """Wall clock that can be frozen and advanced manually (test support).

    Mirrors the semantics of holster `clock.Freeze`/`clock.Advance` used
    throughout the reference test-suite (reference: functional_test.go:160).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._frozen_ns: int | None = None

    def now_ns(self) -> int:
        with self._lock:
            if self._frozen_ns is not None:
                return self._frozen_ns
        return time.time_ns()

    def now_ms(self) -> int:
        """Unix epoch in milliseconds. reference: lrucache.go:107-109."""
        return self.now_ns() // 1_000_000

    def now_datetime(self) -> datetime:
        """Civil time (UTC) for Gregorian interval math."""
        return datetime.fromtimestamp(self.now_ns() / 1e9, tz=timezone.utc)

    # -- test controls ---------------------------------------------------

    def freeze(self) -> "Clock":
        with self._lock:
            self._frozen_ns = time.time_ns() if self._frozen_ns is None else self._frozen_ns
        return self

    def freeze_at(self, ns: int) -> "Clock":
        with self._lock:
            self._frozen_ns = ns
        return self

    def unfreeze(self) -> "Clock":
        with self._lock:
            self._frozen_ns = None
        return self

    def advance(self, *, ms: int = 0, ns: int = 0) -> None:
        """Advance a frozen clock; raises if the clock is not frozen."""
        with self._lock:
            if self._frozen_ns is None:
                raise RuntimeError("Clock.advance() requires a frozen clock")
            self._frozen_ns += ns + ms * 1_000_000

    @property
    def frozen(self) -> bool:
        with self._lock:
            return self._frozen_ns is not None


#: Process-wide default clock (daemon paths); tests inject their own.
SYSTEM_CLOCK = Clock()

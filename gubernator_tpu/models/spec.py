"""Scalar specification of the bucket update — the conformance oracle.

This is the reference's `algorithms.go` re-derived as a pure function
over one slot's state: `(state, input, now) -> (state', output)`.  The
vectorized device kernel (`gubernator_tpu.ops.bucket_kernel`) must be
bit-equivalent to this spec; `tests/test_kernel_vs_spec.py` fuzzes that.

Faithfully preserved reference quirks (each cited):

* Token bucket `status` is sticky: it is only written OVER_LIMIT in the
  "remaining==0 and hits>0" branch and never reset while the item lives
  (reference: algorithms.go:179-184).
* On a duration change that renews an expired bucket, the *stored*
  remaining becomes `limit` but the hits==0 *response* still reports the
  pre-renewal remaining, because the response struct was built earlier
  (reference: algorithms.go:131-136 vs 149-157,173-176).
* "Requested more than available" rejects without consuming
  (reference: algorithms.go:195-202,431-437).
* Leaky leak is only applied when `int64(leak) > 0`, so fractional
  leakage accrues by leaving `UpdatedAt` untouched
  (reference: algorithms.go:387-394; regression test
  functional_test.go:1106 TestLeakyBucketDivBug).
* Leaky: `b.Limit`/`b.Duration` are overwritten from the request every
  time (reference: algorithms.go:359-360); new leaky items store the
  Gregorian-remainder duration instead (reference: algorithms.go:472,479).

Deliberate divergences (reference bugs its tests never observe):

* New Gregorian token-bucket items here expire at the Gregorian boundary;
  the reference stores `now + duration` where duration is the interval
  *enum* (algorithms.go:222-245), expiring the item within ~5ms.
* New Gregorian leaky items compute `rate` from the true interval length;
  the reference computes it from the enum (algorithms.go:462-463),
  yielding rate≈0 for the first response's reset_time only.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional, Tuple

from gubernator_tpu.types import Algorithm, Behavior, Status

# int64 truncation helper: Go's int64(float64) truncates toward zero.
def _trunc(x: float) -> int:
    return int(x)


def quantize_remf(x: float) -> float:
    """Quantize a leaky remaining to the kernel's 32.32 fixed point.

    The device persists `remaining_f` as (int32 whole, uint32 2^-32
    fraction) — see ops/bucket_kernel.py `split_remf` — so the spec
    quantizes identically to stay bit-equal with the kernel.  All
    arithmetic here is exact in float64 (power-of-two scalings)."""
    import math

    w = math.floor(x)
    wc = min(max(w, -(2.0**31)), 2.0**31 - 1)
    return wc + math.floor((x - w) * 2.0**32) / 2.0**32


@dataclass
class SlotState:
    """One key's bucket state — the SoA row (reference: store.go:29-43).

    `t0` is TokenBucketItem.CreatedAt for token buckets and
    LeakyBucketItem.UpdatedAt for leaky buckets.  `expire_at` is the
    cache item TTL (reference: cache.go:30-42 CacheItem.ExpireAt).
    """

    algorithm: int = Algorithm.TOKEN_BUCKET
    limit: int = 0
    remaining: int = 0  # token-bucket remaining (int64)
    remaining_f: float = 0.0  # leaky-bucket remaining (float64)
    duration: int = 0
    t0: int = 0
    expire_at: int = 0
    burst: int = 0
    status: int = Status.UNDER_LIMIT
    invalid_at: int = 0  # store-driven invalidation (reference: cache.go:37-41)


@dataclass
class SpecInput:
    """Per-request fields after host-side Gregorian precompute."""

    hits: int = 0
    limit: int = 0
    duration: int = 0
    burst: int = 0
    algorithm: int = Algorithm.TOKEN_BUCKET
    behavior: int = Behavior.BATCHING
    greg_duration: int = 0  # gregorian_duration(now, duration) when flag set
    greg_expire: int = 0  # gregorian_expiration(now, duration) when flag set


@dataclass
class SpecOutput:
    status: int = Status.UNDER_LIMIT
    limit: int = 0
    remaining: int = 0
    reset_time: int = 0


def _is_live(state: Optional[SlotState], now: int) -> bool:
    """Cache-hit check (reference: lrucache.go:112-138).

    An item is a miss once `expire_at < now` (strict) or once a non-zero
    `invalid_at < now`.
    """
    if state is None:
        return False
    if state.invalid_at != 0 and state.invalid_at < now:
        return False
    if state.expire_at < now:
        return False
    return True


def apply_spec(
    state: Optional[SlotState], inp: SpecInput, now: int
) -> Tuple[Optional[SlotState], SpecOutput]:
    """Apply one request to one slot. Returns (new_state, response).

    new_state None means the slot was removed (RESET_REMAINING on a live
    token bucket, reference: algorithms.go:83-97).
    """
    live = _is_live(state, now)
    if live and state.algorithm != inp.algorithm:
        # Client switched algorithms: remove + recreate
        # (reference: algorithms.go:104-117,333-345).
        live = False
    greg = bool(inp.behavior & Behavior.DURATION_IS_GREGORIAN)
    reset_flag = bool(inp.behavior & Behavior.RESET_REMAINING)

    if inp.algorithm == Algorithm.TOKEN_BUCKET:
        if live:
            return _token_existing(state, inp, now, greg, reset_flag)
        return _token_new(inp, now, greg)
    else:
        if live:
            return _leaky_existing(state, inp, now, greg, reset_flag)
        return _leaky_new(inp, now, greg)


# ---------------------------------------------------------------- token


def _token_existing(
    s: SlotState, r: SpecInput, now: int, greg: bool, reset_flag: bool
) -> Tuple[Optional[SlotState], SpecOutput]:
    """reference: algorithms.go:79-208"""
    if reset_flag:
        # Remove the item entirely (reference: algorithms.go:83-97).
        return None, SpecOutput(Status.UNDER_LIMIT, r.limit, r.limit, 0)

    # Limit change folds the delta into remaining (algorithms.go:120-129).
    rem0 = s.remaining
    if s.limit != r.limit:
        rem0 = max(s.remaining + (r.limit - s.limit), 0)
    limit = r.limit

    created = s.t0
    expire = s.expire_at
    rem_store = rem0

    # Response snapshot taken *before* any renewal (algorithms.go:131-136).
    resp_rem = rem0
    resp_status = s.status
    status_store = s.status

    duration = s.duration
    if s.duration != r.duration:
        # Duration change (algorithms.go:138-162).
        new_expire = r.greg_expire if greg else created + r.duration
        if new_expire <= now:
            # Renew the bucket.
            new_expire = now + r.duration
            created = now
            rem_store = limit
        expire = new_expire
        duration = r.duration

    out = SpecOutput(resp_status, limit, resp_rem, expire)

    if r.hits == 0:
        # Status query only (algorithms.go:173-176).
        pass
    elif resp_rem == 0 and r.hits > 0:
        # Already at the limit (checks the response snapshot;
        # algorithms.go:179-185).
        out = SpecOutput(Status.OVER_LIMIT, limit, resp_rem, expire)
        status_store = Status.OVER_LIMIT
    elif rem_store == r.hits:
        # Hits take the exact remainder (algorithms.go:188-193).
        rem_store = 0
        out = SpecOutput(resp_status, limit, 0, expire)
    elif r.hits > rem_store:
        # Over the limit: reject WITHOUT consuming (algorithms.go:195-202).
        out = SpecOutput(Status.OVER_LIMIT, limit, resp_rem, expire)
    else:
        rem_store = rem_store - r.hits
        out = SpecOutput(resp_status, limit, rem_store, expire)

    new_state = replace(
        s,
        limit=limit,
        remaining=rem_store,
        duration=duration,
        t0=created,
        expire_at=expire,
        status=status_store,
        invalid_at=0,
    )
    return new_state, out


def _token_new(
    r: SpecInput, now: int, greg: bool
) -> Tuple[SlotState, SpecOutput]:
    """reference: algorithms.go:215-272"""
    expire = r.greg_expire if greg else now + r.duration
    remaining = r.limit - r.hits
    status = Status.UNDER_LIMIT
    if r.hits > r.limit:
        # Over on creation: don't consume (algorithms.go:255-261);
        # stored status stays UNDER_LIMIT (zero value of t.Status).
        status = Status.OVER_LIMIT
        remaining = r.limit

    state = SlotState(
        algorithm=Algorithm.TOKEN_BUCKET,
        limit=r.limit,
        remaining=remaining,
        duration=r.duration,
        t0=now,
        expire_at=expire,
        status=Status.UNDER_LIMIT,
    )
    return state, SpecOutput(status, r.limit, remaining, expire)


# ---------------------------------------------------------------- leaky


def _leaky_existing(
    s: SlotState, r: SpecInput, now: int, greg: bool, reset_flag: bool
) -> Tuple[SlotState, SpecOutput]:
    """reference: algorithms.go:329-448"""
    burst = r.burst if r.burst != 0 else r.limit  # algorithms.go:285-287

    rem = s.remaining_f
    if reset_flag:
        rem = float(burst)  # algorithms.go:347-349

    s_burst = s.burst
    if s_burst != burst:
        # algorithms.go:352-357
        if burst > _trunc(rem):
            rem = float(burst)
        s_burst = burst

    limit = r.limit
    duration = r.duration
    if limit > 0:
        rate = float(duration) / float(limit)
    else:
        rate = float("inf")

    eff_duration = duration
    if greg:
        # algorithms.go:365-381
        rate = float(r.greg_duration) / float(limit) if limit > 0 else float("inf")
        eff_duration = r.greg_expire - now

    expire = s.expire_at
    if r.hits != 0:
        expire = now + eff_duration  # algorithms.go:383-385 UpdateExpiration

    # Leak (algorithms.go:387-398).  rate==0 (duration 0) divides by zero
    # in Go too: elapsed/0.0 = +Inf, which refills the bucket to burst.
    # A negative rate (negative duration) divides normally: negative
    # leak, which never applies.
    elapsed = now - s.t0
    if rate != 0:
        leak = float(elapsed) / rate
    else:
        leak = float("inf") if elapsed > 0 else 0.0
    t0 = s.t0
    if leak == float("inf"):
        rem = float(s_burst)
        t0 = now
    elif _trunc(leak) > 0:
        rem += leak
        t0 = now
    if _trunc(rem) > s_burst:
        rem = float(s_burst)

    rem_i = _trunc(rem)
    rate_i = _trunc(rate) if rate != float("inf") else 0
    reset = now + (limit - rem_i) * rate_i
    out = SpecOutput(Status.UNDER_LIMIT, limit, rem_i, reset)

    if rem_i == 0 and r.hits > 0:
        # algorithms.go:416-421 — no mutation of remaining.
        out = SpecOutput(Status.OVER_LIMIT, limit, rem_i, reset)
    elif rem_i == r.hits:
        # algorithms.go:423-429 (also reached for hits==0, rem==0).
        rem -= float(r.hits)
        out = SpecOutput(Status.UNDER_LIMIT, limit, 0, now + limit * rate_i)
    elif r.hits > rem_i:
        # algorithms.go:431-437 — reject without consuming.
        out = SpecOutput(Status.OVER_LIMIT, limit, rem_i, reset)
    elif r.hits == 0:
        pass  # algorithms.go:439-442
    else:
        rem -= float(r.hits)
        out_rem = _trunc(rem)
        out = SpecOutput(
            Status.UNDER_LIMIT, limit, out_rem, now + (limit - out_rem) * rate_i
        )

    new_state = replace(
        s,
        algorithm=Algorithm.LEAKY_BUCKET,
        limit=limit,
        duration=duration,  # raw request duration (algorithms.go:360)
        remaining_f=quantize_remf(rem),
        t0=t0,
        expire_at=expire,
        burst=s_burst,
        invalid_at=0,
    )
    return new_state, out


def _leaky_new(
    r: SpecInput, now: int, greg: bool
) -> Tuple[SlotState, SpecOutput]:
    """reference: algorithms.go:454-516"""
    burst = r.burst if r.burst != 0 else r.limit
    duration = r.duration
    if greg:
        duration = r.greg_expire - now  # algorithms.go:464-473
        rate = float(r.greg_duration) / float(r.limit) if r.limit > 0 else float("inf")
    else:
        rate = float(duration) / float(r.limit) if r.limit > 0 else float("inf")

    remaining = burst - r.hits
    rate_i = _trunc(rate) if rate != float("inf") else 0
    status = Status.UNDER_LIMIT
    rem_f = float(remaining)
    resp_rem = remaining
    if r.hits > burst:
        # algorithms.go:492-498
        status = Status.OVER_LIMIT
        resp_rem = 0
        rem_f = 0.0
    reset = now + (r.limit - resp_rem) * rate_i

    state = SlotState(
        algorithm=Algorithm.LEAKY_BUCKET,
        limit=r.limit,
        remaining_f=quantize_remf(rem_f),
        duration=duration,
        t0=now,
        expire_at=now + duration,
        burst=burst,
        status=Status.UNDER_LIMIT,
    )
    return state, SpecOutput(status, r.limit, resp_rem, reset)

"""Rate-limit algorithm models.

`spec.py` is the scalar, single-slot specification of the token- and
leaky-bucket update — a faithful transcription of the reference
semantics (reference: algorithms.go:31-516) used as the differential
oracle for the vectorized device kernel in `gubernator_tpu.ops`.
`sketch.py` adds the count-min-sketch approximate limiter (a new
algorithm beyond the reference, BASELINE.md stretch config 5).
"""

from gubernator_tpu.models.spec import (
    SlotState,
    SpecInput,
    SpecOutput,
    apply_spec,
)

__all__ = ["SlotState", "SpecInput", "SpecOutput", "apply_spec"]

"""Mesh construction and sharding helpers.

One logical axis — "keys" — shards the bucket-state arrays.  This is
the TPU-native analog of the reference's worker hash ring
(reference: gubernator_pool.go:128-148): each device owns a contiguous
slot range instead of each goroutine owning a hash arc.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

KEYS_AXIS = "keys"

# jax.shard_map stabilized out of jax.experimental between minor jax
# releases; resolve whichever this jax ships so the mesh tier works on
# both (the CI image carries the experimental-only version).
try:
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover - depends on jax version
    from jax.experimental.shard_map import shard_map  # type: ignore


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated placement (the psum-merged GLOBAL columns are
    identical on every device)."""
    return NamedSharding(mesh, PartitionSpec())


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A 1-D mesh over `devices` (default: all local devices)."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (KEYS_AXIS,))


def keys_sharding(mesh: Mesh) -> NamedSharding:
    """Shard a leading-axis array over the keys axis."""
    return NamedSharding(mesh, PartitionSpec(KEYS_AXIS))

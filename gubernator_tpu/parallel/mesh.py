"""Mesh construction and sharding helpers.

One logical axis — "keys" — shards the bucket-state arrays.  This is
the TPU-native analog of the reference's worker hash ring
(reference: gubernator_pool.go:128-148): each device owns a contiguous
slot range instead of each goroutine owning a hash arc.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

KEYS_AXIS = "keys"


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A 1-D mesh over `devices` (default: all local devices)."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (KEYS_AXIS,))


def keys_sharding(mesh: Mesh) -> NamedSharding:
    """Shard a leading-axis array over the keys axis."""
    return NamedSharding(mesh, PartitionSpec(KEYS_AXIS))

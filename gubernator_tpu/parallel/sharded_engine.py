"""ShardedDecisionEngine — bucket state sharded over a device mesh.

The multi-chip execution engine: state arrays have shape
[n_shards, shard_capacity] sharded over the "keys" mesh axis; each
request batch is routed host-side to its owning shard
(fnv1a(key) mod n_shards — the TPU-native replacement for the worker
hash ring, reference: gubernator_pool.go:183-187) and applied by ONE
jitted shard_map step: every chip gathers/updates only its local state
block, so the decision path needs zero inter-chip traffic (PERF.md §7
— the measured argument for why zero-ICI is the optimum here); the
packed per-shard outputs return in the response readback, so cluster
metrics cost no extra transfer.

Per-key serialization and eviction-clear scheduling reuse the round
scheme of the single-device engine (core/engine.py), applied per shard.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from gubernator_tpu.clock import SYSTEM_CLOCK, Clock
from gubernator_tpu.gregorian import (
    GregorianError,
    dt_from_ms,
    gregorian_duration,
    gregorian_expiration,
)
from gubernator_tpu.hashing import fnv1a_64, fnv1a_64_batch, pack_keys
from gubernator_tpu.ops.bucket_kernel import (
    BucketState,
    make_state,
)
from gubernator_tpu.core.native import make_intern_table
from gubernator_tpu.parallel.mesh import (
    KEYS_AXIS,
    keys_sharding,
    make_mesh,
    shard_map as _shard_map,
)
from gubernator_tpu.types import Behavior, RateLimitReq, RateLimitResp, Status

_I32 = np.int32
_I64 = np.int64

# Hot-loop int constants (IntFlag/IntEnum ops are ~1.5µs each in
# CPython; see core/engine.py note).
_GREG = int(Behavior.DURATION_IS_GREGORIAN)
_OVER_I = int(Status.OVER_LIMIT)
_STATUS_OF = {int(st): st for st in Status}


def _pad_size(n: int, floor: int = 64) -> int:
    size = floor
    while size < n:
        size *= 2
    return size


def _squeeze(tree):
    return jax.tree.map(lambda x: x[0], tree)


def _expand(tree):
    return jax.tree.map(lambda x: x[None], tree)


class ShardedDecisionEngine:
    """Decision engine over an N-device mesh (total capacity =
    n_shards × shard_capacity)."""

    def __init__(
        self,
        shard_capacity: int = 50_000,
        *,
        mesh: Optional[Mesh] = None,
        clock: Clock = SYSTEM_CLOCK,
        max_kernel_width: int = 8192,
        store=None,  # gubernator_tpu.store.Store (write-through hooks)
        single_program: Optional[bool] = None,
    ):
        if not jax.config.jax_enable_x64:
            raise RuntimeError("gubernator_tpu requires jax x64")
        import os as _os

        from gubernator_tpu.platform_guard import disable_cpu_persistent_cache

        disable_cpu_persistent_cache()
        self.store = store
        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_shards = self.mesh.shape[KEYS_AXIS]
        # Execution strategy.  shard_map (default) places one state
        # block per mesh device — the real multi-chip path.  The
        # single-program mode runs the SAME per-shard semantics as one
        # vmapped XLA program on one device: on a one-core host (or a
        # one-chip backend serving a sharded keyspace) the per-device
        # program dispatch of an N-wide virtual mesh is pure overhead
        # (measured: 1.68ms -> 3.78ms per identical 2048-item batch
        # going 1 -> 8 virtual CPU devices).  Semantics equivalence is
        # pinned by tests/test_multi_schedule.py.
        if single_program is None:
            single_program = (
                _os.environ.get("GUBER_SHARDS_SINGLE_PROGRAM", "0") == "1"
            )
        self._single_program = bool(single_program)
        self.shard_capacity = shard_capacity
        self.capacity = shard_capacity * self.n_shards
        self.clock = clock
        self.max_kernel_width = max_kernel_width
        # Native C++ tables when buildable (batch schedule fast path).
        self.tables = [
            make_intern_table(shard_capacity) for _ in range(self.n_shards)
        ]
        # All-native tables unlock the single-FFI host tier
        # (git_multi_schedule: routing + interning + rounds + TTL +
        # dispatch order in one call — VERDICT r4 weak #3).
        from gubernator_tpu.core.native import NativeInternTable

        self._multi_ok = all(
            isinstance(t, NativeInternTable) for t in self.tables
        )
        self._lock = threading.Lock()
        self._sweep_cursor = 0  # next window start for incremental sweep
        self.requests_total = 0
        self.over_limit_total = 0
        self.batches_total = 0
        self.rounds_total = 0
        # Decision-plane device dispatch counter (see DecisionEngine).
        self.dispatches_total = 0
        # GLOBAL column merge as a psum over the mesh (ROADMAP item 1 /
        # PERF.md §24): a whole-batch round's per-shard packed outputs
        # are scattered to their request positions ON DEVICE and
        # `lax.psum`'d across the keys axis, so the host reads ONE
        # request-ordered [PACKED_OUT_ROWS, n] buffer instead of
        # unpermuting n_shards row sets — this is the ICI-level
        # aggregation the GLOBAL broadcast's owner re-read rides
        # (cluster/global_manager.py).  GUBER_PSUM_MERGE=0 disables.
        self._use_psum_merge = (
            not self._single_program
            and self.n_shards > 1
            and _os.environ.get("GUBER_PSUM_MERGE", "1") != "0"
        )
        self._merge_progs: Dict[Tuple[int, int], object] = {}
        from gubernator_tpu.utils.metrics import DurationStat

        self.round_duration = DurationStat()
        # Shared d2h transfer batching across concurrent callers
        # (core/readback.py — the mesh outputs combine the same way).
        from gubernator_tpu.core.readback import ReadbackCombiner

        self.readback = ReadbackCombiner()

        if self._single_program:
            # All shard blocks on one device; the vmapped step keeps
            # per-shard isolation inside one XLA program.
            dev0 = next(iter(self.mesh.devices.flat))
            self._state: BucketState = jax.tree.map(
                lambda leaf: jax.device_put(
                    jnp.tile(leaf[None], (self.n_shards, 1)), dev0
                ),
                make_state(shard_capacity),
            )
        else:
            state_spec = jax.tree.map(
                lambda _: keys_sharding(self.mesh), make_state(0)
            )
            # Allocate the sharded state: [n_shards, shard_capacity]
            # blocks, one per mesh device.
            self._state: BucketState = jax.tree.map(
                lambda leaf, sh: jax.device_put(
                    jnp.tile(leaf[None], (self.n_shards, 1)), sh
                ),
                make_state(shard_capacity),
                state_spec,
            )
        self._build_step()

    # ------------------------------------------------------------------

    def _build_step(self):
        mesh = self.mesh
        cap = self.shard_capacity

        pspec = P(KEYS_AXIS)

        if self._single_program:
            self._build_step_single_program()
            return

        def local_clear(occupied, slots):
            # occupied/slots carry the leading shard axis inside
            # shard_map; clear is a per-shard scatter.
            from gubernator_tpu.ops.bucket_kernel import _clear_occupied_impl

            return _clear_occupied_impl(occupied[0], slots[0])[None]

        self._clear_step = jax.jit(
            _shard_map(
                local_clear,
                mesh=mesh,
                in_specs=(pspec, pspec),
                out_specs=pspec,
            )
        )

        from gubernator_tpu.ops.bucket_kernel import (
            SlotValues,
            _collapsed_values,
            _fused_step_core,
            _packed_compute_core,
            _scatter_values,
            fused_step_ok,
        )

        # Packed columnar mesh step (see bucket_kernel PACKED_IN_ROWS):
        # the whole round crosses the host↔device boundary as ONE
        # int32 [n_shards, 16, width] buffer in and ONE
        # [n_shards, 5, width] buffer out — on a dispatch-bound backend
        # transfer count, not bytes, is what the step pays for.
        def local_packed_fused(state, pin):
            new_state, pout = _fused_step_core(_squeeze(state), pin[0])
            return _expand(new_state), pout[None]

        def local_packed_compute(state, pin):
            slot, vals, pout = _packed_compute_core(_squeeze(state), pin[0])
            return slot[None], _expand(vals), pout[None]

        # Collapsed duplicate-segment step per shard (hot keys — see
        # bucket_kernel COLLAPSED_IN_ROWS; the single-device engine's
        # closed form, run under shard_map).
        def local_collapsed_fused(state, pin):
            state1 = _squeeze(state)
            slot, vals2, pout = _collapsed_values(state1, pin[0])
            return _expand(_scatter_values(state1, slot, vals2)), pout[None]

        def local_collapsed_compute(state, pin):
            slot, vals2, pout = _collapsed_values(_squeeze(state), pin[0])
            return slot[None], _expand(vals2), pout[None]

        def local_scatter(state, slot, vals):
            return _expand(
                _scatter_values(_squeeze(state), slot[0], _squeeze(vals))
            )

        state_specs2 = jax.tree.map(lambda _: pspec, make_state(0))
        vals_specs = jax.tree.map(
            lambda _: pspec, SlotValues(*(0,) * len(SlotValues._fields))
        )
        self._packed_fused = jax.jit(
            _shard_map(
                local_packed_fused,
                mesh=mesh,
                in_specs=(state_specs2, pspec),
                out_specs=(state_specs2, pspec),
            ),
            donate_argnums=(0,),
        )
        self._packed_compute = jax.jit(
            _shard_map(
                local_packed_compute,
                mesh=mesh,
                in_specs=(state_specs2, pspec),
                out_specs=(pspec, vals_specs, pspec),
            )
        )
        self._step_scatter = jax.jit(
            _shard_map(
                local_scatter,
                mesh=mesh,
                in_specs=(state_specs2, pspec, vals_specs),
                out_specs=state_specs2,
            ),
            donate_argnums=(0,),
        )
        self._collapsed_fused = jax.jit(
            _shard_map(
                local_collapsed_fused,
                mesh=mesh,
                in_specs=(state_specs2, pspec),
                out_specs=(state_specs2, pspec),
            ),
            donate_argnums=(0,),
        )
        self._collapsed_compute = jax.jit(
            _shard_map(
                local_collapsed_compute,
                mesh=mesh,
                in_specs=(state_specs2, pspec),
                out_specs=(pspec, vals_specs, pspec),
            )
        )
        # Store read-through hydration: sharded counterpart of
        # core.engine load_slots (one batched scatter per round).
        from gubernator_tpu.ops.bucket_kernel import SlotRecord, _load_slots_impl

        def local_load(state, rec):
            return _expand(_load_slots_impl(_squeeze(state), _squeeze(rec)))

        rec_specs = jax.tree.map(
            lambda _: pspec, SlotRecord(*(0,) * len(SlotRecord._fields))
        )
        self._load_step = jax.jit(
            _shard_map(
                local_load,
                mesh=mesh,
                in_specs=(state_specs2, rec_specs),
                out_specs=state_specs2,
            ),
            donate_argnums=(0,),
        )
        # The per-shard program is the same computation as the
        # single-device fused step, so its copy-insertion behavior
        # probes identically at shard capacity.
        self._fused = fused_step_ok(self.shard_capacity)
        self._flat_ok = False  # flat dispatch is single-program-only

    def _build_step_single_program(self):
        """One vmapped XLA program over the [n_shards, ...] leading
        axis instead of one shard_map program per mesh device — the
        same per-shard gather/update/scatter semantics with zero
        per-device dispatch overhead (see __init__)."""
        from gubernator_tpu.ops.bucket_kernel import (
            _clear_occupied_impl,
            _collapsed_values,
            _fused_step_core,
            _load_slots_impl,
            _packed_compute_core,
            _scatter_values,
            fused_step_ok,
        )

        self._clear_step = jax.jit(jax.vmap(_clear_occupied_impl))
        self._packed_fused = jax.jit(
            jax.vmap(_fused_step_core), donate_argnums=(0,)
        )
        self._packed_compute = jax.jit(jax.vmap(_packed_compute_core))
        self._step_scatter = jax.jit(
            jax.vmap(_scatter_values), donate_argnums=(0,)
        )

        def collapsed_fused_one(state, pin):
            slot, vals2, pout = _collapsed_values(state, pin)
            return _scatter_values(state, slot, vals2), pout

        self._collapsed_fused = jax.jit(
            jax.vmap(collapsed_fused_one), donate_argnums=(0,)
        )
        self._collapsed_compute = jax.jit(jax.vmap(_collapsed_values))
        self._load_step = jax.jit(
            jax.vmap(_load_slots_impl), donate_argnums=(0,)
        )
        self._fused = fused_step_ok(self.shard_capacity)

        # Flat executors: the hot columnar path globalizes slots
        # (shard*cap + slot) and runs the WHOLE batch as one
        # non-batched program over the flattened state — no per-shard
        # padded blocks at all.  The [n_shards, cap] canonical layout
        # is reshaped inside jit (free: XLA bitcasts it away), so
        # save/load/sweep/export see the same state they always did.
        n_sh, cap = self.n_shards, self.shard_capacity
        # pack_batch_host padding lanes run up to capacity + width;
        # the int32 slot row caps the flat layout at 2^31.
        self._flat_ok = (
            self.capacity + 2 * self.max_kernel_width < 2**31
        )

        def _flatten(state):
            return jax.tree.map(lambda x: x.reshape(-1), state)

        def _unflatten(state):
            return jax.tree.map(lambda x: x.reshape(n_sh, cap), state)

        def flat_packed_fused(state, pin):
            st, pout = _fused_step_core(_flatten(state), pin[0])
            return _unflatten(st), pout[None]

        def flat_packed_compute(state, pin):
            slot, vals, pout = _packed_compute_core(_flatten(state), pin[0])
            return slot[None], _expand(vals), pout[None]

        def flat_scatter(state, slot, vals):
            return _unflatten(
                _scatter_values(_flatten(state), slot[0], _squeeze(vals))
            )

        def flat_collapsed_fused(state, pin):
            st = _flatten(state)
            slot, vals2, pout = _collapsed_values(st, pin[0])
            return _unflatten(_scatter_values(st, slot, vals2)), pout[None]

        def flat_collapsed_compute(state, pin):
            slot, vals2, pout = _collapsed_values(_flatten(state), pin[0])
            return slot[None], _expand(vals2), pout[None]

        # guberlint: shapes pin [1, PACKED_IN_ROWS, W] per shard, W on the width ladder; state [n_sh, cap] fixed
        self._flat_fused = jax.jit(flat_packed_fused, donate_argnums=(0,))
        # guberlint: shapes same pin/state contract as _flat_fused (split compute half)
        self._flat_compute = jax.jit(flat_packed_compute)
        # guberlint: shapes slot/vals [1, W] on the width ladder; state [n_sh, cap] fixed
        self._flat_scatter = jax.jit(flat_scatter, donate_argnums=(0,))
        # guberlint: shapes pin [1, COLLAPSED_IN_ROWS, W] on the width ladder; state [n_sh, cap] fixed
        self._flat_collapsed_fused = jax.jit(
            flat_collapsed_fused, donate_argnums=(0,)
        )
        # guberlint: shapes same pin/state contract as _flat_collapsed_fused (split compute half)
        self._flat_collapsed_compute = jax.jit(flat_collapsed_compute)

    # ------------------------------------------------------------------

    def shard_of(self, key: str) -> int:
        return fnv1a_64(key.encode()) % self.n_shards

    def _apply_shard_clears(self, clears: List[List[int]]) -> None:
        """Eviction clears, one padded [n_shards, csize] scatter.
        `clears[sh]` lists slots to scrub on shard sh."""
        n_clear = max((len(c) for c in clears), default=0)
        if not n_clear:
            return
        cap = self.shard_capacity
        csize = _pad_size(n_clear, floor=16)
        c = np.tile(
            np.arange(cap, cap + csize, dtype=_I64).astype(_I32),
            (self.n_shards, 1),
        )
        for sh in range(self.n_shards):
            c[sh, : len(clears[sh])] = clears[sh]
        self._state = self._state._replace(
            meta=self._clear_step(self._state.meta, jnp.asarray(c))
        )
        self.dispatches_total += 1

    def _apply_shard_restores(self, restores: List[List[tuple]]) -> None:
        """Hydrate store-provided bucket values into fresh slots on
        every shard: ONE sharded load scatter (padded to the widest
        shard's restore count).  reference: algorithms.go:46-54."""
        from gubernator_tpu.core.engine import build_restore_record
        from gubernator_tpu.ops.bucket_kernel import SlotRecord

        n_sh = self.n_shards
        cap = self.shard_capacity
        size = _pad_size(max(len(r) for r in restores), floor=16)
        cols: Dict[str, List[np.ndarray]] = {}
        for sh in range(n_sh):
            rec = build_restore_record(restores[sh], cap, size=size)
            for name, arr in rec.items():
                cols.setdefault(name, []).append(arr)
        rec_stacked = SlotRecord(
            **{
                name: jnp.asarray(np.stack(arrs))
                for name, arrs in cols.items()
            }
        )
        self._state = self._load_step(self._state, rec_stacked)
        self.dispatches_total += 1

    def get_rate_limits(
        self, requests: Sequence[RateLimitReq], now_ms: Optional[int] = None
    ) -> List[RateLimitResp]:
        if now_ms is None:
            now_ms = self.clock.now_ms()
        n = len(requests)
        if n == 0:
            return []
        responses: List[Optional[RateLimitResp]] = [None] * n
        now_dt = None

        greg_dur = np.zeros(n, dtype=_I64)
        greg_exp = np.zeros(n, dtype=_I64)
        valid: List[int] = []
        for i, r in enumerate(requests):
            if int(r.behavior) & _GREG:
                if now_dt is None:
                    # Same time-source invariant as core.engine: civil
                    # time derives from now_ms, never a second read.
                    now_dt = dt_from_ms(now_ms)
                try:
                    greg_dur[i] = gregorian_duration(now_dt, r.duration)
                    greg_exp[i] = gregorian_expiration(now_dt, r.duration)
                except GregorianError as e:
                    responses[i] = RateLimitResp(error=str(e))
                    continue
            valid.append(i)

        with self._lock:
            self._apply(requests, valid, greg_dur, greg_exp, now_ms, responses)
            self.requests_total += n
            self.batches_total += 1
        return responses  # type: ignore[return-value]

    def _apply(
        self,
        requests: Sequence[RateLimitReq],
        valid: List[int],
        greg_dur: np.ndarray,
        greg_exp: np.ndarray,
        now_ms: int,
        responses: List[Optional[RateLimitResp]],
    ) -> None:
        if not valid:
            return
        n_sh = self.n_shards
        # Route + intern + schedule rounds (per shard).
        seqs: List[Dict[int, int]] = [dict() for _ in range(n_sh)]
        rounds: Dict[int, List[List[Tuple[int, int]]]] = {}
        clear_rounds: Dict[int, List[List[int]]] = {}
        restore_rounds: Dict[int, List[List[tuple]]] = {}
        slot_of: Dict[int, Tuple[int, int]] = {}
        for i in valid:
            key = requests[i].hash_key()
            sh = self.shard_of(key)
            is_new = self.store is not None and not self.tables[sh].contains(key)
            evicted: List[int] = []
            slot = self.tables[sh].intern(key, now_ms, evicted)
            for es in evicted:
                k = seqs[sh].get(es, 0)
                clear_rounds.setdefault(k, [[] for _ in range(n_sh)])[sh].append(es)
            k = seqs[sh].get(slot, 0)
            seqs[sh][slot] = k + 1
            rounds.setdefault(k, [[] for _ in range(n_sh)])[sh].append((i, slot))
            slot_of[i] = (sh, slot)
            if is_new:
                # Read-through (reference: algorithms.go:46-54).
                item = self.store.get(requests[i])
                if item is not None and item.value is not None:
                    restore_rounds.setdefault(k, [[] for _ in range(n_sh)])[
                        sh
                    ].append((slot, item))

        from gubernator_tpu.utils.tracing import span

        expire_of: Dict[int, int] = {}
        # guberlint: ok drift — sharded twin of engine.py's
        # engine.batch site; same stage name keeps the tracing
        # oracle backend-agnostic (tests/test_tracing.py)
        with span("engine.batch", batch=len(valid), rounds=len(rounds)):
            if (
                self.store is None
                and len(rounds) > 1
                and self._collapse_dataclass_sharded(
                    requests, valid, rounds, clear_rounds,
                    greg_dur, greg_exp, now_ms, responses,
                )
            ):
                return
            for k in sorted(set(rounds) | set(clear_rounds)):
                members = rounds.get(k, [[] for _ in range(n_sh)])
                clears = clear_rounds.get(k, [[] for _ in range(n_sh)])
                restores = restore_rounds.get(k)
                # Chunk wide rounds to bound compiled shapes.
                offset = 0
                while True:
                    chunk = [m[offset : offset + self.max_kernel_width] for m in members]
                    if not any(chunk) and offset > 0:
                        break
                    # guberlint: ok drift — sharded twin of
                    # engine.py's engine.round site
                    with span(
                        "engine.round",
                        round=k,
                        width=max(len(c) for c in chunk),
                    ):
                        self._run_round(
                            chunk,
                            clears if offset == 0 else [[] for _ in range(n_sh)],
                            greg_dur,
                            greg_exp,
                            now_ms,
                            requests,
                            responses,
                            restores=restores if offset == 0 else None,
                            expire_of=expire_of,
                        )
                    self.rounds_total += 1
                    offset += self.max_kernel_width
                    if all(offset >= len(m) for m in members):
                        break

        if self.store is not None:
            from gubernator_tpu.core.engine import write_through_store

            write_through_store(
                self.store, requests, valid, greg_dur, now_ms, responses,
                expire_of,
            )

    def _run_round(
        self,
        members: List[List[Tuple[int, int]]],
        clears: List[List[int]],
        greg_dur: np.ndarray,
        greg_exp: np.ndarray,
        now_ms: int,
        requests: Sequence[RateLimitReq],
        responses: List[Optional[RateLimitResp]],
        restores: Optional[List[List[tuple]]] = None,
        expire_of: Optional[Dict[int, int]] = None,
    ) -> None:
        from gubernator_tpu.ops.bucket_kernel import (
            PACKED_IN_ROWS,
            pack_batch_host,
            unpack_out_host,
        )

        n_sh = self.n_shards
        cap = self.shard_capacity
        width = _pad_size(max((len(m) for m in members), default=1))

        # Eviction clears run as a separate sharded scatter (own shape
        # ladder, independent of the apply step's batch width).
        self._apply_shard_clears(clears)
        if restores is not None and any(restores):
            self._apply_shard_restores(restores)

        # One packed [n_sh, 16, width] buffer, host-presorted per shard
        # -- the same 3-op program as the columnar path (PERF.md sec 4);
        # the old per-column transfers paid the per-op dispatch floor
        # 10x per round.
        buf = np.zeros((n_sh, PACKED_IN_ROWS, width), dtype=_I32)
        order_of: List[np.ndarray] = []
        limits_of: List[np.ndarray] = []
        host_expire: List[Tuple[List[int], List[int]]] = [
            ([], []) for _ in range(n_sh)
        ]  # per shard: (slots, expires)
        empty64 = np.empty(0, dtype=_I64)
        for sh in range(n_sh):
            m = len(members[sh])
            if m == 0:
                pack_batch_host(
                    width, now_ms, cap, np.empty(0, dtype=_I32),
                    empty64, empty64, empty64, empty64, empty64, empty64,
                    empty64, empty64, out=buf[sh],
                )
                order_of.append(np.empty(0, dtype=np.int64))
                limits_of.append(empty64)
                continue
            c_slot = np.empty(m, dtype=_I32)
            c_algo = np.empty(m, dtype=_I32)
            c_beh = np.empty(m, dtype=_I32)
            c_hits = np.empty(m, dtype=_I64)
            c_limit = np.empty(m, dtype=_I64)
            c_dur = np.empty(m, dtype=_I64)
            c_burst = np.empty(m, dtype=_I64)
            c_gdur = np.empty(m, dtype=_I64)
            c_gexp = np.empty(m, dtype=_I64)
            for lane, (i, slot) in enumerate(members[sh]):
                r = requests[i]
                c_slot[lane] = slot
                c_algo[lane] = int(r.algorithm)
                beh = int(r.behavior)
                c_beh[lane] = beh
                c_hits[lane] = r.hits
                c_limit[lane] = r.limit
                c_dur[lane] = r.duration
                c_burst[lane] = r.burst
                c_gdur[lane] = greg_dur[i]
                c_gexp[lane] = greg_exp[i]
                exp = (
                    greg_exp[i]
                    if beh & _GREG
                    else now_ms + r.duration
                )
                host_expire[sh][0].append(slot)
                host_expire[sh][1].append(exp)
                if expire_of is not None:
                    expire_of[i] = int(exp)
            sort_idx = np.argsort(c_slot, kind="stable")
            pack_batch_host(
                width, now_ms, cap,
                np.ascontiguousarray(c_slot[sort_idx]),
                c_algo[sort_idx], c_beh[sort_idx], c_hits[sort_idx],
                c_limit[sort_idx], c_dur[sort_idx], c_burst[sort_idx],
                c_gdur[sort_idx], c_gexp[sort_idx],
                out=buf[sh],
            )
            order_of.append(sort_idx)
            limits_of.append(c_limit)

        import time as _time

        t0 = _time.monotonic()
        pin = jnp.asarray(buf)
        if self._fused:
            self._state, pout = self._packed_fused(self._state, pin)
            self.dispatches_total += 1
        else:
            slot_dev, vals, pout = self._packed_compute(self._state, pin)
            self._state = self._step_scatter(self._state, slot_dev, vals)
            self.dispatches_total += 2
        self.round_duration.observe(_time.monotonic() - t0)

        arr = self.readback.register(pout).fetch()
        for sh in range(n_sh):
            mm = len(members[sh])
            if mm == 0:
                continue
            o_status, o_rem, o_reset = unpack_out_host(arr[sh], mm)
            sort_idx = order_of[sh]
            c_limit = limits_of[sh]
            over = 0
            for pos in range(mm):
                sj = int(sort_idx[pos])
                i = members[sh][sj][0]
                st = int(o_status[pos])
                if st == _OVER_I:
                    over += 1
                responses[i] = RateLimitResp(
                    status=_STATUS_OF[st],
                    limit=int(c_limit[sj]),
                    remaining=int(o_rem[pos]),
                    reset_time=int(o_reset[pos]),
                )
            self.over_limit_total += over
        for sh, (e_slots, e_exps) in enumerate(host_expire):
            if e_slots:
                self.tables[sh].set_expiry(
                    np.asarray(e_slots, dtype=_I32), np.asarray(e_exps, dtype=_I64)
                )

    SWEEP_WINDOW = 1 << 17  # see DecisionEngine.SWEEP_WINDOW

    def sweep(
        self, now_ms: Optional[int] = None, max_windows: Optional[int] = None
    ) -> int:
        """Reclaim slots of expired buckets on every shard; returns the
        number freed (sharded counterpart of DecisionEngine.sweep).

        Windowed device-side compaction along the per-shard capacity
        axis: host transfer per window is one count vector [n_shards]
        plus only the freed indices (VERDICT r1 item 4)."""
        from gubernator_tpu.ops.expiry import windowed_sweep

        if now_ms is None:
            now_ms = self.clock.now_ms()

        def release(order, counts, start) -> int:
            counts_np = np.asarray(counts)
            total = 0
            for sh in np.nonzero(counts_np)[0]:
                c = int(counts_np[sh])
                slots = np.asarray(order[sh, :c]).astype(np.int64) + start
                self.tables[sh].release_slots(slots)
                total += c
            return total

        with self._lock:
            return windowed_sweep(
                self, self.shard_capacity, now_ms, max_windows, release
            )

    def warmup(self, max_width: int = 1024) -> None:
        """Pre-compile the sharded step for padded widths up to
        `max_width` per shard and the clear ladder (see
        DecisionEngine.warmup).  Keys are picked so each shard gets
        exactly `width` of them — hashing arbitrary keys would leave
        the per-shard count fluctuating around `width` and compile the
        wrong padded widths."""
        saved = (
            self.requests_total,
            self.batches_total,
            self.rounds_total,
            self.dispatches_total,
            [(t.hits, t.misses) for t in self.tables],
        )
        # Warmup traffic must not reach a write-through Store (it would
        # persist junk __warmup__ keys and pay external round-trips).
        saved_store, self.store = self.store, None
        try:
            # Pre-assign keys per shard by rejection sampling once, at the
            # largest width; smaller widths use prefixes.
            per_shard: List[List[str]] = [[] for _ in range(self.n_shards)]
            i = 0
            while any(len(ks) < max_width for ks in per_shard):
                req = RateLimitReq(name="__warmup__", unique_key=f"{i}")
                sh = self.shard_of(req.hash_key())
                if len(per_shard[sh]) < max_width:
                    per_shard[sh].append(req.unique_key)
                i += 1
            now = self.clock.now_ms()
            width = 64
            while width <= max_width:
                reqs = [
                    RateLimitReq(
                        name="__warmup__",
                        unique_key=k,
                        hits=0,
                        limit=1,
                        duration=1,
                    )
                    for ks in per_shard
                    for k in ks[:width]
                ]
                self.get_rate_limits(reqs, now_ms=now)
                width *= 2
            # Columnar-kernel ladder (the sorted mesh step is a different
            # jitted program than the dataclass-path step; see
            # DecisionEngine.warmup).  Balanced per-shard keys compile the
            # exact [n_shards, width] padded shapes the wire path produces.
            width = 64
            while width <= max_width:
                keys = [
                    f"__warmup___{k}".encode()
                    for ks in per_shard
                    for k in ks[:width]
                ]
                n = len(keys)
                self.apply_columnar(
                    keys,
                    np.zeros(n, dtype=_I32),
                    np.zeros(n, dtype=_I32),
                    np.zeros(n, dtype=_I64),  # hits=0: report-only
                    np.ones(n, dtype=_I64),
                    np.ones(n, dtype=_I64),
                    np.zeros(n, dtype=_I64),
                    now_ms=now,
                )
                width *= 2
            # Duplicate-key ladder: hot-key batches run the per-shard
            # collapsed-segment program, a SEPARATE compile family from
            # the packed step (see DecisionEngine.warmup's
            # b'__warmup__dup' batches) — without it the first hot-key
            # batch on a mesh deployment pays the multi-second XLA
            # compile inside the serving path.  One hot key per shard,
            # reusing the rejection-sampled per-shard keys (the same
            # encoding the columnar ladder above proved routes to each
            # shard), keeps the padded [n_shards, width] shapes
            # identical to serving.
            dup_key = [
                f"__warmup___{ks[0]}".encode() for ks in per_shard
            ]
            width = 64
            while width <= max_width:
                keys = [k for k in dup_key for _ in range(width)]
                n = len(keys)
                self.apply_columnar(
                    keys,
                    np.zeros(n, dtype=_I32),
                    np.zeros(n, dtype=_I32),
                    np.zeros(n, dtype=_I64),
                    np.ones(n, dtype=_I64),
                    np.ones(n, dtype=_I64),
                    np.zeros(n, dtype=_I64),
                    now_ms=now,
                )
                width *= 2
            csize = 16
            cap = self.shard_capacity
            while csize <= max_width:
                dummy = jnp.asarray(
                    np.tile(
                        np.arange(cap, cap + csize, dtype=_I64).astype(_I32),
                        (self.n_shards, 1),
                    )
                )
                self._state = self._state._replace(
                    meta=self._clear_step(self._state.meta, dummy)
                )
                csize *= 2
            # Readback-combiner stack ladder (see DecisionEngine.warmup).
            from gubernator_tpu.ops.bucket_kernel import PACKED_OUT_ROWS

            width = 64
            while width <= max_width:
                self.readback.warmup_stacks(
                    (self.n_shards, PACKED_OUT_ROWS, width), jnp.int32
                )
                width *= 2
            if self._use_psum_merge:
                # psum-merge ladder: the balanced warmup batches above
                # only compile (n_pad, width) keys of the balanced
                # form; real client batches produce ANY pow2 pair with
                # width <= n_pad <= n_shards*width.  Compile the whole
                # universe (<= log(widths) x log(n_shards) programs,
                # each tiny) plus the merged replicated readback
                # stacks, so no serve-time batch pays an XLA compile.
                width = 64
                while width <= max_width:
                    n_pad = width
                    # pow2 bound: non-pow2 mesh sizes still pad the
                    # total batch to the next power of two.
                    while n_pad <= _pad_size(width * self.n_shards):
                        prog = self._merge_prog(n_pad, width)
                        # The dummy pout must carry the SAME sharding
                        # as the real step output (P(keys)) — the jit
                        # cache keys on input shardings, and a host-
                        # committed dummy would warm a program the
                        # serve path never hits.
                        pout = jax.device_put(
                            np.zeros(
                                (self.n_shards, PACKED_OUT_ROWS, width),
                                dtype=np.int32,
                            ),
                            keys_sharding(self.mesh),
                        )
                        pos = np.full(
                            (self.n_shards, width), n_pad, dtype=_I32
                        )
                        np.asarray(prog(pout, jnp.asarray(pos)))
                        self.readback.warmup_stacks(
                            (PACKED_OUT_ROWS, n_pad), jnp.int32
                        )
                        n_pad *= 2
                    width *= 2
            self.sweep(now_ms=now + 2)
            (
                self.requests_total,
                self.batches_total,
                self.rounds_total,
                self.dispatches_total,
                table_stats,
            ) = saved
            for t, (h, m) in zip(self.tables, table_stats):
                if hasattr(t, "discount_stats"):
                    # Native tables re-mirror cumulative C++ counters on
                    # every schedule(); register discounts instead of
                    # restoring attributes (see DecisionEngine.warmup).
                    t.discount_stats(t.hits - h, t.misses - m)
                else:
                    t.hits, t.misses = h, m
        finally:
            # Exception-safety: a failed warmup must not leave
            # persistence disabled (see DecisionEngine.warmup).
            self.store = saved_store

    # ------------------------------------------------------------------
    # Columnar fast path over the mesh — the multi-chip counterpart of
    # DecisionEngine.apply_columnar: vectorized shard routing (one FNV
    # pass), per-shard native scheduling, host presort per shard, ONE
    # shard_map step per round, one packed readback for the whole mesh.

    def apply_columnar(
        self,
        keys,  # List[bytes] | core.engine.PackedKeys
        algo: np.ndarray,
        behavior: np.ndarray,
        hits: np.ndarray,
        limit: np.ndarray,
        duration: np.ndarray,
        burst: np.ndarray,
        now_ms: Optional[int] = None,
        want_async: bool = False,
        route_hashes: Optional[np.ndarray] = None,  # uint64 fnv1a per key
    ):
        if self.store is not None:
            raise RuntimeError(
                "apply_columnar does not support a write-through Store; "
                "use get_rate_limits"
            )
        n = len(keys)
        if now_ms is None:
            now_ms = self.clock.now_ms()
        greg_mask = (behavior & int(Behavior.DURATION_IS_GREGORIAN)) != 0
        if greg_mask.any():
            greg_dur = np.zeros(n, dtype=_I64)
            greg_exp = np.zeros(n, dtype=_I64)
            now_dt = dt_from_ms(now_ms)
            for i in np.nonzero(greg_mask)[0]:
                greg_dur[i] = gregorian_duration(now_dt, int(duration[i]))
                greg_exp[i] = gregorian_expiration(now_dt, int(duration[i]))
        else:
            greg_dur = np.zeros(n, dtype=_I64)
            greg_exp = greg_dur

        from gubernator_tpu.utils.tracing import span

        # guberlint: ok drift — sharded twin of engine.py's
        # engine.columnar site
        with self._lock, span("engine.columnar", batch=n):
            pending = self._apply_columnar_locked(
                keys, algo, behavior, hits, limit, duration, burst,
                greg_dur, greg_exp, greg_mask, now_ms, route_hashes,
            )
            self.requests_total += n
            self.batches_total += 1
        return pending if want_async else pending.get()

    def _apply_columnar_locked(
        self, keys, algo, behavior, hits, limit, duration, burst,
        greg_dur, greg_exp, greg_mask, now_ms, route_hashes=None,
    ):
        from gubernator_tpu.core.engine import PackedKeys

        n_sh = self.n_shards
        cap = self.shard_capacity
        n = len(keys)
        packed = keys if isinstance(keys, PackedKeys) else None
        if self._multi_ok:
            if packed is None:
                # Pack the key list once — the native call needs only
                # (buf, offsets) and computes fnv1a itself.
                packed = PackedKeys.from_list(keys)
                route_hashes = None
            return self._apply_columnar_native(
                packed, algo, behavior, hits, limit, duration, burst,
                greg_dur, greg_exp, greg_mask, now_ms, route_hashes,
            )
        if packed is not None and not all(
            hasattr(t, "schedule_packed") for t in self.tables
        ):
            keys = packed.to_list()
            packed = None

        # 1. Vectorized shard routing: one FNV-1a pass over the batch
        # (or the wire codec's precomputed hashes, when given).
        if route_hashes is not None:
            hashes = np.asarray(route_hashes, dtype=np.uint64)
        else:
            assert packed is None, "PackedKeys requires route_hashes"
            padded, lengths = pack_keys(keys)
            hashes = fnv1a_64_batch(padded, lengths)
        shards = (hashes % np.uint64(n_sh)).astype(np.int64)

        # 2. Per-shard native scheduling.
        shard_idx: List[np.ndarray] = []  # request indices per shard
        shard_slots: List[np.ndarray] = []
        shard_rounds: List[np.ndarray] = []
        clear_by_round: Dict[int, List[List[int]]] = {}
        max_round = 0
        for sh in range(n_sh):
            idx = np.nonzero(shards == sh)[0]
            shard_idx.append(idx)
            if len(idx) == 0:
                shard_slots.append(np.empty(0, dtype=_I32))
                shard_rounds.append(np.empty(0, dtype=_I32))
                continue
            table = self.tables[sh]
            if packed is not None:
                slots, rounds, evicted, evict_rounds = table.schedule_packed(
                    packed.buf, packed.offsets, now_ms,
                    idx=idx.astype(np.int64),
                )
            elif hasattr(table, "schedule"):
                slots, rounds, evicted, evict_rounds = table.schedule(
                    [keys[i] for i in idx], now_ms
                )
            else:
                slots = np.empty(len(idx), dtype=_I32)
                rounds = np.empty(len(idx), dtype=_I32)
                seq: Dict[int, int] = {}
                ev_list: List[int] = []
                ev_rounds: List[int] = []
                for j, i in enumerate(idx):
                    cleared: List[int] = []
                    slot = table.intern(keys[i].decode(), now_ms, cleared)
                    for es in cleared:
                        ev_list.append(es)
                        ev_rounds.append(seq.get(es, 0))
                    k = seq.get(slot, 0)
                    seq[slot] = k + 1
                    slots[j] = slot
                    rounds[j] = k
                evicted = np.asarray(ev_list, dtype=_I32)
                evict_rounds = np.asarray(ev_rounds, dtype=_I32)
            shard_slots.append(slots)
            shard_rounds.append(rounds)
            if len(rounds):
                max_round = max(max_round, int(rounds.max()))
            for es, k in zip(evicted.tolist(), evict_rounds.tolist()):
                clear_by_round.setdefault(k, [[] for _ in range(n_sh)])[
                    sh
                ].append(es)

        # 2b. Hot keys: collapse uniform duplicate segments per shard
        # into one mesh dispatch per chunk (see core.engine
        # _try_collapse and bucket_kernel's closed form).
        pieces: Optional[List[tuple]] = None
        if max_round > 0:
            pieces = self._try_collapse_sharded(
                shard_idx, shard_slots, clear_by_round,
                algo, behavior, hits, limit, duration, burst,
                greg_dur, greg_exp, now_ms,
            )
        if pieces is not None:
            for sh in range(n_sh):
                if len(shard_idx[sh]):
                    self.tables[sh].set_expiry(
                        shard_slots[sh],
                        np.where(greg_mask, greg_exp, now_ms + duration)
                        .astype(_I64)[shard_idx[sh]],
                    )
            from gubernator_tpu.core.engine import PendingColumnar as _PC

            return _PC(self, pieces, limit, n)

        # 3. One mesh step per round (chunked by max_kernel_width).
        pieces = []
        for k in range(max_round + 1):
            members = [
                shard_idx[sh][shard_rounds[sh] == k] if len(shard_idx[sh]) else shard_idx[sh]
                for sh in range(n_sh)
            ]
            m_slots = [
                shard_slots[sh][shard_rounds[sh] == k]
                if len(shard_slots[sh])
                else shard_slots[sh]
                for sh in range(n_sh)
            ]
            if not any(len(m) for m in members) and k not in clear_by_round:
                continue
            clears = clear_by_round.get(k)
            if clears is not None:
                self._apply_shard_clears(clears)
            offset = 0
            while True:
                chunk_members = [
                    m[offset : offset + self.max_kernel_width] for m in members
                ]
                chunk_slots = [
                    s[offset : offset + self.max_kernel_width] for s in m_slots
                ]
                if offset > 0 and not any(len(m) for m in chunk_members):
                    break
                whole_batch = (
                    max_round == 0
                    and offset == 0
                    and all(
                        len(m) <= self.max_kernel_width for m in members
                    )
                )
                pieces.append(
                    self._dispatch_sorted_chunk(
                        chunk_members, chunk_slots,
                        algo, behavior, hits, limit, duration, burst,
                        greg_dur, greg_exp, now_ms,
                        merge_n=n if whole_batch else None,
                    )
                )
                self.rounds_total += 1
                offset += self.max_kernel_width
                if all(offset >= len(m) for m in members):
                    break

        # 4. TTL mirror, per shard.
        expires = np.where(greg_mask, greg_exp, now_ms + duration).astype(_I64)
        for sh in range(n_sh):
            if len(shard_idx[sh]):
                self.tables[sh].set_expiry(
                    shard_slots[sh], expires[shard_idx[sh]]
                )

        from gubernator_tpu.core.engine import PendingColumnar

        return PendingColumnar(self, pieces, limit, n)

    def _apply_columnar_native(
        self, packed, algo, behavior, hits, limit, duration, burst,
        greg_dur, greg_exp, greg_mask, now_ms, route_hashes,
    ):
        """The whole host tier in ONE FFI call (git_multi_schedule):
        shard routing, per-table interning/LRU/eviction, round
        assignment, TTL mirror writes, and the shard-grouped
        (slot, round)-sorted dispatch order.  Replaces the per-shard
        Python loop of nonzero/schedule/set_expiry/argsort calls —
        the serialized host work VERDICT r4 weak #3 measured at ~5ms
        per 8-shard batch on a one-core host."""
        from gubernator_tpu.core.engine import PendingColumnar
        from gubernator_tpu.core.native import multi_schedule

        n_sh = self.n_shards
        n = len(packed.offsets) - 1
        expires = np.where(
            greg_mask, greg_exp, np.int64(now_ms) + duration
        ).astype(_I64)
        (max_round, _shard, slots, rounds, order, counts,
         evicted, evict_shard, evict_rounds) = multi_schedule(
            self.tables, packed.buf, packed.offsets, route_hashes,
            now_ms, expires,
        )
        flat = self._single_program and self._flat_ok
        if flat:
            # Globalize slots: shard*cap + slot.  The concatenated
            # order array is then globally slot-sorted (global slot is
            # monotone in (shard, slot)), so the whole batch dispatches
            # as ONE flat program — no per-shard padded blocks.
            gslots = (
                slots.astype(np.int64)
                + _shard.astype(np.int64) * self.shard_capacity
            ).astype(_I32)
            segs = [order]
            seg_slots = gslots
        else:
            bounds = np.zeros(n_sh + 1, dtype=np.int64)
            np.cumsum(counts, out=bounds[1:])
            segs = [order[bounds[sh]:bounds[sh + 1]] for sh in range(n_sh)]
            seg_slots = slots
        clear_by_round: Dict[int, List[List[int]]] = {}
        for s, sh, k in zip(
            evicted.tolist(), evict_shard.tolist(), evict_rounds.tolist()
        ):
            clear_by_round.setdefault(k, [[] for _ in range(n_sh)])[
                sh
            ].append(s)

        if max_round > 0:
            per_shard = [
                (seg, seg_slots[seg]) if len(seg) else None for seg in segs
            ]
            pieces = self._collapse_presorted(
                per_shard, clear_by_round, algo, behavior, hits, limit,
                duration, burst, greg_dur, greg_exp, now_ms, flat=flat,
            )
            if pieces is not None:
                return PendingColumnar(self, pieces, limit, n)

        pieces = []
        for k in range(max_round + 1):
            if max_round == 0:
                members = segs
            else:
                # Round filtering preserves the per-shard slot sort.
                members = [
                    seg[rounds[seg] == k] if len(seg) else seg
                    for seg in segs
                ]
            if not any(len(m) for m in members) and k not in clear_by_round:
                continue
            clears = clear_by_round.get(k)
            if clears is not None:
                self._apply_shard_clears(clears)
            m_slots = [seg_slots[m] for m in members]
            offset = 0
            while True:
                chunk_members = [
                    m[offset : offset + self.max_kernel_width]
                    for m in members
                ]
                chunk_slots = [
                    s[offset : offset + self.max_kernel_width]
                    for s in m_slots
                ]
                if offset > 0 and not any(len(m) for m in chunk_members):
                    break
                whole_batch = (
                    max_round == 0
                    and offset == 0
                    and all(
                        len(m) <= self.max_kernel_width for m in members
                    )
                )
                pieces.append(
                    self._dispatch_sorted_chunk(
                        chunk_members, chunk_slots,
                        algo, behavior, hits, limit, duration, burst,
                        greg_dur, greg_exp, now_ms, presorted=True,
                        flat=flat,
                        merge_n=n if whole_batch else None,
                    )
                )
                self.rounds_total += 1
                offset += self.max_kernel_width
                if all(offset >= len(m) for m in members):
                    break
        return PendingColumnar(self, pieces, limit, n)

    def _collapse_dataclass_sharded(
        self,
        requests: Sequence[RateLimitReq],
        valid: List[int],
        rounds: Dict[int, List[List[Tuple[int, int]]]],
        clear_rounds: Dict[int, List[List[int]]],
        greg_dur: np.ndarray,
        greg_exp: np.ndarray,
        now_ms: int,
        responses: List[Optional[RateLimitResp]],
    ) -> bool:
        """Hot-key batches on the sharded dataclass path: build columns
        once and reuse the sharded collapse.  Returns False for the
        rounds fallback (see core.engine._collapse_dataclass)."""
        from gubernator_tpu.ops.bucket_kernel import unpack_out_host
        from gubernator_tpu.utils.tracing import span

        if any(k > 0 for k in clear_rounds):
            return False
        n_sh = self.n_shards
        nv = len(valid)
        pos_of = {i: j for j, i in enumerate(valid)}
        c_algo = np.empty(nv, dtype=_I32)
        c_beh = np.empty(nv, dtype=_I32)
        c_hits = np.empty(nv, dtype=_I64)
        c_limit = np.empty(nv, dtype=_I64)
        c_dur = np.empty(nv, dtype=_I64)
        c_burst = np.empty(nv, dtype=_I64)
        c_gdur = np.empty(nv, dtype=_I64)
        c_gexp = np.empty(nv, dtype=_I64)
        expire = np.empty(nv, dtype=_I64)
        for j, i in enumerate(valid):
            r = requests[i]
            c_algo[j] = int(r.algorithm)
            beh = int(r.behavior)
            c_beh[j] = beh
            c_hits[j] = r.hits
            c_limit[j] = r.limit
            c_dur[j] = r.duration
            c_burst[j] = r.burst
            c_gdur[j] = greg_dur[i]
            c_gexp[j] = greg_exp[i]
            expire[j] = greg_exp[i] if beh & _GREG else now_ms + r.duration

        # Rebuild per-shard (column positions, slots) in arrival order.
        shard_idx: List[np.ndarray] = []
        shard_slots: List[np.ndarray] = []
        per_shard: List[List[Tuple[int, int]]] = [[] for _ in range(n_sh)]
        for k in sorted(rounds):
            for sh in range(n_sh):
                per_shard[sh].extend(rounds[k][sh])
        for sh in range(n_sh):
            # Arrival order within a key is the ROUND order (k ascending
            # per slot); restore global arrival order by request index.
            items = sorted(per_shard[sh], key=lambda t: pos_of[t[0]])
            shard_idx.append(
                np.asarray([pos_of[i] for i, _ in items], dtype=np.int64)
            )
            shard_slots.append(
                np.asarray([s for _, s in items], dtype=_I32)
            )

        # guberlint: ok drift — sharded twin of engine.py's
        # engine.collapsed site
        with span("engine.collapsed", width=nv):
            pieces = self._try_collapse_sharded(
                shard_idx, shard_slots, clear_rounds,
                c_algo, c_beh, c_hits, c_limit, c_dur, c_burst,
                c_gdur, c_gexp, now_ms,
            )
        if pieces is None:
            return False
        over = 0
        for pout, dst_rows, chunk_m, _width in pieces:
            arr = pout.fetch()
            for sh in range(n_sh):
                mm = chunk_m[sh]
                if mm == 0:
                    continue
                st, rem, rst = unpack_out_host(arr[sh], mm)
                for p, j in enumerate(dst_rows[sh].tolist()):
                    i = valid[j]
                    s = int(st[p])
                    if s == _OVER_I:
                        over += 1
                    responses[i] = RateLimitResp(
                        status=_STATUS_OF[s],
                        limit=int(c_limit[j]),
                        remaining=int(rem[p]),
                        reset_time=int(rst[p]),
                    )
        self.over_limit_total += over
        for sh in range(n_sh):
            if len(shard_idx[sh]):
                self.tables[sh].set_expiry(
                    shard_slots[sh], expire[shard_idx[sh]]
                )
        return True

    def _try_collapse_sharded(
        self, shard_idx, shard_slots, clear_by_round,
        algo, behavior, hits, limit, duration, burst,
        greg_dur, greg_exp, now_ms,
    ) -> Optional[List[tuple]]:
        """Per-shard duplicate-segment collapse; returns pieces or None
        for the rounds fallback (same preconditions as the single-device
        engine's _try_collapse)."""
        per_shard: List[Optional[tuple]] = []
        for sh in range(self.n_shards):
            idx = shard_idx[sh]
            if len(idx) == 0:
                per_shard.append(None)
                continue
            order = np.argsort(shard_slots[sh], kind="stable")
            per_shard.append((idx[order], shard_slots[sh][order]))
        return self._collapse_presorted(
            per_shard, clear_by_round, algo, behavior, hits, limit,
            duration, burst, greg_dur, greg_exp, now_ms,
        )

    def _collapse_presorted(
        self, per_shard, clear_by_round,
        algo, behavior, hits, limit, duration, burst,
        greg_dur, greg_exp, now_ms, flat=False,
    ) -> Optional[List[tuple]]:
        """Collapse over per-shard (src, s_slots) pairs already sorted
        by (slot, arrival) — the native multi_schedule order, or the
        argsort in _try_collapse_sharded.  flat=True: one pseudo-shard
        of globalized slots (see _dispatch_sorted_chunk)."""
        from gubernator_tpu.ops.bucket_kernel import (
            COLLAPSED_IN_ROWS,
            pack_collapsed_host,
        )
        from gubernator_tpu.types import Algorithm

        if any(k > 0 for k in clear_by_round):
            return None  # mid-batch slot reuse
        n_sh = 1 if flat else self.n_shards
        cap = self.capacity if flat else self.shard_capacity
        cols = (algo, behavior, hits, limit, duration, burst,
                greg_dur, greg_exp)
        rst_bit = int(Behavior.RESET_REMAINING)
        leaky = int(Algorithm.LEAKY_BUCKET)

        for p in per_shard:
            if p is None:
                continue
            src, s_slots = p
            uniq, seg_start, counts = np.unique(
                s_slots, return_index=True, return_counts=True
            )
            seg_of = np.repeat(np.arange(len(uniq), dtype=np.int64), counts)
            dup = counts[seg_of] > 1
            for col in cols:
                cs = col[src]
                if not np.array_equal(
                    cs[dup], cs[seg_start][seg_of][dup]
                ):
                    return None
            beh_s = behavior[src]
            if bool((((beh_s & rst_bit) != 0) & dup).any()):
                return None
            if bool(
                (((algo[src] == leaky) & (hits[src] < 0)) & dup).any()
            ):
                return None

        clears = clear_by_round.get(0)
        if clears is not None:
            self._apply_shard_clears(clears)

        max_lanes = max(
            (len(p[0]) for p in per_shard if p is not None), default=0
        )
        pieces: List[tuple] = []
        empty64 = np.empty(0, dtype=_I64)
        for lo in range(0, max_lanes, self.max_kernel_width):
            chunk_m = [
                min(max(len(p[0]) - lo, 0), self.max_kernel_width)
                if p is not None
                else 0
                for p in per_shard
            ]
            width = _pad_size(max(chunk_m))
            buf = np.zeros((n_sh, COLLAPSED_IN_ROWS, width), dtype=_I32)
            dst_rows: List[np.ndarray] = []
            for sh in range(n_sh):
                m = chunk_m[sh]
                if m == 0:
                    pack_collapsed_host(
                        width, now_ms, cap, np.empty(0, dtype=_I32),
                        empty64,
                        (empty64,) * 8,
                        np.empty(0, dtype=_I32), np.empty(0, dtype=_I32),
                        out=buf[sh],
                    )
                    dst_rows.append(np.empty(0, dtype=np.int64))
                    continue
                src, s_slots = per_shard[sh]
                c_src = src[lo : lo + m]
                c_slots = s_slots[lo : lo + m]
                c_uniq, c_start, c_counts = np.unique(
                    c_slots, return_index=True, return_counts=True
                )
                c_seg_of = np.repeat(
                    np.arange(len(c_uniq), dtype=np.int64), c_counts
                )
                c_pos = np.arange(m, dtype=np.int64) - c_start[c_seg_of]
                pack_collapsed_host(
                    width, now_ms, cap,
                    np.ascontiguousarray(c_uniq, dtype=_I32),
                    c_counts.astype(np.int64),
                    tuple(col[c_src][c_start] for col in cols),
                    c_seg_of.astype(_I32),
                    c_pos.astype(_I32),
                    out=buf[sh],
                )
                dst_rows.append(c_src)

            import time as _time

            t0 = _time.monotonic()
            pin = jnp.asarray(buf)
            if flat:
                if self._fused:
                    self._state, pout = self._flat_collapsed_fused(
                        self._state, pin
                    )
                    self.dispatches_total += 1
                else:
                    slot_dev, vals2, pout = self._flat_collapsed_compute(
                        self._state, pin
                    )
                    self._state = self._flat_scatter(
                        self._state, slot_dev, vals2
                    )
                    self.dispatches_total += 2
            elif self._fused:
                self._state, pout = self._collapsed_fused(self._state, pin)
                self.dispatches_total += 1
            else:
                slot_dev, vals2, pout = self._collapsed_compute(
                    self._state, pin
                )
                self._state = self._step_scatter(self._state, slot_dev, vals2)
                self.dispatches_total += 2
            self.round_duration.observe(_time.monotonic() - t0)
            self.rounds_total += 1
            pieces.append(
                (self.readback.register(pout), dst_rows, chunk_m, width)
            )
        return pieces

    def _merge_prog(self, n_pad: int, width: int):
        """Jitted psum column merge: per-shard packed outputs
        [n_shards, PACKED_OUT_ROWS, width] + per-shard request
        positions [n_shards, width] (padding = out-of-range, dropped)
        → ONE replicated request-ordered [PACKED_OUT_ROWS, n_pad]
        buffer.  Each request index appears on exactly one shard, so
        the scatter-then-psum is an exact merge."""
        key = (n_pad, width)
        prog = self._merge_progs.get(key)
        if prog is None:
            from gubernator_tpu.ops.bucket_kernel import PACKED_OUT_ROWS

            pspec = P(KEYS_AXIS)

            def local_merge(pout, pos):
                base = jnp.zeros((PACKED_OUT_ROWS, n_pad), dtype=jnp.int32)
                own = base.at[:, pos[0]].set(pout[0], mode="drop")
                return jax.lax.psum(own, KEYS_AXIS)

            # guberlint: shapes pout [n_shards, PACKED_OUT_ROWS, W], pos [n_shards, W]; n_pad/W pinned by the cache key (pow2 ladders)
            prog = jax.jit(
                _shard_map(
                    local_merge,
                    mesh=self.mesh,
                    in_specs=(pspec, pspec),
                    out_specs=P(),
                )
            )
            self._merge_progs[key] = prog
        return prog

    def _dispatch_sorted_chunk(
        self, members, m_slots, algo, behavior, hits, limit, duration,
        burst, greg_dur, greg_exp, now_ms, presorted=False, flat=False,
        merge_n=None,
    ):
        """Pack one presorted [n_sh, PACKED_IN_ROWS, width] round
        buffer, dispatch the packed mesh step (one h2d + one or two
        kernels + one async d2h for the WHOLE mesh), start the async
        readback.  Returns a PendingColumnar piece:
        (packed, dst_idx rows, m per shard, width).

        flat=True (single-program mode): members is ONE pseudo-shard of
        globalized slots; the buffer is [1, PACKED_IN_ROWS, width] and
        the flat executors reshape state to [capacity] inside jit."""
        from gubernator_tpu.ops.bucket_kernel import (
            PACKED_IN_ROWS,
            pack_batch_host,
        )

        n_sh = 1 if flat else self.n_shards
        cap = self.capacity if flat else self.shard_capacity
        width = _pad_size(max((len(m) for m in members), default=1))

        buf = np.zeros((n_sh, PACKED_IN_ROWS, width), dtype=_I32)
        dst_rows = []
        empty_cols = np.empty(0, dtype=_I64)
        for sh in range(n_sh):
            m = len(members[sh])
            if m == 0:
                dst_rows.append(np.empty(0, dtype=np.int64))
                pack_batch_host(
                    width, now_ms, cap, np.empty(0, dtype=_I32),
                    empty_cols, empty_cols, empty_cols, empty_cols,
                    empty_cols, empty_cols, empty_cols, empty_cols,
                    out=buf[sh],
                )
                continue
            if presorted:
                idx_sorted = members[sh]
                slots_sorted = m_slots[sh]
            else:
                order = np.argsort(m_slots[sh], kind="stable")
                idx_sorted = members[sh][order]
                slots_sorted = m_slots[sh][order]
            pack_batch_host(
                width,
                now_ms,
                cap,
                np.ascontiguousarray(slots_sorted, dtype=_I32),
                algo[idx_sorted],
                behavior[idx_sorted],
                hits[idx_sorted],
                limit[idx_sorted],
                duration[idx_sorted],
                burst[idx_sorted],
                greg_dur[idx_sorted],
                greg_exp[idx_sorted],
                out=buf[sh],
            )
            dst_rows.append(idx_sorted)

        import time as _time

        t0 = _time.monotonic()
        pin = jnp.asarray(buf)
        if flat:
            if self._fused:
                self._state, pout = self._flat_fused(self._state, pin)
                self.dispatches_total += 1
            else:
                slot_dev, vals, pout = self._flat_compute(self._state, pin)
                self._state = self._flat_scatter(self._state, slot_dev, vals)
                self.dispatches_total += 2
        elif self._fused:
            self._state, pout = self._packed_fused(self._state, pin)
            self.dispatches_total += 1
        else:
            slot_dev, vals, pout = self._packed_compute(self._state, pin)
            self._state = self._step_scatter(self._state, slot_dev, vals)
            self.dispatches_total += 2
        if merge_n is not None and self._use_psum_merge and not flat:
            # psum GLOBAL merge: scatter every shard's lanes to their
            # request positions on device and sum across the mesh —
            # one replicated, already-request-ordered readback.
            n_pad = _pad_size(merge_n)
            pos = np.full((n_sh, width), n_pad, dtype=_I32)
            for sh in range(n_sh):
                if len(dst_rows[sh]):
                    pos[sh, : len(dst_rows[sh])] = dst_rows[sh]
            merged = self._merge_prog(n_pad, width)(pout, jnp.asarray(pos))
            self.dispatches_total += 1
            self.round_duration.observe(_time.monotonic() - t0)
            return (
                self.readback.register(merged),
                np.arange(merge_n, dtype=np.int64),
                merge_n,
                n_pad,
            )
        self.round_duration.observe(_time.monotonic() - t0)
        return (
            self.readback.register(pout), dst_rows,
            [len(m) for m in members], width,
        )

    # ------------------------------------------------------------------
    # Bulk persistence (Loader; reference: store.go:69-78).  Load/save
    # happen at startup/shutdown, so both use one full host↔device
    # round trip of the sharded state instead of per-item scatters.

    def load(self, loader) -> int:
        """Restore a CacheItem stream into the sharded state."""
        from gubernator_tpu.store import LeakyBucketItem, TokenBucketItem
        from gubernator_tpu.parallel.mesh import keys_sharding

        from gubernator_tpu.ops.bucket_kernel import (
            pack_state_host,
            unpack_state_host,
        )

        now_ms = self.clock.now_ms()
        with self._lock:
            # Decode the current state into logical columns, apply the
            # stream, re-encode once — bulk startup path, O(state) by
            # design.
            host = unpack_state_host(self._state)
            host = {k: np.array(v) for k, v in host.items()}  # writable
            count = 0
            for item in loader.load():
                v = item.value
                if v is None or not item.key:
                    continue
                sh = self.shard_of(item.key)
                cleared: List[int] = []
                slot = self.tables[sh].intern(item.key, now_ms, cleared)
                for es in cleared:
                    host["occupied"][sh, es] = False
                self.tables[sh].set_expiry(
                    np.asarray([slot], dtype=_I32),
                    np.asarray([item.expire_at], dtype=_I64),
                )
                host["occupied"][sh, slot] = True
                host["algo"][sh, slot] = int(item.algorithm)
                host["limit"][sh, slot] = v.limit
                host["duration"][sh, slot] = v.duration
                host["expire"][sh, slot] = item.expire_at
                host["invalid"][sh, slot] = item.invalid_at
                if isinstance(v, TokenBucketItem):
                    host["status"][sh, slot] = v.status
                    host["remaining"][sh, slot] = v.remaining
                    host["remf_hi"][sh, slot] = 0
                    host["remf_lo"][sh, slot] = 0
                    host["t0"][sh, slot] = v.created_at
                    host["burst"][sh, slot] = 0
                elif isinstance(v, LeakyBucketItem):
                    host["status"][sh, slot] = 0
                    from gubernator_tpu.store import words_from_float

                    w = (
                        v.remaining_words
                        if v.remaining_words is not None
                        else words_from_float(v.remaining)
                    )
                    host["remf_hi"][sh, slot] = w[0]
                    host["remf_lo"][sh, slot] = np.uint32(w[1])
                    host["t0"][sh, slot] = v.updated_at
                    host["burst"][sh, slot] = v.burst
                count += 1
            packed = pack_state_host(host)
            placement = (
                next(iter(self.mesh.devices.flat))
                if self._single_program
                else keys_sharding(self.mesh)
            )
            self._state = BucketState(
                **{
                    f: jax.device_put(a, placement)
                    for f, a in packed.items()
                }
            )
        return count

    def export_items(self):
        """Full-fidelity snapshot as CacheItems (all shards)."""
        from gubernator_tpu.store import CacheItem, LeakyBucketItem, TokenBucketItem
        from gubernator_tpu.types import Algorithm

        with self._lock:
            from gubernator_tpu.ops.bucket_kernel import unpack_state_host

            u = unpack_state_host(self._state)
            occ = u["occupied"]
            algo = u["algo"]
            status = u["status"]
            limit = u["limit"]
            remaining = u["remaining"]
            remf_hi = u["remf_hi"]
            remf_lo = u["remf_lo"]
            duration = u["duration"]
            t0 = u["t0"]
            expire = u["expire"]
            burst = u["burst"]
            invalid = u["invalid"]
            located = [
                (sh, int(sl), self.tables[sh].key_for_slot(int(sl)))
                for sh, sl in zip(*np.nonzero(occ))
            ]
        from gubernator_tpu.store import item_from_record

        for sh, sl, key in located:
            if key is None:
                continue
            yield item_from_record(
                key=key,
                algorithm=int(algo[sh, sl]),
                status=int(status[sh, sl]),
                limit=int(limit[sh, sl]),
                remaining=int(remaining[sh, sl]),
                remf_hi=int(remf_hi[sh, sl]),
                remf_lo=int(remf_lo[sh, sl]),
                duration=int(duration[sh, sl]),
                t0=int(t0[sh, sl]),
                expire_at=int(expire[sh, sl]),
                burst=int(burst[sh, sl]),
                invalid_at=int(invalid[sh, sl]),
            )

    def save(self, loader) -> None:
        loader.save(self.export_items())

    def cache_size(self) -> int:
        return sum(len(t) for t in self.tables)

    def close(self) -> None:
        pass

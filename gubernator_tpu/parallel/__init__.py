"""Device-mesh parallelism: sharded bucket state + key→shard routing.

The reference shards its key space twice: across peers via a
consistent-hash ring (reference: replicated_hash.go) and across local
CPU workers via a linear hash ring (reference: gubernator_pool.go:128-187).
Here the intra-node tier becomes a 1-D `jax.sharding.Mesh` over TPU
chips: bucket state arrays are sharded over the "keys" axis, each
~500µs batch is routed host-side to its owning shard, and one
shard_map'ed kernel call updates every shard in parallel with zero
cross-chip traffic on the decision path (SURVEY.md §2.2); the step ends
with a psum over the mesh so cluster metrics ride ICI.
"""

from gubernator_tpu.parallel.mesh import make_mesh
from gubernator_tpu.parallel.sharded_engine import ShardedDecisionEngine

__all__ = ["make_mesh", "ShardedDecisionEngine"]

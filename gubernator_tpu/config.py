"""Configuration structs + env-driven loading (GUBER_* surface).

reference: config.go — BehaviorConfig (:44-65, defaults :113-123),
library Config (:68-110), DaemonConfig (:169-229), env loading
SetupDaemonConfig (:247-451) with optional KEY=VALUE config file
(fromEnvFile :556-584).
"""

from __future__ import annotations

import os
import socket
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

# ----------------------------------------------------------------------
# Durations are float seconds host-side; the wire/kernels use int ms.

MICROSECOND = 1e-6
MILLISECOND = 1e-3


@dataclass
class BehaviorConfig:
    """Batching / GLOBAL / multi-region knobs.

    reference: config.go:44-65; defaults config.go:113-123 (500µs wait,
    500ms timeout, 1000-item limit for each tier).
    """

    # Peer-forward batching (reference: peer_client.go:380-453).
    batch_timeout: float = 0.5
    batch_wait: float = 500 * MICROSECOND
    batch_limit: int = 1000

    # GLOBAL manager (reference: global.go).
    global_timeout: float = 0.5
    global_sync_wait: float = 500 * MICROSECOND
    global_batch_limit: int = 1000

    # Multi-region manager (reference: multiregion.go; grown into the
    # federation plane of RESILIENCE.md §12).
    multi_region_timeout: float = 0.5
    multi_region_sync_wait: float = 500 * MICROSECOND
    multi_region_batch_limit: int = 1000
    # Total wall budget for one cross-region fan-out barrier, seconds
    # (GUBER_MULTI_REGION_FANOUT_DEADLINE): one slow/dead region must
    # not stall a flush window past this, whatever the per-RPC
    # timeout is.
    multi_region_fanout_deadline: float = 2.0
    # Cross-region deltas that failed to reach a region are re-queued
    # (bound to that region) until this old, seconds; older deltas
    # drop COUNTED (gubernator_multiregion_hits_dropped) — the healed
    # region's buckets have moved on and replaying stale deltas would
    # double-count (GUBER_MULTI_REGION_REQUEUE_AGE; 0 disables
    # re-queueing, restoring the pre-§12 fire-and-forget drop — but
    # still counted).
    multi_region_requeue_age: float = 10.0
    # Per-region retry backoff between failed push rounds — capped
    # exponential with FULL jitter (cluster/health.backoff_delay;
    # GUBER_MULTI_REGION_BACKOFF / _CAP).  Rides the batcher's
    # deferred re-admission, so an open region circuit cannot spin a
    # flush worker.
    multi_region_backoff: float = 0.05
    multi_region_backoff_cap: float = 2.0

    # Load-adaptive batching windows (GUBER_ADAPTIVE_WINDOWS, default
    # on): every *_wait above becomes a CAP — idle batchers flush
    # immediately and the wait grows toward the cap only while batches
    # actually fill (cluster/batch_loop.AdaptiveWait; VERDICT r5 weak
    # #2's stacked-window fix).  Off restores fixed waits (tests that
    # drive syncs manually; operators who want the exact reference
    # cadence).
    adaptive_windows: bool = True

    # ---- peer health plane (cluster/health.py; RESILIENCE.md) -------
    # Consecutive transport failures before a peer's circuit opens
    # (GUBER_CIRCUIT_FAILURES).
    circuit_failures: int = 3
    # Initial circuit open period, seconds; doubles per consecutive
    # re-open up to the cap (GUBER_CIRCUIT_BACKOFF / _CAP).
    circuit_backoff: float = 0.5
    circuit_backoff_cap: float = 30.0
    # Forward retry-loop backoff between owner re-pick attempts —
    # capped exponential with full jitter (GUBER_FORWARD_BACKOFF /
    # _CAP).  The reference's loop re-picked with zero delay.
    forward_backoff: float = 0.01
    forward_backoff_cap: float = 0.25
    # Degraded-mode local answering (GUBER_DEGRADED_LOCAL, default
    # on): when every owner candidate is circuit-open/unreachable,
    # answer from this node's own engine (flagged in response
    # metadata) instead of returning an error string.  Off restores
    # the reference's fail-closed semantics.  Availability costs
    # bounded over-admission: ≤ N_partitions × limit per key
    # (RESILIENCE.md derives the bound).
    degraded_local: bool = True
    # Total wall budget for one GLOBAL fan-out barrier, seconds
    # (GUBER_GLOBAL_FANOUT_DEADLINE): one dead peer must not stall a
    # flush cycle past this, whatever the per-RPC timeout is.
    global_fanout_deadline: float = 2.0
    # GLOBAL hits that failed to reach their owner are re-queued for
    # the next window until this old, seconds; older hits are dropped
    # (counted) — the owner's state has moved on and replaying stale
    # hits would double-count against fresh windows
    # (GUBER_HIT_REQUEUE_AGE; 0 disables re-queueing).
    hit_requeue_age: float = 5.0


@dataclass
class Config:
    """Library-level config for a service instance.

    reference: config.go:68-110 (Config struct); defaults
    SetDefaults config.go:112-147.
    """

    behaviors: BehaviorConfig = field(default_factory=BehaviorConfig)
    # Total bucket slots on this node (reference default 50k cache size,
    # config.go:294; here: slots across the device mesh).
    cache_size: int = 50_000
    # Consistent-hash function for the cluster ring ("fnv1" | "fnv1a").
    # reference: config.go:395-417
    hash_algorithm: str = "fnv1"
    # Picker type: "replicated-hash" (default) | "consistent-hash"
    # (GUBER_PEER_PICKER; reference: config.go:395-417).
    peer_picker: str = "replicated-hash"
    # Virtual ring points per peer for replicated-hash
    # (GUBER_REPLICATED_HASH_REPLICAS; reference default 512).
    picker_replicas: int = 512
    # This node's datacenter (MULTI_REGION routing).
    data_center: str = ""
    # Local peer identity; set by the daemon once listeners are bound.
    instance_id: str = ""
    # grpc.ChannelCredentials for dialing peers (None = plaintext);
    # set by the daemon when TLS is configured.
    peer_credentials: Optional[object] = None
    # Group-commit window for client-facing wire batches (seconds);
    # 0 disables.  Concurrent RPCs inside the window share ONE engine
    # dispatch — the local-tier analog of the peer BatchWait
    # (net/wire_window.py; SURVEY §7.1's batching front-end).
    local_batch_wait: float = 0.0
    # Group-commit cap for the GLOBAL serve route's engine sub-batches
    # (GUBER_GLOBAL_SERVE_WINDOW; 0 disables).  On a GLOBAL node the
    # engine is hit from several directions at once — client serves,
    # peer hit pushes, local miss copies — each paying its own device
    # dispatch.  This window (load-adaptive, like every round-6
    # window: an isolated apply fires immediately) lets concurrent
    # GLOBAL applies share one dispatch, which is what keeps the
    # cluster-tier median flat when the hit pipeline runs hot.
    global_serve_window: float = 0.002
    # Count-min-sketch approximate limiter (Behavior.SKETCH;
    # GUBER_SKETCH_*): window / depth / width of the two-epoch sketch
    # (ops/sketch.py; BASELINE config 5).
    sketch_window_ms: int = 1_000
    sketch_depth: int = 4
    sketch_width: int = 1 << 20
    # Host-tier decision ledger (core/ledger.py; GUBER_LEDGER, default
    # on): sticky over-limit answers + bounded credit leases serve
    # hot-key decisions without a device dispatch.  GUBER_LEDGER=0
    # restores the dispatch-per-decision path exactly.
    ledger: bool = True
    # Per-key lease credit budget — also the per-key over-admission
    # bound when an external racer reads the device before the lease
    # settles (GUBER_LEDGER_LEASE).
    ledger_lease: int = 512
    # Lease lifetime (seconds); expiry settles consumed credits back to
    # the device off the critical path (GUBER_LEDGER_LEASE_TTL).
    ledger_lease_ttl: float = 0.2
    # Hits within a 1s window before a key is granted a lease
    # (GUBER_LEDGER_HOT_THRESHOLD).
    ledger_hot_threshold: int = 8
    # Ledger entry LRU capacity (GUBER_LEDGER_KEYS).
    ledger_keys: int = 65536
    # Background settle flush period, seconds; 0 = manual/tests only
    # (GUBER_LEDGER_SETTLE_INTERVAL).
    ledger_settle_interval: float = 0.05


# ----------------------------------------------------------------------
# Canonical GUBER_* env-surface index (guberlint's drift pass pins it:
# every knob read ANYWHERE must appear in this file and in the README
# knob table).  Daemon knobs load in setup_daemon_config below; the
# debug/infra knobs here are read at their point of use — they gate
# process bootstrap (before a DaemonConfig exists) or test-only builds,
# so hauling them through the dataclass would be ceremony.  Each entry
# names its read site.

KNOWN_ENV_KNOBS = (
    # Engine / device plane.
    "GUBER_PLATFORM",         # daemon.py: jax platform override (cpu/tpu)
    "GUBER_BACKEND_PROBE",    # daemon.py: probe the backend in a subprocess
    "GUBER_BACKEND_PROBE_TIMEOUT",  # daemon.py: probe wall budget, seconds
    "GUBER_PUMP",             # core/engine.py: step-pump mode override
    "GUBER_PUMP_SCAN",        # core/pump.py: fused-scan round loop toggle
    "GUBER_FUSED",            # core/engine.py: fused-step impl select
                              # (auto|pallas|interpret|xla|split)
    "GUBER_WINDOW_DEPTH",     # core/pump.py + core/readback.py:
                              # double-buffered h2d/d2h window depth
    "GUBER_PSUM_MERGE",       # parallel/sharded_engine.py: psum column
                              # merge over the mesh (0 disables)
    "GUBER_MULTI_THREADS",    # core/native.py: native scheduler threads
    "GUBER_SHARDS_SINGLE_PROGRAM",  # parallel/sharded_engine.py: one
                              # pjit program across shards vs per-shard
    # Paged device bucket state (core/paging.py; PERF.md §30).
    "GUBER_PAGED",            # config.env_paged → core/engine.py: page
                              # the bucket state behind a page table
                              # (0 keeps the dense plane, the A/B arm)
    "GUBER_PAGE_SIZE",        # config.env_page_size → core/engine.py:
                              # bucket rows per device page (pow2 ≥ 16)
    "GUBER_PAGED_RESIDENT",   # config.env_paged_resident →
                              # core/engine.py: resident device frames
                              # (pages); 0 = every page resident
    # Build / test infra.
    "GUBER_NATIVE_SAN",       # core/native_build.py: TSan/ASan build tag
    # Process bootstrap (read before config loads).
    "GUBER_LOG_LEVEL",        # utils/logging_setup.py
    "GUBER_LOG_FORMAT",       # utils/logging_setup.py ("json" | "text")
    "GUBER_TRACING",          # utils/tracing.py ("memory" recorder)
    # Observability plane (OBSERVABILITY.md) — read at point of use.
    "GUBER_TRACE_TAIL_FACTOR",   # utils/flight_recorder.py: p99 multiple
    "GUBER_TRACE_TAIL_MIN_MS",   # utils/flight_recorder.py: floor, ms
    "GUBER_TRACE_TAIL_CAP",      # utils/flight_recorder.py: ring size
    "GUBER_HOTKEYS",             # utils/hotkeys.py: top-K sketch on/off
    "GUBER_HOTKEYS_K",           # utils/hotkeys.py: counter capacity
    "GUBER_HOTKEYS_WINDOW",      # utils/hotkeys.py: rate decay window, s
    "GUBER_NATIVE_EVENTS",       # net/h2_fast.py: C event ring on/off
    "GUBER_NATIVE_EVENTS_CAP",   # net/h2_fast.py: ring record capacity
    "GUBER_NATIVE_EVENTS_INTERVAL",  # utils/native_events.py: drain period
    # Fleet observability plane (obs/; OBSERVABILITY.md §§9-10).
    "GUBER_OBS",                 # daemon.py: fleet rollup + watchdog on/off
    "GUBER_OBS_RPC_TIMEOUT",     # obs/fleet.py: per-peer ObsSnapshot timeout
    "GUBER_OBS_FANOUT_DEADLINE",  # obs/fleet.py: rollup fan-out barrier
    "GUBER_SLO_INTERVAL",        # obs/slo.py: watchdog tick period (0=off)
    "GUBER_SLO_FLEET",           # obs/slo.py: ticks scrape the whole fleet
    "GUBER_SLO_FAST_WINDOWS",    # obs/slo.py: fast burn pair "short,long" s
    "GUBER_SLO_SLOW_WINDOWS",    # obs/slo.py: slow burn pair "short,long" s
    "GUBER_SLO_WATCH_KEYS",      # obs/slo.py: admission-bound watched keys
    "GUBER_METRICS_EXEMPLARS",   # utils/metrics.py: bucket trace exemplars
    # Event front (net/h2_fast.py; h2_server.cpp reactors, PERF §26).
    "GUBER_H2_EVENT_FRONT",      # net/h2_fast.py: epoll reactor front on/off
    "GUBER_H2_REACTORS",         # net/h2_fast.py: reactor threads (0=ncpu-1)
    "GUBER_H2_IDLE_TIMEOUT",     # net/h2_fast.py: idle-conn reap (GOAWAY)
    # Columnar feeder plane (net/h2_fast.py; columnar_feeder.cpp).
    "GUBER_NATIVE_FEEDER",       # net/h2_fast.py: C columnar feeder on/off
    "GUBER_FEEDER_RING_SLOTS",   # net/h2_fast.py: ring window count
    "GUBER_FEEDER_RING_ROWS",    # net/h2_fast.py: rows per ring window
    "GUBER_FEEDER_RING_KEYBYTES",  # net/h2_fast.py: key bytes per window
    "GUBER_RETRY_HINTS",         # net/h2_fast.py: retry_after_ms metadata
                              # on native OVER_LIMIT answers
    # Discovery plane (read by the k8s watcher, not the daemon config).
    "GUBER_K8S_NAMESPACE",    # discovery/kubernetes.py
    "GUBER_K8S_POD_SELECTOR",  # discovery/kubernetes.py
    # Multi-region federation plane (cluster/multiregion.py;
    # RESILIENCE.md §12).  These are daemon knobs — they load in
    # setup_daemon_config below like every BehaviorConfig field — and
    # are ALSO indexed here because they define the cross-region
    # resilience surface operators tune as one unit.
    "GUBER_MULTI_REGION_FANOUT_DEADLINE",  # setup_daemon_config:
                              # cross-region fan-out barrier budget
    "GUBER_MULTI_REGION_REQUEUE_AGE",  # setup_daemon_config: retry
                              # backlog age cap (drops counted past it)
    "GUBER_MULTI_REGION_BACKOFF",  # setup_daemon_config: per-region
                              # retry backoff base (full jitter)
    "GUBER_MULTI_REGION_BACKOFF_CAP",  # setup_daemon_config: per-region
                              # retry backoff ceiling
)


def env_window_depth(default: int = 2) -> int:
    """The GUBER_WINDOW_DEPTH knob, shared by the step pump's h2d
    pre-staging and the readback combiner's d2h window prefetch
    (core/pump.py / core/readback.py) — one parser so the two sides
    cannot drift."""
    try:
        return int(os.environ.get("GUBER_WINDOW_DEPTH", "") or default)
    except ValueError:
        return default


def env_paged() -> bool:
    """GUBER_PAGED: page the device bucket state behind a page table
    with LRU host spill (core/paging.py).  Default off — the dense
    plane is the A/B control arm (PERF.md §30)."""
    return os.environ.get("GUBER_PAGED", "").strip() == "1"


def env_page_size(default: int = 512) -> int:
    """GUBER_PAGE_SIZE: bucket rows per device page.  Must be a power
    of two ≥ 16 (slot→(page,row) splits are shift/mask on the
    translate hot path; the clear/restore scatter floor is 16);
    anything else falls back to the default."""
    try:
        v = int(os.environ.get("GUBER_PAGE_SIZE", "") or default)
    except ValueError:
        return default
    if v < 16 or v & (v - 1):
        return default
    return v


def env_paged_resident(default: int = 0) -> int:
    """GUBER_PAGED_RESIDENT: device frames (resident pages).  0 keeps
    every page resident — paged layout, dense footprint; a smaller
    value is what buys the 10-100x key space over device memory."""
    try:
        return max(0, int(os.environ.get("GUBER_PAGED_RESIDENT", "") or default))
    except ValueError:
        return default


def _env(d: Dict[str, str], key: str, default: str = "") -> str:
    return d.get(key, os.environ.get(key, default)) or default


def _env_int(d: Dict[str, str], key: str, default: int) -> int:
    v = _env(d, key)
    return int(v) if v else default


def _env_float_seconds(d: Dict[str, str], key: str, default: float) -> float:
    """Parse Go-style duration strings ("500us", "30s", "1m") or float
    seconds. reference duration envs like GUBER_BATCH_WAIT."""
    v = _env(d, key)
    if not v:
        return default
    return parse_duration(v)


_DURATION_UNITS = [
    ("ms", MILLISECOND),
    ("us", MICROSECOND),
    ("µs", MICROSECOND),
    ("ns", 1e-9),
    ("s", 1.0),
    ("m", 60.0),
    ("h", 3600.0),
]


def parse_duration(v: str) -> float:
    """Parse a Go duration string into float seconds."""
    v = v.strip()
    try:
        return float(v)
    except ValueError:
        pass
    # Compound forms like "1m30s" parse unit-by-unit.
    total = 0.0
    num = ""
    i = 0
    while i < len(v):
        c = v[i]
        if c.isdigit() or c in ".+-":
            num += c
            i += 1
            continue
        for unit, mult in _DURATION_UNITS:
            if v.startswith(unit, i) and (
                i + len(unit) == len(v) or v[i + len(unit)].isdigit() or v[i + len(unit)] in ".+-"
            ):
                if not num:
                    raise ValueError(f"bad duration {v!r}")
                total += float(num) * mult
                num = ""
                i += len(unit)
                break
        else:
            raise ValueError(f"bad duration {v!r}")
    if num:
        raise ValueError(f"bad duration {v!r}")
    return total


def load_env_file(path: str) -> Dict[str, str]:
    """Read a KEY=VALUE config file (reference: config.go:556-584).

    Lines starting with # and blank lines are ignored; values are also
    exported into os.environ, matching the reference's behavior of
    loading the file into the environment.
    """
    out: Dict[str, str] = {}
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if "=" not in line:
                raise ValueError(f"{path}:{lineno}: expected KEY=VALUE")
            k, _, v = line.partition("=")
            out[k.strip()] = v.strip()
    os.environ.update(out)
    return out


@dataclass
class DaemonConfig:
    """Process-level config. reference: config.go:169-229."""

    grpc_listen_address: str = "localhost:81"
    http_listen_address: str = "localhost:80"
    # Optional plain-HTTP status listener when mTLS is on
    # (reference: daemon.go:279-307).
    http_status_listen_address: str = ""
    advertise_address: str = ""
    cache_size: int = 50_000
    data_center: str = ""
    behaviors: BehaviorConfig = field(default_factory=BehaviorConfig)
    hash_algorithm: str = "fnv1"

    # Peer discovery: "member-list" | "etcd" | "dns" | "k8s" | "none"
    # (reference default member-list, config.go:300).
    peer_discovery_type: str = "none"
    # Static cluster membership for discovery "none"
    # (GUBER_STATIC_PEERS): comma-separated peer gRPC addresses
    # (including this node's advertise address).  The fixed-topology
    # deployment mode — compose files, systemd units, bench clusters —
    # where running a discovery plane would be ceremony.
    static_peers: List[str] = field(default_factory=list)
    # Static seed peers / memberlist known hosts.
    member_list_address: str = ""
    known_hosts: List[str] = field(default_factory=list)
    advertise_port: int = 7946  # reference: config.go:373
    # DNS discovery.
    dns_fqdn: str = ""
    dns_poll_interval: float = 300.0
    # etcd discovery (auth/TLS block — reference: config.go:363-370,
    # 440-496).
    etcd_endpoints: List[str] = field(default_factory=list)
    etcd_key_prefix: str = "/gubernator/peers/"
    etcd_dial_timeout: float = 5.0
    etcd_user: str = ""
    etcd_password: str = ""
    etcd_advertise_address: str = ""  # default: the node advertise addr
    etcd_data_center: str = ""  # default: the node data center
    etcd_tls_ca: str = ""
    etcd_tls_cert: str = ""
    etcd_tls_key: str = ""
    etcd_tls_skip_verify: bool = False

    # Picker selection (see Config.peer_picker / picker_replicas).
    peer_picker: str = "replicated-hash"
    picker_replicas: int = 512

    # gRPC keepalive: close server connections older than this many
    # seconds (0 = never; reference: daemon.go:110-115).
    grpc_max_conn_age_sec: int = 0

    # gRPC server handler threads (GUBER_GRPC_WORKERS).  The engine is
    # a serial device resource, so a handler count far above the CPU
    # count only grows the lock/GIL convoy: excess RPCs queue in the
    # executor (FIFO, cheap) instead of as runnable threads.  The
    # reference sizes its worker pool by NumCPU the same way
    # (gubernator_pool.go:128-149).
    grpc_workers: int = 32

    # Debug logging (GUBER_DEBUG; reference: config.go:275).
    debug: bool = False

    # Approximate limiter (see Config.sketch_*).
    sketch_window_ms: int = 1_000
    sketch_depth: int = 4
    sketch_width: int = 1 << 20

    # Host-tier decision ledger (see Config.ledger_*).
    ledger: bool = True
    ledger_lease: int = 512
    ledger_lease_ttl: float = 0.2
    ledger_hot_threshold: int = 8
    ledger_keys: int = 65536
    ledger_settle_interval: float = 0.05

    # TLS (None = plaintext); see gubernator_tpu.net.tls.
    tls: Optional["object"] = None

    # Device-mesh shape for the sharded engine; None = all local devices.
    device_count: Optional[int] = None

    # Period of the device expiry sweep that reclaims slots of expired
    # buckets (the LRU evicts on pressure regardless; the sweep keeps
    # cache_size metrics honest and slots recycled).  0 disables.
    sweep_interval: float = 30.0
    # Client-facing wire group-commit window (0 = off); see Config.
    local_batch_wait: float = 0.0
    # GLOBAL serve-route group-commit cap (see Config).
    global_serve_window: float = 0.002
    # Native h2 fast front (net/h2_fast.py): "" = disabled;
    # "127.0.0.1:0" binds an ephemeral port.
    h2_fast_address: str = ""
    h2_fast_window: float = 0.002
    # SO_REUSEPORT listener lanes for the fast front (GUBER_H2_LANES);
    # 0 = one lane per CPU.  Accept/framing/decide run on per-lane /
    # per-connection C threads, so lanes are what lets the front scale
    # across cores instead of serializing on one listener.
    h2_lanes: int = 0
    # ---- elastic membership (cluster/membership.py; RESILIENCE §10) -
    # Wall budget for one epoch transition, seconds: a handoff that
    # cannot deliver (target broken/suspect) delays the epoch commit
    # up to this long, then forfeits the undeliverable rows
    # (GUBER_MEMBERSHIP_EPOCH_TIMEOUT).
    membership_epoch_timeout: float = 30.0
    # Bucket rows per TransferBuckets RPC during ownership handoff
    # (GUBER_HANDOFF_WINDOW).
    handoff_window: int = 512
    # Wall budget for a planned-leave drain to ship every held bucket,
    # seconds (GUBER_DRAIN_DEADLINE).  A clean drain reports zero
    # forfeited rows well inside it.
    drain_deadline: float = 30.0

    # ---- hot-key replication plane (cluster/replication.py;
    # RESILIENCE.md §11) ----------------------------------------------
    # Master switch (GUBER_REPLICATION, default on): promote the
    # measured hottest keys to replicated ownership — the owner splits
    # the limit into per-replica PRE-DEBITED credit leases, every
    # replica answers locally, demotion on cooldown.  Off restores
    # consistent-hash-only routing exactly.
    replication: bool = True
    # Observed hits/sec (hotkeys windowed rate) before the owner
    # promotes a key (GUBER_REPL_PROMOTE_RATE).  Demotion arms at half
    # this rate.
    repl_promote_rate: float = 2000.0
    # Seconds a promoted key must stay below the demote rate before it
    # converges back to single-owner (GUBER_REPL_COOLDOWN hysteresis).
    repl_cooldown: float = 10.0
    # Per-replica credit slice per grant — also the per-replica term
    # of the N_replicas × lease over-admission bound
    # (GUBER_REPL_LEASE).
    repl_lease: int = 2048
    # Replica lease lifetime, seconds (GUBER_REPL_LEASE_TTL); the
    # owner refreshes ahead of it, and a broken replica's lease
    # expires into the bound.
    repl_lease_ttl: float = 1.0
    # Promotion/demotion scan period, seconds (GUBER_REPL_INTERVAL).
    repl_interval: float = 0.5
    # Max concurrently replicated keys per owner (GUBER_REPL_MAX_KEYS).
    repl_max_keys: int = 16
    # Replica-count policy (GUBER_REPL_MAX_REPLICAS): cap each hot
    # key's grant fan-out to the N least-loaded local-DC peers (load =
    # in-flight RPCs + queued batch items toward the peer) instead of
    # every peer.  0 = unlimited (every local-DC peer, the pre-policy
    # behavior).  Cuts grant-refresh fan-out on big clusters while the
    # over-admission bound tightens with it (≤ N × lease).
    repl_max_replicas: int = 0

    # Native decision plane (GUBER_NATIVE_LEDGER, default on): delegate
    # the ledger's exact fast path (sticky over-limit + lease drains)
    # into the C front so hot-key RPCs never enter Python.  Only
    # engaged when the decision ledger itself is on and the engine runs
    # the live system clock.
    native_ledger: bool = True

    metric_flags: List[str] = field(default_factory=list)


def setup_daemon_config(
    config_file: Optional[str] = None, env: Optional[Dict[str, str]] = None
) -> DaemonConfig:
    """Build a DaemonConfig from GUBER_* env vars (+ optional file).

    reference: config.go:247-451 (SetupDaemonConfig).
    """
    d: Dict[str, str] = dict(env or {})
    if config_file:
        d.update(load_env_file(config_file))

    behaviors = BehaviorConfig(
        batch_timeout=_env_float_seconds(d, "GUBER_BATCH_TIMEOUT", 0.5),
        batch_wait=_env_float_seconds(d, "GUBER_BATCH_WAIT", 500 * MICROSECOND),
        batch_limit=_env_int(d, "GUBER_BATCH_LIMIT", 1000),
        global_timeout=_env_float_seconds(d, "GUBER_GLOBAL_TIMEOUT", 0.5),
        global_sync_wait=_env_float_seconds(
            d, "GUBER_GLOBAL_SYNC_WAIT", 500 * MICROSECOND
        ),
        global_batch_limit=_env_int(d, "GUBER_GLOBAL_BATCH_LIMIT", 1000),
        multi_region_timeout=_env_float_seconds(d, "GUBER_MULTI_REGION_TIMEOUT", 0.5),
        multi_region_sync_wait=_env_float_seconds(
            d, "GUBER_MULTI_REGION_SYNC_WAIT", 500 * MICROSECOND
        ),
        multi_region_batch_limit=_env_int(d, "GUBER_MULTI_REGION_BATCH_LIMIT", 1000),
        multi_region_fanout_deadline=_env_float_seconds(
            d, "GUBER_MULTI_REGION_FANOUT_DEADLINE", 2.0
        ),
        multi_region_requeue_age=_env_float_seconds(
            d, "GUBER_MULTI_REGION_REQUEUE_AGE", 10.0
        ),
        multi_region_backoff=_env_float_seconds(
            d, "GUBER_MULTI_REGION_BACKOFF", 0.05
        ),
        multi_region_backoff_cap=_env_float_seconds(
            d, "GUBER_MULTI_REGION_BACKOFF_CAP", 2.0
        ),
        adaptive_windows=_env(d, "GUBER_ADAPTIVE_WINDOWS", "1").strip().lower()
        not in ("0", "false", "no", "off"),
        circuit_failures=_env_int(d, "GUBER_CIRCUIT_FAILURES", 3),
        circuit_backoff=_env_float_seconds(d, "GUBER_CIRCUIT_BACKOFF", 0.5),
        circuit_backoff_cap=_env_float_seconds(
            d, "GUBER_CIRCUIT_BACKOFF_CAP", 30.0
        ),
        forward_backoff=_env_float_seconds(
            d, "GUBER_FORWARD_BACKOFF", 0.01
        ),
        forward_backoff_cap=_env_float_seconds(
            d, "GUBER_FORWARD_BACKOFF_CAP", 0.25
        ),
        degraded_local=_env(d, "GUBER_DEGRADED_LOCAL", "1").strip().lower()
        not in ("0", "false", "no", "off"),
        global_fanout_deadline=_env_float_seconds(
            d, "GUBER_GLOBAL_FANOUT_DEADLINE", 2.0
        ),
        hit_requeue_age=_env_float_seconds(d, "GUBER_HIT_REQUEUE_AGE", 5.0),
    )

    peer_picker = _env(d, "GUBER_PEER_PICKER", "replicated-hash")
    # Validate via the single source of truth (cluster.hash_ring).
    from gubernator_tpu.cluster.hash_ring import make_picker

    make_picker(peer_picker, "fnv1")
    # When the picker is selected explicitly, the reference defaults
    # its hash to fnv1a (config.go:403); otherwise fnv1.
    hash_default = "fnv1a" if _env(d, "GUBER_PEER_PICKER") else "fnv1"
    hash_algorithm = _env(d, "GUBER_PEER_PICKER_HASH", hash_default)
    if hash_algorithm not in ("fnv1", "fnv1a"):
        raise ValueError(
            f"GUBER_PEER_PICKER_HASH={hash_algorithm!r}: want fnv1 or fnv1a"
        )
    picker_replicas = _env_int(d, "GUBER_REPLICATED_HASH_REPLICAS", 512)
    discovery = _env(d, "GUBER_PEER_DISCOVERY_TYPE", "none")
    if discovery not in ("none", "member-list", "etcd", "dns", "k8s"):
        raise ValueError(
            f"GUBER_PEER_DISCOVERY_TYPE={discovery!r}: want none, "
            "member-list, etcd, dns or k8s"
        )

    tls = None
    if _env(d, "GUBER_TLS_CA") or _env(d, "GUBER_TLS_CERT") or _env(d, "GUBER_TLS_AUTO"):
        from gubernator_tpu.net.tls import TLSConfig

        tls = TLSConfig(
            ca_file=_env(d, "GUBER_TLS_CA"),
            ca_key_file=_env(d, "GUBER_TLS_CA_KEY"),
            cert_file=_env(d, "GUBER_TLS_CERT"),
            key_file=_env(d, "GUBER_TLS_KEY"),
            auto_tls=_env(d, "GUBER_TLS_AUTO") in ("1", "true", "yes"),
            client_auth=_env(d, "GUBER_TLS_CLIENT_AUTH"),
            client_auth_ca_file=_env(d, "GUBER_TLS_CLIENT_AUTH_CA_CERT"),
            client_auth_cert_file=_env(d, "GUBER_TLS_CLIENT_AUTH_CERT"),
            client_auth_key_file=_env(d, "GUBER_TLS_CLIENT_AUTH_KEY"),
        )

    dc = _env(d, "GUBER_DATA_CENTER")
    device_count = _env_int(d, "GUBER_DEVICE_COUNT", 0) or None

    return DaemonConfig(
        grpc_listen_address=_env(d, "GUBER_GRPC_ADDRESS", "localhost:81"),
        http_listen_address=_env(d, "GUBER_HTTP_ADDRESS", "localhost:80"),
        http_status_listen_address=_env(d, "GUBER_STATUS_HTTP_ADDRESS", ""),
        advertise_address=_env(d, "GUBER_ADVERTISE_ADDRESS", ""),
        cache_size=_env_int(d, "GUBER_CACHE_SIZE", 50_000),
        data_center=dc,
        behaviors=behaviors,
        hash_algorithm=hash_algorithm,
        peer_discovery_type=discovery,
        static_peers=[
            h.strip()
            for h in _env(d, "GUBER_STATIC_PEERS", "").split(",")
            if h.strip()
        ],
        member_list_address=_env(d, "GUBER_MEMBERLIST_ADDRESS", ""),
        known_hosts=[
            h.strip()
            for h in _env(d, "GUBER_MEMBERLIST_KNOWN_NODES", "").split(",")
            if h.strip()
        ],
        advertise_port=_env_int(d, "GUBER_MEMBERLIST_ADVERTISE_PORT", 7946),
        dns_fqdn=_env(d, "GUBER_DNS_FQDN", ""),
        dns_poll_interval=_env_float_seconds(d, "GUBER_DNS_POLL_INTERVAL", 300.0),
        etcd_endpoints=[
            h.strip()
            for h in _env(d, "GUBER_ETCD_ENDPOINTS", "").split(",")
            if h.strip()
        ],
        etcd_key_prefix=_env(d, "GUBER_ETCD_KEY_PREFIX", "/gubernator/peers/"),
        etcd_dial_timeout=_env_float_seconds(d, "GUBER_ETCD_DIAL_TIMEOUT", 5.0),
        etcd_user=_env(d, "GUBER_ETCD_USER"),
        etcd_password=_env(d, "GUBER_ETCD_PASSWORD"),
        etcd_advertise_address=_env(d, "GUBER_ETCD_ADVERTISE_ADDRESS"),
        etcd_data_center=_env(d, "GUBER_ETCD_DATA_CENTER", dc),
        etcd_tls_ca=_env(d, "GUBER_ETCD_TLS_CA"),
        etcd_tls_cert=_env(d, "GUBER_ETCD_TLS_CERT"),
        etcd_tls_key=_env(d, "GUBER_ETCD_TLS_KEY"),
        etcd_tls_skip_verify=_env(d, "GUBER_ETCD_TLS_SKIP_VERIFY")
        in ("1", "true", "yes"),
        peer_picker=peer_picker,
        picker_replicas=picker_replicas,
        grpc_max_conn_age_sec=_env_int(d, "GUBER_GRPC_MAX_CONN_AGE_SEC", 0),
        grpc_workers=_env_int(d, "GUBER_GRPC_WORKERS", 32),
        debug=_env(d, "GUBER_DEBUG") in ("1", "true", "yes"),
        sketch_window_ms=int(
            _env_float_seconds(d, "GUBER_SKETCH_WINDOW", 1.0) * 1000
        ),
        sketch_depth=_env_int(d, "GUBER_SKETCH_DEPTH", 4),
        sketch_width=_env_int(d, "GUBER_SKETCH_WIDTH", 1 << 20),
        ledger=_env(d, "GUBER_LEDGER", "1").strip().lower()
        not in ("0", "false", "no", "off"),
        ledger_lease=_env_int(d, "GUBER_LEDGER_LEASE", 512),
        ledger_lease_ttl=_env_float_seconds(
            d, "GUBER_LEDGER_LEASE_TTL", 0.2
        ),
        ledger_hot_threshold=_env_int(d, "GUBER_LEDGER_HOT_THRESHOLD", 8),
        ledger_keys=_env_int(d, "GUBER_LEDGER_KEYS", 65536),
        ledger_settle_interval=_env_float_seconds(
            d, "GUBER_LEDGER_SETTLE_INTERVAL", 0.05
        ),
        tls=tls,
        device_count=device_count,
        sweep_interval=_env_float_seconds(d, "GUBER_SWEEP_INTERVAL", 30.0),
        local_batch_wait=_env_float_seconds(d, "GUBER_LOCAL_BATCH_WAIT", 0.0),
        global_serve_window=_env_float_seconds(
            d, "GUBER_GLOBAL_SERVE_WINDOW", 0.002
        ),
        replication=_env(d, "GUBER_REPLICATION", "1").strip().lower()
        not in ("0", "false", "no", "off"),
        repl_promote_rate=float(
            _env(d, "GUBER_REPL_PROMOTE_RATE") or 2000.0
        ),
        repl_cooldown=_env_float_seconds(d, "GUBER_REPL_COOLDOWN", 10.0),
        repl_lease=_env_int(d, "GUBER_REPL_LEASE", 2048),
        repl_lease_ttl=_env_float_seconds(
            d, "GUBER_REPL_LEASE_TTL", 1.0
        ),
        repl_interval=_env_float_seconds(d, "GUBER_REPL_INTERVAL", 0.5),
        repl_max_keys=_env_int(d, "GUBER_REPL_MAX_KEYS", 16),
        repl_max_replicas=_env_int(d, "GUBER_REPL_MAX_REPLICAS", 0),
        membership_epoch_timeout=_env_float_seconds(
            d, "GUBER_MEMBERSHIP_EPOCH_TIMEOUT", 30.0
        ),
        handoff_window=_env_int(d, "GUBER_HANDOFF_WINDOW", 512),
        drain_deadline=_env_float_seconds(d, "GUBER_DRAIN_DEADLINE", 30.0),
        h2_fast_address=_env(d, "GUBER_H2_FAST_ADDRESS", ""),
        h2_fast_window=_env_float_seconds(d, "GUBER_H2_FAST_WINDOW", 0.002),
        h2_lanes=_env_int(d, "GUBER_H2_LANES", 0),
        native_ledger=_env(d, "GUBER_NATIVE_LEDGER", "1").strip().lower()
        not in ("0", "false", "no", "off"),
        metric_flags=[
            f.strip()
            for f in _env(d, "GUBER_METRIC_FLAGS", "").split(",")
            if f.strip()
        ],
    )


def resolve_advertise_address(listen: str, advertise: str = "") -> str:
    """Resolve 0.0.0.0/:: listen addresses to a routable advertise
    address. reference: net.go:28-49."""
    if advertise:
        return advertise
    host, _, port = listen.rpartition(":")
    if host in ("0.0.0.0", "::", ""):
        host = socket.gethostbyname(socket.gethostname())
    return f"{host}:{port}"

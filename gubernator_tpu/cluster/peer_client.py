"""PeerClient — connection + request batcher toward one owner peer.

reference: peer_client.go.  Semantics preserved:

- Lazy dial on first use (:96-162); TLS credentials optional.
- BATCHING (default): requests enqueue into a per-peer queue drained by
  a batcher thread that flushes when `batch_wait` (500µs default) has
  elapsed since the first queued item or the queue reaches
  `batch_limit` (1000); responses are redistributed to callers in order
  (:308-376, :380-453, :457-516).
- NO_BATCHING: a single-item unary RPC (:185-195).
- Graceful shutdown drains queued + in-flight requests before closing
  the channel (:519-553); requests after shutdown fail NotReady.
- `last_errs` keeps a 5-minute TTL window of recent errors for
  HealthCheck aggregation (:277-306).
- `PeerError.not_ready` distinguishes retryable connection states; the
  router's forward path retries on it (:556-580).

Beyond the reference: every send passes the peer health plane
(cluster/health.py) — a per-peer circuit breaker gates the RPC
*before* any dial, transport-shaped outcomes (UNAVAILABLE, deadline)
feed the state machine, and the seeded fault injector
(cluster/faults.py) taps the same choke point so chaos tests exercise
the identical failure paths production would.

Flushes run on a small per-client executor so a slow RPC doesn't stall
the next 500µs window (the reference fires a goroutine per flush).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

import grpc

from gubernator_tpu.cluster import faults
from gubernator_tpu.cluster.health import PeerHealth
from gubernator_tpu.config import BehaviorConfig
from gubernator_tpu.net import serde
from gubernator_tpu.utils import tracing
from gubernator_tpu.net.grpc_service import PeersV1Stub, dial
from gubernator_tpu.net.pb import peers_pb2 as peers_pb
from gubernator_tpu.types import (
    Behavior,
    PeerInfo,
    RateLimitReq,
    RateLimitResp,
    UpdatePeerGlobal,
    has_behavior,
)

_LAST_ERRS_TTL = 300.0  # reference: peer_client.go:64 (5-minute TTL LRU)
_LAST_ERRS_CAP = 100

# gRPC codes that mean "the transport failed", not "the peer answered
# with an application error" — only these feed the circuit breaker as
# failures.
_TRANSPORT_CODES = (
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.DEADLINE_EXCEEDED,
)
# Codes that PROVE the peer processed the request and answered with an
# application-level status — these close/clear the circuit.  Anything
# in neither set (INTERNAL from an RST_STREAM, CANCELLED from a local
# channel teardown, UNKNOWN, ...) is ambiguous and must move the
# circuit in NEITHER direction: treating an LB that resets every
# stream as "healthy" would keep the circuit closed through the exact
# storm the health plane exists to prevent.
_ANSWERED_CODES = (
    grpc.StatusCode.INVALID_ARGUMENT,
    grpc.StatusCode.OUT_OF_RANGE,
    grpc.StatusCode.FAILED_PRECONDITION,
    grpc.StatusCode.RESOURCE_EXHAUSTED,
    grpc.StatusCode.PERMISSION_DENIED,
    grpc.StatusCode.UNAUTHENTICATED,
    grpc.StatusCode.NOT_FOUND,
    grpc.StatusCode.ALREADY_EXISTS,
    grpc.StatusCode.UNIMPLEMENTED,
)


class PeerError(RuntimeError):
    """Error talking to a peer; `not_ready` means the peer was not
    connected and the caller may retry against a re-picked owner;
    `circuit_open` means the health plane refused the send without
    dialing (the peer is BROKEN and no probe is due) — retrying the
    same peer is pointless until its circuit half-opens.

    reference: peer_client.go:556-580 (PeerErr / NotReady).
    """

    def __init__(
        self, message: str, *, not_ready: bool = False,
        circuit_open: bool = False,
    ):
        super().__init__(message)
        self.not_ready = not_ready
        self.circuit_open = circuit_open


class _Pending:
    __slots__ = ("req", "future")

    def __init__(self, req: RateLimitReq):
        self.req = req
        self.future: Future = Future()


class PeerClient:
    """A connection to one peer with request batching."""

    def __init__(
        self,
        info: PeerInfo,
        behaviors: Optional[BehaviorConfig] = None,
        *,
        credentials: Optional[grpc.ChannelCredentials] = None,
        flush_stat=None,  # utils.metrics.DurationStat (shared, optional)
    ):
        self.info = info
        self.behaviors = behaviors or BehaviorConfig()
        self._credentials = credentials
        self._flush_stat = flush_stat
        # Who is sending through this client (stamped by set_peers);
        # the fault injector keys asymmetric partitions on (src, dst).
        self.src_addr = ""
        b = self.behaviors
        self.health = PeerHealth(
            info.grpc_address,
            failure_threshold=b.circuit_failures,
            backoff=b.circuit_backoff,
            backoff_cap=b.circuit_backoff_cap,
        )
        self._channel: Optional[grpc.Channel] = None
        self._stub: Optional[PeersV1Stub] = None
        self._raw_get_peer = None
        self._raw_update_globals = None
        self._raw_transfer = None
        self._raw_replicate = None
        self._raw_obs = None
        self._lock = threading.Lock()
        self._queue: List[_Pending] = []
        self._queue_cv = threading.Condition(self._lock)
        self._closing = False
        self._batcher: Optional[threading.Thread] = None
        self._flusher: Optional[ThreadPoolExecutor] = None
        self._inflight = 0
        self._drained = threading.Condition(self._lock)
        self._last_errs: Dict[str, float] = {}

    # -- connection ----------------------------------------------------

    def _connect(self) -> PeersV1Stub:
        """Lazy dial. reference: peer_client.go:96-162."""
        with self._lock:
            if self._closing:
                raise PeerError("already disconnecting", not_ready=True)
            if self._stub is None:
                self._channel = dial(
                    self.info.grpc_address, credentials=self._credentials
                )
                self._stub = PeersV1Stub(self._channel)
                from gubernator_tpu.net.grpc_service import PEERS_SERVICE

                # Raw variants: no per-item pb objects on the GLOBAL
                # planes (see send_peer_hits / update_peer_globals_raw).
                self._raw_get_peer = self._channel.unary_unary(
                    f"/{PEERS_SERVICE}/GetPeerRateLimits",
                    request_serializer=lambda raw: raw,
                    response_deserializer=lambda raw: raw,
                )
                self._raw_update_globals = self._channel.unary_unary(
                    f"/{PEERS_SERVICE}/UpdatePeerGlobals",
                    request_serializer=lambda raw: raw,
                    response_deserializer=lambda raw: raw,
                )
                self._raw_transfer = self._channel.unary_unary(
                    f"/{PEERS_SERVICE}/TransferBuckets",
                    request_serializer=lambda raw: raw,
                    response_deserializer=lambda raw: raw,
                )
                self._raw_replicate = self._channel.unary_unary(
                    f"/{PEERS_SERVICE}/ReplicateKeys",
                    request_serializer=lambda raw: raw,
                    response_deserializer=lambda raw: raw,
                )
                self._raw_obs = self._channel.unary_unary(
                    f"/{PEERS_SERVICE}/ObsSnapshot",
                    request_serializer=lambda raw: raw,
                    response_deserializer=lambda raw: raw,
                )
                self._flusher = ThreadPoolExecutor(
                    max_workers=4,
                    thread_name_prefix=f"guber-flush-{self.info.grpc_address}",
                )
                self._batcher = threading.Thread(
                    target=self._run,
                    name=f"guber-batch-{self.info.grpc_address}",
                    daemon=True,
                )
                self._batcher.start()
            return self._stub

    def _gate(self) -> None:
        """The pre-dial health gate every send passes: refuse instantly
        (no dial, no connect timeout) when the circuit is open, then
        run the send through the fault injector when one is installed.
        Injected faults are recorded as real transport failures — the
        chaos tests exercise the same bookkeeping production does."""
        if not self.health.allow():
            tracing.add_event(
                "circuit_open", peer=self.info.grpc_address
            )
            raise PeerError(
                f"circuit open to {self.info.grpc_address} "
                f"(probe in {self.health.retry_after():.2f}s)",
                not_ready=True,
                circuit_open=True,
            )
        inj = faults.active()
        if inj is not None:
            try:
                inj.check(self.src_addr, self.info.grpc_address)
            except faults.FaultError as e:
                self.health.record_failure()
                self._set_last_err(str(e))
                raise PeerError(str(e), not_ready=True) from e

    def _observe_rpc_error(self, e: grpc.RpcError) -> None:
        """Feed the circuit breaker from a real RPC failure: transport
        codes are failures, application-status codes prove the peer
        answered (success), and ambiguous codes move the circuit in
        neither direction (a held half-open probe slot is reclaimed by
        PeerHealth.probe_timeout)."""
        code = e.code()
        if code in _TRANSPORT_CODES:
            self.health.record_failure()
        elif code in _ANSWERED_CODES:
            self.health.record_success()

    def _set_last_err(self, err: str) -> None:
        now = time.monotonic()
        with self._lock:
            self._last_errs[err] = now
            if len(self._last_errs) > _LAST_ERRS_CAP:
                for k in sorted(self._last_errs, key=self._last_errs.get)[
                    : len(self._last_errs) - _LAST_ERRS_CAP
                ]:
                    del self._last_errs[k]

    def last_errs(self) -> List[str]:
        """Recent (≤5 min) errors. reference: peer_client.go:294-306."""
        cutoff = time.monotonic() - _LAST_ERRS_TTL
        with self._lock:
            self._last_errs = {
                k: t for k, t in self._last_errs.items() if t >= cutoff
            }
            return list(self._last_errs)

    # -- public API ----------------------------------------------------

    def get_peer_rate_limit(
        self, req: RateLimitReq, timeout: Optional[float] = None
    ) -> RateLimitResp:
        """Forward one request; batched unless NO_BATCHING.

        reference: peer_client.go:171-205.
        """
        if has_behavior(req.behavior, Behavior.NO_BATCHING):
            resps = self.get_peer_rate_limits([req], timeout=timeout)
            return resps[0]
        return self._get_batched(req, timeout)

    def get_peer_rate_limits(
        self, reqs: Sequence[RateLimitReq], timeout: Optional[float] = None
    ) -> List[RateLimitResp]:
        """Unary batch RPC. reference: peer_client.go:208-246."""
        from gubernator_tpu.utils.tracing import span

        with span(
            "peer.batch_rpc", peer=self.info.grpc_address, batch=len(reqs)
        ):
            return self._get_peer_rate_limits_traced(reqs, timeout)

    def _get_peer_rate_limits_traced(
        self, reqs: Sequence[RateLimitReq], timeout: Optional[float] = None
    ) -> List[RateLimitResp]:
        self._gate()
        stub = self._connect()
        msg = peers_pb.GetPeerRateLimitsReq(
            requests=[serde.rate_limit_req_to_pb(r) for r in reqs]
        )
        with self._lock:
            if self._closing:
                raise PeerError("already disconnecting", not_ready=True)
            self._inflight += 1
        try:
            resp = stub.GetPeerRateLimits(
                msg, timeout=timeout or self.behaviors.batch_timeout,
                metadata=tracing.grpc_metadata(),
            )
            self.health.record_success()
        except grpc.RpcError as e:
            err = f"GetPeerRateLimits to {self.info.grpc_address}: {e.code().name}: {e.details()}"
            self._set_last_err(err)
            self._observe_rpc_error(e)
            raise PeerError(
                err, not_ready=e.code() == grpc.StatusCode.UNAVAILABLE
            ) from e
        finally:
            with self._lock:
                self._inflight -= 1
                self._drained.notify_all()
        if len(resp.rate_limits) != len(reqs):
            err = "number of rate limits in peer response does not match request"
            self._set_last_err(err)
            raise PeerError(err)
        return [serde.rate_limit_resp_from_pb(r) for r in resp.rate_limits]

    def send_peer_hits(
        self, reqs: Sequence[RateLimitReq], timeout: Optional[float] = None
    ) -> None:
        """GLOBAL hit forwarding: same RPC as get_peer_rate_limits but
        the responses are ignored by contract (reference global.go:
        124-164 discards them), so skip the per-item response parse —
        the owner's authoritative answer arrives via the broadcast."""
        self.send_peer_hits_raw(
            peers_pb.GetPeerRateLimitsReq(
                requests=[serde.rate_limit_req_to_pb(r) for r in reqs]
            ).SerializeToString(),
            timeout=timeout,
        )

    def send_peer_hits_raw(
        self, payload: bytes, timeout: Optional[float] = None
    ) -> None:
        """Pre-encoded GetPeerRateLimitsReq bytes (the columnar hit
        windows C-encode straight from their aggregation columns)."""
        self._gate()
        self._connect()
        with self._lock:
            if self._closing:
                raise PeerError("already disconnecting", not_ready=True)
            raw = self._raw_get_peer
            self._inflight += 1
        try:
            raw(
                payload,
                timeout=timeout or self.behaviors.global_timeout,
                metadata=tracing.grpc_metadata(),
            )
            self.health.record_success()
        except grpc.RpcError as e:
            err = f"GetPeerRateLimits(hits) to {self.info.grpc_address}: {e.code().name}: {e.details()}"
            self._set_last_err(err)
            self._observe_rpc_error(e)
            raise PeerError(
                err, not_ready=e.code() == grpc.StatusCode.UNAVAILABLE
            ) from e
        finally:
            with self._lock:
                self._inflight -= 1
                self._drained.notify_all()

    def update_peer_globals(
        self, globals_: Sequence[UpdatePeerGlobal], timeout: Optional[float] = None
    ) -> None:
        """Push authoritative GLOBAL state to this peer.

        reference: peer_client.go:248-275.
        """
        self._gate()
        stub = self._connect()
        msg = peers_pb.UpdatePeerGlobalsReq(
            globals=[serde.update_peer_global_to_pb(g) for g in globals_]
        )
        with self._lock:
            if self._closing:
                raise PeerError("already disconnecting", not_ready=True)
            self._inflight += 1
        try:
            stub.UpdatePeerGlobals(
                msg, timeout=timeout or self.behaviors.global_timeout,
                metadata=tracing.grpc_metadata(),
            )
            self.health.record_success()
        except grpc.RpcError as e:
            err = f"UpdatePeerGlobals to {self.info.grpc_address}: {e.code().name}: {e.details()}"
            self._set_last_err(err)
            self._observe_rpc_error(e)
            raise PeerError(
                err, not_ready=e.code() == grpc.StatusCode.UNAVAILABLE
            ) from e
        finally:
            with self._lock:
                self._inflight -= 1
                self._drained.notify_all()

    def update_peer_globals_raw(
        self, payload: bytes, timeout: Optional[float] = None
    ) -> None:
        """Push one pre-encoded UpdatePeerGlobalsReq (native broadcast
        plane — the payload is C-encoded once per window and shared by
        every peer push)."""
        self._gate()
        self._connect()
        with self._lock:
            if self._closing:
                raise PeerError("already disconnecting", not_ready=True)
            raw = self._raw_update_globals
            self._inflight += 1
        try:
            raw(
                payload, timeout=timeout or self.behaviors.global_timeout,
                metadata=tracing.grpc_metadata(),
            )
            self.health.record_success()
        except grpc.RpcError as e:
            err = f"UpdatePeerGlobals to {self.info.grpc_address}: {e.code().name}: {e.details()}"
            self._set_last_err(err)
            self._observe_rpc_error(e)
            raise PeerError(
                err, not_ready=e.code() == grpc.StatusCode.UNAVAILABLE
            ) from e
        finally:
            with self._lock:
                self._inflight -= 1
                self._drained.notify_all()

    def transfer_buckets_raw(
        self, payload: bytes, timeout: Optional[float] = None
    ) -> None:
        """Ship one window of bucket-state rows to this peer — the
        ownership-transfer protocol (cluster/handoff.py encodes the
        payload; the receiver restores through the engine's bulk-load
        scatter).  Membership-change-rate traffic, never the decision
        hot path."""
        self._gate()
        self._connect()
        with self._lock:
            if self._closing:
                raise PeerError("already disconnecting", not_ready=True)
            raw = self._raw_transfer
            self._inflight += 1
        try:
            raw(
                payload, timeout=timeout or self.behaviors.batch_timeout,
                metadata=tracing.grpc_metadata(),
            )
            self.health.record_success()
        except grpc.RpcError as e:
            err = f"TransferBuckets to {self.info.grpc_address}: {e.code().name}: {e.details()}"
            self._set_last_err(err)
            self._observe_rpc_error(e)
            raise PeerError(
                err, not_ready=e.code() == grpc.StatusCode.UNAVAILABLE
            ) from e
        finally:
            with self._lock:
                self._inflight -= 1
                self._drained.notify_all()

    def replicate_keys_raw(
        self, payload: bytes, timeout: Optional[float] = None
    ) -> bytes:
        """Ship one hot-key replication message (grant or revoke) to
        this peer and return the raw JSON response — the promotion
        protocol (cluster/replication.py encodes both sides; the
        response carries superseded leases' credit accounting).
        Promotion-rate traffic, never the decision hot path."""
        self._gate()
        self._connect()
        with self._lock:
            if self._closing:
                raise PeerError("already disconnecting", not_ready=True)
            raw = self._raw_replicate
            self._inflight += 1
        try:
            resp = raw(
                payload, timeout=timeout or self.behaviors.global_timeout,
                metadata=tracing.grpc_metadata(),
            )
            self.health.record_success()
            return resp
        except grpc.RpcError as e:
            err = f"ReplicateKeys to {self.info.grpc_address}: {e.code().name}: {e.details()}"
            self._set_last_err(err)
            self._observe_rpc_error(e)
            raise PeerError(
                err, not_ready=e.code() == grpc.StatusCode.UNAVAILABLE
            ) from e
        finally:
            with self._lock:
                self._inflight -= 1
                self._drained.notify_all()

    def obs_snapshot_raw(
        self, timeout: Optional[float] = None
    ) -> bytes:
        """Pull this peer's observability snapshot (counters, gauges,
        raw stage histograms) for the fleet rollup merge
        (obs/fleet.py).  Scrape-rate traffic, never the decision hot
        path; the empty request body is the protocol."""
        self._gate()
        self._connect()
        with self._lock:
            if self._closing:
                raise PeerError("already disconnecting", not_ready=True)
            raw = self._raw_obs
            self._inflight += 1
        try:
            resp = raw(
                b"", timeout=timeout or self.behaviors.global_timeout,
                metadata=tracing.grpc_metadata(),
            )
            self.health.record_success()
            return resp
        except grpc.RpcError as e:
            err = f"ObsSnapshot to {self.info.grpc_address}: {e.code().name}: {e.details()}"
            self._set_last_err(err)
            self._observe_rpc_error(e)
            raise PeerError(
                err, not_ready=e.code() == grpc.StatusCode.UNAVAILABLE
            ) from e
        finally:
            with self._lock:
                self._inflight -= 1
                self._drained.notify_all()

    # -- batching ------------------------------------------------------

    def _get_batched(
        self, req: RateLimitReq, timeout: Optional[float]
    ) -> RateLimitResp:
        """Enqueue and wait. reference: peer_client.go:308-376."""
        # Fail fast BEFORE enqueueing: a circuit-open peer must cost
        # the caller one dict probe, not a full batch_timeout wait on
        # a future that can only fail.  Non-consuming peek — the
        # batcher's flush runs the real (probe-slot-taking) gate.
        if not self.health.would_allow():
            raise PeerError(
                f"circuit open to {self.info.grpc_address} "
                f"(probe in {self.health.retry_after():.2f}s)",
                not_ready=True,
                circuit_open=True,
            )
        self._connect()
        pending = _Pending(req)
        with self._lock:
            if self._closing:
                raise PeerError("already disconnecting", not_ready=True)
            self._queue.append(pending)
            self._queue_cv.notify()
        try:
            result = pending.future.result(
                timeout=timeout or self.behaviors.batch_timeout
            )
        except TimeoutError:
            raise PeerError(
                f"timeout waiting for batched response from {self.info.grpc_address}"
            )
        if isinstance(result, Exception):
            raise result
        return result

    def _run(self) -> None:
        """Batcher loop: flush at batch_limit or an occupancy-adaptive
        wait capped at batch_wait. reference: peer_client.go:380-453 —
        the interval only matters while traffic actually queues, so an
        isolated forwarded request no longer pays the window (the
        cluster-tier p50 mechanism, VERDICT r5 weak #2)."""
        from gubernator_tpu.cluster.batch_loop import AdaptiveWait

        limit = self.behaviors.batch_limit
        cap = self.behaviors.batch_wait
        if getattr(self.behaviors, "adaptive_windows", True):
            adaptive = AdaptiveWait(cap, limit)
        else:

            class _Fixed:
                @staticmethod
                def next_wait() -> float:
                    return cap

                @staticmethod
                def observe(_n: int) -> None:
                    pass

            adaptive = _Fixed()
        while True:
            with self._lock:
                while not self._queue and not self._closing:
                    self._queue_cv.wait()
                if self._closing and not self._queue:
                    return
                # First item arrived; hold the window open until the
                # adaptive deadline or the batch limit.
                deadline = time.monotonic() + adaptive.next_wait()
                while len(self._queue) < limit and not self._closing:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._queue_cv.wait(remaining)
                batch = self._queue[:limit]
                del self._queue[: len(batch)]
                adaptive.observe(len(batch))
                self._inflight += 1
            assert self._flusher is not None
            self._flusher.submit(self._send_queue, batch)

    def _send_queue(self, batch: List[_Pending]) -> None:
        """One flush: RPC + redistribute responses in order.

        reference: peer_client.go:457-516.
        """
        from gubernator_tpu.utils.tracing import span

        t0 = time.monotonic()
        with span(
            "peer.flush", peer=self.info.grpc_address, batch=len(batch)
        ):
            self._send_queue_traced(batch)
        if self._flush_stat is not None:
            self._flush_stat.observe(time.monotonic() - t0)

    def _send_queue_traced(self, batch: List[_Pending]) -> None:
        try:
            self._gate()
            msg = peers_pb.GetPeerRateLimitsReq(
                requests=[serde.rate_limit_req_to_pb(p.req) for p in batch]
            )
            assert self._stub is not None
            resp = self._stub.GetPeerRateLimits(
                msg, timeout=self.behaviors.batch_timeout,
                metadata=tracing.grpc_metadata(),
            )
            self.health.record_success()
            if len(resp.rate_limits) != len(batch):
                raise PeerError(
                    "number of rate limits in peer response does not match request"
                )
            for p, r in zip(batch, resp.rate_limits):
                p.future.set_result(serde.rate_limit_resp_from_pb(r))
        except Exception as e:  # noqa: BLE001 — every caller gets the error
            if isinstance(e, grpc.RpcError):
                self._observe_rpc_error(e)
                err_text = f"GetPeerRateLimits batch to {self.info.grpc_address}: {e.code().name}"
                e = PeerError(
                    err_text, not_ready=e.code() == grpc.StatusCode.UNAVAILABLE
                )
            self._set_last_err(str(e))
            for p in batch:
                if not p.future.done():
                    p.future.set_result(e)
        finally:
            with self._lock:
                self._inflight -= 1
                self._drained.notify_all()

    # -- lifecycle -----------------------------------------------------

    def shutdown(self, timeout: float = 5.0) -> None:
        """Stop accepting work, drain queue + in-flight, close channel.

        reference: peer_client.go:519-553.
        """
        with self._lock:
            if self._closing:
                return
            self._closing = True
            self._queue_cv.notify_all()
        if self._batcher is not None:
            self._batcher.join(timeout)
        deadline = time.monotonic() + timeout
        with self._lock:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._drained.wait(remaining)
        if self._flusher is not None:
            self._flusher.shutdown(wait=True)
        if self._channel is not None:
            self._channel.close()

    def queue_length(self) -> int:
        with self._lock:
            return len(self._queue)

    def inflight(self) -> int:
        """Load signal toward this peer: RPCs currently in flight plus
        queued batch items awaiting a flush.  The replica-count policy
        (cluster/replication.py, GUBER_REPL_MAX_REPLICAS) sorts on it
        to grant hot-key leases to the least-loaded peers."""
        with self._lock:
            return self._inflight + len(self._queue)

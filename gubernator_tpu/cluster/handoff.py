"""Ownership-transfer protocol: ship bucket rows to their new owner.

The other half of elastic membership (cluster/membership.py): when an
epoch transition moves a key range off this node — a peer joined and
now owns it, or this node is draining out — the range's LIVE bucket
state (packed-slot snapshot rows, full fidelity including the leaky
32.32 fixed-point words) travels to the new owner in batched windows
over a dedicated peer RPC (``PeersV1/TransferBuckets``), instead of
being dropped on the floor the way a static-membership restart would.

Protocol shape, sender side (one pass per epoch transition):

1. **Barrier** — ``ledger.invalidate_keys`` on every moving key first:
   live credit leases are revoked (native-plane leases pulled via the
   dp_pull path) and their unused credit settles back synchronously,
   so the device rows snapshotted next are sequential-exact.
2. **Snapshot** — one ``engine.export_items()`` sweep, filtered to the
   moving keys (expired rows are skipped; there is nothing to move).
3. **Ship** — rows grouped by target owner, sent in windows of
   ``GUBER_HANDOFF_WINDOW`` rows per RPC with explicit timeouts and a
   capped-exponential/full-jitter backoff between retries.  The peer
   health plane gates every send: a broken target delays the epoch
   commit (the membership manager waits on the sender) until the
   epoch deadline, after which the remaining rows are **forfeited** —
   counted, and safe under the same N_partitions × limit
   over-admission bound RESILIENCE.md proves for degraded answering
   (the new owner simply starts those buckets fresh).

Receiver side: rows restore through the engine's bulk-load scatter
(the same path the persistence Loader uses), after invalidating any
local ledger entries for those keys.  A restore OVERWRITES a bucket
the receiver may have freshly created between cutover and row arrival
— the hits admitted into that fresh bucket are forgotten, which is
exactly the bounded over-admission the window's length controls (and
strictly tighter than forfeiting the source's whole count).

Dead source (kill mid-handoff, unplanned leave): nothing ships; every
moved key is implicitly forfeited and the bound still holds — the old
owner admitted ≤ limit before dying, the new owner admits ≤ limit
fresh.  tests/test_membership.py pins both the zero-forfeit drain and
the kill-during-handoff bound deterministically.

Paged state (GUBER_PAGED, core/paging.py) changes neither side of the
wire: `export_items` streams resident rows from the device snapshot
and cold rows straight from the host page store (same leaky 32.32
fidelity — the host copy IS the packed words), and the receiver's
bulk-load restore splits per row — resident pages scatter on device,
cold pages pack host-side — so a handoff of a mostly-cold key range
never faults the whole range through the receiver's resident frames.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from gubernator_tpu.store import CacheItem, LeakyBucketItem, TokenBucketItem
from gubernator_tpu.types import Algorithm

log = logging.getLogger("gubernator_tpu.handoff")

_TOKEN = int(Algorithm.TOKEN_BUCKET)
_LEAKY = int(Algorithm.LEAKY_BUCKET)


# ----------------------------------------------------------------------
# Wire format: one JSON document per TransferBuckets RPC.  JSON (not a
# new protobuf) because no grpc_python_plugin exists in this image
# (net/grpc_service.py documents the constraint) and the handoff plane
# is windows-of-hundreds-of-rows at membership-change rate, not the
# per-decision hot path — schema clarity beats codec speed here.


def encode_transfer(
    epoch: int, src_addr: str, items: List[CacheItem], *, boot: str = ""
) -> bytes:
    """Serialize one window of bucket rows.

    `boot` is the sender's per-process token: epochs are per-process
    counters that restart at 1 on reboot, so the receiver's
    stale-window guard compares epochs only within one (src, boot)
    stream.

    Row layouts (positional, by algorithm):
      token: [key, 0, expire_at, invalid_at, status, limit, duration,
              remaining, created_at]
      leaky: [key, 1, expire_at, invalid_at, limit, duration, burst,
              updated_at, remf_hi, remf_lo]
    """
    rows = []
    for it in items:
        v = it.value
        if isinstance(v, TokenBucketItem):
            rows.append(
                [it.key, _TOKEN, it.expire_at, it.invalid_at, v.status,
                 v.limit, v.duration, v.remaining, v.created_at]
            )
        elif isinstance(v, LeakyBucketItem):
            hi, lo = v.remaining_words or (int(v.remaining), 0)
            rows.append(
                [it.key, _LEAKY, it.expire_at, it.invalid_at, v.limit,
                 v.duration, v.burst, v.updated_at, hi, lo]
            )
    doc = {"epoch": epoch, "src": src_addr, "boot": boot, "rows": rows}
    # Wire-propagated trace context (OBSERVABILITY.md): the sender's
    # active span rides the window as a W3C traceparent string, so a
    # handoff's receive restores under the transition's trace even
    # when the transport metadata is absent (tests calling
    # receive_transfer directly).  Absent when tracing is off.
    from gubernator_tpu.utils import tracing

    ctx = tracing.current_context()
    if ctx is not None:
        doc["traceparent"] = tracing.format_traceparent(ctx)
    return json.dumps(doc, separators=(",", ":")).encode()


def decode_transfer(raw: bytes) -> Tuple[int, str, str, List[CacheItem]]:
    """Inverse of encode_transfer — (epoch, src, boot, items); raises
    ValueError on malformed payloads (the RPC adapter maps that to
    INVALID_ARGUMENT)."""
    return decode_transfer_obj(json.loads(raw))


def decode_transfer_obj(obj) -> Tuple[int, str, str, List[CacheItem]]:
    """decode_transfer over an already-parsed document (the receiver
    parses once for the traceparent AND the rows)."""
    items: List[CacheItem] = []
    for row in obj["rows"]:
        key, algo, expire_at, invalid_at = row[0], row[1], row[2], row[3]
        if algo == _TOKEN:
            value = TokenBucketItem(
                status=row[4], limit=row[5], duration=row[6],
                remaining=row[7], created_at=row[8],
            )
        elif algo == _LEAKY:
            hi, lo = row[8], row[9]
            value = LeakyBucketItem(
                limit=row[4], duration=row[5], burst=row[6],
                updated_at=row[7],
                remaining=float(hi) + float(lo) * 2.0**-32,
                remaining_words=(hi, lo),
            )
        else:
            raise ValueError(f"unknown algorithm {algo!r} in transfer row")
        items.append(
            CacheItem(
                key=key, value=value, expire_at=expire_at,
                algorithm=algo, invalid_at=invalid_at,
            )
        )
    return (
        int(obj["epoch"]), str(obj.get("src", "")),
        str(obj.get("boot", "")), items,
    )


class ListLoader:
    """Loader-protocol shim over an in-memory row list: the receiver
    reuses the engine's bulk-restore scatter (engine.load) verbatim."""

    def __init__(self, items: List[CacheItem]):
        self.items = items

    def load(self) -> Iterable[CacheItem]:
        return self.items

    def save(self, items) -> None:  # pragma: no cover - protocol stub
        raise NotImplementedError("handoff loader is restore-only")


# ----------------------------------------------------------------------
# Receiver


def receive_transfer(instance, raw: bytes) -> int:
    """Restore one shipped window into the local engine; returns rows
    applied.  Ledger entries for the keys are invalidated first (their
    local view predates the authoritative shipped rows); expired rows
    are dropped rather than interned just to be swept.

    Stale-epoch guard: a window carrying an epoch LOWER than the last
    one seen from the same (source, boot) stream is dropped — a
    delayed/retried ship from a superseded transition must not
    overwrite rows a newer transition already installed.  Epochs are
    per-process counters that restart on reboot, so a changed boot
    token resets the tracking (a restarted node's fresh stream is
    never mistaken for staleness).  The check-then-update on the seen
    map is unlocked: the benign race admits at worst one stale
    window, the pre-guard behavior."""
    from gubernator_tpu.utils import tracing

    obj = json.loads(raw)
    if tracing.active():
        # Join the sender's trace via the window's embedded
        # traceparent (skipped when the RPC adapter's metadata span is
        # already open — nesting wins then).  One parse serves both
        # the traceparent and the rows.
        remote = None
        if tracing.current_context() is None:
            tp = obj.get("traceparent", "") if isinstance(obj, dict) else ""
            remote = tracing.parse_traceparent(tp) if tp else None
        with tracing.span("handoff.receive", remote_parent=remote) as s:
            n = _receive_transfer(instance, obj)
            if s is not None:
                s.set_attribute("rows", n)
            return n
    return _receive_transfer(instance, obj)


def _receive_transfer(instance, obj) -> int:
    epoch, src, boot, items = decode_transfer_obj(obj)
    if src:
        seen = instance.handoff_epoch_seen
        last = seen.get(src)
        if last is not None and last[0] == boot and epoch < last[1]:
            return 0
        seen[src] = (boot, epoch)
    now_ms = instance.engine.clock.now_ms()
    live = [it for it in items if it.expire_at == 0 or it.expire_at > now_ms]
    if not live:
        return 0
    if instance.ledger is not None:
        instance.ledger.invalidate_keys([it.key.encode() for it in live])
    instance.engine.load(ListLoader(live))
    instance.handoff_counters["received"] += len(live)
    return len(live)


# ----------------------------------------------------------------------
# Sender


class HandoffSender:
    """Ship a set of bucket rows to their new owners, window by window.

    One sender per epoch transition (or per drain).  `targets` maps
    owner address → (PeerClient, rows).  Rows that cannot be delivered
    before `deadline` — circuit stays open, RPCs keep failing — are
    forfeited and counted; everything else ships with explicit
    per-RPC timeouts and backoff between retries, so one broken
    target can delay (never wedge) the epoch commit.
    """

    def __init__(
        self,
        *,
        epoch: int,
        src_addr: str,
        src_boot: str = "",
        window: int,
        rpc_timeout: float,
        backoff: float,
        backoff_cap: float,
        counters: Dict[str, int],
        on_window: Optional[Callable[[str, int], None]] = None,
        stop: Optional[threading.Event] = None,
    ):
        self.epoch = epoch
        self.src_addr = src_addr
        self.src_boot = src_boot
        self.window = max(1, window)
        self.rpc_timeout = rpc_timeout
        self.backoff = backoff
        self.backoff_cap = backoff_cap
        # Shared with the owning V1Instance: {"shipped","forfeited",...}.
        self.counters = counters
        # Test hook: called after every delivered window (the seeded
        # kill-during-handoff chaos test injects its fault here, so
        # "mid-handoff" is a deterministic point, not a sleep race).
        self.on_window = on_window
        # Shutdown signal (the membership manager's): a daemon closing
        # mid-handoff must not keep retrying toward a long epoch
        # deadline — the remaining rows forfeit immediately (they are
        # lost either way; the count stays truthful).
        self.stop = stop

    def ship(
        self,
        targets: Dict[str, Tuple[object, List[CacheItem]]],
        deadline: float,
    ) -> Dict[str, int]:
        """Deliver every target's rows; returns
        {"shipped": n, "forfeited": n}.  Blocking — the membership
        manager runs it on its transition thread, drain runs it
        inline."""
        from gubernator_tpu.utils.tracing import span

        with span(
            "handoff.ship", epoch=self.epoch, targets=len(targets)
        ):
            return self._ship_traced(targets, deadline)

    def _ship_traced(
        self,
        targets: Dict[str, Tuple[object, List[CacheItem]]],
        deadline: float,
    ) -> Dict[str, int]:
        from gubernator_tpu.cluster.health import backoff_delay
        from gubernator_tpu.cluster.peer_client import PeerError

        shipped = 0
        forfeited = 0
        pending = {
            addr: (peer, list(rows))
            for addr, (peer, rows) in targets.items()
            if rows
        }
        attempt = 0
        while pending:
            if self.stop is not None and self.stop.is_set():
                # Daemon closing: the tail cannot ship and is lost —
                # forfeit it now instead of retrying into teardown.
                for addr, (_peer, rows) in pending.items():
                    forfeited += len(rows)
                    log.warning(
                        "handoff to %s forfeited %d rows at shutdown",
                        addr, len(rows),
                    )
                pending.clear()
                break
            made_progress = False
            for addr in list(pending):
                peer, rows = pending[addr]
                window, rest = rows[: self.window], rows[self.window:]
                payload = encode_transfer(
                    self.epoch, self.src_addr, window, boot=self.src_boot
                )
                try:
                    peer.transfer_buckets_raw(
                        payload, timeout=self.rpc_timeout
                    )
                except PeerError as e:
                    if time.monotonic() >= deadline:
                        # Epoch deadline: forfeit this target's tail.
                        # The new owner starts these buckets fresh —
                        # bounded over-admission, RESILIENCE.md §10.
                        forfeited += len(rows)
                        del pending[addr]
                        log.warning(
                            "handoff to %s forfeited %d rows past the "
                            "epoch deadline: %s", addr, len(rows), e,
                        )
                        continue
                    # Broken/unreachable target: the retry below backs
                    # off; a circuit-open refusal costs one dict probe
                    # so waiting out the window is cheap.
                    continue
                shipped += len(window)
                made_progress = True
                if rest:
                    pending[addr] = (peer, rest)
                else:
                    del pending[addr]
                if self.on_window is not None:
                    self.on_window(addr, len(window))
            if pending and not made_progress:
                if time.monotonic() >= deadline:
                    for addr, (_peer, rows) in pending.items():
                        forfeited += len(rows)
                        log.warning(
                            "handoff to %s forfeited %d rows at the "
                            "epoch deadline", addr, len(rows),
                        )
                    pending.clear()
                    break
                delay = min(
                    backoff_delay(attempt, self.backoff, self.backoff_cap),
                    max(0.0, deadline - time.monotonic()),
                )
                attempt += 1
                if self.stop is not None:
                    # Interruptible backoff: shutdown cuts the wait.
                    self.stop.wait(delay)
                else:
                    time.sleep(delay)
            else:
                attempt = 0
        self.counters["shipped"] += shipped
        self.counters["forfeited"] += forfeited
        return {"shipped": shipped, "forfeited": forfeited}


def snapshot_moved_rows(
    instance,
    owners_of: Callable[[List[str]], List[Optional[object]]],
    was_mine: Optional[Callable[[List[str]], List[bool]]] = None,
) -> Dict[str, Tuple[object, List[CacheItem]]]:
    """Snapshot every held bucket MOVING off this node: its owner
    under the NEW view is another node AND this node was its
    authoritative owner before the change.

    `owners_of(keys)` maps hash keys → owning PeerClient under the NEW
    view (None = unroutable, kept local; an is_owner client = us).
    `was_mine(keys)` maps hash keys → whether this node owned them
    under the OLD view — REQUIRED for correctness whenever the engine
    can hold non-authoritative local copies (degraded answers, GLOBAL
    miss-local copies): without it, a membership event anywhere in
    the cluster would ship those stale copies onto their healthy
    owners' authoritative state.  None means "everything held is
    mine" (bare-engine callers/tests).
    Returns HandoffSender-shaped targets: {addr: (client, rows)}.

    Two passes over the engine snapshot: the first finds the moving
    keys so their ledger state can be settled back to the device
    (lease credit revoked via invalidate_keys — the dp_pull path for
    native-plane leases), the second re-reads the now-sequential rows
    that actually ship.
    """
    now_ms = instance.engine.clock.now_ms()

    def _moving() -> Dict[str, object]:
        keys: List[str] = []
        for it in instance.engine.export_items():
            if it.expire_at and it.expire_at <= now_ms:
                continue
            keys.append(it.key)
        owners = owners_of(keys)
        mine = was_mine(keys) if was_mine is not None else [True] * len(keys)
        return {
            k: client
            for k, client, m in zip(keys, owners, mine)
            if m and client is not None and not client.info.is_owner
        }

    moving = _moving()
    if not moving:
        return {}
    if instance.ledger is not None:
        instance.ledger.invalidate_keys([k.encode() for k in moving])
    out: Dict[str, Tuple[object, List[CacheItem]]] = {}
    for it in instance.engine.export_items():
        client = moving.get(it.key)
        if client is None:
            continue
        if it.expire_at and it.expire_at <= now_ms:
            continue
        out.setdefault(
            client.info.grpc_address, (client, [])
        )[1].append(it)
    return out

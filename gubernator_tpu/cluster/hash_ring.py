"""Consistent-hash peer routing.

Host-level key→owner-peer assignment with the same semantics as the
reference (reference: replicated_hash.go:29-119): each peer contributes
`replicas` virtual points on a uint64 ring — point i is
`hash(str(i) + md5hex(grpc_address))` — and a key routes to the first
ring point clockwise from `hash(key)`.  Hash is FNV-1 by default, FNV-1a
selectable (reference: config.go:395-417).

TPU-first twist: routing is *batch-vectorized*.  The ring is a sorted
numpy uint64 array, a request batch is hashed in one vectorized FNV pass
(`hashing.fnv1_64_batch`) and routed with one `np.searchsorted` — the
host-side analog of the device kernel's gather, so the per-request
Python cost stays flat as batches grow.

Distribution caveat (reference-faithful — fasthash fnv1 is the
reference's default too): FNV-1's LAST operation is an xor, so keys
that differ only in their final byte(s) produce hashes that differ
only in low bits and fall into the SAME ring gap — sequentially
suffixed names like "key0".."key999" collapse onto ~one owner per
suffix-length class.  Real keys (entropy before the tail) distribute
fine; quantified: a byte changed k positions before the end moves the
hash by ~Δ·prime^k, so synthetic key generators should keep ≥3
constant bytes AFTER the varying ones, and
`GUBER_PEER_PICKER_HASH=fnv1a` (final op: multiply, full avalanche)
avoids the property entirely.

`RegionPicker` keeps one ring per datacenter for MULTI_REGION routing
(reference: region_picker.go:33-111).
"""

from __future__ import annotations

import hashlib
from typing import Callable, Dict, Generic, List, Optional, Sequence, TypeVar

import numpy as np

from gubernator_tpu.hashing import fnv1_64, fnv1a_64, fnv1_64_batch, fnv1a_64_batch, pack_keys
from gubernator_tpu.types import PeerInfo

# reference: replicated_hash.go:29 (defaultReplicas = 512)
DEFAULT_REPLICAS = 512

T = TypeVar("T")  # the member type: anything carrying a PeerInfo via .info


class PoolEmptyError(RuntimeError):
    """reference: replicated_hash.go:106 ("unable to pick a peer; pool is empty")"""

    def __init__(self) -> None:
        super().__init__("unable to pick a peer; pool is empty")


_SCALAR = {"fnv1": fnv1_64, "fnv1a": fnv1a_64}
_BATCH = {"fnv1": fnv1_64_batch, "fnv1a": fnv1a_64_batch}


class ReplicatedConsistentHash(Generic[T]):
    """Ring of virtual peer replicas; keys route via binary search.

    Members are arbitrary objects exposing `.info -> PeerInfo`; identity
    is `info.grpc_address` (reference: replicated_hash.go:78-79).
    """

    def __init__(self, hash_name: str = "fnv1", replicas: int = DEFAULT_REPLICAS):
        if hash_name not in _SCALAR:
            raise ValueError(f"unknown hash {hash_name!r}; want fnv1 or fnv1a")
        self.hash_name = hash_name
        self.replicas = replicas
        self._hash: Callable[[bytes], int] = _SCALAR[hash_name]
        self._members: Dict[str, T] = {}
        # Virtual ring points per member address, computed once on first
        # add (vectorized) and reused across rebuilds.
        self._points: Dict[str, np.ndarray] = {}
        # Sorted ring: hashes[i] is the ring point, owner_idx[i] indexes
        # into _member_list.
        self._member_list: List[T] = []
        self._hashes = np.empty(0, dtype=np.uint64)
        self._owner_idx = np.empty(0, dtype=np.int32)

    def new(self) -> "ReplicatedConsistentHash[T]":
        """Fresh empty picker with the same configuration (ring-point
        cache carried over so re-adding a known peer is free).

        reference: replicated_hash.go:61-67
        """
        picker = ReplicatedConsistentHash(self.hash_name, self.replicas)
        picker._points = dict(self._points)
        return picker

    # -- membership ----------------------------------------------------

    def add(self, member: T) -> None:
        """reference: replicated_hash.go:78-91"""
        info: PeerInfo = member.info  # type: ignore[attr-defined]
        self._members[info.grpc_address] = member
        self._rebuild()

    def add_all(self, members: Sequence[T]) -> None:
        for m in members:
            info: PeerInfo = m.info  # type: ignore[attr-defined]
            self._members[info.grpc_address] = m
        self._rebuild()

    def _member_points(self, address: str) -> np.ndarray:
        """The member's `replicas` ring points, cached after first use.

        Virtual point i = hash(str(i) + md5hex(address))
        (reference: replicated_hash.go:81-84), all `replicas` points
        hashed in one vectorized pass.
        """
        points = self._points.get(address)
        if points is None:
            key = hashlib.md5(address.encode()).hexdigest()
            padded, lengths = pack_keys(
                [(str(i) + key).encode() for i in range(self.replicas)]
            )
            points = _BATCH[self.hash_name](padded, lengths)
            self._points[address] = points
        return points

    def _rebuild(self) -> None:
        self._member_list = list(self._members.values())
        addresses = [m.info.grpc_address for m in self._member_list]  # type: ignore[attr-defined]
        # Prune cached points of departed members — new() copies the
        # cache forward on every membership change, so without pruning
        # it would grow with every address ever seen.
        self._points = {a: p for a, p in self._points.items() if a in self._members}
        if not addresses:
            self._hashes = np.empty(0, dtype=np.uint64)
            self._owner_idx = np.empty(0, dtype=np.int32)
            return
        hashes = np.concatenate([self._member_points(a) for a in addresses])
        owners = np.repeat(
            np.arange(len(addresses), dtype=np.int32), self.replicas
        )
        order = np.argsort(hashes, kind="stable")
        self._hashes = hashes[order]
        self._owner_idx = owners[order]

    def size(self) -> int:
        return len(self._members)

    def peers(self) -> List[T]:
        return list(self._members.values())

    def get_by_peer_info(self, info: PeerInfo) -> Optional[T]:
        """reference: replicated_hash.go:99-101"""
        return self._members.get(info.grpc_address)

    # -- routing -------------------------------------------------------

    def get(self, key: str) -> T:
        """Owner of one key. reference: replicated_hash.go:104-119"""
        if not self._member_list:
            raise PoolEmptyError()
        h = self._hash(key.encode())
        idx = int(np.searchsorted(self._hashes, np.uint64(h), side="left"))
        if idx == len(self._hashes):
            idx = 0
        return self._member_list[self._owner_idx[idx]]

    def get_batch(self, keys: Sequence[str]) -> List[T]:
        """Vectorized owner lookup for a whole request batch."""
        if not keys:
            return []
        padded, lengths = pack_keys([k.encode() for k in keys])
        return self.get_batch_hashed(_BATCH[self.hash_name](padded, lengths))

    def get_batch_dual_hashed(self, fnv1, fnv1a) -> List[T]:
        """Owner lookup given BOTH precomputed hash columns (the
        native wire codec emits fnv1 and fnv1a per key) — the single
        place that picks the column matching `hash_name`."""
        return self.get_batch_hashed(
            np.asarray(fnv1 if self.hash_name == "fnv1" else fnv1a)
        )

    def get_batch_hashed(self, hashes: np.ndarray) -> List[T]:
        """Owner lookup from precomputed key hashes (the native wire
        codec emits both fnv1 and fnv1a per key; pick the column
        matching `hash_name`)."""
        if not self._member_list:
            raise PoolEmptyError()
        idx = np.searchsorted(self._hashes, hashes, side="left")
        idx[idx == len(self._hashes)] = 0
        owners = self._owner_idx[idx]
        return [self._member_list[i] for i in owners]


class ConsistentHash(ReplicatedConsistentHash[T]):
    """Non-replicated picker: ONE ring point per peer (the point is
    hash(grpc_address)) — the reference's legacy 'consistent-hash'
    GUBER_PEER_PICKER choice (config.go:395-417).  Cheaper rebuilds,
    lumpier key distribution; replicated-hash remains the default."""

    def __init__(self, hash_name: str = "fnv1"):
        super().__init__(hash_name, replicas=1)

    def new(self) -> "ConsistentHash[T]":
        picker = ConsistentHash(self.hash_name)
        picker._points = dict(self._points)
        return picker

    def _member_points(self, address: str) -> np.ndarray:
        points = self._points.get(address)
        if points is None:
            points = np.asarray(
                [self._hash(address.encode())], dtype=np.uint64
            )
            self._points[address] = points
        return points


def make_picker(
    picker: str, hash_name: str, replicas: int = DEFAULT_REPLICAS
):
    """GUBER_PEER_PICKER → picker instance (reference config.go:395-417)."""
    if picker in ("", "replicated-hash"):
        return ReplicatedConsistentHash(hash_name, replicas)
    if picker == "consistent-hash":
        return ConsistentHash(hash_name)
    raise ValueError(
        f"GUBER_PEER_PICKER={picker!r} is invalid; choices are "
        "['replicated-hash', 'consistent-hash']"
    )


class RingMember:
    """Address-only ring member: a PeerInfo with no client attached.
    Membership planning (cluster/membership.py) builds throwaway rings
    over candidate views before any PeerClient exists for them."""

    __slots__ = ("info",)

    def __init__(self, info: PeerInfo):
        self.info = info


def address_ring(
    infos: Sequence[PeerInfo],
    hash_name: str = "fnv1",
    picker: str = "replicated-hash",
    replicas: int = DEFAULT_REPLICAS,
) -> "ReplicatedConsistentHash[RingMember]":
    """A routing ring over bare PeerInfos (no clients, no daemon) —
    the membership plane's way of asking "who WOULD own key k under
    view V" without mutating any serving state."""
    ring = make_picker(picker, hash_name, replicas)
    ring.add_all([RingMember(i) for i in infos])
    return ring


class DualRingWindow:
    """Old + new rings valid simultaneously during a membership
    cutover (the DualMap-style routing window, PAPERS.md).

    While an epoch transition is in flight, requests ROUTE to the new
    ring's owner, but the old ring's owner remains an ACCEPTABLE
    destination.  In this codebase the acceptance half is realized by
    the peer-serving contract itself — `get_peer_rate_limits`
    receivers answer authoritatively and never re-forward, so
    in-flight forwards and hit pushes keyed to the old owner cannot
    404 — which makes this object the window's *verification and
    introspection* surface rather than a serving-path gate: the
    membership manager exposes it (`dual_window()`) while a cutover
    is open, and tests/test_hash_ring.py pins its invariant — every
    key lands on its old or new owner, never a third node."""

    __slots__ = ("old", "new")

    def __init__(
        self,
        old: "ReplicatedConsistentHash",
        new: "ReplicatedConsistentHash",
    ):
        self.old = old
        self.new = new

    def owner(self, key: str) -> str:
        """Routing decision: the NEW ring's owner address (traffic
        converges toward the post-cutover topology)."""
        return self.new.get(key).info.grpc_address

    def owners(self, key: str):
        """(old_owner_addr, new_owner_addr) for one key."""
        return (
            self.old.get(key).info.grpc_address,
            self.new.get(key).info.grpc_address,
        )

    def acceptable(self, key: str, addr: str) -> bool:
        """True when `addr` may serve `key` during the window (it is
        the key's owner in the old OR the new ring)."""
        # guberlint: invariant dual-window-no-third-owner
        return addr in self.owners(key)

    def moved(self, key: str) -> bool:
        old_addr, new_addr = self.owners(key)
        return old_addr != new_addr


class RegionPicker(Generic[T]):
    """One consistent-hash ring per datacenter.

    reference: region_picker.go:33-111.  `get_clients(key)` returns the
    key's owner in *every* region (used by MULTI_REGION replication).
    """

    def __init__(self, hash_name: str = "fnv1", replicas: int = DEFAULT_REPLICAS):
        self.hash_name = hash_name
        self.replicas = replicas
        self._regions: Dict[str, ReplicatedConsistentHash[T]] = {}

    def new(self) -> "RegionPicker[T]":
        return RegionPicker(self.hash_name, self.replicas)

    def add(self, member: T) -> None:
        """reference: region_picker.go:104-111"""
        info: PeerInfo = member.info  # type: ignore[attr-defined]
        picker = self._regions.get(info.datacenter)
        if picker is None:
            picker = ReplicatedConsistentHash(self.hash_name, self.replicas)
            self._regions[info.datacenter] = picker
        picker.add(member)

    def get_clients(self, key: str) -> List[T]:
        """The key's owner in every region. reference: region_picker.go:63-75"""
        return [picker.get(key) for picker in self._regions.values()]

    def get_by_peer_info(self, info: PeerInfo) -> Optional[T]:
        """reference: region_picker.go:78-85"""
        for picker in self._regions.values():
            member = picker.get_by_peer_info(info)
            if member is not None:
                return member
        return None

    def pickers(self) -> Dict[str, ReplicatedConsistentHash[T]]:
        return self._regions

    def peers(self) -> List[T]:
        out: List[T] = []
        for picker in self._regions.values():
            out.extend(picker.peers())
        return out

    def size(self) -> int:
        return sum(p.size() for p in self._regions.values())

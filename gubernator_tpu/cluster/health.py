"""Peer health plane: per-peer circuit-breaker state machine.

The reference fails *closed and loud* when a peer dies: every forward
re-dials the dead address until the gRPC connect timeout fires, and
the 5-retry ownership-migration loop spins with no backoff
(gubernator.go:333-422).  "Designing Scalable Rate Limiting Systems"
(PAPERS.md) names graceful degradation under partition as the defining
property of a production limiter, and "When Two is Worse Than One"
shows that exactly this backoff-free retry/redundancy amplifies tail
latency.  This module is the missing availability layer:

    healthy ──failure──▶ suspect ──N failures──▶ broken
       ▲                    │                      │ open period
       │                    └──success──▶ healthy  │ (exp. backoff)
       │                                           ▼
       └───────success──── half-open ◀──probe due──┘
                              │
                              └──failure──▶ broken (period doubles)

State is driven entirely by RPC outcomes (`record_success` /
`record_failure`) observed in PeerClient; `allow()` is the circuit
gate every send consults *before* dialing, so a broken peer costs one
dict probe per request, not a connect timeout.  While BROKEN, exactly
one caller per open-period expiry wins the HALF_OPEN probe slot; its
outcome decides whether the circuit closes or re-opens with a doubled
(capped) period.

RESILIENCE.md documents the transition table, the degradation
semantics built on top of this gate, and the operator knobs.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict

HEALTHY = "healthy"
SUSPECT = "suspect"
BROKEN = "broken"
HALF_OPEN = "half-open"

STATES = (HEALTHY, SUSPECT, BROKEN, HALF_OPEN)

# Aggregate circuit states for one REMOTE REGION, derived from the
# per-peer breakers of the region's members (cluster/multiregion.py;
# RESILIENCE.md §12).
REGION_HEALTHY = "healthy"
REGION_DEGRADED = "degraded"
REGION_OPEN = "open"


def aggregate_region_state(healths) -> str:
    """Fold member PeerHealth breakers into one region-level state:

    - ``open``    — not a single member would accept a send right now
      (every circuit is open inside its period / probing): the region
      is unreachable, MULTI_REGION answers carry
      ``metadata.degraded_region=true``, and the §12 drift bound
      (over-admission ≤ N_regions × limit per window) is the active
      guarantee until a probe heals a member.
    - ``degraded`` — some members are broken/half-open but at least
      one accepts sends: pushes still flow (the region ring re-routes
      nothing — per-key owners are fixed — but the region is not yet
      lost, and answers stay unflagged).
    - ``healthy`` — every member's circuit is closed.

    An empty region reads healthy: no members means nothing to push
    and no drift to bound."""
    any_member = False
    any_allow = False
    any_broken = False
    for h in healths:
        any_member = True
        if h.would_allow():
            any_allow = True
        if h.state() in (BROKEN, HALF_OPEN):
            any_broken = True
    if not any_member:
        return REGION_HEALTHY
    if not any_allow:
        return REGION_OPEN
    return REGION_DEGRADED if any_broken else REGION_HEALTHY

# Process-wide jitter source for backoff_delay callers that don't
# thread their own rng.  Deterministic tests pass a seeded Random.
_jitter_rng = random.Random()


def backoff_delay(
    attempt: int,
    base: float,
    cap: float,
    rng: random.Random | None = None,
) -> float:
    """Capped exponential backoff with FULL jitter: uniform in
    [0, min(cap, base * 2^attempt)].  Full jitter (not equal jitter)
    because the forward retry loop's failure mode is a synchronized
    herd re-picking the same dead owner — spreading retries across the
    whole window is what de-correlates them ("When Two is Worse Than
    One", PAPERS.md)."""
    if base <= 0:
        return 0.0
    ceiling = min(cap, base * (2 ** max(0, attempt)))
    return (rng or _jitter_rng).uniform(0.0, ceiling)


class PeerHealth:
    """Circuit breaker for ONE peer address.

    Thread-safe; every method is a few dict/int ops under a tiny lock
    (the gate sits on the forward hot path, but only on its failure
    branches — a healthy peer costs one lock acquire + two compares).
    """

    __slots__ = (
        "addr", "failure_threshold", "backoff", "backoff_cap",
        "probe_timeout", "_lock", "_state", "_failures", "_open_until",
        "_open_period", "_probe_inflight", "_probe_started",
        "transitions", "_now",
    )

    # guberlint: guard _state, _failures, _open_until, _open_period, _probe_inflight, _probe_started by _lock

    def __init__(
        self,
        addr: str,
        *,
        failure_threshold: int = 3,
        backoff: float = 0.5,
        backoff_cap: float = 30.0,
        probe_timeout: float = 5.0,
        now: Callable[[], float] = time.monotonic,
    ):
        self.addr = addr
        self.failure_threshold = max(1, failure_threshold)
        self.backoff = backoff
        self.backoff_cap = backoff_cap
        # A half-open probe that never reports an outcome (its sender
        # died between winning the slot and the RPC — e.g. a client
        # mid-shutdown raising before the dial) would otherwise hold
        # the slot forever and permanently blacklist the peer; past
        # this many seconds the slot is reclaimed by the next caller.
        self.probe_timeout = probe_timeout
        self._lock = threading.Lock()
        self._state = HEALTHY
        self._failures = 0
        self._open_until = 0.0
        self._open_period = 0.0
        self._probe_inflight = False
        self._probe_started = 0.0
        # to-state -> count, scraped as
        # gubernator_circuit_transitions{peer,to}.  Mutated only under
        # _lock; reads are a snapshot copy.
        self.transitions: Dict[str, int] = {}
        self._now = now

    # -- gates ---------------------------------------------------------

    def allow(self) -> bool:
        """Circuit gate, consulted before every RPC send.  True in
        HEALTHY/SUSPECT.  In BROKEN: once the open period expires, the
        FIRST caller transitions to HALF_OPEN and wins the single probe
        slot; everyone else (and everyone before expiry) is refused
        without a dial.  In HALF_OPEN: refused while the probe is in
        flight."""
        with self._lock:
            if self._state in (HEALTHY, SUSPECT):
                return True
            now = self._now()
            if self._state == BROKEN:
                if now < self._open_until:
                    return False
                self._to(HALF_OPEN)
                self._probe_inflight = True
                self._probe_started = now
                return True
            # HALF_OPEN: one probe at a time — but reclaim a slot whose
            # probe never reported back (probe_timeout), or the peer is
            # blacklisted forever.
            if (
                self._probe_inflight
                and now - self._probe_started < self.probe_timeout
            ):
                return False
            self._probe_inflight = True
            self._probe_started = now
            return True

    def would_allow(self) -> bool:
        """Non-consuming peek: would `allow()` grant a send right now?
        Fan-out planners use it to skip submitting pool tasks for
        broken peers without stealing the half-open probe slot."""
        with self._lock:
            if self._state in (HEALTHY, SUSPECT):
                return True
            if self._state == BROKEN:
                return self._now() >= self._open_until
            return (
                not self._probe_inflight
                or self._now() - self._probe_started >= self.probe_timeout
            )

    # -- outcome feedback ---------------------------------------------

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_inflight = False
            if self._state != HEALTHY:
                self._to(HEALTHY)
                self._open_period = 0.0

    def record_failure(self) -> None:
        """One RPC-level failure (UNAVAILABLE / deadline / reset).
        Only *transport-shaped* outcomes should feed this — an
        application error from a live peer is a success for circuit
        purposes (the peer answered)."""
        with self._lock:
            if self._state == HALF_OPEN:
                # Probe failed: re-open with a doubled (capped) period.
                self._probe_inflight = False
                self._reopen()
                return
            if self._state == BROKEN:
                return  # already open; a racing in-flight RPC failed
            self._failures += 1
            if self._state == HEALTHY:
                self._to(SUSPECT)
            if self._failures >= self.failure_threshold:
                self._reopen()

    # -- introspection -------------------------------------------------

    def state(self) -> str:
        with self._lock:
            # Surface expiry lazily: a broken peer whose open period
            # has elapsed reads as broken until someone probes, which
            # is accurate — no probe has succeeded yet.
            return self._state

    def transition_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.transitions)

    def retry_after(self) -> float:
        """Seconds until the next probe is allowed (0 when closed)."""
        with self._lock:
            if self._state != BROKEN:
                return 0.0
            return max(0.0, self._open_until - self._now())

    # -- internals (caller holds _lock) --------------------------------

    def _reopen(self) -> None:  # guberlint: holds _lock
        self._open_period = (
            min(self.backoff_cap, self._open_period * 2)
            if self._open_period > 0
            else self.backoff
        )
        self._open_until = self._now() + self._open_period
        self._failures = 0
        self._to(BROKEN)

    # guberlint: invariant circuit-legal-transitions
    def _to(self, state: str) -> None:  # guberlint: holds _lock
        if state != self._state:
            self._state = state
            self.transitions[state] = self.transitions.get(state, 0) + 1

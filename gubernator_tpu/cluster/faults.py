"""Deterministic fault injection for the cluster transport.

Chaos tests need *repeatable* failure: a seeded injector that decides
drop / delay / reset per send from one `random.Random(seed)` stream,
plus binary asymmetric partitions that need no randomness at all.
The injection point is the narrow waist every peer RPC already passes
through — PeerClient's send methods call `check(src, dst)` right
before the wire — so one wrapper covers the forward path, the GLOBAL
hit fan-out, the broadcast plane, and multi-region pushes.

Faults raise `FaultError`, which PeerClient maps to the same
`PeerError(not_ready=True)` a real UNAVAILABLE produces: the health
plane, circuit breakers, and degraded-mode answering see an injected
partition exactly as they would a dead NIC.  Latency faults sleep in
the sending thread (the caller's own timeout budget still applies).

Installation is process-global (`install()` / `uninstall()`), matching
the in-process ClusterHarness where all "nodes" share one interpreter;
`ClusterHarness.partition()/heal()` are the operator-shaped veneer.
Nothing in this module is imported on the serving path unless an
injector is installed — the gate in PeerClient is one module-attribute
read when idle.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, Optional, Set, Tuple


class FaultError(RuntimeError):
    """An injected transport fault (mapped to PeerError(not_ready))."""

    def __init__(self, kind: str, src: str, dst: str):
        super().__init__(f"injected {kind} {src or '?'} -> {dst}")
        self.kind = kind


class FaultInjector:
    """Seeded per-send fault decisions + asymmetric partitions.

    Rates are evaluated in a fixed order (drop, reset, latency) against
    one seeded stream, so two injectors with the same seed and the same
    send sequence make identical decisions.  Partition rules are
    binary and direction-sensitive: `partition(a, b)` blocks a→b only
    (the classic asymmetric-partition failure), `partition_both` blocks
    both directions; `heal()` removes matching rules (None wildcards).
    """

    def __init__(
        self,
        seed: int = 0,
        *,
        drop_rate: float = 0.0,
        reset_rate: float = 0.0,
        latency_rate: float = 0.0,
        latency_s: float = 0.05,
    ):
        self.seed = seed
        self.drop_rate = drop_rate
        self.reset_rate = reset_rate
        self.latency_rate = latency_rate
        self.latency_s = latency_s
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        # Directed blocks: (src, dst); "*" wildcards one side.
        self._partitions: Set[Tuple[str, str]] = set()  # guberlint: guarded-by _lock
        # Directed ALWAYS-ON latency links: (src, dst) -> seconds.
        # Unlike the rate-based latency_rate (a random spike model),
        # these emulate a link's deterministic RTT — the inter-region
        # DCN hop the crossregion bench injects (RESILIENCE.md §12).
        self._latency_links: Dict[Tuple[str, str], float] = {}  # guberlint: guarded-by _lock
        self.injected: Dict[str, int] = {}  # guberlint: guarded-by _lock

    # -- partitions ----------------------------------------------------

    def partition(self, src: str, dst: str) -> None:
        """Block src→dst sends (one direction — asymmetric)."""
        with self._lock:
            self._partitions.add((src, dst))

    def partition_both(self, a: str, b: str) -> None:
        with self._lock:
            self._partitions.add((a, b))
            self._partitions.add((b, a))

    def isolate(self, addr: str) -> None:
        """Block every send to AND from `addr`."""
        with self._lock:
            self._partitions.add((addr, "*"))
            self._partitions.add(("*", addr))

    def heal(self, src: Optional[str] = None, dst: Optional[str] = None) -> None:
        """Remove partition rules matching (src, dst); None wildcards
        that side, so `heal()` clears every rule.  Only the ARGUMENT
        side wildcards: a stored `isolate()` rule like ("*", "B") is
        removed by heal(), heal(dst="B") or heal("*", "B"), but never
        as a side effect of healing some other node's partitions."""
        with self._lock:
            self._partitions = {
                (s, d)
                for (s, d) in self._partitions
                if not (
                    (src is None or s == src)
                    and (dst is None or d == dst)
                )
            }

    # -- directed latency links ----------------------------------------

    def add_latency(self, src: str, dst: str, seconds: float) -> None:
        """Inject a deterministic per-send delay on src→dst (one
        direction; "*" wildcards a side) — inter-region RTT emulation.
        Stacks with the rate-based latency model; the largest matching
        link wins when wildcards overlap."""
        with self._lock:
            self._latency_links[(src, dst)] = seconds

    def clear_latency(
        self, src: Optional[str] = None, dst: Optional[str] = None
    ) -> None:
        """Remove latency links matching (src, dst); None wildcards
        that side (argument-side only, like heal())."""
        with self._lock:
            self._latency_links = {
                (s, d): v
                for (s, d), v in self._latency_links.items()
                if not (
                    (src is None or s == src)
                    and (dst is None or d == dst)
                )
            }

    def _link_delay_locked(self, src: str, dst: str) -> float:  # guberlint: holds _lock
        links = self._latency_links
        if not links:
            return 0.0
        return max(
            links.get((src, dst), 0.0),
            links.get((src, "*"), 0.0),
            links.get(("*", dst), 0.0),
        )

    def _partitioned(self, src: str, dst: str) -> bool:  # guberlint: holds _lock
        p = self._partitions
        return (
            (src, dst) in p
            or (src, "*") in p
            or ("*", dst) in p
        )

    # -- the per-send gate ---------------------------------------------

    def check(self, src: str, dst: str) -> None:
        """Decide this send's fate.  Raises FaultError for drops,
        partitions, and resets; sleeps for latency spikes; returns for
        clean sends.  Decisions draw from the seeded stream in a fixed
        order so equal seeds replay equal fates."""
        with self._lock:
            if self._partitioned(src, dst):
                self._count("partition")
                raise FaultError("partition", src, dst)
            # Single draw per configured rate, fixed order.
            if self.drop_rate > 0 and self._rng.random() < self.drop_rate:
                self._count("drop")
                raise FaultError("drop", src, dst)
            if self.reset_rate > 0 and self._rng.random() < self.reset_rate:
                self._count("reset")
                raise FaultError("reset", src, dst)
            delay = 0.0
            if self.latency_rate > 0 and self._rng.random() < self.latency_rate:
                self._count("latency")
                delay = self.latency_s
            link = self._link_delay_locked(src, dst)
            if link > 0:
                self._count("link_latency")
                delay += link
        if delay > 0:
            time.sleep(delay)

    def _count(self, kind: str) -> None:  # guberlint: holds _lock
        self.injected[kind] = self.injected.get(kind, 0) + 1

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.injected)


# -- process-global installation ---------------------------------------

_active: Optional[FaultInjector] = None


def install(injector: FaultInjector) -> FaultInjector:
    """Install the process-global injector (chaos tests / harness)."""
    global _active
    _active = injector
    return injector


def uninstall() -> None:
    global _active
    _active = None


def active() -> Optional[FaultInjector]:
    return _active

"""Interval-driven aggregation loop — the framework's host-side batcher.

The reference builds this pattern three times (peer batching
peer_client.go:380-453, GLOBAL hit/broadcast loops global.go:78-202,
multi-region multiregion.go:43-92): accumulate items into an aggregate,
flush when the aggregate reaches `batch_limit` or `sync_wait` has
elapsed since the first item.  This is the one host-side primitive that
feeds the TPU step cadence, so it lives in one place.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Generic, TypeVar

K = TypeVar("K")
V = TypeVar("V")


class IntervalBatcher(Generic[K, V]):
    """Aggregate (key, item) pairs; flush at batch_limit or sync_wait.

    `combine(existing, item) -> merged` merges a new item into the
    aggregate for its key (None existing for the first).  `flush(dict)`
    runs on the batcher thread; long work should hop to an executor.
    """

    def __init__(
        self,
        sync_wait: float,
        batch_limit: int,
        combine: Callable,
        flush: Callable[[Dict[K, V]], None],
        *,
        name: str = "batcher",
        chunked: bool = False,
        drain_limit: int | None = None,
        max_pending: int | None = None,
        overflow: str = "block",
    ):
        self.sync_wait = sync_wait
        self.batch_limit = batch_limit
        # Max items taken per flush CYCLE (None = drain everything).
        # Under overload an unbounded drain turns into one multi-second
        # flush that holds the GIL/core against the serving threads and
        # blows peer RPC deadlines (the GLOBAL p99 tail, PERF.md §15);
        # a bounded drain keeps each flush ~batch-sized and lets the
        # loop run back-to-back cycles until the queue is level.
        self._drain_limit = drain_limit
        # Queue bound.  overflow="block": producers wait for drain
        # space (the reference's unbuffered-channel backpressure,
        # global.go:68-74) — safe only where no flush path can
        # re-enter the producer side, or a full cluster deadlocks.
        # overflow="drop_oldest": shed the oldest chunks and count
        # them (safe for supersedable traffic like status broadcasts).
        self._max_pending = max_pending
        self._overflow = overflow
        self.dropped = 0
        self._combine = combine
        self._flush = flush
        # chunked=True: the flush callable accepts (dict, chunks) and
        # add_chunk is available — the columnar wire path queues whole
        # column slices in O(1) instead of per-item dict merges, and
        # the flush thread does the per-key work off the serving path.
        self._chunked = chunked
        self._items: Dict[K, V] = {}
        self._chunks: list = []
        self._chunk_count = 0
        self._oldest_ts = 0.0  # arrival of the oldest queued item
        self._lock = threading.Lock()
        # Flush ORDERING without blocking producers: each snapshot
        # takes a turn number under the queue lock; flushes then run
        # strictly in turn order, coordinated on a separate condition
        # so add()/add_many()/add_chunk() never wait on an in-flight
        # flush (a later flush_now snapshot broadcasting before an
        # older batcher snapshot would regress peer caches).
        self._turn_cv = threading.Condition(threading.Lock())
        self._next_turn = 0  # next turn number to hand out
        self._done_turn = 0  # turns fully flushed
        self._cv = threading.Condition(self._lock)
        self._space = threading.Condition(self._lock)  # drain freed room
        self._closing = False
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()

    def _admit_locked(self, incoming: int) -> bool:
        """Enforce max_pending before enqueueing `incoming` items
        (caller holds the lock).  Returns False when closing."""
        if self._closing:
            return False
        if self._max_pending is None:
            return True
        if self._overflow == "block":
            # Admit only when the WHOLE batch fits (a 1000-item chunk
            # must not slip past the cap through one free slot) — but
            # an oversized batch is always admitted into an empty
            # queue, or it could never be admitted at all.
            while not self._closing:
                pending = len(self._items) + self._chunk_count
                if pending == 0 or pending + incoming <= self._max_pending:
                    break
                self._space.wait(timeout=1.0)
            return not self._closing
        # drop_oldest: shed whole chunks first (cheap), then items.
        while (
            len(self._items) + self._chunk_count + incoming
            > self._max_pending
            and self._chunks
        ):
            _, cnt, _ts = self._chunks.pop(0)
            self._chunk_count -= cnt
            self.dropped += cnt
        while (
            len(self._items) + self._chunk_count + incoming
            > self._max_pending
            and self._items
        ):
            self._items.pop(next(iter(self._items)))
            self.dropped += 1
        return True

    def add(self, key: K, item) -> None:
        with self._lock:
            if not self._admit_locked(1):
                return
            if not self._items and not self._chunks:
                self._oldest_ts = time.monotonic()
            self._items[key] = self._combine(self._items.get(key), item)
            self._cv.notify()

    def pending(self) -> int:
        """Items currently queued for the next flush (metrics gauge)."""
        with self._lock:
            return len(self._items) + self._chunk_count

    def backlog_age(self) -> float:
        """Seconds since the oldest still-queued item arrived (metrics
        gauge: a healthy batcher keeps this near sync_wait; growth
        means flushes cannot keep up with enqueues)."""
        with self._lock:
            if not self._items and not self._chunks:
                return 0.0
            return time.monotonic() - self._oldest_ts

    def add_many(self, pairs) -> None:
        """Batch enqueue under ONE lock acquisition — a 1000-item wire
        batch must not pay 1000 lock round-trips (VERDICT r1 weak 8)."""
        pairs = list(pairs)  # admission control needs the real count
        with self._lock:
            if not self._admit_locked(len(pairs)):
                return
            if not self._items and not self._chunks:
                self._oldest_ts = time.monotonic()
            items = self._items
            combine = self._combine
            for key, item in pairs:
                items[key] = combine(items.get(key), item)
            self._cv.notify()

    def add_chunk(self, chunk, count: int) -> None:
        """Queue one columnar chunk (O(1): stores references only).
        Requires chunked=True."""
        assert self._chunked
        with self._lock:
            if not self._admit_locked(count):
                return
            if not self._items and not self._chunks:
                self._oldest_ts = time.monotonic()
            self._chunks.append((chunk, count, time.monotonic()))
            self._chunk_count += count
            self._cv.notify()

    def _run(self) -> None:
        while True:
            with self._lock:
                while not self._items and not self._chunks and not self._closing:
                    self._cv.wait()
                if self._closing and not self._items and not self._chunks:
                    return
                deadline = time.monotonic() + self.sync_wait
                while (
                    len(self._items) + self._chunk_count < self.batch_limit
                    and not self._closing
                ):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
                batch, chunks = self._drain_locked()
                turn = self._take_turn()
            try:
                self._flush_in_turn(turn, batch, chunks)
            except Exception:  # noqa: BLE001 — loop must survive flush errors
                import logging

                logging.getLogger("gubernator_tpu").exception(
                    "batcher flush failed"
                )

    def _drain_locked(self, limit: int | None = -1):
        """Take up to `drain_limit` queued items (caller holds the
        lock).  Returns (items_dict, chunk_list).  limit=None forces a
        full drain (flush_now / tests)."""
        if limit == -1:
            limit = self._drain_limit
        if (
            limit is None
            or len(self._items) + self._chunk_count <= limit
        ):
            batch, self._items = self._items, {}
            pairs, self._chunks = self._chunks, []
            self._chunk_count = 0
            self._space.notify_all()
            return batch, [c for c, _, _ in pairs]
        taken = 0
        batch: Dict[K, V] = {}
        # CPython dicts iterate in insertion order: oldest keys first.
        for k in list(self._items.keys()):
            if taken >= limit:
                break
            batch[k] = self._items.pop(k)
            taken += 1
        chunks = []
        while self._chunks and taken < limit:
            ch, cnt, _ts = self._chunks.pop(0)
            chunks.append(ch)
            self._chunk_count -= cnt
            taken += cnt
        # Re-anchor the backlog age on the oldest REMAINING chunk's
        # real arrival time — resetting to now() here made the gauge
        # read "healthy" through the exact sustained overload it
        # exists to expose.  With only dict items left the old anchor
        # stands (per-key arrival is untracked; overestimating age is
        # the safe direction for an overload gauge).
        if self._chunks:
            self._oldest_ts = self._chunks[0][2]
        elif not self._items:
            self._oldest_ts = time.monotonic()
        self._space.notify_all()
        return batch, chunks

    def _take_turn(self) -> int:
        """Reserve the next flush turn.  Caller holds the queue lock —
        the snapshot and its turn number are taken atomically."""
        with self._turn_cv:
            turn = self._next_turn
            self._next_turn += 1
        return turn

    def _flush_in_turn(self, turn: int, batch, chunks) -> None:
        """Run the flush when (and only when) its turn comes up, so
        snapshot order == delivery order; always advances the turn."""
        with self._turn_cv:
            while self._done_turn != turn:
                self._turn_cv.wait()
        try:
            if batch or chunks:
                if self._chunked:
                    self._flush(batch, chunks)
                else:
                    self._flush(batch)
        finally:
            with self._turn_cv:
                self._done_turn = turn + 1
                self._turn_cv.notify_all()

    def flush_now(self) -> None:
        """Flush everything queued immediately, on the caller's thread
        (operational drains + deterministic tests).  Returns only after
        every OLDER snapshot's flush AND this drain complete (turn
        ordering); producers never wait on flush execution."""
        with self._lock:
            batch, chunks = self._drain_locked(limit=None)
            turn = self._take_turn()
        self._flush_in_turn(turn, batch, chunks)

    def close(self, timeout: float = 5.0) -> None:
        """Stop, flushing anything still queued."""
        with self._lock:
            if self._closing:
                return
            self._closing = True
            self._cv.notify_all()
            self._space.notify_all()
        self._thread.join(timeout)

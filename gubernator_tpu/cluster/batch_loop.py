"""Interval-driven aggregation loop — the framework's host-side batcher.

The reference builds this pattern three times (peer batching
peer_client.go:380-453, GLOBAL hit/broadcast loops global.go:78-202,
multi-region multiregion.go:43-92): accumulate items into an aggregate,
flush when the aggregate reaches `batch_limit` or `sync_wait` has
elapsed since the first item.  This is the one host-side primitive that
feeds the TPU step cadence, so it lives in one place.

Round 6 (VERDICT r5 weak #2): `sync_wait` is now a CAP, not a fixed
delay.  Every tier grew one of these windows, and on the GLOBAL path
they stack in series (client window + hit window + broadcast window),
so a fixed wait taxes the cluster-tier MEDIAN even when nothing would
have batched.  AdaptiveWait keeps the reference's interval semantics
(peer_client.go:380-453: the ticker only matters when traffic is
actually queueing) but sizes the wait by measured occupancy: an idle
batcher fires immediately; the wait grows toward the cap only while
batches actually fill.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Generic, Optional, TypeVar

K = TypeVar("K")
V = TypeVar("V")


class AdaptiveWait:
    """Load-adaptive batching window: 0 under low occupancy, `cap`
    when batches fill.

    Occupancy is an EWMA of flush fill fraction (drained items ÷
    batch_limit).  The wait is `cap * min(1, ewma / fill_target)`:
    once windows fill past `fill_target` of the limit the full cap is
    worth paying (amortization), below it the wait shrinks linearly to
    zero — a single-caller batcher flushes as soon as the item lands
    instead of idling out its window (the cluster-tier p50 mechanism,
    VERDICT r5 weak #2).  The feedback is self-correcting: firing
    immediately under trickle load keeps batches small, which keeps
    the wait at ~0; under a herd, even zero-wait windows fill while
    the previous flush runs, which grows the wait toward the cap.
    """

    __slots__ = ("cap", "limit", "fill_target", "alpha", "_ewma")

    def __init__(
        self,
        cap: float,
        limit: int,
        *,
        fill_target: float = 0.5,
        alpha: float = 0.4,
    ):
        self.cap = cap
        self.limit = max(1, limit)
        self.fill_target = fill_target
        self.alpha = alpha
        self._ewma = 0.0  # start idle: the first window fires fast

    def next_wait(self) -> float:
        if self.cap <= 0:
            return 0.0
        frac = min(1.0, self._ewma / self.fill_target)
        w = self.cap * frac
        # Sub-50µs sleeps cost more in scheduler churn than they buy.
        return w if w >= 50e-6 else 0.0

    def observe(self, drained: int) -> None:
        fill = min(1.0, drained / self.limit)
        self._ewma += self.alpha * (fill - self._ewma)


class IntervalBatcher(Generic[K, V]):
    """Aggregate (key, item) pairs; flush at batch_limit or an
    occupancy-adaptive wait capped at sync_wait.

    `combine(existing, item) -> merged` merges a new item into the
    aggregate for its key (None existing for the first).  `flush(dict)`
    runs on the batcher thread (ordered mode) or a small flush pool
    (ordered=False) — see `flush_workers`.
    """

    def __init__(
        self,
        sync_wait: float,
        batch_limit: int,
        combine: Callable,
        flush: Callable[[Dict[K, V]], None],
        *,
        name: str = "batcher",
        chunked: bool = False,
        drain_limit: int | None = None,
        item_drain_limit: int | None = None,
        max_pending: int | None = None,
        overflow: str = "block",
        adaptive: bool = True,
        flush_workers: int = 0,
        wait_stat=None,  # DurationStat: queue age at drain (window wait)
        age_stat=None,  # DurationStat: oldest-item age at flush END
    ):
        self.sync_wait = sync_wait
        self.batch_limit = batch_limit
        # sync_wait as an occupancy-scaled cap (AdaptiveWait) vs the
        # pre-round-6 fixed wait (tests that pin window timing).
        self._adaptive = (
            AdaptiveWait(sync_wait, batch_limit) if adaptive else None
        )
        # Max items taken per flush CYCLE (None = drain everything).
        # Under overload an unbounded drain turns into one multi-second
        # flush that holds the GIL/core against the serving threads and
        # blows peer RPC deadlines (the GLOBAL p99 tail, PERF.md §15);
        # a bounded drain keeps each flush ~batch-sized and lets the
        # loop run back-to-back cycles until the queue is level.
        # (Columnar flushes that aggregate their drain vectorized can
        # safely take None + max_pending as the bound instead —
        # item_drain_limit then still caps the DICT items per cycle,
        # whose flush cost is per-key Python, not one numpy pass.)
        self._drain_limit = drain_limit
        self._item_drain_limit = item_drain_limit
        # Queue bound.  overflow="block": producers wait for drain
        # space (the reference's unbuffered-channel backpressure,
        # global.go:68-74) — safe only where no flush path can
        # re-enter the producer side, or a full cluster deadlocks.
        # overflow="drop_oldest": shed the oldest chunks and count
        # them (safe for supersedable traffic like status broadcasts).
        self._max_pending = max_pending
        self._overflow = overflow
        self.dropped = 0  # guberlint: guarded-by _lock
        self._combine = combine
        self._flush = flush
        self._wait_stat = wait_stat
        self._age_stat = age_stat
        # Deferred re-admission (requeue_many delay=): failed-flush
        # items HELD until a due time, invisible to the drain until
        # they come due — the damped-retry primitive.  A flush that
        # re-queues toward a broken peer with a backoff delay must not
        # spin the loop (re-admitted items would drain again
        # immediately) and must not sleep on a flush worker (a parked
        # worker is exactly the stall the health plane exists to
        # prevent); held batches instead bound the loop's wait, so the
        # retry fires at its due time even with zero fresh traffic —
        # which is what lets a healed peer converge after the clients
        # go quiet.  Entries: (due_monotonic, pairs, oldest_ts).
        self._held: list = []  # guberlint: guarded-by _lock
        # chunked=True: the flush callable accepts (dict, chunks) and
        # add_chunk is available — the columnar wire path queues whole
        # column slices in O(1) instead of per-item dict merges, and
        # the flush thread does the per-key work off the serving path.
        self._chunked = chunked
        self._items: Dict[K, V] = {}  # guberlint: guarded-by _lock
        self._chunks: list = []  # guberlint: guarded-by _lock
        self._chunk_count = 0  # guberlint: guarded-by _lock
        # Arrival of the oldest queued item.
        self._oldest_ts = 0.0  # guberlint: guarded-by _lock
        self._lock = threading.Lock()
        # Flush ORDERING without blocking producers: each snapshot
        # takes a turn number under the queue lock; flushes then run
        # strictly in turn order, coordinated on a separate condition
        # so add()/add_many()/add_chunk() never wait on an in-flight
        # flush (a later flush_now snapshot broadcasting before an
        # older batcher snapshot would regress peer caches).
        self._turn_cv = threading.Condition(threading.Lock())
        # Next turn number to hand out.
        self._next_turn = 0  # guberlint: guarded-by _turn_cv
        # Turns fully flushed (ordered mode).
        self._done_turn = 0  # guberlint: guarded-by _turn_cv
        # In-flight turns (pooled mode).
        self._active_turns: set = set()  # guberlint: guarded-by _turn_cv
        self._cv = threading.Condition(self._lock)
        self._space = threading.Condition(self._lock)  # drain freed room
        self._closing = False  # guberlint: guarded-by _lock
        # flush_workers > 0: flushes hop to a bounded pool so the NEXT
        # window opens while this flush's RPCs are still in flight —
        # the batching cadence overlaps the network instead of
        # serializing behind it (the pipelined-GLOBAL-flush half of
        # VERDICT r5 weak #2).  Only valid for commutative flushes
        # (hit sums); supersedable traffic needs delivery order and
        # keeps flush_workers=0.
        self._flush_pool = None
        self._flush_slots = None
        if flush_workers > 0:
            from concurrent.futures import ThreadPoolExecutor

            self._flush_pool = ThreadPoolExecutor(
                max_workers=flush_workers,
                thread_name_prefix=f"{name}-flush",
            )
            self._flush_slots = threading.Semaphore(flush_workers)
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()

    def _admit_locked(self, incoming: int) -> bool:
        """Enforce max_pending before enqueueing `incoming` items
        (caller holds the lock).  Returns False when closing."""
        if self._closing:
            return False
        if self._max_pending is None:
            return True
        if self._overflow == "block":
            # Admit only when the WHOLE batch fits (a 1000-item chunk
            # must not slip past the cap through one free slot) — but
            # an oversized batch is always admitted into an empty
            # queue, or it could never be admitted at all.  Held
            # (deferred-retry) items occupy pending space: the memory
            # bound covers the retry backlog too.
            while not self._closing:
                pending = (
                    len(self._items) + self._chunk_count
                    + self._held_count_locked()
                )
                if pending == 0 or pending + incoming <= self._max_pending:
                    break
                self._space.wait(timeout=1.0)
            return not self._closing
        # drop_oldest: shed whole chunks first (cheap), then items.
        shed_chunks = False
        while (
            len(self._items) + self._chunk_count + incoming
            > self._max_pending
            and self._chunks
        ):
            _, cnt, _ts = self._chunks.pop(0)
            self._chunk_count -= cnt
            self.dropped += cnt
            shed_chunks = True
        while (
            len(self._items) + self._chunk_count + incoming
            > self._max_pending
            and self._items
        ):
            self._items.pop(next(iter(self._items)))
            self.dropped += 1
        if shed_chunks:
            # Re-anchor the backlog age on the oldest SURVIVING chunk —
            # keeping the shed items' arrival time overstated the gauge
            # for as long as the overload lasted (ADVICE r5).  With
            # only dict items left the old anchor stands (per-key
            # arrival is untracked; overestimating is the safe
            # direction for an overload gauge).
            if self._chunks:
                self._oldest_ts = self._chunks[0][2]
            elif not self._items:
                self._oldest_ts = time.monotonic()
        return True

    def add(self, key: K, item) -> None:
        with self._lock:
            if not self._admit_locked(1):
                return
            if not self._items and not self._chunks:
                self._oldest_ts = time.monotonic()
            self._items[key] = self._combine(self._items.get(key), item)
            self._cv.notify()

    def pending(self) -> int:
        """Items currently queued for the next flush, INCLUDING held
        deferred-retry batches (metrics gauge)."""
        with self._lock:
            return (
                len(self._items) + self._chunk_count
                + self._held_count_locked()
            )

    def backlog_age(self) -> float:
        """Seconds since the oldest still-queued item arrived (metrics
        gauge: a healthy batcher keeps this near sync_wait; growth
        means flushes cannot keep up with enqueues).  Held retry
        batches count with their ORIGINAL enqueue time — the failure
        episode they carry is exactly what this gauge exists to
        expose."""
        with self._lock:
            oldest = None
            if self._items or self._chunks:
                oldest = self._oldest_ts
            for _due, _pairs, held_oldest in self._held:
                if held_oldest and (oldest is None or held_oldest < oldest):
                    oldest = held_oldest
            if oldest is None:
                return 0.0
            return time.monotonic() - oldest

    def _held_count_locked(self) -> int:  # guberlint: holds _lock
        return sum(len(pairs) for _due, pairs, _ts in self._held)

    def _promote_held_locked(self, force: bool = False):
        """Move held batches whose due time arrived (all of them when
        `force`) into the live queue; returns the earliest remaining
        due time, or None when nothing is held.  Caller holds the
        lock."""  # guberlint: holds _lock
        if not self._held:
            return None
        now = time.monotonic()
        keep = []
        earliest = None
        for due, pairs, oldest_ts in self._held:
            if not force and due > now:
                keep.append((due, pairs, oldest_ts))
                if earliest is None or due < earliest:
                    earliest = due
                continue
            if not self._items and not self._chunks:
                self._oldest_ts = oldest_ts if oldest_ts else now
            elif oldest_ts and oldest_ts < self._oldest_ts:
                self._oldest_ts = oldest_ts
            items = self._items
            combine = self._combine
            for key, item in pairs:
                items[key] = combine(items.get(key), item)
        self._held = keep
        return earliest

    def current_wait(self) -> float:
        """The wait the next window will use (sync_wait when the
        batcher is non-adaptive) — metrics gauge + tests."""
        if self._adaptive is None:
            return self.sync_wait
        # Under the queue lock: AdaptiveWait state is owned by the
        # batcher thread's drain (observe() runs under _lock), so an
        # unlocked scrape could read mid-update EWMA state.
        with self._lock:
            return self._adaptive.next_wait()

    def add_many(self, pairs) -> None:
        """Batch enqueue under ONE lock acquisition — a 1000-item wire
        batch must not pay 1000 lock round-trips (VERDICT r1 weak 8)."""
        pairs = list(pairs)  # admission control needs the real count
        with self._lock:
            if not self._admit_locked(len(pairs)):
                return
            if not self._items and not self._chunks:
                self._oldest_ts = time.monotonic()
            items = self._items
            combine = self._combine
            for key, item in pairs:
                items[key] = combine(items.get(key), item)
            self._cv.notify()

    def requeue_many(
        self,
        pairs,
        oldest_ts: float | None = None,
        delay: float = 0.0,
    ) -> int:
        """Re-enqueue failed-flush items WITHOUT blocking: flush
        threads must never wait on producer admission (a blocked flush
        worker is exactly the stall the health plane exists to
        prevent).  Items that don't fit under max_pending are dropped
        and counted; returns the number admitted.  `oldest_ts` is the
        items' ORIGINAL first-enqueue time: re-queued items already
        waited at least one window, and re-anchoring backlog age at
        now() would hide exactly the failure-episode backlog the gauge
        exists to expose.

        `delay` > 0 defers re-admission: the batch is HELD invisible
        to the drain until `delay` seconds pass (the capped-backoff
        retry cycle toward a broken peer — re-admitting immediately
        would spin the loop against an open circuit, and sleeping on
        the flush worker would stall healthy traffic).  The loop's
        idle wait is bounded by the earliest held due time, so the
        retry fires on schedule even with zero fresh traffic."""
        pairs = list(pairs)
        admitted = 0
        with self._lock:
            if self._closing:
                return 0
            if delay > 0:
                if self._max_pending is not None:
                    space = self._max_pending - (
                        len(self._items) + self._chunk_count
                        + self._held_count_locked()
                    )
                    if space < len(pairs):
                        self.dropped += len(pairs) - max(0, space)
                        pairs = pairs[: max(0, space)]
                if not pairs:
                    return 0
                self._held.append(
                    (time.monotonic() + delay, pairs, oldest_ts or 0.0)
                )
                # Wake the loop so its idle wait re-arms with the new
                # due time (a plain cv.wait() would sleep past it).
                self._cv.notify()
                return len(pairs)
            if not self._items and not self._chunks:
                self._oldest_ts = (
                    oldest_ts if oldest_ts else time.monotonic()
                )
            elif oldest_ts and oldest_ts < self._oldest_ts:
                self._oldest_ts = oldest_ts
            items = self._items
            combine = self._combine
            for key, item in pairs:
                if (
                    self._max_pending is not None
                    and len(items) + self._chunk_count >= self._max_pending
                    and key not in items
                ):
                    self.dropped += 1
                    continue
                items[key] = combine(items.get(key), item)
                admitted += 1
            if admitted:
                self._cv.notify()
        return admitted

    def add_chunk(self, chunk, count: int) -> None:
        """Queue one columnar chunk (O(1): stores references only).
        Requires chunked=True."""
        assert self._chunked
        with self._lock:
            if not self._admit_locked(count):
                return
            if not self._items and not self._chunks:
                self._oldest_ts = time.monotonic()
            self._chunks.append((chunk, count, time.monotonic()))
            self._chunk_count += count
            self._cv.notify()

    def _run(self) -> None:
        while True:
            if self._flush_slots is not None:
                # Reserve a flush slot BEFORE draining: when the pool
                # is saturated the queue keeps absorbing (bounded by
                # max_pending) instead of a drained snapshot sitting in
                # a handoff limbo the gauges can't see.
                self._flush_slots.acquire()
            with self._lock:
                while True:
                    # Promote due held retries first (forced on close:
                    # the final drain must deliver-or-fail the whole
                    # retry backlog, not strand it); an undue backlog
                    # bounds the idle wait so retries fire on schedule
                    # without fresh traffic.
                    earliest = self._promote_held_locked(
                        force=self._closing
                    )
                    if self._items or self._chunks or self._closing:
                        break
                    if earliest is None:
                        self._cv.wait()
                    else:
                        self._cv.wait(
                            max(0.0, earliest - time.monotonic())
                        )
                if self._closing and not self._items and not self._chunks:
                    if self._flush_slots is not None:
                        self._flush_slots.release()
                    return
                wait = (
                    self._adaptive.next_wait()
                    if self._adaptive is not None
                    else self.sync_wait
                )
                deadline = time.monotonic() + wait
                while (
                    len(self._items) + self._chunk_count < self.batch_limit
                    and not self._closing
                ):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
                drained_oldest = self._oldest_ts
                batch, chunks = self._drain_locked()
                turn = self._take_turn()
            if self._flush_pool is not None:
                self._flush_pool.submit(
                    self._flush_pooled, turn, batch, chunks, drained_oldest
                )
                continue
            try:
                self._flush_in_turn(turn, batch, chunks, drained_oldest)
            except Exception:  # noqa: BLE001 — loop must survive flush errors
                import logging

                from gubernator_tpu.utils.metrics import record_swallowed

                record_swallowed("batcher.flush")
                logging.getLogger("gubernator_tpu").exception(
                    "batcher flush failed"
                )

    def _drain_locked(self, limit: int | None = -1):
        """Take up to `drain_limit` queued items (caller holds the
        lock).  Returns (items_dict, chunk_list).  limit=None forces a
        full drain (flush_now / tests)."""
        from_loop = limit == -1
        if limit == -1:
            limit = self._drain_limit
        # item_drain_limit applies only to the loop's cycles; an
        # explicit flush_now/close drain (limit=None from the caller)
        # takes everything.
        item_cap = self._item_drain_limit if from_loop else None
        if (
            limit is None
            and item_cap is not None
            and len(self._items) > item_cap
        ):
            # Full chunk drain (vectorized flush) but a BOUNDED dict
            # drain: dict items cost per-key Python in the flush, so
            # an unbounded dict backlog would be the §15 monster
            # flush all over again.
            taken = 0
            batch: Dict[K, V] = {}
            for k in list(self._items.keys()):
                if taken >= item_cap:
                    break
                batch[k] = self._items.pop(k)
                taken += 1
            pairs, self._chunks = self._chunks, []
            drained = taken + self._chunk_count
            self._chunk_count = 0
            if self._adaptive is not None:
                self._adaptive.observe(drained)
            if self._wait_stat is not None:
                self._wait_stat.observe(
                    max(0.0, time.monotonic() - self._oldest_ts)
                )
            # Dict items remain and per-key arrival is untracked: the
            # old anchor stands (overestimating age is the safe
            # direction for an overload gauge).
            self._space.notify_all()
            return batch, [c for c, _, _ in pairs]
        drained = len(self._items) + self._chunk_count
        if limit is None or drained <= limit:
            if self._adaptive is not None:
                self._adaptive.observe(drained)
            if self._wait_stat is not None and drained:
                self._wait_stat.observe(
                    max(0.0, time.monotonic() - self._oldest_ts)
                )
            batch, self._items = self._items, {}
            pairs, self._chunks = self._chunks, []
            self._chunk_count = 0
            self._space.notify_all()
            return batch, [c for c, _, _ in pairs]
        if self._adaptive is not None:
            self._adaptive.observe(limit)
        if self._wait_stat is not None:
            self._wait_stat.observe(
                max(0.0, time.monotonic() - self._oldest_ts)
            )
        taken = 0
        batch: Dict[K, V] = {}
        # CPython dicts iterate in insertion order: oldest keys first.
        for k in list(self._items.keys()):
            if taken >= limit:
                break
            batch[k] = self._items.pop(k)
            taken += 1
        chunks = []
        while self._chunks and taken < limit:
            ch, cnt, _ts = self._chunks.pop(0)
            chunks.append(ch)
            self._chunk_count -= cnt
            taken += cnt
        # Re-anchor the backlog age on the oldest REMAINING chunk's
        # real arrival time — resetting to now() here made the gauge
        # read "healthy" through the exact sustained overload it
        # exists to expose.  With only dict items left the old anchor
        # stands (per-key arrival is untracked; overestimating age is
        # the safe direction for an overload gauge).
        if self._chunks:
            self._oldest_ts = self._chunks[0][2]
        elif not self._items:
            self._oldest_ts = time.monotonic()
        self._space.notify_all()
        return batch, chunks

    def _take_turn(self) -> int:
        """Reserve the next flush turn.  Caller holds the queue lock —
        the snapshot and its turn number are taken atomically."""
        with self._turn_cv:
            turn = self._next_turn
            self._next_turn += 1
            if self._flush_pool is not None:
                self._active_turns.add(turn)
        return turn

    def _flush_in_turn(
        self, turn: int, batch, chunks, drained_oldest: float = 0.0
    ) -> None:
        """Run the flush when (and only when) its turn comes up, so
        snapshot order == delivery order; always advances the turn."""
        with self._turn_cv:
            while self._done_turn != turn:
                self._turn_cv.wait()
        try:
            self._flush_batch(batch, chunks, drained_oldest)
        finally:
            with self._turn_cv:
                self._done_turn = turn + 1
                self._turn_cv.notify_all()

    def _flush_pooled(
        self, turn: int, batch, chunks, drained_oldest: float
    ) -> None:
        """Pool-mode flush: runs CONCURRENTLY with other flushes (no
        turn wait — only commutative flushes use the pool); completion
        is tracked per turn so flush_now can wait out older snapshots."""
        try:
            self._flush_batch(batch, chunks, drained_oldest)
        except Exception:  # noqa: BLE001 — pool must survive flush errors
            import logging

            from gubernator_tpu.utils.metrics import record_swallowed

            record_swallowed("batcher.flush_pooled")
            logging.getLogger("gubernator_tpu").exception(
                "batcher flush failed"
            )
        finally:
            self._flush_slots.release()
            with self._turn_cv:
                self._active_turns.discard(turn)
                self._turn_cv.notify_all()

    def _flush_batch(self, batch, chunks, drained_oldest: float) -> None:
        if batch or chunks:
            if self._chunked:
                self._flush(batch, chunks)
            else:
                self._flush(batch)
            if self._age_stat is not None and drained_oldest:
                # Enqueue→delivered age of the snapshot's oldest item:
                # the stage a consumer of this batcher actually waits
                # (broadcast age in the GLOBAL budget).
                self._age_stat.observe(
                    max(0.0, time.monotonic() - drained_oldest)
                )

    def flush_now(self, force_held: bool = False) -> None:
        """Flush everything queued immediately, on the caller's thread
        (operational drains + deterministic tests).  Returns only after
        every OLDER snapshot's flush AND this drain complete; producers
        never wait on flush execution.  `force_held=True` also promotes
        not-yet-due held retry batches into this drain (convergence
        probes after a heal: deliver the backlog NOW instead of waiting
        out the backoff)."""
        with self._lock:
            self._promote_held_locked(force=force_held)
            drained_oldest = self._oldest_ts
            batch, chunks = self._drain_locked(limit=None)
            turn = self._take_turn()
        if self._flush_pool is None:
            self._flush_in_turn(turn, batch, chunks, drained_oldest)
            return
        try:
            self._flush_batch(batch, chunks, drained_oldest)
        finally:
            with self._turn_cv:
                self._active_turns.discard(turn)
                self._turn_cv.notify_all()
                # Older concurrent flushes may still be in flight;
                # everything enqueued before this call is either in
                # our snapshot or in one of them.
                while any(t < turn for t in self._active_turns):
                    self._turn_cv.wait()

    def close(self, timeout: float = 5.0) -> None:
        """Stop, flushing anything still queued."""
        with self._lock:
            if self._closing:
                return
            self._closing = True
            self._cv.notify_all()
            self._space.notify_all()
        self._thread.join(timeout)
        if self._flush_pool is not None:
            self._flush_pool.shutdown(wait=True)

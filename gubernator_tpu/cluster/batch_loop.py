"""Interval-driven aggregation loop — the framework's host-side batcher.

The reference builds this pattern three times (peer batching
peer_client.go:380-453, GLOBAL hit/broadcast loops global.go:78-202,
multi-region multiregion.go:43-92): accumulate items into an aggregate,
flush when the aggregate reaches `batch_limit` or `sync_wait` has
elapsed since the first item.  This is the one host-side primitive that
feeds the TPU step cadence, so it lives in one place.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Generic, TypeVar

K = TypeVar("K")
V = TypeVar("V")


class IntervalBatcher(Generic[K, V]):
    """Aggregate (key, item) pairs; flush at batch_limit or sync_wait.

    `combine(existing, item) -> merged` merges a new item into the
    aggregate for its key (None existing for the first).  `flush(dict)`
    runs on the batcher thread; long work should hop to an executor.
    """

    def __init__(
        self,
        sync_wait: float,
        batch_limit: int,
        combine: Callable,
        flush: Callable[[Dict[K, V]], None],
        *,
        name: str = "batcher",
        chunked: bool = False,
    ):
        self.sync_wait = sync_wait
        self.batch_limit = batch_limit
        self._combine = combine
        self._flush = flush
        # chunked=True: the flush callable accepts (dict, chunks) and
        # add_chunk is available — the columnar wire path queues whole
        # column slices in O(1) instead of per-item dict merges, and
        # the flush thread does the per-key work off the serving path.
        self._chunked = chunked
        self._items: Dict[K, V] = {}
        self._chunks: list = []
        self._chunk_count = 0
        self._lock = threading.Lock()
        # Serializes flush EXECUTION (the queue lock only guards the
        # swap): flush_now must not race the batcher thread's in-flight
        # flush — two concurrent broadcast flushes could deliver a
        # staler state snapshot after a fresher one, regressing peer
        # caches — and must not return before that flush completes.
        self._flush_lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._closing = False
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()

    def add(self, key: K, item) -> None:
        with self._lock:
            if self._closing:
                return
            self._items[key] = self._combine(self._items.get(key), item)
            self._cv.notify()

    def pending(self) -> int:
        """Items currently queued for the next flush (metrics gauge)."""
        with self._lock:
            return len(self._items) + self._chunk_count

    def add_many(self, pairs) -> None:
        """Batch enqueue under ONE lock acquisition — a 1000-item wire
        batch must not pay 1000 lock round-trips (VERDICT r1 weak 8)."""
        with self._lock:
            if self._closing:
                return
            items = self._items
            combine = self._combine
            for key, item in pairs:
                items[key] = combine(items.get(key), item)
            self._cv.notify()

    def add_chunk(self, chunk, count: int) -> None:
        """Queue one columnar chunk (O(1): stores references only).
        Requires chunked=True."""
        assert self._chunked
        with self._lock:
            if self._closing:
                return
            self._chunks.append(chunk)
            self._chunk_count += count
            self._cv.notify()

    def _run(self) -> None:
        while True:
            with self._lock:
                while not self._items and not self._chunks and not self._closing:
                    self._cv.wait()
                if self._closing and not self._items and not self._chunks:
                    return
                deadline = time.monotonic() + self.sync_wait
                while (
                    len(self._items) + self._chunk_count < self.batch_limit
                    and not self._closing
                ):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
                batch = self._items
                self._items = {}
                chunks = self._chunks
                self._chunks = []
                self._chunk_count = 0
                # Hand-over-hand: take the flush lock BEFORE releasing
                # the queue lock, so snapshot order == flush order (a
                # later flush_now snapshot must never broadcast before
                # this older one — lock order is always _lock →
                # _flush_lock, so no deadlock).
                self._flush_lock.acquire()
            try:
                if self._chunked:
                    self._flush(batch, chunks)
                else:
                    self._flush(batch)
            except Exception:  # noqa: BLE001 — loop must survive flush errors
                import logging

                logging.getLogger("gubernator_tpu").exception(
                    "batcher flush failed"
                )
            finally:
                self._flush_lock.release()

    def flush_now(self) -> None:
        """Flush everything queued immediately, on the caller's thread
        (operational drains + deterministic tests).  Returns only after
        any in-flight batcher-thread flush AND this drain complete
        (the shared _flush_lock serializes both)."""
        with self._lock:
            batch = self._items
            self._items = {}
            chunks = self._chunks
            self._chunks = []
            self._chunk_count = 0
            # Same hand-over-hand as _run: snapshot order == flush
            # order across the batcher thread and drain callers.
            self._flush_lock.acquire()
        try:
            if not batch and not chunks:
                return
            if self._chunked:
                self._flush(batch, chunks)
            else:
                self._flush(batch)
        finally:
            self._flush_lock.release()

    def close(self, timeout: float = 5.0) -> None:
        """Stop, flushing anything still queued."""
        with self._lock:
            if self._closing:
                return
            self._closing = True
            self._cv.notify_all()
        self._thread.join(timeout)

"""Interval-driven aggregation loop — the framework's host-side batcher.

The reference builds this pattern three times (peer batching
peer_client.go:380-453, GLOBAL hit/broadcast loops global.go:78-202,
multi-region multiregion.go:43-92): accumulate items into an aggregate,
flush when the aggregate reaches `batch_limit` or `sync_wait` has
elapsed since the first item.  This is the one host-side primitive that
feeds the TPU step cadence, so it lives in one place.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Generic, TypeVar

K = TypeVar("K")
V = TypeVar("V")


class IntervalBatcher(Generic[K, V]):
    """Aggregate (key, item) pairs; flush at batch_limit or sync_wait.

    `combine(existing, item) -> merged` merges a new item into the
    aggregate for its key (None existing for the first).  `flush(dict)`
    runs on the batcher thread; long work should hop to an executor.
    """

    def __init__(
        self,
        sync_wait: float,
        batch_limit: int,
        combine: Callable,
        flush: Callable[[Dict[K, V]], None],
        *,
        name: str = "batcher",
        chunked: bool = False,
    ):
        self.sync_wait = sync_wait
        self.batch_limit = batch_limit
        self._combine = combine
        self._flush = flush
        # chunked=True: the flush callable accepts (dict, chunks) and
        # add_chunk is available — the columnar wire path queues whole
        # column slices in O(1) instead of per-item dict merges, and
        # the flush thread does the per-key work off the serving path.
        self._chunked = chunked
        self._items: Dict[K, V] = {}
        self._chunks: list = []
        self._chunk_count = 0
        self._lock = threading.Lock()
        # Flush ORDERING without blocking producers: each snapshot
        # takes a turn number under the queue lock; flushes then run
        # strictly in turn order, coordinated on a separate condition
        # so add()/add_many()/add_chunk() never wait on an in-flight
        # flush (a later flush_now snapshot broadcasting before an
        # older batcher snapshot would regress peer caches).
        self._turn_cv = threading.Condition(threading.Lock())
        self._next_turn = 0  # next turn number to hand out
        self._done_turn = 0  # turns fully flushed
        self._cv = threading.Condition(self._lock)
        self._closing = False
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()

    def add(self, key: K, item) -> None:
        with self._lock:
            if self._closing:
                return
            self._items[key] = self._combine(self._items.get(key), item)
            self._cv.notify()

    def pending(self) -> int:
        """Items currently queued for the next flush (metrics gauge)."""
        with self._lock:
            return len(self._items) + self._chunk_count

    def add_many(self, pairs) -> None:
        """Batch enqueue under ONE lock acquisition — a 1000-item wire
        batch must not pay 1000 lock round-trips (VERDICT r1 weak 8)."""
        with self._lock:
            if self._closing:
                return
            items = self._items
            combine = self._combine
            for key, item in pairs:
                items[key] = combine(items.get(key), item)
            self._cv.notify()

    def add_chunk(self, chunk, count: int) -> None:
        """Queue one columnar chunk (O(1): stores references only).
        Requires chunked=True."""
        assert self._chunked
        with self._lock:
            if self._closing:
                return
            self._chunks.append(chunk)
            self._chunk_count += count
            self._cv.notify()

    def _run(self) -> None:
        while True:
            with self._lock:
                while not self._items and not self._chunks and not self._closing:
                    self._cv.wait()
                if self._closing and not self._items and not self._chunks:
                    return
                deadline = time.monotonic() + self.sync_wait
                while (
                    len(self._items) + self._chunk_count < self.batch_limit
                    and not self._closing
                ):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
                batch = self._items
                self._items = {}
                chunks = self._chunks
                self._chunks = []
                self._chunk_count = 0
                turn = self._take_turn()
            try:
                self._flush_in_turn(turn, batch, chunks)
            except Exception:  # noqa: BLE001 — loop must survive flush errors
                import logging

                logging.getLogger("gubernator_tpu").exception(
                    "batcher flush failed"
                )

    def _take_turn(self) -> int:
        """Reserve the next flush turn.  Caller holds the queue lock —
        the snapshot and its turn number are taken atomically."""
        with self._turn_cv:
            turn = self._next_turn
            self._next_turn += 1
        return turn

    def _flush_in_turn(self, turn: int, batch, chunks) -> None:
        """Run the flush when (and only when) its turn comes up, so
        snapshot order == delivery order; always advances the turn."""
        with self._turn_cv:
            while self._done_turn != turn:
                self._turn_cv.wait()
        try:
            if batch or chunks:
                if self._chunked:
                    self._flush(batch, chunks)
                else:
                    self._flush(batch)
        finally:
            with self._turn_cv:
                self._done_turn = turn + 1
                self._turn_cv.notify_all()

    def flush_now(self) -> None:
        """Flush everything queued immediately, on the caller's thread
        (operational drains + deterministic tests).  Returns only after
        every OLDER snapshot's flush AND this drain complete (turn
        ordering); producers never wait on flush execution."""
        with self._lock:
            batch = self._items
            self._items = {}
            chunks = self._chunks
            self._chunks = []
            self._chunk_count = 0
            turn = self._take_turn()
        self._flush_in_turn(turn, batch, chunks)

    def close(self, timeout: float = 5.0) -> None:
        """Stop, flushing anything still queued."""
        with self._lock:
            if self._closing:
                return
            self._closing = True
            self._cv.notify_all()
        self._thread.join(timeout)

"""Cluster tier: peer topology, routing, forwarding, GLOBAL sync.

The host-level distribution plane of the framework (SURVEY.md §2.2/§2.3):
consistent-hash key→owner routing, batched peer forwarding over gRPC,
async GLOBAL aggregation/broadcast.  The device-level plane (key→shard
within the mesh, ICI collectives) lives in `gubernator_tpu.parallel`.
"""

from gubernator_tpu.cluster.hash_ring import (
    DEFAULT_REPLICAS,
    DualRingWindow,
    ReplicatedConsistentHash,
    RegionPicker,
)
from gubernator_tpu.cluster.membership import MembershipManager

__all__ = [
    "DEFAULT_REPLICAS",
    "DualRingWindow",
    "MembershipManager",
    "ReplicatedConsistentHash",
    "RegionPicker",
]

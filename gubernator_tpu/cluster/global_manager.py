"""GLOBAL behavior: async hit aggregation + owner broadcast.

reference: global.go.  Two independent interval loops:

- hits loop (non-owners): aggregate queued hits per key (summing
  `hits`, global.go:92-95), then group per owner peer and forward via
  `GetPeerRateLimits` (global.go:124-164).
- broadcast loop (owner): dedupe updated keys per window, re-read own
  authoritative state with GLOBAL cleared and hits=0, and push
  `UpdatePeerGlobals` to every other peer (global.go:167-250).

The broadcast's local re-read rides the TPU engine as one batch (the
reference loops per key); the per-peer fan-out is host gRPC over DCN —
the ICI-level aggregation lives in the sharded engine's psum step.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import replace
from typing import TYPE_CHECKING, Dict, List

from gubernator_tpu.cluster.batch_loop import IntervalBatcher
from gubernator_tpu.cluster.peer_client import PeerError
from gubernator_tpu.config import BehaviorConfig
from gubernator_tpu.types import (
    MAX_BATCH_SIZE,
    Algorithm,
    Behavior,
    RateLimitReq,
    RateLimitResp,
    Status,
    UpdatePeerGlobal,
)

if TYPE_CHECKING:
    from gubernator_tpu.service import V1Instance

log = logging.getLogger("gubernator_tpu.global")


def _combine_hits(existing: RateLimitReq | None, r: RateLimitReq) -> RateLimitReq:
    """Sum hits for the same key within a window. reference: global.go:92-95."""
    if existing is None:
        return r
    return replace(existing, hits=existing.hits + r.hits)


def _combine_updates(existing: RateLimitReq | None, r: RateLimitReq) -> RateLimitReq:
    """Broadcasts dedupe by key, keeping the latest. reference: global.go:176."""
    return r


class GlobalManager:
    """reference: global.go:33-66 (globalManager)."""

    def __init__(self, conf: BehaviorConfig, instance: "V1Instance"):
        self.conf = conf
        self.instance = instance
        from concurrent.futures import ThreadPoolExecutor

        from gubernator_tpu.utils.metrics import DurationStat

        # Metrics counters (scraped via utils.metrics).  Guarded by a
        # tiny lock: hits flushes run CONCURRENTLY on the flush pool,
        # and `x += 1` is not atomic across bytecodes.
        self._counter_lock = threading.Lock()
        self.async_sends = 0  # guberlint: guarded-by _counter_lock
        self.broadcasts = 0  # guberlint: guarded-by _counter_lock
        # Apply-order sequence for serve-time update chunks
        # (next_update_seq; itertools.count.__next__ is atomic).
        import itertools

        self._update_seq = itertools.count(1)
        # reference: guber_async_durations / guber_broadcast_durations
        # (global.go:41-57).
        self.hits_duration = DurationStat()
        self.broadcast_duration = DurationStat()
        # Stage timers for the cluster-tier p50 budget (VERDICT r5
        # next-round #3): how long queued hits wait for their window,
        # how long each owner RPC takes, and the enqueue→delivered age
        # of broadcast updates.  Exported as
        # gubernator_stage_duration{stage=...} via utils.metrics.
        self.hits_window_wait = DurationStat()
        self.owner_rpc_duration = DurationStat()
        self.broadcast_age = DurationStat()
        # Fan-out pool: owner RPCs and per-peer broadcast pushes run
        # CONCURRENTLY so one flush's wall time is the slowest RPC,
        # not the sum — and (with the hits batcher's flush workers)
        # the RPC wait overlaps serving instead of stalling the next
        # window (the pipelined-GLOBAL-flush half of VERDICT r5 #2).
        self._rpc_pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="guber-global-rpc"
        )
        drain = conf.global_batch_limit
        # Hits must not be lost (dropping under-counts the owner), so
        # a full hits queue BLOCKS the enqueueing serving thread — the
        # reference's channel backpressure (global.go:68-70).  No
        # deadlock: hits are only enqueued from client-facing handlers,
        # and the flush→owner RPC path never re-enters a hits queue.
        # drain_limit=None: the flush aggregates its whole drain
        # vectorized and chunks RPCs at MAX_BATCH_SIZE, so a deep
        # queue collapses into ONE aggregation pass instead of a
        # serial stream of window-sized flushes (each of which paid
        # its own ring pass + RPC round trip — the r5 mechanism that
        # pegged the queue and put the flush on the serving threads'
        # critical path via backpressure).  max_pending bounds the
        # drain; two flush workers keep a window aggregating while
        # the previous window's RPCs are in flight.
        self._hits = IntervalBatcher(
            conf.global_sync_wait,
            conf.global_batch_limit,
            _combine_hits,
            self._send_hits,
            name="guber-global-hits",
            chunked=True,
            drain_limit=None,
            item_drain_limit=drain,
            max_pending=16 * drain,
            overflow="block",
            adaptive=conf.adaptive_windows,
            flush_workers=2,
            wait_stat=self.hits_window_wait,
        )
        # Broadcast updates are supersedable (peers keep the latest
        # status; cache entries expire), so overload sheds the OLDEST
        # queued updates instead of blocking — blocking here could
        # deadlock a saturated cluster: the owner-side serving path
        # enqueues updates while handling the peers' own hits RPCs.
        # Flushes stay turn-ordered (a later status must never land
        # on a peer before an older one), so no flush pool here; the
        # overlap comes from the per-peer concurrent pushes inside
        # each flush.
        self._updates = IntervalBatcher(
            conf.global_sync_wait,
            conf.global_batch_limit,
            _combine_updates,
            self._broadcast_peers,
            name="guber-global-bcast",
            chunked=True,
            drain_limit=drain,
            max_pending=16 * drain,
            overflow="drop_oldest",
            adaptive=conf.adaptive_windows,
            age_stat=self.broadcast_age,
        )

    def queue_hit(self, r: RateLimitReq) -> None:
        """Queue hits observed by a non-owner. reference: global.go:68-70."""
        self._hits.add(r.hash_key(), r)

    def queue_hits_many(self, reqs) -> None:
        """Batch variant of queue_hit: one batcher lock per wire batch."""
        self._hits.add_many((r.hash_key(), r) for r in reqs)

    def queue_update(self, r: RateLimitReq) -> None:
        """Mark a key the owner must re-broadcast. reference: global.go:72-74."""
        self._updates.add(r.hash_key(), r)

    def queue_updates_many(self, reqs) -> None:
        """Batch enqueue under one lock (wire batches are ≤1000 items;
        a lock per item contends with the flush thread)."""
        self._updates.add_many((r.hash_key(), r) for r in reqs)

    # -- columnar enqueue (the wire fast path: O(1) per batch) ---------

    def queue_hits_chunk(self, dec, idx) -> None:
        """Queue (DecodedBatch, index array) — no per-item Python on
        the serving thread; the flush aggregates vectorized."""
        self._hits.add_chunk((dec, idx), len(idx))

    def next_update_seq(self) -> int:
        """Apply-order stamp for serve-time update chunks.  Callers
        take it IMMEDIATELY after their engine apply returns, so
        chunk sequence ≈ engine-apply order even when a slow thread
        reaches queue_updates_chunk after a faster later apply —
        without it, latest-wins dedupe keyed on queue position could
        broadcast a superseded status last.  (Residual window: two
        same-key submissions sharing one merged serve-window dispatch
        stamp in return order; their one-occurrence status skew is
        corrected by the next hit on the key — the GLOBAL plane's
        eventual-consistency contract.)"""
        return next(self._update_seq)

    def queue_updates_chunk(self, dec, idx, status, limit, remaining,
                            reset, seq: int = 0) -> None:
        """Queue owner-side updates WITH their serve-time decision
        columns: the broadcast pushes these captured statuses directly
        (latest occurrence in apply order wins), so the flush does no
        engine re-read and no per-key Python — the owner's serve
        already was the authoritative read of exactly these keys."""
        self._updates.add_chunk(
            (dec, idx, status, limit, remaining, reset, seq), len(idx)
        )

    # -- chunk aggregation (flush threads, window-amortized) -----------

    @staticmethod
    def _aggregate_chunks(chunks, sum_hits: bool) -> Dict[str, RateLimitReq]:
        """Per-key aggregation of queued (dec, idx) chunks, grouped by
        the decoded (fnv1a, fnv1) hash PAIR with numpy — hits summed
        (hits loop) or latest-wins (broadcast dedupe, reference:
        global.go:92-95, 176).  Python runs once per UNIQUE key, not
        per item: hot-key windows aggregate thousands of occurrences
        into a handful of groups entirely in numpy.  Key identity by
        two independent 64-bit FNV variants — a pair collision within
        one sync window is ~2^-128, far below memory-error rates."""
        import numpy as np

        if not chunks:
            return {}
        groups = GlobalManager._hash_pair_groups(chunks)
        if groups is None:
            return {}
        sums, last_flat, _, _ = groups
        # Flat source refs so the per-unique pass can reach the latest
        # occurrence's full row.
        chunk_id = np.repeat(
            np.arange(len(chunks), dtype=np.int64),
            [len(idx) for _, idx in chunks],
        )
        flat_j = np.concatenate([idx for _, idx in chunks])

        out: Dict[str, RateLimitReq] = {}
        raws = [dec.key_buf.tobytes() for dec, _ in chunks]
        for g in range(len(sums)):
            fl = int(last_flat[g])
            dec, _ = chunks[int(chunk_id[fl])]
            raw = raws[int(chunk_id[fl])]
            j = int(flat_j[fl])
            a, b = int(dec.key_offsets[j]), int(dec.key_offsets[j + 1])
            kb = raw[a:b]
            nl = int(dec.name_len[j])
            out[kb.decode()] = RateLimitReq(
                name=kb[:nl].decode(),
                unique_key=kb[nl + 1:].decode(),
                hits=int(sums[g]) if sum_hits else int(dec.hits[j]),
                limit=int(dec.limit[j]),
                duration=int(dec.duration[j]),
                algorithm=int(dec.algo[j]),
                behavior=int(dec.behavior[j]),
                burst=int(dec.burst[j]),
            )
        return out

    # -- flush paths (run on batcher threads) --------------------------

    def _send_hits(self, hits: Dict[str, RateLimitReq], chunks=None) -> None:
        """Group aggregated hits per owner and forward.

        reference: global.go:124-164 (sendHits).
        """
        import time

        from gubernator_tpu.utils.tracing import span

        if not hits and chunks:
            # Hot case (all traffic arrived via the wire fast path):
            # aggregate, route, encode and send entirely columnar —
            # zero request objects per key (VERDICT r3 #2).
            t0 = time.monotonic()
            if self._send_hits_columnar(chunks):
                self.hits_duration.observe(time.monotonic() - t0)
                return
        for k, r in self._aggregate_chunks(chunks or [], sum_hits=True).items():
            hits[k] = _combine_hits(hits.get(k), r)
        if not hits:
            return
        t0 = time.monotonic()
        with span("global.hits_window", keys=len(hits)):
            self._send_hits_traced(hits)
        self.hits_duration.observe(time.monotonic() - t0)

    def _send_hits_columnar(self, chunks) -> bool:
        """Columnar hits fan-out: returns False to use the dataclass
        fallback (codec unavailable / empty picker)."""
        import numpy as np

        from gubernator_tpu.net import wire_codec
        from gubernator_tpu.utils.tracing import span

        if wire_codec.load() is None:
            return False
        agg = self._aggregate_chunk_columns(chunks)
        if agg is None:
            return True  # nothing queued
        (key_buf, starts, lens, name_len, algo, behavior, hits_col,
         limit, duration, burst, h1, h1a) = agg
        owners = self.instance.get_peer_batch_hashed(h1, h1a)
        if owners is None:
            return False
        n = len(algo)
        with span("global.hits_window", keys=n):
            by_addr: Dict[str, list] = {}
            clients = {}
            for i, peer in enumerate(owners):
                addr = peer.info.grpc_address
                by_addr.setdefault(addr, []).append(i)
                clients[addr] = peer

            def _send_one_owner(addr: str, idx_list: list) -> None:
                import time as _time

                peer = clients[addr]
                idx = np.asarray(idx_list, dtype=np.int64)
                try:
                    if peer.info.is_owner:
                        # Ownership moved to us between queue and
                        # flush (rare): behave like the owner path —
                        # materialize just this group.
                        self.instance.apply_local_batch(
                            [
                                self._req_from_columns(
                                    key_buf, starts, lens, name_len,
                                    algo, behavior, hits_col, limit,
                                    duration, burst, int(i),
                                )
                                for i in idx_list
                            ]
                        )
                        return
                    for lo in range(0, len(idx), MAX_BATCH_SIZE):
                        sub = idx[lo:lo + MAX_BATCH_SIZE]
                        sub_buf, sub_off = wire_codec.gather_key_slices(
                            key_buf, starts[sub], lens[sub]
                        )
                        payload = wire_codec.encode_peer_reqs(
                            sub_buf, sub_off, name_len[sub],
                            algo[sub], behavior[sub], hits_col[sub],
                            limit[sub], duration[sub], burst[sub],
                        )
                        t_rpc = _time.monotonic()
                        peer.send_peer_hits_raw(
                            payload, timeout=self.conf.global_timeout
                        )
                        self.owner_rpc_duration.observe(
                            _time.monotonic() - t_rpc
                        )
                except PeerError as e:
                    log.error(
                        "error sending global hits to '%s': %s", addr, e
                    )

            # One task per owner: the window's wall time is the
            # slowest owner, not the sum over owners.
            if len(by_addr) == 1:
                addr, idx_list = next(iter(by_addr.items()))
                _send_one_owner(addr, idx_list)
            else:
                futs = [
                    self._rpc_pool.submit(_send_one_owner, addr, idx_list)
                    for addr, idx_list in by_addr.items()
                ]
                self._await_all(futs)
        with self._counter_lock:
            self.async_sends += 1
        return True

    @staticmethod
    def _req_from_columns(key_buf, starts, lens, name_len, algo,
                          behavior, hits, limit, duration, burst,
                          i: int) -> RateLimitReq:
        a = int(starts[i])
        kb = key_buf[a:a + int(lens[i])].tobytes()
        nl = int(name_len[i])
        return RateLimitReq(
            name=kb[:nl].decode(),
            unique_key=kb[nl + 1:].decode(),
            hits=int(hits[i]),
            limit=int(limit[i]),
            duration=int(duration[i]),
            algorithm=int(algo[i]),
            behavior=int(behavior[i]),
            burst=int(burst[i]),
        )

    @staticmethod
    def _union_key_columns(pairs):
        """Union key buffer + per-flat-occurrence (start, len) for a
        list of (dec, idx) chunk pairs — the shared indexing base of
        both flush aggregations (the broadcast encode and the hits
        column aggregation must never fork this math)."""
        import numpy as np

        bufs = [dec.key_buf for dec, _ in pairs]
        bases = np.zeros(len(bufs) + 1, dtype=np.int64)
        np.cumsum([len(b) for b in bufs], out=bases[1:])
        union = np.concatenate(bufs) if len(bufs) > 1 else bufs[0]
        starts = np.concatenate(
            [
                dec.key_offsets[:-1][idx] + bases[c]
                for c, (dec, idx) in enumerate(pairs)
            ]
        )
        lens = np.concatenate(
            [
                (dec.key_offsets[1:] - dec.key_offsets[:-1])[idx]
                for dec, idx in pairs
            ]
        )
        return union, starts, lens

    @staticmethod
    def _hash_pair_groups(chunks):
        """Shared grouping core for both flush aggregations: group the
        queued occurrences by the (fnv1a, fnv1) pair and return
        (summed hits per group, flat index of each group's LATEST
        occurrence, flat fnv1a, flat fnv1) — or None when nothing is
        queued.  The latest-occurrence trick depends on lexsort's
        stability (positions ascend within equal keys)."""
        import numpy as np

        if not chunks:
            return None
        h_a = np.concatenate([dec.fnv1a[idx] for dec, idx in chunks])
        if len(h_a) == 0:
            return None
        h_b = np.concatenate([dec.fnv1[idx] for dec, idx in chunks])
        hits = np.concatenate([dec.hits[idx] for dec, idx in chunks])
        order = np.lexsort((h_b, h_a))
        sa, sb = h_a[order], h_b[order]
        new_group = np.empty(len(order), dtype=bool)
        new_group[0] = True
        new_group[1:] = (sa[1:] != sa[:-1]) | (sb[1:] != sb[:-1])
        starts = np.nonzero(new_group)[0]
        sums = np.add.reduceat(hits[order], starts)
        ends = np.append(starts[1:], len(order))
        return sums, order[ends - 1], h_a, h_b

    @staticmethod
    def _aggregate_chunk_columns(chunks):
        """Vectorized per-key aggregation to COLUMNS (no request
        objects): returns (union key_buf, per-unique starts/lens,
        name_len, algo, behavior, summed hits, limit, duration, burst,
        fnv1, fnv1a) with latest-occurrence config fields, or None if
        nothing is queued.  Grouping identity: the (fnv1a, fnv1) hash
        pair (see _aggregate_chunks)."""
        import numpy as np

        groups = GlobalManager._hash_pair_groups(chunks)
        if groups is None:
            return None
        sums, sel, h_a, h_b = groups
        algo = np.concatenate([dec.algo[idx] for dec, idx in chunks])
        behavior = np.concatenate(
            [dec.behavior[idx] for dec, idx in chunks]
        )
        limit = np.concatenate([dec.limit[idx] for dec, idx in chunks])
        duration = np.concatenate(
            [dec.duration[idx] for dec, idx in chunks]
        )
        burst = np.concatenate([dec.burst[idx] for dec, idx in chunks])
        name_len = np.concatenate(
            [dec.name_len[idx] for dec, idx in chunks]
        )
        union, starts, lens = GlobalManager._union_key_columns(chunks)

        return (
            union, starts[sel], lens[sel], name_len[sel], algo[sel],
            behavior[sel], sums, limit[sel], duration[sel], burst[sel],
            h_b[sel], h_a[sel],
        )

    def _send_hits_traced(self, hits: Dict[str, RateLimitReq]) -> None:
        by_peer: Dict[str, List[RateLimitReq]] = {}
        clients = {}
        keys = list(hits.keys())
        try:
            # ONE ring lookup pass for the window (a per-key get_peer
            # burned ~27% of the cluster tier's core — PERF.md r4).
            peers = self.instance.get_peer_batch(keys)
        except Exception as e:  # noqa: BLE001
            log.error("while getting peers for hit window: %s", e)
            return
        for key, peer in zip(keys, peers):
            if peer is None:
                continue
            addr = peer.info.grpc_address
            by_peer.setdefault(addr, []).append(hits[key])
            clients[addr] = peer
        def _send_one(addr: str, reqs: List[RateLimitReq]) -> None:
            import time as _time

            peer = clients[addr]
            try:
                if peer.info.is_owner:
                    # Ownership may have moved to us between the queue
                    # and the flush; apply locally instead of dialing
                    # ourselves.
                    self.instance.apply_local_batch(reqs)
                else:
                    # Under burst load the window can aggregate more
                    # distinct keys than one RPC may carry; chunk to
                    # the wire's hard batch limit (gubernator.go:41).
                    for lo in range(0, len(reqs), MAX_BATCH_SIZE):
                        t_rpc = _time.monotonic()
                        peer.send_peer_hits(
                            reqs[lo : lo + MAX_BATCH_SIZE],
                            timeout=self.conf.global_timeout,
                        )
                        self.owner_rpc_duration.observe(
                            _time.monotonic() - t_rpc
                        )
            except PeerError as e:
                log.error("error sending global hits to '%s': %s", addr, e)

        if len(by_peer) == 1:
            addr, reqs = next(iter(by_peer.items()))
            _send_one(addr, reqs)
        else:
            futs = [
                self._rpc_pool.submit(_send_one, addr, reqs)
                for addr, reqs in by_peer.items()
            ]
            self._await_all(futs)
        with self._counter_lock:
            self.async_sends += 1

    def _broadcast_peers(self, updates: Dict[str, RateLimitReq], chunks=None) -> None:
        """Push authoritative statuses to every peer.

        reference: global.go:205-250 (broadcastPeers).  Columnar chunks
        carry their serve-time decision columns (queue_updates_chunk),
        so the hot path encodes them straight to the wire — no engine
        re-read, no per-key Python; only the dataclass path (pb
        traffic, stores) still re-reads its own state.
        """
        import time

        from gubernator_tpu.utils.tracing import span

        chunks = chunks or []
        n_keys = len(updates) + sum(len(c[1]) for c in chunks)
        if n_keys == 0:
            return
        t0 = time.monotonic()
        with span("global.broadcast", keys=n_keys):
            if chunks:
                payloads = self._broadcast_chunks_encoded(chunks)
                if payloads is None:
                    # Codec unavailable: aggregate into the dataclass
                    # path below (statuses re-read there).
                    updates = dict(updates)
                    updates.update(
                        self._aggregate_chunks(
                            [(d, i) for d, i, *_ in chunks],
                            sum_hits=False,
                        )
                    )
                elif payloads:

                    def _push_raw(peer) -> None:
                        try:
                            for raw in payloads:
                                peer.update_peer_globals_raw(
                                    raw, timeout=self.conf.global_timeout
                                )
                        except PeerError as e:
                            if not e.not_ready:
                                log.error(
                                    "while broadcasting global updates "
                                    "to '%s': %s",
                                    peer.info.grpc_address,
                                    e,
                                )

                    self._fanout_peers(_push_raw)
                    if not updates:
                        # One broadcast WINDOW = one count; when dict
                        # updates ride the same flush, the traced path
                        # below does the counting.
                        with self._counter_lock:
                            self.broadcasts += 1
            if updates:
                self._broadcast_peers_traced(updates)
        self.broadcast_duration.observe(time.monotonic() - t0)

    def _broadcast_chunks_encoded(self, chunks):
        """Serve-time columns → UpdatePeerGlobalsReq payload chunks,
        deduped latest-wins by the (fnv1a, fnv1) key-hash pair — all
        numpy + C, zero per-key Python.  None = codec unavailable
        (callers fall back to the dataclass re-read)."""
        import numpy as np

        from gubernator_tpu.net import wire_codec

        if wire_codec.load() is None:
            return None
        # Order by apply-completion sequence so "latest occurrence"
        # means latest ENGINE APPLY, not latest enqueue (stable sort:
        # in-chunk request order is already apply order).
        chunks = sorted(chunks, key=lambda c: c[6] if len(c) > 6 else 0)
        pairs = [(dec, idx) for dec, idx, *_ in chunks]
        groups = self._hash_pair_groups(pairs)
        if groups is None:
            return []
        _sums, sel, _, _ = groups
        algo = np.concatenate([dec.algo[idx] for dec, idx in pairs])[sel]
        st = np.concatenate([c[2] for c in chunks])[sel]
        lim = np.concatenate([c[3] for c in chunks])[sel]
        rem = np.concatenate([c[4] for c in chunks])[sel]
        rst = np.concatenate([c[5] for c in chunks])[sel]
        union, starts, lens = self._union_key_columns(pairs)
        starts = starts[sel]
        lens = lens[sel]
        n = len(sel)
        payloads = []
        for lo in range(0, n, MAX_BATCH_SIZE):
            hi = min(lo + MAX_BATCH_SIZE, n)
            sub_buf, off = wire_codec.gather_key_slices(
                union, starts[lo:hi], lens[lo:hi]
            )
            payloads.append(
                wire_codec.encode_globals(
                    sub_buf, off, algo[lo:hi], st[lo:hi],
                    lim[lo:hi], rem[lo:hi], rst[lo:hi],
                )
            )
        return payloads

    def _broadcast_peers_traced(self, updates: Dict[str, RateLimitReq]) -> None:
        payloads = self._reread_encoded(updates)
        if payloads is not None:
            # Native plane: one C-encoded UpdatePeerGlobalsReq per
            # MAX_BATCH chunk, pushed raw to every peer (the broadcast
            # fires every sync window — the pb path's per-item objects
            # were ~25% of the cluster tier's core, PERF.md r4).
            if not payloads:
                return

            def _push_raw(peer) -> None:
                try:
                    for raw in payloads:
                        peer.update_peer_globals_raw(
                            raw, timeout=self.conf.global_timeout
                        )
                except PeerError as e:
                    if not e.not_ready:
                        log.error(
                            "while broadcasting global updates to '%s': %s",
                            peer.info.grpc_address,
                            e,
                        )

            self._fanout_peers(_push_raw)
            with self._counter_lock:
                self.broadcasts += 1
            return
        globals_ = self._reread_own_state(updates)
        if not globals_:
            return

        def _push_pb(peer) -> None:
            try:
                # Chunk: keep each UpdatePeerGlobals under the wire's
                # batch/message-size limits under burst load.
                for lo in range(0, len(globals_), MAX_BATCH_SIZE):
                    peer.update_peer_globals(
                        globals_[lo : lo + MAX_BATCH_SIZE],
                        timeout=self.conf.global_timeout,
                    )
            except PeerError as e:
                if not e.not_ready:
                    log.error(
                        "while broadcasting global updates to '%s': %s",
                        peer.info.grpc_address,
                        e,
                    )

        self._fanout_peers(_push_pb)
        with self._counter_lock:
            self.broadcasts += 1

    def _fanout_peers(self, push) -> None:
        """Run `push(peer)` for every non-self peer, CONCURRENTLY when
        there is more than one: the broadcast's wall time is the
        slowest peer, not the sum over peers.  Per-peer delivery order
        is preserved because broadcast flushes themselves stay
        turn-ordered (each flush completes all its pushes before the
        next flush starts)."""
        peers = [
            p for p in self.instance.get_peer_list()
            if not p.info.is_owner  # exclude ourselves
        ]
        if not peers:
            return
        if len(peers) == 1:
            push(peers[0])
            return
        self._await_all([self._rpc_pool.submit(push, p) for p in peers])

    @staticmethod
    def _await_all(futs) -> None:
        """Wait for EVERY fan-out task, logging failures per task — a
        sequential f.result() loop would abandon (and silently
        swallow) the remaining tasks on the first non-PeerError."""
        for f in futs:
            try:
                f.result()
            except Exception:  # noqa: BLE001 — peers must not sink peers
                from gubernator_tpu.utils.metrics import record_swallowed

                record_swallowed("global.fanout")
                log.exception("global fan-out task failed")

    def _reread_encoded(self, updates: Dict[str, RateLimitReq]):
        """Columnar re-read + native encode: returns a list of
        UpdatePeerGlobalsReq payload chunks, or None to use the pb
        fallback (codec unavailable, store attached, Gregorian keys)."""
        from gubernator_tpu.net import wire_codec

        if wire_codec.load() is None:
            return None
        eng = self.instance.engine
        if getattr(eng, "apply_columnar", None) is None or getattr(
            eng, "store", None
        ) is not None:
            return None
        import numpy as np

        items = list(updates.values())
        n = len(items)
        if n == 0:
            return []
        keys_b = [r.hash_key().encode() for r in items]
        algo = np.fromiter((int(r.algorithm) for r in items), np.int32, n)
        behavior = np.fromiter(
            (int(r.behavior) & ~int(Behavior.GLOBAL) for r in items),
            np.int32, n,
        )
        limit = np.fromiter((r.limit for r in items), np.int64, n)
        duration = np.fromiter((r.duration for r in items), np.int64, n)
        burst = np.fromiter((r.burst for r in items), np.int64, n)
        try:
            st, lim, rem, rst = eng.apply_columnar(
                keys_b, algo, behavior,
                np.zeros(n, dtype=np.int64),  # hits=0: report-only
                limit, duration, burst,
            )
        except Exception:  # noqa: BLE001 — e.g. invalid Gregorian
            return None
        ledger = getattr(self.instance, "ledger", None)
        if ledger is not None:
            # Leases PRE-DEBIT their credit, so the device UNDER-reports
            # the logical remaining by the held (unconsumed) budget;
            # the broadcast must add it back or peers under-admit.
            rem = np.asarray(rem).copy()
            ledger.readonly_overlay(keys_b, rem)
        key_buf = np.frombuffer(b"".join(keys_b), dtype=np.uint8)
        key_off = np.zeros(n + 1, dtype=np.int64)
        np.cumsum([len(k) for k in keys_b], out=key_off[1:])
        payloads = []
        for lo in range(0, n, MAX_BATCH_SIZE):
            hi = min(lo + MAX_BATCH_SIZE, n)
            sub_off = (key_off[lo:hi + 1] - key_off[lo])
            payloads.append(wire_codec.encode_globals(
                key_buf[key_off[lo]:key_off[hi]], sub_off,
                algo[lo:hi], st[lo:hi], lim[lo:hi], rem[lo:hi],
                rst[lo:hi],
            ))
        return payloads

    def _reread_own_state(
        self, updates: Dict[str, RateLimitReq]
    ) -> List[UpdatePeerGlobal]:
        """Status query (hits=0, GLOBAL cleared) of every queued key.

        Columnar when the engine allows it — broadcast windows fire
        every global_sync_wait (500µs default) and hold the engine
        lock, so the dataclass path's per-item Python here throttled
        the whole node under GLOBAL load (profiled ~20ms per 1000-key
        window; columnar is ~3ms).  reference: global.go:205-228."""
        eng = self.instance.engine
        items = list(updates.values())
        apply_columnar = getattr(eng, "apply_columnar", None)
        if apply_columnar is not None and getattr(eng, "store", None) is None:
            import numpy as np

            n = len(items)
            keys_str = [r.hash_key() for r in items]
            algo = np.fromiter((int(r.algorithm) for r in items), np.int32, n)
            behavior = np.fromiter(
                (int(r.behavior) & ~int(Behavior.GLOBAL) for r in items),
                np.int32,
                n,
            )
            limit = np.fromiter((r.limit for r in items), np.int64, n)
            duration = np.fromiter((r.duration for r in items), np.int64, n)
            burst = np.fromiter((r.burst for r in items), np.int64, n)
            try:
                st, lim, rem, rst = apply_columnar(
                    [k.encode() for k in keys_str],
                    algo,
                    behavior,
                    np.zeros(n, dtype=np.int64),  # hits=0: report-only
                    limit,
                    duration,
                    burst,
                )
            except Exception:  # noqa: BLE001 — e.g. a queued key with an
                # invalid Gregorian interval; the dataclass path turns
                # that into a per-item error response instead.
                return self._reread_dataclass(items)
            ledger = getattr(self.instance, "ledger", None)
            if ledger is not None:
                rem = np.asarray(rem).copy()
                ledger.readonly_overlay(
                    [k.encode() for k in keys_str], rem
                )
            status_of = {int(s): s for s in Status}
            return [
                UpdatePeerGlobal(
                    key=keys_str[i],
                    status=RateLimitResp(
                        status=status_of[int(st[i])],
                        limit=int(lim[i]),
                        remaining=int(rem[i]),
                        reset_time=int(rst[i]),
                    ),
                    algorithm=Algorithm(int(algo[i])),
                )
                for i in range(n)
            ]
        return self._reread_dataclass(items)

    def _reread_dataclass(
        self, items: List[RateLimitReq]
    ) -> List[UpdatePeerGlobal]:
        reqs = [
            replace(
                r,
                behavior=int(r.behavior) & ~int(Behavior.GLOBAL),
                hits=0,
            )
            for r in items
        ]
        resps = self.instance.apply_local_batch(reqs)
        globals_: List[UpdatePeerGlobal] = []
        for r, resp in zip(reqs, resps):
            if resp.error:
                log.error(
                    "while broadcasting update to peers for '%s': %s",
                    r.hash_key(),
                    resp.error,
                )
                continue
            globals_.append(
                UpdatePeerGlobal(
                    key=r.hash_key(),
                    status=resp,
                    algorithm=Algorithm(r.algorithm),
                )
            )
        return globals_

    def flush_now(self) -> None:
        """Synchronously drain both windows: forward aggregated hits
        to owners, then broadcast re-read state to peers.  Bounds the
        eventually-consistent lag on demand (graceful drains, tests)."""
        self._hits.flush_now()
        self._updates.flush_now()

    def close(self) -> None:
        self._hits.close()
        self._updates.close()
        self._rpc_pool.shutdown(wait=True)

"""GLOBAL behavior: async hit aggregation + owner broadcast.

reference: global.go.  Two independent interval loops:

- hits loop (non-owners): aggregate queued hits per key (summing
  `hits`, global.go:92-95), then group per owner peer and forward via
  `GetPeerRateLimits` (global.go:124-164).
- broadcast loop (owner): dedupe updated keys per window, re-read own
  authoritative state with GLOBAL cleared and hits=0, and push
  `UpdatePeerGlobals` to every other peer (global.go:167-250).

The broadcast's local re-read rides the TPU engine as one batch (the
reference loops per key); the per-peer fan-out is host gRPC over DCN —
the ICI-level aggregation lives in the sharded engine's psum step.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import replace
from typing import TYPE_CHECKING, Dict, List

from gubernator_tpu.cluster.batch_loop import IntervalBatcher
from gubernator_tpu.cluster.peer_client import PeerError
from gubernator_tpu.config import BehaviorConfig
from gubernator_tpu.types import (
    MAX_BATCH_SIZE,
    Algorithm,
    Behavior,
    RateLimitReq,
    RateLimitResp,
    Status,
    UpdatePeerGlobal,
)

if TYPE_CHECKING:
    from gubernator_tpu.service import V1Instance

log = logging.getLogger("gubernator_tpu.global")


def _combine_hits(existing: RateLimitReq | None, r: RateLimitReq) -> RateLimitReq:
    """Sum hits for the same key within a window. reference: global.go:92-95."""
    if existing is None:
        return r
    return replace(existing, hits=existing.hits + r.hits)


def _combine_updates(existing: RateLimitReq | None, r: RateLimitReq) -> RateLimitReq:
    """Broadcasts dedupe by key, keeping the latest. reference: global.go:176."""
    return r


class GlobalManager:
    """reference: global.go:33-66 (globalManager)."""

    def __init__(self, conf: BehaviorConfig, instance: "V1Instance"):
        self.conf = conf
        self.instance = instance
        from concurrent.futures import ThreadPoolExecutor

        from gubernator_tpu.utils.metrics import DurationStat

        # Metrics counters (scraped via utils.metrics).  Guarded by a
        # tiny lock: hits flushes run CONCURRENTLY on the flush pool,
        # and `x += 1` is not atomic across bytecodes.
        self._counter_lock = threading.Lock()
        self.async_sends = 0  # guberlint: guarded-by _counter_lock
        self.broadcasts = 0  # guberlint: guarded-by _counter_lock
        # Health-plane accounting (RESILIENCE.md): broadcast pushes
        # skipped because the peer's circuit is open, hit windows
        # re-queued for a later retry, and re-queued hits dropped at
        # the age cap.
        self.broadcasts_skipped = 0  # guberlint: guarded-by _counter_lock
        # Skips because the peer's PREVIOUS push outlived the fan-out
        # deadline (slow-but-healthy peer) — distinct from circuit
        # skips so an operator can tell the two episodes apart.
        self.broadcasts_skipped_inflight = 0  # guberlint: guarded-by _counter_lock
        self.hits_requeued = 0  # guberlint: guarded-by _counter_lock
        self.hits_requeue_dropped = 0  # guberlint: guarded-by _counter_lock
        # First-queued timestamp per re-queued hit key: the age cap
        # that stops a long-dead owner's hits from replaying forever
        # (conf.hit_requeue_age; bounded at _REQUEUE_KEY_CAP keys).
        self._requeue_lock = threading.Lock()
        self._requeue_first: Dict[str, float] = {}  # guberlint: guarded-by _requeue_lock
        # Per-peer in-flight broadcast push (addr -> Future): the
        # bounded _await_all barrier can stop WAITING on a slow push,
        # but per-peer delivery ORDER must survive it — a flush-N
        # payload landing after flush N+1's would regress the peer's
        # cache.  A peer with an unfinished older push is skipped this
        # window (supersedable traffic; it catches up next window),
        # so pushes to any one peer stay serialized in flush order.
        # Only the turn-ordered broadcast flush thread touches this —
        # no lock needed.
        self._bcast_inflight: Dict[str, object] = {}
        # Apply-order sequence for serve-time update chunks
        # (next_update_seq; itertools.count.__next__ is atomic).
        import itertools

        self._update_seq = itertools.count(1)
        # Trace seeds: the window flushes aggregate MANY decisions, so
        # a window span adopts the context of the FIRST decision that
        # queued into it since the last flush — that is what stitches
        # forwarder → owner → broadcast into one cross-process trace
        # (OBSERVABILITY.md).  Benign-race Optionals: a lost store
        # means one window anchors to a different (equally valid)
        # decision; tracing-off pays one global check at the enqueue
        # sites and nothing else.
        self._hits_seed = None
        self._updates_seed = None
        # reference: guber_async_durations / guber_broadcast_durations
        # (global.go:41-57).
        self.hits_duration = DurationStat()
        self.broadcast_duration = DurationStat()
        # Stage timers for the cluster-tier p50 budget (VERDICT r5
        # next-round #3): how long queued hits wait for their window,
        # how long each owner RPC takes, and the enqueue→delivered age
        # of broadcast updates.  Exported as
        # gubernator_stage_duration{stage=...} via utils.metrics.
        self.hits_window_wait = DurationStat()
        self.owner_rpc_duration = DurationStat()
        self.broadcast_age = DurationStat()
        # Fan-out pool: owner RPCs and per-peer broadcast pushes run
        # CONCURRENTLY so one flush's wall time is the slowest RPC,
        # not the sum — and (with the hits batcher's flush workers)
        # the RPC wait overlaps serving instead of stalling the next
        # window (the pipelined-GLOBAL-flush half of VERDICT r5 #2).
        self._rpc_pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="guber-global-rpc"
        )
        drain = conf.global_batch_limit
        # Hits must not be lost (dropping under-counts the owner), so
        # a full hits queue BLOCKS the enqueueing serving thread — the
        # reference's channel backpressure (global.go:68-70).  No
        # deadlock: hits are only enqueued from client-facing handlers,
        # and the flush→owner RPC path never re-enters a hits queue.
        # drain_limit=None: the flush aggregates its whole drain
        # vectorized and chunks RPCs at MAX_BATCH_SIZE, so a deep
        # queue collapses into ONE aggregation pass instead of a
        # serial stream of window-sized flushes (each of which paid
        # its own ring pass + RPC round trip — the r5 mechanism that
        # pegged the queue and put the flush on the serving threads'
        # critical path via backpressure).  max_pending bounds the
        # drain; two flush workers keep a window aggregating while
        # the previous window's RPCs are in flight.
        self._hits = IntervalBatcher(
            conf.global_sync_wait,
            conf.global_batch_limit,
            _combine_hits,
            self._send_hits,
            name="guber-global-hits",
            chunked=True,
            drain_limit=None,
            item_drain_limit=drain,
            max_pending=16 * drain,
            overflow="block",
            adaptive=conf.adaptive_windows,
            flush_workers=2,
            wait_stat=self.hits_window_wait,
        )
        # Broadcast updates are supersedable (peers keep the latest
        # status; cache entries expire), so overload sheds the OLDEST
        # queued updates instead of blocking — blocking here could
        # deadlock a saturated cluster: the owner-side serving path
        # enqueues updates while handling the peers' own hits RPCs.
        # Flushes stay turn-ordered (a later status must never land
        # on a peer before an older one), so no flush pool here; the
        # overlap comes from the per-peer concurrent pushes inside
        # each flush.
        self._updates = IntervalBatcher(
            conf.global_sync_wait,
            conf.global_batch_limit,
            _combine_updates,
            self._broadcast_peers,
            name="guber-global-bcast",
            chunked=True,
            drain_limit=drain,
            max_pending=16 * drain,
            overflow="drop_oldest",
            adaptive=conf.adaptive_windows,
            age_stat=self.broadcast_age,
        )

    def _seed_hits_trace(self) -> None:
        """Adopt the enqueuing decision's span context for the next
        hits window (first-in wins; one global check when off)."""
        from gubernator_tpu.utils import tracing

        if tracing.active() and self._hits_seed is None:
            self._hits_seed = tracing.current_context()

    def _seed_updates_trace(self) -> None:
        from gubernator_tpu.utils import tracing

        if tracing.active() and self._updates_seed is None:
            self._updates_seed = tracing.current_context()

    def queue_hit(self, r: RateLimitReq) -> None:
        """Queue hits observed by a non-owner. reference: global.go:68-70."""
        self._seed_hits_trace()
        self._hits.add(r.hash_key(), r)

    def queue_hits_many(self, reqs) -> None:
        """Batch variant of queue_hit: one batcher lock per wire batch."""
        self._seed_hits_trace()
        self._hits.add_many((r.hash_key(), r) for r in reqs)

    def queue_update(self, r: RateLimitReq) -> None:
        """Mark a key the owner must re-broadcast. reference: global.go:72-74."""
        self._seed_updates_trace()
        self._updates.add(r.hash_key(), r)

    def queue_updates_many(self, reqs) -> None:
        """Batch enqueue under one lock (wire batches are ≤1000 items;
        a lock per item contends with the flush thread)."""
        self._seed_updates_trace()
        self._updates.add_many((r.hash_key(), r) for r in reqs)

    # -- columnar enqueue (the wire fast path: O(1) per batch) ---------

    def queue_hits_chunk(self, dec, idx) -> None:
        """Queue (DecodedBatch, index array) — no per-item Python on
        the serving thread; the flush aggregates vectorized."""
        self._seed_hits_trace()
        self._hits.add_chunk((dec, idx), len(idx))

    def next_update_seq(self) -> int:
        """Apply-order stamp for serve-time update chunks.  Callers
        take it IMMEDIATELY after their engine apply returns, so
        chunk sequence ≈ engine-apply order even when a slow thread
        reaches queue_updates_chunk after a faster later apply —
        without it, latest-wins dedupe keyed on queue position could
        broadcast a superseded status last.  (Residual window: two
        same-key submissions sharing one merged serve-window dispatch
        stamp in return order; their one-occurrence status skew is
        corrected by the next hit on the key — the GLOBAL plane's
        eventual-consistency contract.)"""
        return next(self._update_seq)

    def queue_updates_chunk(self, dec, idx, status, limit, remaining,
                            reset, seq: int = 0) -> None:
        """Queue owner-side updates WITH their serve-time decision
        columns: the broadcast pushes these captured statuses directly
        (latest occurrence in apply order wins), so the flush does no
        engine re-read and no per-key Python — the owner's serve
        already was the authoritative read of exactly these keys."""
        self._seed_updates_trace()
        self._updates.add_chunk(
            (dec, idx, status, limit, remaining, reset, seq), len(idx)
        )

    # -- hit re-queue (owners that come back; RESILIENCE.md) -----------

    # Outstanding re-queued keys are bounded at this many windows'
    # worth of batch_limit — past it, new failures drop (counted)
    # instead of growing an unbounded retry backlog for a dead owner.
    _REQUEUE_KEY_CAP_WINDOWS = 4
    # Minimum spacing between requeue cycles.  Without it the loop
    # [flush → circuit-open fail (no dial, ~µs) → requeue → notify →
    # adaptive ~0 window → flush ...] spins a flush worker at
    # microsecond cadence against an open circuit, inflating
    # hits_requeued by orders of magnitude and burning a core for the
    # whole open period.  50ms bounds the spin at 20 retry windows/s —
    # far above any circuit probe cadence that could heal it.
    _REQUEUE_DAMP = 0.05

    def _requeue_hits(self, reqs) -> None:
        """Give hits that failed to reach their owner another window,
        bounded and age-capped.  Hits are precious (dropping
        under-counts the owner) but not immortal: past
        `conf.hit_requeue_age` the owner's buckets have moved on and
        replaying the backlog would double-count against fresh
        windows, so old hits drop (counted).  Re-enqueue is
        non-blocking (IntervalBatcher.requeue_many) — this runs on
        flush threads, which must never wait on producer admission."""
        import time

        age_cap = self.conf.hit_requeue_age
        if age_cap <= 0 or not reqs:
            return
        # Damp the retry cadence BEFORE re-admitting (we run on a
        # flush worker; the hits pool has a second worker for healthy
        # owners, and hits are async by contract).
        time.sleep(self._REQUEUE_DAMP)
        key_cap = self._REQUEUE_KEY_CAP_WINDOWS * self.conf.global_batch_limit
        now = time.monotonic()
        keep = []
        dropped = 0
        oldest = now
        with self._requeue_lock:
            first_map = self._requeue_first
            if len(first_map) >= key_cap // 2:
                # Sweep ORPHAN entries past the age cap: an item
                # dropped at the batcher's max_pending bound never
                # flows through the age check or the delivery clear
                # again, and without the sweep such orphans would
                # accumulate across outage episodes until the cap
                # permanently disabled re-queueing.  Keys in THIS
                # batch are excluded — deleting theirs would hand the
                # per-item loop a fresh timestamp and let expired hits
                # replay forever, the exact harm the age cap exists to
                # prevent.  O(map ≤ key_cap), behind the damped retry
                # cadence.
                # Only the unambiguous orphan band (> 2×cap) may be
                # swept: entries in (cap, 2×cap] can belong to ANOTHER
                # owner's requeue task running concurrently on the
                # pool — deleting one would hand that task a fresh
                # timestamp and replay its expired hits.  A live
                # episode touches its entry every ~damp interval, so
                # nothing live ever reaches 2×cap.
                batch_keys = {r.hash_key() for r in reqs}
                for k in [
                    k for k, t in first_map.items()
                    if now - t > 2 * age_cap and k not in batch_keys
                ]:
                    del first_map[k]
            for r in reqs:
                k = r.hash_key()
                first = first_map.get(k)
                if first is None:
                    if len(first_map) >= key_cap:
                        dropped += 1
                        continue
                    first_map[k] = first = now
                if now - first > age_cap:
                    if now - first > 2 * age_cap:
                        # Far past the cap = a stale ORPHAN from a
                        # previous episode (its requeue was refused at
                        # the batcher bound, so delivery never cleared
                        # it) — a LIVE episode retries every ~damp
                        # interval and would have hit the (cap, 2cap]
                        # band first.  Treat this failure as the new
                        # episode's first.
                        first_map[k] = first = now
                    else:
                        del first_map[k]
                        dropped += 1
                        continue
                if first < oldest:
                    oldest = first
                keep.append((k, r))
        # oldest = the survivors' original first-enqueue time, so the
        # backlog-age gauge keeps exposing the failure episode instead
        # of re-anchoring at now() every retry window.
        admitted = (
            self._hits.requeue_many(keep, oldest_ts=oldest) if keep else 0
        )
        with self._counter_lock:
            self.hits_requeued += admitted
            self.hits_requeue_dropped += dropped + (len(keep) - admitted)

    def _requeue_enabled(self) -> bool:
        """Cheap gate the columnar failure path checks BEFORE
        materializing request objects for _requeue_hits."""
        return self.conf.hit_requeue_age > 0

    def _clear_requeued(self, keys) -> None:
        """Delivered hits leave the re-queue age table (stale entries
        would age-drop a key's NEXT failure episode prematurely).
        Callers guard on the table being non-empty, so the healthy
        path never pays per-key work here."""
        with self._requeue_lock:
            for k in keys:
                self._requeue_first.pop(k, None)

    # -- chunk aggregation (flush threads, window-amortized) -----------

    @staticmethod
    def _aggregate_chunks(chunks, sum_hits: bool) -> Dict[str, RateLimitReq]:
        """Per-key aggregation of queued (dec, idx) chunks, grouped by
        the decoded (fnv1a, fnv1) hash PAIR with numpy — hits summed
        (hits loop) or latest-wins (broadcast dedupe, reference:
        global.go:92-95, 176).  Python runs once per UNIQUE key, not
        per item: hot-key windows aggregate thousands of occurrences
        into a handful of groups entirely in numpy.  Key identity by
        two independent 64-bit FNV variants — a pair collision within
        one sync window is ~2^-128, far below memory-error rates."""
        import numpy as np

        if not chunks:
            return {}
        groups = GlobalManager._hash_pair_groups(chunks)
        if groups is None:
            return {}
        sums, last_flat, _, _ = groups
        # Flat source refs so the per-unique pass can reach the latest
        # occurrence's full row.
        chunk_id = np.repeat(
            np.arange(len(chunks), dtype=np.int64),
            [len(idx) for _, idx in chunks],
        )
        flat_j = np.concatenate([idx for _, idx in chunks])

        out: Dict[str, RateLimitReq] = {}
        raws = [dec.key_buf.tobytes() for dec, _ in chunks]
        for g in range(len(sums)):
            fl = int(last_flat[g])
            dec, _ = chunks[int(chunk_id[fl])]
            raw = raws[int(chunk_id[fl])]
            j = int(flat_j[fl])
            a, b = int(dec.key_offsets[j]), int(dec.key_offsets[j + 1])
            kb = raw[a:b]
            nl = int(dec.name_len[j])
            out[kb.decode()] = RateLimitReq(
                name=kb[:nl].decode(),
                unique_key=kb[nl + 1:].decode(),
                hits=int(sums[g]) if sum_hits else int(dec.hits[j]),
                limit=int(dec.limit[j]),
                duration=int(dec.duration[j]),
                algorithm=int(dec.algo[j]),
                behavior=int(dec.behavior[j]),
                burst=int(dec.burst[j]),
            )
        return out

    # -- flush paths (run on batcher threads) --------------------------

    @staticmethod
    def _traced_task(name: str, ctx, fn, **attrs):
        """Wrap a fan-out task so its span re-anchors to the window's
        context on the rpc pool thread (tracing.current_context is
        thread-local).  ctx=None (tracing off) returns fn unwrapped —
        the disabled path pays nothing."""
        if ctx is None:
            return fn

        def run(*args):
            from gubernator_tpu.utils.tracing import span

            with span(name, parent_ctx=ctx, **attrs):
                return fn(*args)

        return run

    def _send_hits(self, hits: Dict[str, RateLimitReq], chunks=None) -> None:
        """Group aggregated hits per owner and forward.

        reference: global.go:124-164 (sendHits).
        """
        import time

        from gubernator_tpu.utils.tracing import span

        # Adopt (and clear) the first enqueuer's span context for this
        # window — the forwarder half of the cross-process stitch.
        ctx, self._hits_seed = self._hits_seed, None
        if not hits and chunks:
            # Hot case (all traffic arrived via the wire fast path):
            # aggregate, route, encode and send entirely columnar —
            # zero request objects per key (VERDICT r3 #2).
            t0 = time.monotonic()
            if self._send_hits_columnar(chunks, ctx):
                self.hits_duration.observe(time.monotonic() - t0)
                return
        for k, r in self._aggregate_chunks(chunks or [], sum_hits=True).items():
            hits[k] = _combine_hits(hits.get(k), r)
        if not hits:
            return
        t0 = time.monotonic()
        with span("global.hits_window", keys=len(hits), parent_ctx=ctx):
            self._send_hits_traced(hits)
        self.hits_duration.observe(time.monotonic() - t0)

    def _send_hits_columnar(self, chunks, ctx=None) -> bool:
        """Columnar hits fan-out: returns False to use the dataclass
        fallback (codec unavailable / empty picker)."""
        import numpy as np

        from gubernator_tpu.net import wire_codec
        from gubernator_tpu.utils.tracing import span

        if wire_codec.load() is None:
            return False
        agg = self._aggregate_chunk_columns(chunks)
        if agg is None:
            return True  # nothing queued
        (key_buf, starts, lens, name_len, algo, behavior, hits_col,
         limit, duration, burst, h1, h1a) = agg
        owners = self.instance.get_peer_batch_hashed(h1, h1a)
        if owners is None:
            return False
        n = len(algo)
        with span("global.hits_window_columnar", keys=n, parent_ctx=ctx):
            from gubernator_tpu.utils import tracing

            wctx = tracing.current_context()
            by_addr: Dict[str, list] = {}
            clients = {}
            for i, peer in enumerate(owners):
                addr = peer.info.grpc_address
                by_addr.setdefault(addr, []).append(i)
                clients[addr] = peer

            def _send_one_owner(addr: str, idx_list: list) -> None:
                import time as _time

                peer = clients[addr]
                idx = np.asarray(idx_list, dtype=np.int64)
                sent = 0
                try:
                    if peer.info.is_owner:
                        # Ownership moved to us between queue and
                        # flush (rare): behave like the owner path —
                        # materialize just this group.
                        self.instance.apply_local_batch(
                            [
                                self._req_from_columns(
                                    key_buf, starts, lens, name_len,
                                    algo, behavior, hits_col, limit,
                                    duration, burst, int(i),
                                )
                                for i in idx_list
                            ]
                        )
                        sent = len(idx_list)
                    else:
                        for lo in range(0, len(idx), MAX_BATCH_SIZE):
                            sub = idx[lo:lo + MAX_BATCH_SIZE]
                            sub_buf, sub_off = wire_codec.gather_key_slices(
                                key_buf, starts[sub], lens[sub]
                            )
                            payload = wire_codec.encode_peer_reqs(
                                sub_buf, sub_off, name_len[sub],
                                algo[sub], behavior[sub], hits_col[sub],
                                limit[sub], duration[sub], burst[sub],
                            )
                            t_rpc = _time.monotonic()
                            peer.send_peer_hits_raw(
                                payload, timeout=self.conf.global_timeout
                            )
                            self.owner_rpc_duration.observe(
                                _time.monotonic() - t_rpc
                            )
                            sent = lo + len(sub)
                except PeerError as e:
                    log.error(
                        "error sending global hits to '%s': %s", addr, e
                    )
                    if e.not_ready and self._requeue_enabled():
                        # Unreachable owner: the UNSENT hits get
                        # another window (bounded, age-capped) so an
                        # owner that comes back converges instead of
                        # permanently under-counting.  (The enabled
                        # gate runs first — materializing a window of
                        # request objects just to discard them would
                        # tax the flush threads for nothing.)
                        self._requeue_hits(
                            [
                                self._req_from_columns(
                                    key_buf, starts, lens, name_len,
                                    algo, behavior, hits_col, limit,
                                    duration, burst, int(i),
                                )
                                for i in idx_list[sent:]
                            ]
                        )
                # The DELIVERED prefix leaves the age table even when
                # a later chunk failed (stale first-ts would age-drop
                # the key's next failure episode prematurely).
                # guberlint: ok lock — non-empty peek only; a stale
                # read worst-case runs one redundant clear pass
                if sent and self._requeue_first:
                    self._clear_requeued(
                        key_buf[
                            int(starts[i]):int(starts[i]) + int(lens[i])
                        ].tobytes().decode()
                        for i in idx_list[:sent]
                    )

            # One task per owner: the window's wall time is the
            # slowest owner, not the sum over owners — and even a
            # single owner rides the pool so the fan-out deadline
            # bounds the flush (a sync send would stall the whole
            # cycle for the per-RPC timeout when that owner is dead).
            futs = [
                self._rpc_pool.submit(
                    self._traced_task(
                        "global.owner_rpc", wctx, _send_one_owner,
                        peer=addr,
                    ),
                    addr, idx_list,
                )
                for addr, idx_list in by_addr.items()
            ]
            self._await_all(futs)
        with self._counter_lock:
            self.async_sends += 1
        return True

    @staticmethod
    def _req_from_columns(key_buf, starts, lens, name_len, algo,
                          behavior, hits, limit, duration, burst,
                          i: int) -> RateLimitReq:
        a = int(starts[i])
        kb = key_buf[a:a + int(lens[i])].tobytes()
        nl = int(name_len[i])
        return RateLimitReq(
            name=kb[:nl].decode(),
            unique_key=kb[nl + 1:].decode(),
            hits=int(hits[i]),
            limit=int(limit[i]),
            duration=int(duration[i]),
            algorithm=int(algo[i]),
            behavior=int(behavior[i]),
            burst=int(burst[i]),
        )

    @staticmethod
    def _union_key_columns(pairs):
        """Union key buffer + per-flat-occurrence (start, len) for a
        list of (dec, idx) chunk pairs — the shared indexing base of
        both flush aggregations (the broadcast encode and the hits
        column aggregation must never fork this math)."""
        import numpy as np

        bufs = [dec.key_buf for dec, _ in pairs]
        bases = np.zeros(len(bufs) + 1, dtype=np.int64)
        np.cumsum([len(b) for b in bufs], out=bases[1:])
        union = np.concatenate(bufs) if len(bufs) > 1 else bufs[0]
        starts = np.concatenate(
            [
                dec.key_offsets[:-1][idx] + bases[c]
                for c, (dec, idx) in enumerate(pairs)
            ]
        )
        lens = np.concatenate(
            [
                (dec.key_offsets[1:] - dec.key_offsets[:-1])[idx]
                for dec, idx in pairs
            ]
        )
        return union, starts, lens

    @staticmethod
    def _hash_pair_groups(chunks):
        """Shared grouping core for both flush aggregations: group the
        queued occurrences by the (fnv1a, fnv1) pair and return
        (summed hits per group, flat index of each group's LATEST
        occurrence, flat fnv1a, flat fnv1) — or None when nothing is
        queued.  The latest-occurrence trick depends on lexsort's
        stability (positions ascend within equal keys)."""
        import numpy as np

        if not chunks:
            return None
        h_a = np.concatenate([dec.fnv1a[idx] for dec, idx in chunks])
        if len(h_a) == 0:
            return None
        h_b = np.concatenate([dec.fnv1[idx] for dec, idx in chunks])
        hits = np.concatenate([dec.hits[idx] for dec, idx in chunks])
        order = np.lexsort((h_b, h_a))
        sa, sb = h_a[order], h_b[order]
        new_group = np.empty(len(order), dtype=bool)
        new_group[0] = True
        new_group[1:] = (sa[1:] != sa[:-1]) | (sb[1:] != sb[:-1])
        starts = np.nonzero(new_group)[0]
        sums = np.add.reduceat(hits[order], starts)
        ends = np.append(starts[1:], len(order))
        return sums, order[ends - 1], h_a, h_b

    @staticmethod
    def _aggregate_chunk_columns(chunks):
        """Vectorized per-key aggregation to COLUMNS (no request
        objects): returns (union key_buf, per-unique starts/lens,
        name_len, algo, behavior, summed hits, limit, duration, burst,
        fnv1, fnv1a) with latest-occurrence config fields, or None if
        nothing is queued.  Grouping identity: the (fnv1a, fnv1) hash
        pair (see _aggregate_chunks)."""
        import numpy as np

        groups = GlobalManager._hash_pair_groups(chunks)
        if groups is None:
            return None
        sums, sel, h_a, h_b = groups
        algo = np.concatenate([dec.algo[idx] for dec, idx in chunks])
        behavior = np.concatenate(
            [dec.behavior[idx] for dec, idx in chunks]
        )
        limit = np.concatenate([dec.limit[idx] for dec, idx in chunks])
        duration = np.concatenate(
            [dec.duration[idx] for dec, idx in chunks]
        )
        burst = np.concatenate([dec.burst[idx] for dec, idx in chunks])
        name_len = np.concatenate(
            [dec.name_len[idx] for dec, idx in chunks]
        )
        union, starts, lens = GlobalManager._union_key_columns(chunks)

        return (
            union, starts[sel], lens[sel], name_len[sel], algo[sel],
            behavior[sel], sums, limit[sel], duration[sel], burst[sel],
            h_b[sel], h_a[sel],
        )

    def _send_hits_traced(self, hits: Dict[str, RateLimitReq]) -> None:
        from gubernator_tpu.utils import tracing

        wctx = tracing.current_context()
        by_peer: Dict[str, List[RateLimitReq]] = {}
        clients = {}
        keys = list(hits.keys())
        try:
            # ONE ring lookup pass for the window (a per-key get_peer
            # burned ~27% of the cluster tier's core — PERF.md r4).
            peers = self.instance.get_peer_batch(keys)
        except Exception as e:  # noqa: BLE001
            log.error("while getting peers for hit window: %s", e)
            return
        for key, peer in zip(keys, peers):
            if peer is None:
                continue
            addr = peer.info.grpc_address
            by_peer.setdefault(addr, []).append(hits[key])
            clients[addr] = peer
        def _send_one(addr: str, reqs: List[RateLimitReq]) -> None:
            import time as _time

            peer = clients[addr]
            sent = 0
            try:
                if peer.info.is_owner:
                    # Ownership may have moved to us between the queue
                    # and the flush; apply locally instead of dialing
                    # ourselves.
                    self.instance.apply_local_batch(reqs)
                    sent = len(reqs)
                else:
                    # Under burst load the window can aggregate more
                    # distinct keys than one RPC may carry; chunk to
                    # the wire's hard batch limit (gubernator.go:41).
                    for lo in range(0, len(reqs), MAX_BATCH_SIZE):
                        t_rpc = _time.monotonic()
                        peer.send_peer_hits(
                            reqs[lo : lo + MAX_BATCH_SIZE],
                            timeout=self.conf.global_timeout,
                        )
                        self.owner_rpc_duration.observe(
                            _time.monotonic() - t_rpc
                        )
                        sent = min(lo + MAX_BATCH_SIZE, len(reqs))
            except PeerError as e:
                log.error("error sending global hits to '%s': %s", addr, e)
                if e.not_ready:
                    self._requeue_hits(reqs[sent:])
            # The DELIVERED prefix leaves the age table even when a
            # later chunk failed — a stale first-ts would age-drop the
            # key's next failure episode prematurely.
            # guberlint: ok lock — non-empty peek only; a stale read
            # worst-case runs one redundant clear pass
            if sent and self._requeue_first:
                self._clear_requeued(r.hash_key() for r in reqs[:sent])

        # Single owners ride the pool too — the fan-out deadline must
        # bound the flush cycle whatever the per-RPC timeout is.
        futs = [
            self._rpc_pool.submit(
                self._traced_task(
                    "global.owner_rpc_pb", wctx, _send_one, peer=addr
                ),
                addr, reqs,
            )
            for addr, reqs in by_peer.items()
        ]
        self._await_all(futs)
        with self._counter_lock:
            self.async_sends += 1

    def _broadcast_peers(self, updates: Dict[str, RateLimitReq], chunks=None) -> None:
        """Push authoritative statuses to every peer.

        reference: global.go:205-250 (broadcastPeers).  Columnar chunks
        carry their serve-time decision columns (queue_updates_chunk),
        so the hot path encodes them straight to the wire — no engine
        re-read, no per-key Python; only the dataclass path (pb
        traffic, stores) still re-reads its own state.
        """
        import time

        from gubernator_tpu.utils.tracing import span

        chunks = chunks or []
        n_keys = len(updates) + sum(len(c[1]) for c in chunks)
        if n_keys == 0:
            return
        # Adopt the first enqueuer's span context — on an owner that
        # is the serving RPC's handler span, so the broadcast joins
        # the decision's cross-process trace.
        ctx, self._updates_seed = self._updates_seed, None
        t0 = time.monotonic()
        with span("global.broadcast", keys=n_keys, parent_ctx=ctx):
            if chunks:
                payloads = self._broadcast_chunks_encoded(chunks)
                if payloads is None:
                    # Codec unavailable: aggregate into the dataclass
                    # path below (statuses re-read there).
                    updates = dict(updates)
                    updates.update(
                        self._aggregate_chunks(
                            [(d, i) for d, i, *_ in chunks],
                            sum_hits=False,
                        )
                    )
                elif payloads:

                    def _push_raw(peer) -> None:
                        try:
                            for raw in payloads:
                                peer.update_peer_globals_raw(
                                    raw, timeout=self.conf.global_timeout
                                )
                        except PeerError as e:
                            if not e.not_ready:
                                log.error(
                                    "while broadcasting global updates "
                                    "to '%s': %s",
                                    peer.info.grpc_address,
                                    e,
                                )

                    self._fanout_peers(_push_raw)
                    if not updates:
                        # One broadcast WINDOW = one count; when dict
                        # updates ride the same flush, the traced path
                        # below does the counting.
                        with self._counter_lock:
                            self.broadcasts += 1
            if updates:
                self._broadcast_peers_traced(updates)
        self.broadcast_duration.observe(time.monotonic() - t0)

    def _broadcast_chunks_encoded(self, chunks):
        """Serve-time columns → UpdatePeerGlobalsReq payload chunks,
        deduped latest-wins by the (fnv1a, fnv1) key-hash pair — all
        numpy + C, zero per-key Python.  None = codec unavailable
        (callers fall back to the dataclass re-read)."""
        import numpy as np

        from gubernator_tpu.net import wire_codec

        if wire_codec.load() is None:
            return None
        # Order by apply-completion sequence so "latest occurrence"
        # means latest ENGINE APPLY, not latest enqueue (stable sort:
        # in-chunk request order is already apply order).
        chunks = sorted(chunks, key=lambda c: c[6] if len(c) > 6 else 0)
        pairs = [(dec, idx) for dec, idx, *_ in chunks]
        groups = self._hash_pair_groups(pairs)
        if groups is None:
            return []
        _sums, sel, _, _ = groups
        algo = np.concatenate([dec.algo[idx] for dec, idx in pairs])[sel]
        st = np.concatenate([c[2] for c in chunks])[sel]
        lim = np.concatenate([c[3] for c in chunks])[sel]
        rem = np.concatenate([c[4] for c in chunks])[sel]
        rst = np.concatenate([c[5] for c in chunks])[sel]
        union, starts, lens = self._union_key_columns(pairs)
        starts = starts[sel]
        lens = lens[sel]
        n = len(sel)
        payloads = []
        for lo in range(0, n, MAX_BATCH_SIZE):
            hi = min(lo + MAX_BATCH_SIZE, n)
            sub_buf, off = wire_codec.gather_key_slices(
                union, starts[lo:hi], lens[lo:hi]
            )
            payloads.append(
                wire_codec.encode_globals(
                    sub_buf, off, algo[lo:hi], st[lo:hi],
                    lim[lo:hi], rem[lo:hi], rst[lo:hi],
                )
            )
        return payloads

    def _broadcast_peers_traced(self, updates: Dict[str, RateLimitReq]) -> None:
        payloads = self._reread_encoded(updates)
        if payloads is not None:
            # Native plane: one C-encoded UpdatePeerGlobalsReq per
            # MAX_BATCH chunk, pushed raw to every peer (the broadcast
            # fires every sync window — the pb path's per-item objects
            # were ~25% of the cluster tier's core, PERF.md r4).
            if not payloads:
                return

            def _push_raw(peer) -> None:
                try:
                    for raw in payloads:
                        peer.update_peer_globals_raw(
                            raw, timeout=self.conf.global_timeout
                        )
                except PeerError as e:
                    if not e.not_ready:
                        log.error(
                            "while broadcasting global updates to '%s': %s",
                            peer.info.grpc_address,
                            e,
                        )

            self._fanout_peers(_push_raw)
            with self._counter_lock:
                self.broadcasts += 1
            return
        globals_ = self._reread_own_state(updates)
        if not globals_:
            return

        def _push_pb(peer) -> None:
            try:
                # Chunk: keep each UpdatePeerGlobals under the wire's
                # batch/message-size limits under burst load.
                for lo in range(0, len(globals_), MAX_BATCH_SIZE):
                    peer.update_peer_globals(
                        globals_[lo : lo + MAX_BATCH_SIZE],
                        timeout=self.conf.global_timeout,
                    )
            except PeerError as e:
                if not e.not_ready:
                    log.error(
                        "while broadcasting global updates to '%s': %s",
                        peer.info.grpc_address,
                        e,
                    )

        self._fanout_peers(_push_pb)
        with self._counter_lock:
            self.broadcasts += 1

    def _fanout_peers(self, push) -> None:
        """Run `push(peer)` for every non-self peer, CONCURRENTLY when
        there is more than one: the broadcast's wall time is the
        slowest peer, not the sum over peers.  Per-peer delivery order
        is preserved because broadcast flushes themselves stay
        turn-ordered (each flush completes all its pushes before the
        next flush starts).

        Circuit-open peers are skipped up front (counted): broadcasts
        are supersedable, so a broken peer simply misses windows until
        its circuit half-opens — at which point the next fan-out IS
        the probe.  `would_allow` is the non-consuming peek; the
        consuming gate runs inside the peer's own send.

        Peers whose PREVIOUS push is still in flight (it outlived the
        fan-out deadline) are skipped too: starting a second push to
        the same peer while an older one runs could deliver a stale
        status LAST — per-peer delivery order is the invariant the
        no-flush-pool design of `_updates` exists for."""
        from gubernator_tpu.utils import tracing

        wctx = tracing.current_context()
        skipped_circuit = 0
        skipped_inflight = 0
        peers = []
        inflight = self._bcast_inflight
        current = set()
        for p in self.instance.get_peer_list():
            if p.info.is_owner:  # exclude ourselves
                continue
            addr = p.info.grpc_address
            current.add(addr)
            prev = inflight.get(addr)
            if prev is not None and not prev.done():
                skipped_inflight += 1
                continue
            if not p.health.would_allow():
                skipped_circuit += 1
                continue
            peers.append(p)
        # Prune departed peers (membership churn would otherwise grow
        # the map one dead Future per replaced pod, forever).
        for addr in [a for a in inflight if a not in current]:
            if inflight[addr].done():
                del inflight[addr]
        if skipped_circuit or skipped_inflight:
            with self._counter_lock:
                self.broadcasts_skipped += skipped_circuit
                self.broadcasts_skipped_inflight += skipped_inflight
        if not peers:
            return
        # Even a single peer rides the pool + bounded barrier: running
        # the push synchronously on the flush thread would make the
        # fan-out deadline inert in exactly the 2-node case (a dead
        # peer would stall every flush for the full per-RPC timeout
        # until its circuit opens).
        futs = []
        for p in peers:
            f = self._rpc_pool.submit(
                self._traced_task(
                    "global.broadcast_push", wctx, push,
                    peer=p.info.grpc_address,
                ),
                p,
            )
            inflight[p.info.grpc_address] = f
            futs.append(f)
        # Broadcast pushes are supersedable → queued tasks may be
        # cancelled at the deadline (hit sends must never be).
        self._await_all(futs, cancel_on_deadline=True)

    def _await_all(self, futs, cancel_on_deadline: bool = False) -> None:
        """Wait for every fan-out task, logging failures per task — a
        sequential bare f.result() loop would abandon (and silently
        swallow) the remaining tasks on the first non-PeerError — but
        never past ONE total budget for the whole barrier
        (conf.global_fanout_deadline, GUBER_GLOBAL_FANOUT_DEADLINE):
        one dead/slow peer must not stall the flush cycle for a full
        gRPC timeout per peer.  A task that outlives the budget keeps
        running on the pool (its own RPC timeout bounds it) and its
        eventual transport error feeds the peer's circuit breaker; the
        timed-out wait itself is counted via record_swallowed.

        `cancel_on_deadline` pulls back queued-but-not-started tasks —
        ONLY safe for supersedable traffic (broadcast pushes, where a
        skipped window is corrected by the next one).  Hit-send tasks
        must NEVER be cancelled: a cancelled task's body never runs,
        so neither the send nor its PeerError→requeue recovery would
        — the hits would be silently lost and the owner would
        under-count."""
        import time
        from concurrent.futures import TimeoutError as FutTimeout

        from gubernator_tpu.utils.metrics import record_swallowed

        deadline = time.monotonic() + max(
            0.05, self.conf.global_fanout_deadline
        )
        for f in futs:
            try:
                f.result(timeout=max(0.0, deadline - time.monotonic()))
            except FutTimeout:
                if cancel_on_deadline:
                    f.cancel()
                record_swallowed("global.fanout_deadline")
                log.warning(
                    "global fan-out task exceeded the barrier budget; "
                    "not waiting (the send's own timeout + circuit "
                    "breaker bound it)"
                )
            except Exception:  # noqa: BLE001 — peers must not sink peers
                record_swallowed("global.fanout")
                log.exception("global fan-out task failed")

    def _reread_encoded(self, updates: Dict[str, RateLimitReq]):
        """Columnar re-read + native encode: returns a list of
        UpdatePeerGlobalsReq payload chunks, or None to use the pb
        fallback (codec unavailable, store attached, Gregorian keys)."""
        from gubernator_tpu.net import wire_codec

        if wire_codec.load() is None:
            return None
        eng = self.instance.engine
        if getattr(eng, "apply_columnar", None) is None or getattr(
            eng, "store", None
        ) is not None:
            return None
        import numpy as np

        items = list(updates.values())
        n = len(items)
        if n == 0:
            return []
        keys_b = [r.hash_key().encode() for r in items]
        algo = np.fromiter((int(r.algorithm) for r in items), np.int32, n)
        behavior = np.fromiter(
            (int(r.behavior) & ~int(Behavior.GLOBAL) for r in items),
            np.int32, n,
        )
        limit = np.fromiter((r.limit for r in items), np.int64, n)
        duration = np.fromiter((r.duration for r in items), np.int64, n)
        burst = np.fromiter((r.burst for r in items), np.int64, n)
        try:
            st, lim, rem, rst = eng.apply_columnar(
                keys_b, algo, behavior,
                np.zeros(n, dtype=np.int64),  # hits=0: report-only
                limit, duration, burst,
            )
        except Exception:  # noqa: BLE001 — e.g. invalid Gregorian
            return None
        ledger = getattr(self.instance, "ledger", None)
        if ledger is not None:
            # Leases PRE-DEBIT their credit, so the device UNDER-reports
            # the logical remaining by the held (unconsumed) budget;
            # the broadcast must add it back or peers under-admit.
            rem = np.asarray(rem).copy()
            ledger.readonly_overlay(keys_b, rem)
        key_buf = np.frombuffer(b"".join(keys_b), dtype=np.uint8)
        key_off = np.zeros(n + 1, dtype=np.int64)
        np.cumsum([len(k) for k in keys_b], out=key_off[1:])
        payloads = []
        for lo in range(0, n, MAX_BATCH_SIZE):
            hi = min(lo + MAX_BATCH_SIZE, n)
            sub_off = (key_off[lo:hi + 1] - key_off[lo])
            payloads.append(wire_codec.encode_globals(
                key_buf[key_off[lo]:key_off[hi]], sub_off,
                algo[lo:hi], st[lo:hi], lim[lo:hi], rem[lo:hi],
                rst[lo:hi],
            ))
        return payloads

    def _reread_own_state(
        self, updates: Dict[str, RateLimitReq]
    ) -> List[UpdatePeerGlobal]:
        """Status query (hits=0, GLOBAL cleared) of every queued key.

        Columnar when the engine allows it — broadcast windows fire
        every global_sync_wait (500µs default) and hold the engine
        lock, so the dataclass path's per-item Python here throttled
        the whole node under GLOBAL load (profiled ~20ms per 1000-key
        window; columnar is ~3ms).  reference: global.go:205-228."""
        eng = self.instance.engine
        items = list(updates.values())
        apply_columnar = getattr(eng, "apply_columnar", None)
        if apply_columnar is not None and getattr(eng, "store", None) is None:
            import numpy as np

            n = len(items)
            keys_str = [r.hash_key() for r in items]
            algo = np.fromiter((int(r.algorithm) for r in items), np.int32, n)
            behavior = np.fromiter(
                (int(r.behavior) & ~int(Behavior.GLOBAL) for r in items),
                np.int32,
                n,
            )
            limit = np.fromiter((r.limit for r in items), np.int64, n)
            duration = np.fromiter((r.duration for r in items), np.int64, n)
            burst = np.fromiter((r.burst for r in items), np.int64, n)
            try:
                st, lim, rem, rst = apply_columnar(
                    [k.encode() for k in keys_str],
                    algo,
                    behavior,
                    np.zeros(n, dtype=np.int64),  # hits=0: report-only
                    limit,
                    duration,
                    burst,
                )
            except Exception:  # noqa: BLE001 — e.g. a queued key with an
                # invalid Gregorian interval; the dataclass path turns
                # that into a per-item error response instead.
                return self._reread_dataclass(items)
            ledger = getattr(self.instance, "ledger", None)
            if ledger is not None:
                rem = np.asarray(rem).copy()
                ledger.readonly_overlay(
                    [k.encode() for k in keys_str], rem
                )
            status_of = {int(s): s for s in Status}
            return [
                UpdatePeerGlobal(
                    key=keys_str[i],
                    status=RateLimitResp(
                        status=status_of[int(st[i])],
                        limit=int(lim[i]),
                        remaining=int(rem[i]),
                        reset_time=int(rst[i]),
                    ),
                    algorithm=Algorithm(int(algo[i])),
                )
                for i in range(n)
            ]
        return self._reread_dataclass(items)

    def _reread_dataclass(
        self, items: List[RateLimitReq]
    ) -> List[UpdatePeerGlobal]:
        reqs = [
            replace(
                r,
                behavior=int(r.behavior) & ~int(Behavior.GLOBAL),
                hits=0,
            )
            for r in items
        ]
        resps = self.instance.apply_local_batch(reqs)
        globals_: List[UpdatePeerGlobal] = []
        for r, resp in zip(reqs, resps):
            if resp.error:
                log.error(
                    "while broadcasting update to peers for '%s': %s",
                    r.hash_key(),
                    resp.error,
                )
                continue
            globals_.append(
                UpdatePeerGlobal(
                    key=r.hash_key(),
                    status=resp,
                    algorithm=Algorithm(r.algorithm),
                )
            )
        return globals_

    def flush_now(self) -> None:
        """Synchronously drain both windows: forward aggregated hits
        to owners, then broadcast re-read state to peers.  Bounds the
        eventually-consistent lag on demand (graceful drains, tests)."""
        self._hits.flush_now()
        self._updates.flush_now()

    def close(self) -> None:
        self._hits.close()
        self._updates.close()
        self._rpc_pool.shutdown(wait=True)
